PY ?= python

.PHONY: lint test test-fast

lint:
	$(PY) tools/lint.py

test: lint
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q

test-fast:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' -x

PY ?= python

.PHONY: lint typecheck analyze sentinel test test-fast trace-demo chaos service-chaos bench-pushdown bench-decode bench-wire bench-incremental bench-reader bench-encfold bench-forensics bench-chaos bench-service bench-mesh bench-sharing bench-window clean-native

lint:
	$(PY) tools/lint.py

# mypy strict on the typed core (deequ_tpu/lint, deequ_tpu/observe —
# see [tool.mypy] in pyproject.toml), permissive elsewhere. Degrades to
# a notice when mypy is not installed: the repo must stay checkable in
# environments that cannot add packages.
typecheck:
	@if $(PY) -c "import mypy" 2>/dev/null; then \
		$(PY) -m mypy deequ_tpu/lint deequ_tpu/observe; \
	else \
		echo "typecheck: mypy not installed — skipping (pip install mypy to enable)"; \
	fi

# the full static-analysis suite: repo lints, types, and a smoke
# EXPLAIN over the benchmark plan (proves the cost analyzer runs
# end-to-end without touching data)
analyze: lint typecheck
	JAX_PLATFORMS=cpu $(PY) tools/explain_bench.py

# regression sentinel: anomaly strategies over the engine telemetry
# series (ENGINE_METRICS.json, appended by bench runs) and the
# committed BENCH_r0*.json history; exits nonzero when throughput or
# phase shares regress — see BENCH.md
sentinel:
	JAX_PLATFORMS=cpu $(PY) tools/sentinel.py

trace-demo:
	JAX_PLATFORMS=cpu PYTHONPATH=.:examples $(PY) examples/tracing_example.py

# row-group pushdown A/B over a sorted-key parquet file: same
# where-heavy plan with DEEQU_TPU_PUSHDOWN=0 then =1, bit-identity
# asserted, skipped-group counts from the traced pass. Refreshes
# BENCH_PUSHDOWN.json (methodology: BENCH.md round 8)
bench-pushdown:
	JAX_PLATFORMS=cpu BENCH_MODE=pushdown $(PY) bench.py

# decode fast-path A/B over the 50-column wide stream shape: same
# decode-bound plan with DEEQU_TPU_DECODE_FASTPATH=0 then =1 (plus a
# worker-pool pass), bit-identity asserted, decode self-seconds from
# traced passes. Refreshes BENCH_DECODE.json (methodology: BENCH.md
# round 9)
BENCH_DECODE_ROWS ?= 4000000
bench-decode:
	JAX_PLATFORMS=cpu BENCH_MODE=decode BENCH_ROWS=$(BENCH_DECODE_ROWS) $(PY) bench.py

# decode-to-wire fusion A/B over the same 50-column wide stream shape:
# same packed-wire-safe plan with DEEQU_TPU_WIRE_FUSED=0 then =1,
# bit-identity asserted, decode+prep combined self-seconds from traced
# warm passes plus warm-jit cold-IO wall times. Refreshes
# BENCH_WIRE.json (methodology: BENCH.md round 10)
BENCH_WIRE_ROWS ?= 4000000
bench-wire:
	JAX_PLATFORMS=cpu BENCH_MODE=wire BENCH_ROWS=$(BENCH_WIRE_ROWS) $(PY) bench.py

# persistent partition-state cache A/B: cold full scan fills the
# repository, one partition is appended, then a cache-off full rescan
# races the warm incremental pass (cached loads + 1 scanned partition).
# Aborts unless metrics are bit-identical and the trace pins exactly one
# partition scanned. Refreshes BENCH_INCREMENTAL.json (methodology:
# BENCH.md round 11)
BENCH_INCREMENTAL_ROWS ?= 6000000
bench-incremental:
	JAX_PLATFORMS=cpu BENCH_MODE=incremental BENCH_ROWS=$(BENCH_INCREMENTAL_ROWS) $(PY) bench.py

# native parquet reader A/B over the cold 50-column stream shape under
# the 50ms object-store stall model: same plan with
# DEEQU_TPU_NATIVE_READER=0 then =1, bit-identity asserted, decode-stage
# self-seconds from traced passes plus untraced cold-IO wall times.
# Refreshes BENCH_READER.json (methodology: BENCH.md round 12)
BENCH_READER_ROWS ?= 4000000
bench-reader:
	JAX_PLATFORMS=cpu BENCH_MODE=reader BENCH_ROWS=$(BENCH_READER_ROWS) $(PY) bench.py

# encoded-data fold A/B on the low-cardinality half of the 50-column
# wide-stream shape: same plan with DEEQU_TPU_ENCODED_FOLD=0 (row-width
# expansion) then =1 (run/dictionary folding), native reader on both
# sides, bit-identity asserted — the bench ABORTS on any metric
# mismatch or plan/runtime drift. Refreshes BENCH_ENCFOLD.json
# (methodology: BENCH.md round 20)
BENCH_ENCFOLD_ROWS ?= 4000000
bench-encfold:
	JAX_PLATFORMS=cpu BENCH_MODE=encfold BENCH_ROWS=$(BENCH_ENCFOLD_ROWS) $(PY) bench.py

# failure-forensics capture A/B on the wide-stream shape: the same
# verification run with .with_forensics() off then on, bit-identity
# asserted; a completeness constraint failing ~3% of rows makes every
# batch capture-heavy. Refreshes BENCH_FORENSICS.json (methodology:
# BENCH.md round 13)
BENCH_FORENSICS_ROWS ?= 2000000
bench-forensics:
	JAX_PLATFORMS=cpu BENCH_MODE=forensics BENCH_ROWS=$(BENCH_FORENSICS_ROWS) $(PY) bench.py

# seeded fault matrix (ISSUE 13): the chaos harness's injection
# schedule determinism + retry/cancel/watchdog semantics, the chaos
# differential (IO errors, short reads, corrupt pages, worker deaths,
# stalls -> bit-identical on both placements), the SIGKILL-resume
# test, and the injected-fault shutdown audits
chaos:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_faults.py -q
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_suite_differential_fuzz.py -q -k "chaos or sigkill"
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_pipeline_shutdown.py -q -k "injected or cancellation"

# fleet-service fault matrix (ISSUE 14): seeded chaos on the four
# service.* points (admission, queue pop, worker, scheduler tick) with
# cross-tenant blast-radius containment asserted bit-identically, plus
# the full service unit/integration suite (admission codes, quotas,
# breakers, preempt->resume bit-identity, drain audits)
service-chaos:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_service_chaos.py -q
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_service.py -q
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_pipeline_shutdown.py -q -k "service"

# service scheduling benchmark (ISSUE 14): interactive p99 latency on a
# single-worker service while a heavy partitioned profile holds the
# pool — must stay within 2x of solo p99 because every interactive
# arrival preempts the heavy run at a partition boundary and the heavy
# run completes from committed states. Refreshes BENCH_SERVICE.json
BENCH_SERVICE_ROWS ?= 2000000
bench-service:
	JAX_PLATFORMS=cpu BENCH_SERVICE_ROWS=$(BENCH_SERVICE_ROWS) $(PY) tools/bench_service.py

# resilience-machinery A/B on the wide-stream shape: the same
# verification run plain vs armed (RunController + every fault point
# deciding at rate 0), bit-identity asserted, plus one seeded fault
# pass that must land bit-identical. Proves <2% clean-path overhead.
# Refreshes BENCH_CHAOS.json (methodology: BENCH.md round 14)
BENCH_CHAOS_ROWS ?= 2000000
bench-chaos:
	JAX_PLATFORMS=cpu BENCH_MODE=chaos BENCH_ROWS=$(BENCH_CHAOS_ROWS) $(PY) bench.py

# sharded streaming scan scaling curve (ISSUE 15): the IO-latency-bound
# cold pass at 1/2/4 REAL processes, rendezvous partition sharding,
# states-only allgather. Must reach >=3x wall at 4 processes with
# per-process scan throughput within 15% of solo, and every mesh size
# must report metrics bit-identical to the solo pass. Refreshes
# BENCH_MESH.json (methodology: BENCH.md round 15)
BENCH_MESH_ROWS ?= 128000
bench-mesh:
	JAX_PLATFORMS=cpu BENCH_MESH_ROWS=$(BENCH_MESH_ROWS) $(PY) tools/bench_mesh.py

# fleet-wide scan-sharing benchmark (ISSUE 17): 4 co-tenant suites
# grouped onto ONE proven union scan must finish in <=1.5x a single
# scan's wall time (vs ~4x independent), with every participant
# bit-identical to its solo run and every CONTAINED proof pinned at
# zero drift — the bench ABORTS on any mismatch. Refreshes
# BENCH_SHARING.json (methodology: BENCH.md round 17)
BENCH_SHARING_ROWS ?= 8000000
bench-sharing:
	JAX_PLATFORMS=cpu BENCH_SHARING_ROWS=$(BENCH_SHARING_ROWS) $(PY) tools/bench_sharing.py

# windowed state algebra A/B (ISSUE 18): a 30-partition daily dataset
# is cold-filled, then a warm 7-day sliding window query plus a
# week-over-week drift check — pure DQSG segment merges, zero data rows
# — races cache-off full rescans of the same current+prior week
# partitions. A traced proof pass pins partitions_scanned == 0 and
# every cover span a segment hit; any metric mismatch ABORTS. Refreshes
# BENCH_WINDOW.json (methodology: BENCH.md round 18)
BENCH_WINDOW_ROWS ?= 6000000
bench-window:
	JAX_PLATFORMS=cpu BENCH_MODE=window BENCH_ROWS=$(BENCH_WINDOW_ROWS) $(PY) bench.py

# remove cached native builds (the hash-named .so files): any strays in
# the package tree from older versions plus the per-user cache dir the
# build now prefers
clean-native:
	rm -f deequ_tpu/ops/native/_deequ_native_*.so
	$(PY) -c "from deequ_tpu.ops.native import per_user_cache_dir as d; \
	import glob, os; p = d(); \
	[os.unlink(f) for f in (glob.glob(os.path.join(p, '_deequ_native_*.so')) if p else [])]; \
	print('clean-native:', p or '(no user cache dir)')"

test: lint
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q

test-fast:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' -x

PY ?= python

.PHONY: lint test test-fast trace-demo

lint:
	$(PY) tools/lint.py

trace-demo:
	JAX_PLATFORMS=cpu PYTHONPATH=.:examples $(PY) examples/tracing_example.py

test: lint
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q

test-fast:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' -x

"""Benchmark harness: ColumnProfiler throughput on one chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "rows/s", "vs_baseline": N}

Workload (BASELINE.md bottom row / BASELINE.json configs): a full
ColumnProfiler run — the reference's 3-pass profile
(reference: profiles/ColumnProfiler.scala:81-188) — over a wide mixed
table (numeric, boolean, low-cardinality string, numeric-string columns),
the shape of the TPC-H-style profiling workloads the reference targets.

Baseline: Spark local-mode deequ profiling throughput. Spark is not in
this image, so the number is a documented proxy (see BENCH.md): 2.0M
rows/s for a full profile of a ~6-column mixed table on a modern
multi-core host — deliberately generous to Spark. vs_baseline is
our rows/s divided by that proxy; the build target is >=10.

Knobs (env):
    BENCH_ROWS      rows to profile           (default 10_000_000)
    BENCH_MODE      "profiler" | "scan" | "stream" | "wide" | "lineitem"
                    | "pushdown" | "decode" (default "profiler")
                    stream = full profile over an on-disk Parquet file via
                    Table.scan_parquet (out-of-core; constant host memory)
                    wide = the BASELINE.json 50-column north-star shape;
                    lineitem = 16-column TPC-H lineitem-like (both use a
                    best-of-3 measured SAME-SHAPE pandas denominator)
                    pushdown = row-group pruning A/B (BENCH_PUSHDOWN.json
                    methodology, BENCH.md round 8): the same where-heavy
                    fused scan over a sorted-key Parquet file with
                    DEEQU_TPU_PUSHDOWN=0 then =1, page cache dropped
                    before each timed pass; skipped-group counts come
                    from a traced pass. Refreshes BENCH_PUSHDOWN.json
                    decode = buffer-level decode fast path A/B
                    (BENCH_DECODE.json, BENCH.md round 9): a decode-bound
                    fused scan over the 50-column wide stream shape with
                    DEEQU_TPU_DECODE_FASTPATH=0 then =1, page cache
                    dropped before each timed pass; decode self-seconds
                    come from traced warm passes. Refreshes
                    BENCH_DECODE.json
                    incremental = persistent partition-state cache A/B
                    (BENCH_INCREMENTAL.json, BENCH.md round 11): cold
                    full scan fills the repository, ONE partition is
                    appended, then a cache-off full rescan races the
                    warm incremental pass that loads every unchanged
                    partition's states and scans only the new file;
                    aborts unless metrics are bit-identical and exactly
                    one partition scanned. BENCH_INCR_PARTS sets the
                    partition count (default 12, min 10)
                    window = windowed state algebra A/B
                    (BENCH_WINDOW.json, BENCH.md round 18): a
                    30-partition daily dataset is cold-filled, then a
                    warm 7-day sliding window query PLUS a week-over-week
                    drift check (all segment merges, zero data rows)
                    races cache-off full rescans of the same
                    current+prior week partitions; a traced proof pass
                    pins partitions_scanned == 0 and every cover span a
                    segment hit, and any metric mismatch aborts.
                    BENCH_WINDOW_PARTS sets the day count (default 30)
                    reader = native parquet page->wire reader A/B
                    (BENCH_READER.json, BENCH.md round 12): the decode
                    bench's 50-column wide-stream scan under a 50 ms
                    per-row-group source stall (DEEQU_TPU_SOURCE_STALL_MS)
                    with DEEQU_TPU_NATIVE_READER=0 then =1, page cache
                    dropped before each timed pass; decode-stage busy
                    seconds come from traced warm passes. Refreshes
                    BENCH_READER.json
                    forensics = failure-forensics capture A/B
                    (BENCH_FORENSICS.json, ISSUE 12): the same
                    50-column wide-stream verification run with
                    .with_forensics() off then on — a completeness
                    constraint failing on every column-null (~3% of
                    rows) makes the capture side churn its reservoirs
                    on every batch, the worst case. Aborts unless
                    check statuses and metrics are bit-identical;
                    reports best-of-reps wall per side and the
                    enabled-side overhead pct
    BENCH_TIMED     timed repetitions, best-of (default 5: shared-vCPU
                     boxes show 20-30% run-to-run noise; best-of-5 reads
                     the machine's actual capability. Compile happens
                     during the warmup run)
    BENCH_PARQUET   path for the stream-mode file (default /tmp/bench.parquet;
                     reused if it already has BENCH_ROWS rows)
    BENCH_SHAPES    "0" skips the shape regression loop (default on: a
                     profiler-mode run also re-runs the wide @4M and
                     lineitem @10M shapes in subprocesses and refreshes
                     BENCH_WIDE.json / BENCH_LINEITEM.json in place)
    BENCH_COLD      "1" + mode=stream: ONE cold pass (no warmup, no reps)
                    timed end-to-end incl. jit compile — the methodology
                    behind BENCH_STREAM_100M/1B.json; adds rows/elapsed_s/
                    peak_rss_mb fields to the JSON line
    BENCH_STREAM_SHAPE  "default" (6-col) | "wide" (50-col stream shape,
                    build_wide_stream_table): the table the stream-mode
                    parquet file holds. wide defaults BENCH_PARQUET to
                    /tmp/bench_wide.parquet and measures a same-shape
                    pandas denominator (BENCH_STREAM_1B_WIDE.json)
    BENCH_PIPELINE_AB  "1" + mode=stream + BENCH_COLD=1: run the cold
                    pass TWICE — DEEQU_TPU_PIPELINE=0 (fully serial:
                    synchronous decode, inline prep) then =1 (staged
                    pipeline) — dropping the OS page cache before each
                    (best-effort, needs root) so both pay real disk IO.
                    A traced pipelined warm-up pass runs first (jit +
                    imports + the occupancy rows), then both timed
                    passes run warm-jit/cold-IO and UNTRACED (equal
                    footing). The JSON gains a pipeline_ab
                    field: serial_s, pipelined_s, speedup, occupancy
                    (bottleneck first). Headline value = PIPELINED pass
    BENCH_SOURCE_STALL_MS  with BENCH_PIPELINE_AB: inject this many ms
                    of source wait per row-group read into BOTH sides
                    (DEEQU_TPU_SOURCE_STALL_MS; object-store latency
                    model) — measures how much source wait the pipeline
                    hides when local disk+readahead are too fast for
                    decode/IO overlap to show
    BENCH_TRACE     "1" (or the --trace flag): after the timed reps, run
                     ONE extra traced pass (deequ_tpu.observe) — adds
                     trace_file plus a trace_phases_s breakdown
                     (plan/dispatch/transfer/merge self-time seconds) to
                     the JSON record. The Chrome trace itself lands at
                     DEEQU_TPU_TRACE_OUT or a tempdir default; load it
                     in https://ui.perfetto.dev. Shape subprocesses
                     inherit the flag.
    BENCH_PLATFORM  force a jax platform ("cpu" | "tpu" | unset=default).
                     The JAX_PLATFORMS env var does NOT override the axon
                     TPU plugin on this box; this knob forces it in code.
                     "cpu" is the fast-link stand-in for measuring the
                     DEEQU_TPU_PLACEMENT=device path where "transfer" is
                     a memcpy (a PCIe/ICI-class link proxy).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# Spark local-mode full-profile proxy, rows/s (justification: BENCH.md).
# Used as a FLOOR under the measured single-core pandas/numpy reference
# implementation (measure_reference_profile_rows_per_sec): the
# denominator is max(measured, proxy), i.e. always at least as generous
# to Spark as the documented proxy.
SPARK_LOCAL_PROFILE_ROWS_PER_SEC = 2.0e6
# Spark local-mode fused scalar-scan proxy, rows/s (BENCH.md)
SPARK_LOCAL_SCAN_ROWS_PER_SEC = 10.0e6

CATEGORIES = np.array(
    ["auto", "beauty", "books", "garden", "grocery", "home", "music",
     "office", "outdoors", "pets", "sports", "tools", "toys", "video"],
    dtype=object,
)


def build_table(n_rows: int, seed: int = 0):
    """Wide mixed table: 3 numeric, 1 bool, 2 string (low + mid card)."""
    from deequ_tpu.data.table import Table

    rng = np.random.default_rng(seed)
    price = rng.lognormal(3.0, 1.0, n_rows)
    price[rng.random(n_rows) < 0.02] = np.nan  # 2% nulls
    qty = rng.integers(1, 100, n_rows)
    discount = rng.random(n_rows)
    flag = rng.random(n_rows) < 0.5
    category = CATEGORIES[rng.integers(0, len(CATEGORIES), n_rows)]
    # numeric-looking string column (profiler infers Integral, casts, and
    # runs the numeric pass on it — the reference's pass-2 cast path)
    code_dict = np.array([str(v) for v in rng.integers(0, 100_000, 4096)],
                        dtype=object)
    code = code_dict[rng.integers(0, len(code_dict), n_rows)]
    return Table.from_numpy(
        {"price": price, "qty": qty, "discount": discount,
         "flag": flag, "category": category, "code": code}
    )


def build_wide_table(n_rows: int, seed: int = 0):
    """BASELINE.json north-star shape: a 50-column mixed table (the 1B
    config at reduced rows). 20 float64 (2 with nulls), 10 int64 (6
    low-range, 4 wide), 5 bool, 10 low-cardinality string, 5
    numeric-string — the mix exercises every profiler path at width
    (per-column Python dispatch is the thing this measures)."""
    from deequ_tpu.data.table import Table

    rng = np.random.default_rng(seed)
    data = {}
    for i in range(20):
        col = (
            rng.lognormal(2.0, 1.0, n_rows)
            if i % 2
            else rng.random(n_rows) * (i + 1)
        )
        if i < 2:
            col[rng.random(n_rows) < 0.03] = np.nan
        data[f"f{i:02d}"] = col
    for i in range(10):
        if i < 6:
            data[f"i{i:02d}"] = rng.integers(0, 100 * (i + 1), n_rows)
        else:
            data[f"i{i:02d}"] = rng.integers(0, 10**9, n_rows)
    for i in range(5):
        data[f"b{i}"] = rng.random(n_rows) < (0.2 + 0.15 * i)
    for i in range(10):
        pool = CATEGORIES[: 3 + i]
        data[f"s{i:02d}"] = pool[rng.integers(0, len(pool), n_rows)]
    for i in range(5):
        pool = np.array(
            [str(v) for v in rng.integers(0, 2000 * (i + 1), 4096)],
            dtype=object,
        )
        data[f"c{i}"] = pool[rng.integers(0, len(pool), n_rows)]
    return Table.from_numpy(data)


def build_wide_stream_table(n_rows: int, seed: int = 0):
    """The 50-column wide shape for the OUT-OF-CORE stream bench: same
    column mix as build_wide_table (floats / ints / bools / low-card
    strings / numeric-strings) but with parquet-compact value
    distributions — quantized decimals (integers/100, the TPC-H money
    shape) and windowed ints, which dictionary-encode to ~1-2 bytes per
    value. The in-memory wide shape's 20 continuous f64 columns alone
    would make a 1B-row file ~160GB (incompressible entropy), which
    does not fit this box; one column (f00) stays continuous lognormal
    with nulls so the select-kernel family path rides the stream too."""
    from deequ_tpu.data.table import Table

    rng = np.random.default_rng(seed)
    data = {}
    f00 = rng.lognormal(2.0, 1.0, n_rows)
    f00[rng.random(n_rows) < 0.03] = np.nan
    data["f00"] = f00
    for i in range(1, 20):
        r = (200, 1_000, 2_000, 10_000)[i % 4]
        data[f"f{i:02d}"] = rng.integers(0, r, n_rows) / 100.0
    for i in range(10):
        if i < 6:
            data[f"i{i:02d}"] = rng.integers(0, 100 * (i + 1), n_rows)
        else:
            data[f"i{i:02d}"] = rng.integers(0, 50_000, n_rows)
    for i in range(5):
        data[f"b{i}"] = rng.random(n_rows) < (0.2 + 0.15 * i)
    for i in range(10):
        pool = CATEGORIES[: 3 + i]
        data[f"s{i:02d}"] = pool[rng.integers(0, len(pool), n_rows)]
    for i in range(5):
        pool = np.array(
            [str(v) for v in rng.integers(0, 2000 * (i + 1), 4096)],
            dtype=object,
        )
        data[f"c{i}"] = pool[rng.integers(0, len(pool), n_rows)]
    return Table.from_numpy(data)


def build_lineitem_table(n_rows: int, seed: int = 0):
    """BASELINE.json config 3: TPC-H lineitem-like, 16 columns. Dates
    are ISO strings (~2.4k distinct), l_comment uses a bounded template
    dictionary (~32k distinct) instead of TPC-H's per-row-unique text —
    the bounded-dictionary simplification is documented in BENCH.md."""
    from deequ_tpu.data.table import Table

    rng = np.random.default_rng(seed)
    n = n_rows
    days = np.array(
        [
            f"199{y}-{m:02d}-{d:02d}"
            for y in range(2, 9)
            for m in range(1, 13)
            for d in range(1, 29)
        ],
        dtype=object,
    )
    instruct = np.array(
        ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"],
        dtype=object,
    )
    modes = np.array(
        ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"],
        dtype=object,
    )
    words = np.array(
        ["carefully", "quickly", "furiously", "slyly", "blithely", "deposits",
         "requests", "packages", "theodolites", "accounts", "instructions",
         "foxes", "pinto beans", "ideas", "dependencies", "platelets"],
        dtype=object,
    )
    comments = np.array(
        [
            f"{a} {b} {c}"
            for a in words
            for b in words
            for c in words[:8]
        ],
        dtype=object,
    )
    quantity = rng.integers(1, 51, n)
    price_per_unit = rng.integers(90_000, 110_000, n) / 100.0
    return Table.from_numpy(
        {
            "l_orderkey": rng.integers(1, max(n // 4, 2), n),
            "l_partkey": rng.integers(1, 200_001, n),
            "l_suppkey": rng.integers(1, 10_001, n),
            "l_linenumber": rng.integers(1, 8, n),
            "l_quantity": quantity,
            "l_extendedprice": quantity * price_per_unit,
            "l_discount": rng.integers(0, 11, n) / 100.0,
            "l_tax": rng.integers(0, 9, n) / 100.0,
            "l_returnflag": np.array(["A", "N", "R"], dtype=object)[
                rng.integers(0, 3, n)
            ],
            "l_linestatus": np.array(["O", "F"], dtype=object)[
                rng.integers(0, 2, n)
            ],
            "l_shipdate": days[rng.integers(0, len(days), n)],
            "l_commitdate": days[rng.integers(0, len(days), n)],
            "l_receiptdate": days[rng.integers(0, len(days), n)],
            "l_shipinstruct": instruct[rng.integers(0, 4, n)],
            "l_shipmode": modes[rng.integers(0, 7, n)],
            "l_comment": comments[rng.integers(0, len(comments), n)],
        }
    )


def run_profiler(table):
    from deequ_tpu.profiles.column_profiler import ColumnProfiler

    return ColumnProfiler.profile(table)


def scan_analyzers():
    """The BASELINE.json config-2 analyzer plan, exposed so `make
    analyze` (tools/explain_bench.py) can EXPLAIN the exact plan the
    benchmark executes."""
    from deequ_tpu.analyzers import (
        ApproxCountDistinct,
        Completeness,
        Maximum,
        Mean,
        Minimum,
        Size,
        StandardDeviation,
        Sum,
    )

    return [
        Size(),
        Completeness("price"),
        Mean("price"),
        Minimum("price"),
        Maximum("price"),
        Sum("price"),
        StandardDeviation("price"),
        ApproxCountDistinct("qty"),
        Mean("discount"),
        StandardDeviation("discount"),
    ]


def run_scan(table):
    """BASELINE.json config 2: fused scalar scan (Mean/StdDev/Min/Max +
    friends) on numeric columns — one pass."""
    from deequ_tpu.ops.fused import FusedScanPass

    results = FusedScanPass(scan_analyzers()).run(table)
    for r in results:
        r.state_or_raise()
    return results


PUSHDOWN_SELECTIVITY = 0.1  # fraction of the key range the where keeps


def pushdown_where(n_rows: int) -> str:
    """The selective filter every pushdown-mode member carries: k is
    globally sorted on disk, so row-group min/max windows prove ~90% of
    the groups all-false before any Arrow decode."""
    return f"k < {int(n_rows * PUSHDOWN_SELECTIVITY)}"


def pushdown_analyzers(n_rows: int):
    """The where-heavy plan for BENCH_MODE=pushdown (BENCH.md round 8):
    every member carries the SAME selective predicate — the row-group
    pruner only skips a group when every fused member filters it, so a
    single unfiltered member would silently disable the A/B."""
    from deequ_tpu.analyzers import (
        Completeness,
        Compliance,
        Maximum,
        Mean,
        Minimum,
        Size,
        StandardDeviation,
        Sum,
    )

    w = pushdown_where(n_rows)
    return [
        Size(where=w),
        Completeness("v", where=w),
        Mean("v", where=w),
        Minimum("v", where=w),
        Maximum("v", where=w),
        Sum("v", where=w),
        StandardDeviation("v", where=w),
        Compliance("v above -200", "v >= -200", where=w),
    ]


def write_pushdown_parquet(
    n_rows: int,
    path: str,
    chunk: int = 2_000_000,
    row_group_size: int = 250_000,
) -> None:
    """Sorted-key Parquet for the pushdown A/B: k is globally sorted so
    row-group min/max are disjoint windows (maximally prunable); v
    carries 2% NaN so the DOUBLE null-bound soundness rules run on the
    hot path; s is a low-cardinality string column the stats never
    judge."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    writer = None
    done = 0
    while done < n_rows:
        rows = min(chunk, n_rows - done)
        rng = np.random.default_rng(done)
        v = rng.normal(0.0, 50.0, rows)
        v[rng.random(rows) < 0.02] = np.nan
        at = pa.table(
            {
                "k": np.arange(done, done + rows, dtype=np.int64),
                "v": v,
                "s": pa.array(
                    CATEGORIES[rng.integers(0, len(CATEGORIES), rows)],
                    type=pa.string(),
                ),
            }
        )
        if writer is None:
            writer = pq.ParquetWriter(path, at.schema)
        writer.write_table(at, row_group_size=row_group_size)
        done += rows
    if writer is not None:
        writer.close()


def run_pushdown_bench(n_rows: int) -> None:
    """BENCH_MODE=pushdown: A/B the static row-group pruner
    (deequ_tpu.lint.pushdown) on a where-heavy fused scan over a
    sorted-key Parquet file. Same discipline as the pipeline A/B: a
    traced warm-up pass first (jit + imports; its prune spans carry the
    observed skipped-group counts), one traced pass per side for decode
    self-seconds (tracing is a thumb on the scale, so traced passes are
    never the timed ones), then two warm-jit cold-IO UNTRACED timed
    passes with DEEQU_TPU_PUSHDOWN=0 / =1, the page cache dropped
    before each. The run aborts if the two sides' metrics differ — a
    speedup that changes a result is worthless. Refreshes
    BENCH_PUSHDOWN.json next to this file (round/config preserved)."""
    import pyarrow.parquet as pq

    from deequ_tpu import observe
    from deequ_tpu.data.table import Table
    from deequ_tpu.ops.fused import FusedScanPass

    path = os.environ.get("BENCH_PARQUET", "/tmp/bench_pushdown.parquet")
    t_gen = time.perf_counter()
    if not (
        os.path.exists(path) and pq.ParquetFile(path).metadata.num_rows == n_rows
    ):
        write_pushdown_parquet(n_rows, path)
    gen_s = time.perf_counter() - t_gen

    analyzers = pushdown_analyzers(n_rows)

    def run_once():
        table = Table.scan_parquet(path)
        snapshot = {}
        for r in FusedScanPass(analyzers).run(table):
            value = r.analyzer.compute_metric_from(r.state_or_raise()).value
            v = (
                value.get()
                if value.is_success
                else type(value.exception).__name__
            )
            if isinstance(v, float) and v != v:
                v = "nan"  # nan != nan would defeat the A/B comparison
            snapshot[repr(r.analyzer)] = v
        return snapshot

    # warm-up FIRST (traced, pushdown ON): compiles every program, pays
    # the one-time imports, and its prune spans carry the observed
    # skipped-group counts
    os.environ["DEEQU_TPU_PUSHDOWN"] = "1"
    with observe.tracing() as tracer_warm:
        warm_snapshot = run_once()
    prune = {
        "groups_total": 0,
        "groups_skipped": 0,
        "rows_skipped": 0,
        "wheres_elided": 0,
    }

    def visit(span):
        if span.name == "prune":
            for key in prune:
                prune[key] += int(span.attrs.get(key, 0))
        for child in span.children:
            visit(child)

    for root in tracer_warm.roots:
        visit(root)

    # decode self-seconds per side from one traced pass each. The
    # warm-up above is NOT used for this: it pays cold imports and
    # file-cache misses, which would inflate the on side's decode time.
    # Both of these traced passes run warm (jit and page cache), so the
    # decode delta isolates the decode work pruning removed.
    os.environ["DEEQU_TPU_PUSHDOWN"] = "0"
    with observe.tracing() as tracer_off:
        run_once()
    os.environ["DEEQU_TPU_PUSHDOWN"] = "1"
    with observe.tracing() as tracer_on:
        run_once()

    def decode_busy_s(roots) -> float:
        return next(
            (
                row["busy_s"]
                for row in observe.pipeline_occupancy(roots)
                if row["stage"] == "decode"
            ),
            0.0,
        )

    os.environ["DEEQU_TPU_PUSHDOWN"] = "0"
    cache_dropped = _drop_page_cache()
    t0 = time.perf_counter()
    off_snapshot = run_once()
    off_s = time.perf_counter() - t0

    os.environ["DEEQU_TPU_PUSHDOWN"] = "1"
    _drop_page_cache()
    t0 = time.perf_counter()
    on_snapshot = run_once()
    on_s = time.perf_counter() - t0

    if off_snapshot != on_snapshot or warm_snapshot != on_snapshot:
        raise SystemExit(
            "pushdown A/B: metric mismatch between the pruned and "
            f"unpruned sides\noff: {off_snapshot}\non:  {on_snapshot}"
        )

    rec = {
        "metric": "pushdown_rows_per_sec_per_chip",
        "value": round(n_rows / on_s, 1),
        "unit": "rows/s",
        "rows": n_rows,
        "where": pushdown_where(n_rows),
        "pushdown_ab": {
            "off_s": round(off_s, 2),
            "on_s": round(on_s, 2),
            "speedup_pct": round(100.0 * (off_s - on_s) / off_s, 1),
            "decode_s_off": round(decode_busy_s(tracer_off.roots), 2),
            "decode_s_on": round(decode_busy_s(tracer_on.roots), 2),
            "rg_total": prune["groups_total"],
            "rg_skipped": prune["groups_skipped"],
            "rows_skipped": prune["rows_skipped"],
            "wheres_elided": prune["wheres_elided"],
            "bit_identical": True,
            "page_cache_dropped": cache_dropped,
            "passes": (
                "traced warm-up (on) for prune counts + one traced pass "
                "per side for decode self-seconds; both timed passes are "
                "warm-jit, cold-IO, untraced"
            ),
        },
    }
    here = os.path.dirname(os.path.abspath(__file__))
    out_path = os.path.join(here, "BENCH_PUSHDOWN.json")
    try:
        with open(out_path) as fh:
            old = json.load(fh)
        for key in ("round", "config"):
            if key in old and key not in rec:
                rec[key] = old[key]
    except Exception:  # noqa: BLE001 - first write: no fields to carry
        pass
    with open(out_path, "w") as fh:
        json.dump(rec, fh)
        fh.write("\n")
    print(
        f"# bench: pushdown A/B off={off_s:.2f}s on={on_s:.2f}s "
        f"(+{100.0 * (off_s - on_s) / off_s:.1f}%), "
        f"rg {prune['groups_skipped']}/{prune['groups_total']} skipped, "
        f"decode {rec['pushdown_ab']['decode_s_off']:.2f}s -> "
        f"{rec['pushdown_ab']['decode_s_on']:.2f}s; gen={gen_s:.1f}s",
        file=sys.stderr,
    )
    print(json.dumps(rec))


def write_decode_parquet(
    n_rows: int,
    path: str,
    chunk: int = 2_000_000,
    null_frac: float = 0.03,
    row_group_size: int = 0,
) -> None:
    """The decode-wall shape: the 50-column wide stream mix with ~3%
    nulls in EVERY column — the reason a data-quality engine scans a
    table at all. Null-free columns decode near-zero-copy on the host
    chain already; it is the validity handling (fill_null allocation +
    mask extraction + NaN fold, one pass each) that builds the decode
    wall the fast path collapses into a single buffer-level pass."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    writer = None
    done = 0
    seed = 0
    while done < n_rows:
        rows = min(chunk, n_rows - done)
        rng = np.random.default_rng(seed)

        def nullify(values):
            return pa.array(values, mask=rng.random(rows) < null_frac)

        data = {}
        f00 = rng.lognormal(2.0, 1.0, rows)
        f00[rng.random(rows) < 0.03] = np.nan  # NaN rides beside nulls
        data["f00"] = nullify(f00)
        for i in range(1, 20):
            r = (200, 1_000, 2_000, 10_000)[i % 4]
            data[f"f{i:02d}"] = nullify(rng.integers(0, r, rows) / 100.0)
        for i in range(10):
            hi = 100 * (i + 1) if i < 6 else 50_000
            data[f"i{i:02d}"] = nullify(rng.integers(0, hi, rows))
        for i in range(5):
            data[f"b{i}"] = nullify(rng.random(rows) < (0.2 + 0.15 * i))
        for i in range(10):
            pool = CATEGORIES[: 3 + i]
            data[f"s{i:02d}"] = nullify(pool[rng.integers(0, len(pool), rows)])
        for i in range(5):
            pool = np.array(
                [str(v) for v in rng.integers(0, 2000 * (i + 1), 4096)],
                dtype=object,
            )
            data[f"c{i}"] = nullify(pool[rng.integers(0, len(pool), rows)])
        at = pa.table(data)
        if writer is None:
            writer = pq.ParquetWriter(path, at.schema)
        writer.write_table(at, row_group_size=row_group_size or None)
        done += rows
        seed += 1
    if writer is not None:
        writer.close()


def decode_analyzers():
    """The decode-bound plan for BENCH_MODE=decode: Completeness over
    every one of the 50 wide-stream columns plus Mean over the numerics.
    Every consumer here is packed-wire-safe, so the planner proves the
    whole schema (floats, ints, bools, dictionary strings) onto the
    native buffer-level fast path; nothing filters rows, so the scan is
    pure decode + fold and the A/B isolates the decode wall."""
    from deequ_tpu.analyzers import Completeness, Mean

    names = (
        [f"f{i:02d}" for i in range(20)]
        + [f"i{i:02d}" for i in range(10)]
        + [f"b{i}" for i in range(5)]
        + [f"s{i:02d}" for i in range(10)]
        + [f"c{i}" for i in range(5)]
    )
    out = [Completeness(c) for c in names]
    out += [Mean(f"f{i:02d}") for i in range(20)]
    out += [Mean(f"i{i:02d}") for i in range(10)]
    return out


def _decode_stage_busy_s(roots) -> float:
    """Whole decode-stage busy seconds (parquet read + decompression +
    Arrow->Table) from the prefetch producer's pipe_item spans —
    context for the A/B, not its headline metric."""
    from deequ_tpu import observe

    return next(
        (
            row["busy_s"]
            for row in observe.pipeline_occupancy(roots)
            if row["stage"] == "decode"
        ),
        0.0,
    )


def _arrow_decode_self_s(roots) -> float:
    """Decode self-seconds from a traced pass: the sum of the
    `arrow_decode` spans (data/source.py), which wrap exactly the
    Arrow-buffer -> wire conversion the fast path replaces — parquet
    read/decompression stays outside them on both sides."""
    total = 0.0

    def visit(span):
        nonlocal total
        if span.name == "arrow_decode":
            total += span.duration_s
        for child in span.children:
            visit(child)

    for root in roots:
        visit(root)
    return total


def run_decode_bench(n_rows: int) -> None:
    """BENCH_MODE=decode: A/B the buffer-level native decode fast path
    (deequ_tpu.data.arrow_decode) and the row-group decode worker pool
    on a decode-bound fused scan over the 50-column wide stream shape.
    Same discipline as the pushdown A/B: a traced warm-up pass first
    (jit + imports; its decode_fastpath spans carry the planner's
    per-column verdicts), then one traced WARM pass per side for decode
    self-seconds (tracing is a thumb on the scale, so traced passes are
    never the timed ones), one traced pass at the default worker count,
    and finally two warm-jit cold-IO UNTRACED timed passes with
    DEEQU_TPU_DECODE_FASTPATH=0 / =1 at workers=1, the page cache
    dropped before each. The run aborts if any side's metrics differ —
    a decode speedup that changes a result is worthless. Refreshes
    BENCH_DECODE.json next to this file (round/config preserved)."""
    import pyarrow.parquet as pq

    from deequ_tpu import observe
    from deequ_tpu.data.table import Table
    from deequ_tpu.ops.fused import FusedScanPass

    path = os.environ.get("BENCH_PARQUET", "/tmp/bench_decode.parquet")
    t_gen = time.perf_counter()
    if not (
        os.path.exists(path) and pq.ParquetFile(path).metadata.num_rows == n_rows
    ):
        write_decode_parquet(n_rows, path)
    gen_s = time.perf_counter() - t_gen

    analyzers = decode_analyzers()

    def run_once():
        snapshot = {}
        for r in FusedScanPass(analyzers).run(
            Table.scan_parquet(path, batch_rows=1 << 20)
        ):
            value = r.analyzer.compute_metric_from(r.state_or_raise()).value
            v = (
                value.get()
                if value.is_success
                else type(value.exception).__name__
            )
            if isinstance(v, float) and v != v:
                v = "nan"  # nan != nan would defeat the A/B comparison
            snapshot[repr(r.analyzer)] = v
        return snapshot

    workers_n = min(os.cpu_count() or 1, 4)
    os.environ["DEEQU_TPU_DECODE_WORKERS"] = "1"

    # warm-up FIRST (traced, fast path ON): compiles every program, pays
    # the one-time imports, and its decode_fastpath spans carry the
    # planner's per-column verdicts
    os.environ["DEEQU_TPU_DECODE_FASTPATH"] = "1"
    with observe.tracing() as tracer_warm:
        warm_snapshot = run_once()
    plan = {"cols_total": 0, "cols_fast": 0, "cols_fallback": 0}

    def visit(span):
        if span.name == "decode_fastpath":
            for key in plan:
                plan[key] = max(plan[key], int(span.attrs.get(key, 0)))
        for child in span.children:
            visit(child)

    for root in tracer_warm.roots:
        visit(root)

    # decode self-seconds per side from one traced pass each. The
    # warm-up above is NOT used for this: it pays cold imports and
    # file-cache misses, which would inflate the on side's decode time.
    # Both of these traced passes run warm (jit and page cache), so the
    # decode delta isolates the work the fast path removed.
    os.environ["DEEQU_TPU_DECODE_FASTPATH"] = "0"
    with observe.tracing() as tracer_off:
        run_once()
    os.environ["DEEQU_TPU_DECODE_FASTPATH"] = "1"
    with observe.tracing() as tracer_on:
        run_once()
    decode_s_off = _arrow_decode_self_s(tracer_off.roots)
    decode_s_on = _arrow_decode_self_s(tracer_on.roots)
    stage_s_off = _decode_stage_busy_s(tracer_off.roots)
    stage_s_on = _decode_stage_busy_s(tracer_on.roots)

    # the worker pool on top of the fast path (traced, warm): on a
    # single-core box the default collapses to 1 and this re-measures
    # the on side; on multi-core it shows the pool's overlap
    os.environ["DEEQU_TPU_DECODE_WORKERS"] = str(workers_n)
    with observe.tracing() as tracer_pool:
        pool_snapshot = run_once()
    decode_s_pool = _arrow_decode_self_s(tracer_pool.roots)
    os.environ["DEEQU_TPU_DECODE_WORKERS"] = "1"

    os.environ["DEEQU_TPU_DECODE_FASTPATH"] = "0"
    cache_dropped = _drop_page_cache()
    t0 = time.perf_counter()
    off_snapshot = run_once()
    off_s = time.perf_counter() - t0

    os.environ["DEEQU_TPU_DECODE_FASTPATH"] = "1"
    _drop_page_cache()
    t0 = time.perf_counter()
    on_snapshot = run_once()
    on_s = time.perf_counter() - t0

    if not (warm_snapshot == off_snapshot == on_snapshot == pool_snapshot):
        raise SystemExit(
            "decode A/B: metric mismatch between the fast-path and "
            f"host-chain sides\noff: {off_snapshot}\non:  {on_snapshot}"
        )

    reduction = (
        100.0 * (decode_s_off - decode_s_on) / decode_s_off
        if decode_s_off > 0
        else 0.0
    )
    rec = {
        "metric": "decode_rows_per_sec_per_chip",
        "value": round(n_rows / on_s, 1),
        "unit": "rows/s",
        "rows": n_rows,
        "columns": plan["cols_total"],
        "decode_ab": {
            "off_s": round(off_s, 2),
            "on_s": round(on_s, 2),
            "speedup_pct": round(100.0 * (off_s - on_s) / off_s, 1),
            "decode_s_off": round(decode_s_off, 2),
            "decode_s_on": round(decode_s_on, 2),
            "decode_reduction_pct": round(reduction, 1),
            "decode_stage_s_off": round(stage_s_off, 2),
            "decode_stage_s_on": round(stage_s_on, 2),
            "decode_s_workers_n": round(decode_s_pool, 2),
            "workers_n": workers_n,
            "cols_fast": plan["cols_fast"],
            "cols_total": plan["cols_total"],
            "bit_identical": True,
            "page_cache_dropped": cache_dropped,
            "passes": (
                "traced warm-up (on) for planner verdicts + one traced "
                "warm pass per side for decode self-seconds + one traced "
                "pass at the default worker count; both timed passes "
                "are warm-jit, cold-IO, untraced, workers=1"
            ),
        },
    }
    here = os.path.dirname(os.path.abspath(__file__))
    out_path = os.path.join(here, "BENCH_DECODE.json")
    try:
        with open(out_path) as fh:
            old = json.load(fh)
        for key in ("round", "config"):
            if key in old and key not in rec:
                rec[key] = old[key]
    except Exception:  # noqa: BLE001 - first write: no fields to carry
        pass
    with open(out_path, "w") as fh:
        json.dump(rec, fh)
        fh.write("\n")
    print(
        f"# bench: decode A/B off={off_s:.2f}s on={on_s:.2f}s "
        f"(+{100.0 * (off_s - on_s) / off_s:.1f}%), decode self "
        f"{decode_s_off:.2f}s -> {decode_s_on:.2f}s (-{reduction:.1f}%), "
        f"{plan['cols_fast']}/{plan['cols_total']} cols fast; "
        f"gen={gen_s:.1f}s",
        file=sys.stderr,
    )
    print(json.dumps(rec))


def _dispatch_self_s(roots) -> float:
    """Prep self-seconds from a traced pass: the sum of the `dispatch`
    spans (ops/fused.py), which wrap exactly the host wire pack
    (`pack_batch_inputs`) + H2D put that decode-to-wire fusion moves
    into the decode workers — device compute stays async outside."""
    total = 0.0

    def visit(span):
        nonlocal total
        if span.name == "dispatch":
            total += span.duration_s
        for child in span.children:
            visit(child)

    for root in roots:
        visit(root)
    return total


def _occupancy_rows(roots):
    """Stage occupancy rows for the BENCH.md re-baseline table."""
    from deequ_tpu import observe

    return [
        {
            "stage": row["stage"],
            "busy_s": round(float(row["busy_s"]), 2),
            "occupancy": round(float(row["occupancy"]), 3),
        }
        for row in observe.pipeline_occupancy(roots)
    ]


def run_wire_bench(n_rows: int) -> None:
    """BENCH_MODE=wire: A/B decode-to-wire fusion (ISSUE 9) on the same
    50-column wide-stream shape and packed-wire-safe plan as the decode
    bench. DEEQU_TPU_WIRE_FUSED=0 decodes every column to a host Column
    and packs the wire serially in the prep stage; =1 has the decode
    workers emit packed wire slices directly and the prep pack splice
    them in. Same discipline as the decode A/B: a traced warm-up (jit +
    imports + the planner's wire verdict), one traced WARM pass per
    side for decode/prep self-seconds and the stage-occupancy
    re-baseline (traced passes are never the timed ones), then two
    warm-jit cold-IO UNTRACED timed passes. The headline is the
    decode+prep COMBINED self-time — fusion moves pack work between the
    stages, so either stage alone would miscount. Aborts on any metric
    mismatch. Refreshes BENCH_WIRE.json (round/config preserved)."""
    import pyarrow.parquet as pq

    from deequ_tpu import observe
    from deequ_tpu.data.table import Table
    from deequ_tpu.ops.fused import FusedScanPass

    path = os.environ.get("BENCH_PARQUET", "/tmp/bench_decode.parquet")
    t_gen = time.perf_counter()
    if not (
        os.path.exists(path) and pq.ParquetFile(path).metadata.num_rows == n_rows
    ):
        write_decode_parquet(n_rows, path)
    gen_s = time.perf_counter() - t_gen

    analyzers = decode_analyzers()
    # the wire verdict needs packed-only consumers, i.e. device members
    os.environ["DEEQU_TPU_PLACEMENT"] = "device"
    workers_n = min(os.cpu_count() or 1, 4)
    os.environ["DEEQU_TPU_DECODE_WORKERS"] = str(workers_n)

    def run_once():
        snapshot = {}
        for r in FusedScanPass(analyzers).run(
            Table.scan_parquet(path, batch_rows=1 << 20)
        ):
            value = r.analyzer.compute_metric_from(r.state_or_raise()).value
            v = (
                value.get()
                if value.is_success
                else type(value.exception).__name__
            )
            if isinstance(v, float) and v != v:
                v = "nan"  # nan != nan would defeat the A/B comparison
            snapshot[repr(r.analyzer)] = v
        return snapshot

    # warm-up FIRST (traced, fusion ON): compiles every program, pays
    # the one-time imports, and its decode_fastpath span carries the
    # planner's wire verdict
    os.environ["DEEQU_TPU_WIRE_FUSED"] = "1"
    with observe.tracing() as tracer_warm:
        warm_snapshot = run_once()
    plan = {"cols_total": 0, "cols_fast": 0, "cols_wire_fused": 0}

    def visit(span):
        if span.name == "decode_fastpath":
            for key in plan:
                plan[key] = max(plan[key], int(span.attrs.get(key, 0)))
        for child in span.children:
            visit(child)

    for root in tracer_warm.roots:
        visit(root)

    # decode+prep self-seconds per side from one traced WARM pass each
    # (jit and page cache hot, so the delta isolates the moved pack)
    os.environ["DEEQU_TPU_WIRE_FUSED"] = "0"
    with observe.tracing() as tracer_off:
        off_traced_snapshot = run_once()
    os.environ["DEEQU_TPU_WIRE_FUSED"] = "1"
    with observe.tracing() as tracer_on:
        on_traced_snapshot = run_once()
    decode_s_off = _arrow_decode_self_s(tracer_off.roots)
    decode_s_on = _arrow_decode_self_s(tracer_on.roots)
    prep_s_off = _dispatch_self_s(tracer_off.roots)
    prep_s_on = _dispatch_self_s(tracer_on.roots)
    combined_off = decode_s_off + prep_s_off
    combined_on = decode_s_on + prep_s_on
    occupancy_off = _occupancy_rows(tracer_off.roots)
    occupancy_on = _occupancy_rows(tracer_on.roots)

    # warm-jit cold-IO wall times, untraced, page cache dropped
    os.environ["DEEQU_TPU_WIRE_FUSED"] = "0"
    cache_dropped = _drop_page_cache()
    t0 = time.perf_counter()
    off_snapshot = run_once()
    off_s = time.perf_counter() - t0

    os.environ["DEEQU_TPU_WIRE_FUSED"] = "1"
    _drop_page_cache()
    t0 = time.perf_counter()
    on_snapshot = run_once()
    on_s = time.perf_counter() - t0

    if not (
        warm_snapshot == off_traced_snapshot == on_traced_snapshot
        == off_snapshot == on_snapshot
    ):
        raise SystemExit(
            "wire A/B: metric mismatch between the fused and Column "
            f"sides\noff: {off_snapshot}\non:  {on_snapshot}"
        )

    reduction = (
        100.0 * (combined_off - combined_on) / combined_off
        if combined_off > 0
        else 0.0
    )
    rec = {
        "metric": "wire_rows_per_sec_per_chip",
        "value": round(n_rows / on_s, 1),
        "unit": "rows/s",
        "rows": n_rows,
        "columns": plan["cols_total"],
        "wire_ab": {
            "off_s": round(off_s, 2),
            "on_s": round(on_s, 2),
            "speedup_pct": round(100.0 * (off_s - on_s) / off_s, 1),
            "decode_s_off": round(decode_s_off, 2),
            "decode_s_on": round(decode_s_on, 2),
            "prep_s_off": round(prep_s_off, 2),
            "prep_s_on": round(prep_s_on, 2),
            "combined_s_off": round(combined_off, 2),
            "combined_s_on": round(combined_on, 2),
            "combined_reduction_pct": round(reduction, 1),
            "occupancy_off": occupancy_off,
            "occupancy_on": occupancy_on,
            "cols_wire_fused": plan["cols_wire_fused"],
            "cols_fast": plan["cols_fast"],
            "cols_total": plan["cols_total"],
            "workers_n": workers_n,
            "bit_identical": True,
            "page_cache_dropped": cache_dropped,
            "passes": (
                "traced warm-up (on) for the wire verdict + one traced "
                "warm pass per side for decode/prep self-seconds and "
                "stage occupancy; both timed passes are warm-jit, "
                "cold-IO, untraced"
            ),
        },
    }
    here = os.path.dirname(os.path.abspath(__file__))
    out_path = os.path.join(here, "BENCH_WIRE.json")
    try:
        with open(out_path) as fh:
            old = json.load(fh)
        for key in ("round", "config"):
            if key in old and key not in rec:
                rec[key] = old[key]
    except Exception:  # noqa: BLE001 - first write: no fields to carry
        pass
    with open(out_path, "w") as fh:
        json.dump(rec, fh)
        fh.write("\n")
    print(
        f"# bench: wire A/B off={off_s:.2f}s on={on_s:.2f}s "
        f"(+{100.0 * (off_s - on_s) / off_s:.1f}%), decode+prep self "
        f"{combined_off:.2f}s -> {combined_on:.2f}s (-{reduction:.1f}%), "
        f"{plan['cols_wire_fused']}/{plan['cols_total']} cols fused; "
        f"gen={gen_s:.1f}s",
        file=sys.stderr,
    )
    print(json.dumps(rec))


def reader_analyzers():
    """The reader-bound plan for BENCH_MODE=reader: Completeness +
    Mean over the 35 numeric/boolean columns of the 50-column wide
    stream. Column pruning then drops the string columns from the scan
    altogether, so every scanned column-chunk has a native page recipe
    and the A/B isolates the page->wire reader + readahead against the
    pyarrow read chain under the stall model. (Scanning the strings
    too would measure the per-column arrow fallback instead — that
    path's bit-identity is pinned by the differential fuzz tests.)"""
    from deequ_tpu.analyzers import Completeness, Mean

    names = (
        [f"f{i:02d}" for i in range(20)]
        + [f"i{i:02d}" for i in range(10)]
        + [f"b{i}" for i in range(5)]
    )
    out = [Completeness(c) for c in names]
    out += [Mean(f"f{i:02d}") for i in range(20)]
    out += [Mean(f"i{i:02d}") for i in range(10)]
    return out


def _reader_span_stats(roots):
    """Runtime reader tallies from a traced pass: summed `page_decode`
    chunk verdicts + readahead hits and `page_read` bytes. The chunk
    sum is the runtime twin of the planner's reader_chunks_native
    counter — equal when no chunk silently fell off mid-scan."""
    stats = {
        "chunks_native": 0,
        "chunks_fallback": 0,
        "readahead_hits": 0,
        "decode_units": 0,
        "read_bytes": 0,
    }

    def visit(span):
        if span.name == "page_decode":
            stats["chunks_native"] += int(span.attrs.get("chunks_native", 0))
            stats["chunks_fallback"] += int(
                span.attrs.get("chunks_fallback", 0)
            )
            stats["readahead_hits"] += (
                1 if span.attrs.get("readahead_hit") else 0
            )
            stats["decode_units"] += 1
        elif span.name == "page_read":
            stats["read_bytes"] += int(span.attrs.get("bytes_read", 0))
        for child in span.children:
            visit(child)

    for root in roots:
        visit(root)
    return stats


def run_reader_bench(n_rows: int) -> None:
    """BENCH_MODE=reader: A/B the native parquet page->wire reader
    (ISSUE 11) on the decode bench's 50-column wide-stream shape under
    a 50 ms per-row-group source stall (the object-store latency model,
    DEEQU_TPU_SOURCE_STALL_MS). DEEQU_TPU_NATIVE_READER=0 reads every
    column chunk through pyarrow inside the decode workers, paying the
    stall serially with the decompress+decode work; =1 moves the stall
    and the preads onto the dedicated read-ahead fetch thread and
    page-decodes the planner-approved chunks through
    ops/native/parquet_read.c, so IO latency overlaps decode. Same
    discipline as the decode/wire A/Bs: a traced warm-up (jit + imports
    + the planner's reader verdict from its decode_fastpath span), one
    traced WARM pass per side for decode-stage busy seconds and the
    occupancy re-baseline (traced passes are never the timed ones),
    then two warm-jit cold-IO UNTRACED timed passes. The headline is
    the decode-STAGE busy time (pipe_item spans): the reader moves
    work out of the stage entirely, so stage busy — not any one span's
    self time — is what it shrinks. Aborts on any metric mismatch.
    Refreshes BENCH_READER.json (round/config preserved)."""
    import pyarrow.parquet as pq

    from deequ_tpu import observe
    from deequ_tpu.data.table import Table
    from deequ_tpu.ops.fused import FusedScanPass

    # own file, NOT the decode bench's: object-store parquet comes from
    # incremental writers in many small row groups (one ranged GET
    # each) — the layout the stall model charges for and the readahead
    # overlaps
    path = os.environ.get("BENCH_PARQUET", "/tmp/bench_reader.parquet")
    rg_rows = 1 << 15
    t_gen = time.perf_counter()
    if not (
        os.path.exists(path) and pq.ParquetFile(path).metadata.num_rows == n_rows
    ):
        write_decode_parquet(n_rows, path, row_group_size=rg_rows)
    gen_s = time.perf_counter() - t_gen

    analyzers = reader_analyzers()
    # the latency model the readahead overlaps: one 50 ms ranged GET
    # per row group, both sides pay it
    stall_ms = int(os.environ.get("BENCH_READER_STALL_MS", "50"))
    os.environ["DEEQU_TPU_SOURCE_STALL_MS"] = str(stall_ms)
    workers_n = min(os.cpu_count() or 1, 4)
    os.environ["DEEQU_TPU_DECODE_WORKERS"] = str(workers_n)

    def run_once():
        snapshot = {}
        for r in FusedScanPass(analyzers).run(
            Table.scan_parquet(path, batch_rows=1 << 20)
        ):
            value = r.analyzer.compute_metric_from(r.state_or_raise()).value
            v = (
                value.get()
                if value.is_success
                else type(value.exception).__name__
            )
            if isinstance(v, float) and v != v:
                v = "nan"  # nan != nan would defeat the A/B comparison
            snapshot[repr(r.analyzer)] = v
        return snapshot

    # warm-up FIRST (traced, reader ON): compiles every program, pays
    # the one-time imports, and its decode_fastpath span carries the
    # planner's per-chunk reader verdict
    os.environ["DEEQU_TPU_NATIVE_READER"] = "1"
    with observe.tracing() as tracer_warm:
        warm_snapshot = run_once()
    plan = {
        "cols_total": 0,
        "cols_fast": 0,
        "cols_reader": 0,
        "reader_groups": 0,
    }

    def visit(span):
        if span.name == "decode_fastpath":
            for key in plan:
                plan[key] = max(plan[key], int(span.attrs.get(key, 0)))
        for child in span.children:
            visit(child)

    for root in tracer_warm.roots:
        visit(root)

    # decode-stage busy seconds per side from one traced WARM pass each
    # (jit and page cache hot; the stall model still fires, so the
    # delta isolates stall overlap + native page decode)
    os.environ["DEEQU_TPU_NATIVE_READER"] = "0"
    with observe.tracing() as tracer_off:
        off_traced_snapshot = run_once()
    os.environ["DEEQU_TPU_NATIVE_READER"] = "1"
    with observe.tracing() as tracer_on:
        on_traced_snapshot = run_once()
    stage_s_off = _decode_stage_busy_s(tracer_off.roots)
    stage_s_on = _decode_stage_busy_s(tracer_on.roots)
    occupancy_off = _occupancy_rows(tracer_off.roots)
    occupancy_on = _occupancy_rows(tracer_on.roots)
    runtime_stats = _reader_span_stats(tracer_on.roots)
    counters = dict(tracer_on.counters)
    planned_native = int(counters.get("reader_chunks_native", 0))
    if runtime_stats["chunks_native"] != planned_native:
        raise SystemExit(
            "reader A/B: runtime chunk count drifted from the plan "
            f"(planned {planned_native}, page_decode spans saw "
            f"{runtime_stats['chunks_native']}) — a silent mid-scan "
            "fall-off would make the on side's numbers a lie"
        )

    # warm-jit cold-IO wall times, untraced, page cache dropped
    os.environ["DEEQU_TPU_NATIVE_READER"] = "0"
    cache_dropped = _drop_page_cache()
    t0 = time.perf_counter()
    off_snapshot = run_once()
    off_s = time.perf_counter() - t0

    os.environ["DEEQU_TPU_NATIVE_READER"] = "1"
    _drop_page_cache()
    t0 = time.perf_counter()
    on_snapshot = run_once()
    on_s = time.perf_counter() - t0

    if not (
        warm_snapshot == off_traced_snapshot == on_traced_snapshot
        == off_snapshot == on_snapshot
    ):
        raise SystemExit(
            "reader A/B: metric mismatch between the native-reader and "
            f"pyarrow sides\noff: {off_snapshot}\non:  {on_snapshot}"
        )

    reduction = (
        100.0 * (stage_s_off - stage_s_on) / stage_s_off
        if stage_s_off > 0
        else 0.0
    )
    speedup_x = stage_s_off / stage_s_on if stage_s_on > 0 else 0.0
    rec = {
        "metric": "reader_rows_per_sec_per_chip",
        "value": round(n_rows / on_s, 1),
        "unit": "rows/s",
        "rows": n_rows,
        "columns": plan["cols_total"],
        "reader_ab": {
            "off_s": round(off_s, 2),
            "on_s": round(on_s, 2),
            "speedup_pct": round(100.0 * (off_s - on_s) / off_s, 1),
            "decode_stage_s_off": round(stage_s_off, 2),
            "decode_stage_s_on": round(stage_s_on, 2),
            "decode_stage_reduction_pct": round(reduction, 1),
            "decode_stage_speedup_x": round(speedup_x, 2),
            "occupancy_off": occupancy_off,
            "occupancy_on": occupancy_on,
            "stall_ms": stall_ms,
            "cols_reader": plan["cols_reader"],
            "cols_total": plan["cols_total"],
            "reader_groups": plan["reader_groups"],
            "chunks_native": runtime_stats["chunks_native"],
            "chunks_fallback": runtime_stats["chunks_fallback"],
            "readahead_hits": runtime_stats["readahead_hits"],
            "decode_units": runtime_stats["decode_units"],
            "read_mb": round(runtime_stats["read_bytes"] / 1e6, 1),
            "workers_n": workers_n,
            "bit_identical": True,
            "page_cache_dropped": cache_dropped,
            "passes": (
                "traced warm-up (on) for the reader verdict + one "
                "traced warm pass per side for decode-stage busy "
                "seconds and stage occupancy; both timed passes are "
                "warm-jit, cold-IO, untraced"
            ),
        },
    }
    here = os.path.dirname(os.path.abspath(__file__))
    out_path = os.path.join(here, "BENCH_READER.json")
    try:
        with open(out_path) as fh:
            old = json.load(fh)
        for key in ("round", "config"):
            if key in old and key not in rec:
                rec[key] = old[key]
    except Exception:  # noqa: BLE001 - first write: no fields to carry
        pass
    with open(out_path, "w") as fh:
        json.dump(rec, fh)
        fh.write("\n")
    print(
        f"# bench: reader A/B off={off_s:.2f}s on={on_s:.2f}s "
        f"(+{100.0 * (off_s - on_s) / off_s:.1f}%), decode stage "
        f"{stage_s_off:.2f}s -> {stage_s_on:.2f}s "
        f"({speedup_x:.2f}x, -{reduction:.1f}%), "
        f"{runtime_stats['chunks_native']}/"
        f"{runtime_stats['chunks_native'] + runtime_stats['chunks_fallback']}"
        f" chunks native, {runtime_stats['readahead_hits']}/"
        f"{runtime_stats['decode_units']} readahead hits; "
        f"gen={gen_s:.1f}s",
        file=sys.stderr,
    )
    print(json.dumps(rec))


def encfold_analyzers():
    """The encoded-fold plan for BENCH_MODE=encfold: the LOW-CARDINALITY
    half of the 50-column wide stream — the 19 quantized-decimal f
    columns (200-10000 distinct values each, the TPC-H money shape) and
    the 10 windowed int columns. ApproxCountDistinct makes every f
    column a sketch consumer (dictionary-code rollup); Mean over the
    ints rides the footer-proven moments memos (Σ run_len × value over
    RLE runs); the median beside it makes each of those columns a
    select-family job, whose published qkey/rkey memos serve quantile
    AND distinct-count without a row in sight; Completeness everywhere
    folds definition-level runs.
    The i%4==3 f columns (10000 distinct values — past the per-batch
    DISTINCT_PUBLISH_CAP, so a sketch consumer would decline
    publication and expand the stub every batch) carry Completeness
    only: null counts come straight from the def-runs. Column pruning
    drops f00 (continuous lognormal), the bools and the strings, so
    the A/B isolates run-folding against row-width expansion of the
    exact columns the tentpole targets."""
    from deequ_tpu.analyzers import (
        ApproxCountDistinct,
        ApproxQuantile,
        Completeness,
        Mean,
    )

    names = [f"f{i:02d}" for i in range(1, 20)] + [
        f"i{i:02d}" for i in range(10)
    ]
    out = [Completeness(c) for c in names]
    out += [
        ApproxCountDistinct(f"f{i:02d}") for i in range(1, 20) if i % 4 != 3
    ]
    out += [
        ApproxQuantile(f"f{i:02d}", 0.5) for i in range(1, 20) if i % 4 != 3
    ]
    out += [Mean(f"i{i:02d}") for i in range(10)]
    return out


def _encfold_span_stats(roots):
    """Runtime encoded-fold tallies from a traced pass: summed
    `page_decode` run/chunk verdicts. The span sums are the runtime
    twin of the traced encfold_* counters — equal when no decode unit
    went uncounted."""
    stats = {
        "runs_native": 0,
        "chunks_runs": 0,
        "chunks_native": 0,
        "chunks_fallback": 0,
        "read_bytes": 0,
    }

    def visit(span):
        if span.name == "page_decode":
            stats["runs_native"] += int(span.attrs.get("runs_native", 0))
            stats["chunks_runs"] += int(span.attrs.get("chunks_runs", 0))
            stats["chunks_native"] += int(span.attrs.get("chunks_native", 0))
            stats["chunks_fallback"] += int(
                span.attrs.get("chunks_fallback", 0)
            )
        elif span.name == "page_read":
            stats["read_bytes"] += int(span.attrs.get("bytes_read", 0))
        for child in span.children:
            visit(child)

    for root in roots:
        visit(root)
    return stats


def write_encfold_parquet(
    n_rows: int,
    path: str,
    chunk: int = 2_000_000,
    null_frac: float = 0.03,
    row_group_size: int = 0,
) -> None:
    """The CLUSTERED wide-stream shape for the encoded-fold A/B: the
    same 50-column schema as write_decode_parquet, but the
    low-cardinality columns arrive in BURSTS (geometric run lengths,
    mean ~16) instead of a uniform shuffle — the event-stream /
    system-of-record layout parquet's RLE hybrid exists for, where a
    device emits the same status/price-bucket/partition-key for many
    consecutive rows. On this shape the dictionary-index streams
    actually run-length compress, so the run-fold kernels do O(runs)
    work where row expansion does O(rows). The uniform-shuffle worst
    case (runs of length 1, where folding is pure overhead) keeps its
    bit-identity pinned by the fuzz differentials; the planner's
    benefit gate is about consumers, not run shape, so that shape
    belongs to a falloff study, not this headline."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    writer = None
    done = 0
    seed = 0
    while done < n_rows:
        rows = min(chunk, n_rows - done)
        rng = np.random.default_rng(seed)

        def nullify(values):
            return pa.array(values, mask=rng.random(rows) < null_frac)

        def bursts(draw):
            """Clustered value stream: geometric-length runs (mean 16)
            of values drawn by `draw(k)`."""
            n_blocks = max(1, rows // 8)
            lens = rng.geometric(1.0 / 16.0, n_blocks)
            while int(lens.sum()) < rows:
                lens = np.concatenate(
                    [lens, rng.geometric(1.0 / 16.0, n_blocks)]
                )
            return np.repeat(draw(len(lens)), lens)[:rows]

        data = {}
        f00 = rng.lognormal(2.0, 1.0, rows)
        f00[rng.random(rows) < 0.03] = np.nan
        data["f00"] = nullify(f00)
        for i in range(1, 20):
            r = (200, 1_000, 2_000, 10_000)[i % 4]
            data[f"f{i:02d}"] = nullify(
                bursts(lambda k, r=r: rng.integers(0, r, k) / 100.0)
            )
        for i in range(10):
            hi = 100 * (i + 1) if i < 6 else 50_000
            data[f"i{i:02d}"] = nullify(
                bursts(lambda k, hi=hi: rng.integers(0, hi, k))
            )
        for i in range(5):
            data[f"b{i}"] = nullify(rng.random(rows) < (0.2 + 0.15 * i))
        for i in range(10):
            pool = CATEGORIES[: 3 + i]
            data[f"s{i:02d}"] = nullify(pool[rng.integers(0, len(pool), rows)])
        for i in range(5):
            pool = np.array(
                [str(v) for v in rng.integers(0, 2000 * (i + 1), 4096)],
                dtype=object,
            )
            data[f"c{i}"] = nullify(pool[rng.integers(0, len(pool), rows)])
        at = pa.table(data)
        if writer is None:
            writer = pq.ParquetWriter(path, at.schema)
        writer.write_table(at, row_group_size=row_group_size or None)
        done += rows
        seed += 1
    if writer is not None:
        writer.close()


def run_encfold_bench(n_rows: int) -> None:
    """BENCH_MODE=encfold: A/B the encoded-data fold (ISSUE 20) on the
    low-cardinality half of the decode bench's 50-column wide-stream
    shape. DEEQU_TPU_ENCODED_FOLD=0 expands every planner-approved
    chunk to row width (values + validity mask) before folding; =1
    decodes the same chunks to coalesced (run_len, dict_code) streams
    plus definition-level runs, folds moments as Σ(run_len × value),
    rolls dictionary codes up into the sketch families once per chunk,
    and takes null counts straight from the def-runs — no materialized
    rows, no validity mask. Both sides run the native page reader, so
    the delta isolates run-folding itself. Same discipline as the
    decode/wire/reader A/Bs: a traced warm-up (jit + the planner's
    encoded-fold verdict), one traced WARM pass per side for
    decode-stage busy seconds (traced passes are never the timed
    ones), then two warm untraced timed passes. The headline is the
    decode-STAGE busy time: run decoding does O(runs) work where row
    expansion does O(rows), so rows/s scales with ENCODED bytes, not
    logical rows. Aborts on any metric mismatch or plan/runtime drift.
    Refreshes BENCH_ENCFOLD.json (round/config preserved)."""
    import pyarrow.parquet as pq

    from deequ_tpu import observe
    from deequ_tpu.data.table import Table
    from deequ_tpu.ops.fused import FusedScanPass

    path = os.environ.get("BENCH_PARQUET", "/tmp/bench_encfold.parquet")
    rg_rows = 1 << 18
    t_gen = time.perf_counter()
    if not (
        os.path.exists(path) and pq.ParquetFile(path).metadata.num_rows == n_rows
    ):
        write_encfold_parquet(n_rows, path, row_group_size=rg_rows)
    gen_s = time.perf_counter() - t_gen

    analyzers = encfold_analyzers()
    workers_n = min(os.cpu_count() or 1, 4)
    os.environ["DEEQU_TPU_DECODE_WORKERS"] = str(workers_n)
    os.environ["DEEQU_TPU_NATIVE_READER"] = "1"
    # host fold: a device-packed column would expand its stub every
    # batch, so the classifier excludes it by design — the encoded
    # fold is a host-side decode optimization
    os.environ["DEEQU_TPU_PLACEMENT"] = "host"

    def run_once():
        snapshot = {}
        for r in FusedScanPass(analyzers).run(
            Table.scan_parquet(path, batch_rows=1 << 20)
        ):
            value = r.analyzer.compute_metric_from(r.state_or_raise()).value
            v = (
                value.get()
                if value.is_success
                else type(value.exception).__name__
            )
            if isinstance(v, float) and v != v:
                v = "nan"  # nan != nan would defeat the A/B comparison
            snapshot[repr(r.analyzer)] = v
        return snapshot

    # warm-up FIRST (traced, fold ON): compiles every program, pays the
    # one-time imports, and records the planner's encoded-fold verdict
    os.environ["DEEQU_TPU_ENCODED_FOLD"] = "1"
    with observe.tracing() as tracer_warm:
        warm_snapshot = run_once()
    cols_enc = int(tracer_warm.counters.get("encfold_cols", 0))
    cols_total = int(tracer_warm.counters.get("encfold_cols_total", 0))
    if cols_enc == 0:
        raise SystemExit(
            "encfold A/B: the planner approved no column on the "
            "low-cardinality shape — the on side would measure nothing"
        )

    # decode-stage busy seconds per side from one traced WARM pass each
    os.environ["DEEQU_TPU_ENCODED_FOLD"] = "0"
    with observe.tracing() as tracer_off:
        off_traced_snapshot = run_once()
    os.environ["DEEQU_TPU_ENCODED_FOLD"] = "1"
    with observe.tracing() as tracer_on:
        on_traced_snapshot = run_once()
    stage_s_off = _decode_stage_busy_s(tracer_off.roots)
    stage_s_on = _decode_stage_busy_s(tracer_on.roots)
    occupancy_off = _occupancy_rows(tracer_off.roots)
    occupancy_on = _occupancy_rows(tracer_on.roots)
    runtime_stats = _encfold_span_stats(tracer_on.roots)
    off_stats = _encfold_span_stats(tracer_off.roots)
    counters = dict(tracer_on.counters)
    if runtime_stats["runs_native"] != int(counters.get("encfold_runs", 0)):
        raise SystemExit(
            "encfold A/B: per-span run counts drifted from the traced "
            f"total ({runtime_stats['runs_native']} vs "
            f"{counters.get('encfold_runs', 0)})"
        )
    if runtime_stats["chunks_fallback"] > 0:
        raise SystemExit(
            "encfold A/B: a chunk of this all-dictionary shape fell "
            f"back to row width at decode "
            f"({runtime_stats['chunks_fallback']} chunks) — the on "
            "side's numbers would charge the row path to the fold"
        )
    if int(counters.get("encfold_chunks", 0)) == 0:
        raise SystemExit(
            "encfold A/B: no chunk reached the run decoder despite "
            f"{cols_enc} approved column(s)"
        )

    # warm-jit warm-IO wall times, untraced: the fold is decode-bound,
    # not IO-bound — cold-IO timing belongs to the reader A/B
    os.environ["DEEQU_TPU_ENCODED_FOLD"] = "0"
    t0 = time.perf_counter()
    off_snapshot = run_once()
    off_s = time.perf_counter() - t0

    os.environ["DEEQU_TPU_ENCODED_FOLD"] = "1"
    t0 = time.perf_counter()
    on_snapshot = run_once()
    on_s = time.perf_counter() - t0

    if not (
        warm_snapshot == off_traced_snapshot == on_traced_snapshot
        == off_snapshot == on_snapshot
    ):
        raise SystemExit(
            "encfold A/B: metric mismatch between the encoded-fold and "
            f"row-width sides\noff: {off_snapshot}\non:  {on_snapshot}"
        )

    reduction = (
        100.0 * (stage_s_off - stage_s_on) / stage_s_off
        if stage_s_off > 0
        else 0.0
    )
    speedup_x = stage_s_off / stage_s_on if stage_s_on > 0 else 0.0
    runs = int(counters.get("encfold_runs", 0))
    values = int(counters.get("encfold_values", 0))
    rec = {
        "metric": "encfold_rows_per_sec_per_chip",
        "value": round(n_rows / on_s, 1),
        "unit": "rows/s",
        "rows": n_rows,
        "columns": cols_total,
        "encfold_ab": {
            "off_s": round(off_s, 2),
            "on_s": round(on_s, 2),
            "speedup_pct": round(100.0 * (off_s - on_s) / off_s, 1),
            "decode_stage_s_off": round(stage_s_off, 2),
            "decode_stage_s_on": round(stage_s_on, 2),
            "decode_stage_reduction_pct": round(reduction, 1),
            "decode_stage_speedup_x": round(speedup_x, 2),
            "occupancy_off": occupancy_off,
            "occupancy_on": occupancy_on,
            "cols_encfold": cols_enc,
            "cols_total": cols_total,
            "chunks_runs": runtime_stats["chunks_runs"],
            "chunks_row_off": off_stats["chunks_native"],
            "runs": runs,
            "values": values,
            "run_ratio": round(values / runs, 2) if runs else 0.0,
            "codes_folded": int(counters.get("encfold_codes_folded", 0)),
            "bytes_saved_mb": round(
                int(counters.get("encfold_bytes_saved", 0)) / 1e6, 1
            ),
            "encoded_read_mb": round(runtime_stats["read_bytes"] / 1e6, 1),
            "logical_mb": round(n_rows * 8 * cols_total / 1e6, 1),
            "workers_n": workers_n,
            "bit_identical": True,
            "passes": (
                "traced warm-up (on) for the encoded-fold verdict + one "
                "traced warm pass per side for decode-stage busy "
                "seconds; both timed passes are warm-jit, untraced"
            ),
        },
    }
    here = os.path.dirname(os.path.abspath(__file__))
    out_path = os.path.join(here, "BENCH_ENCFOLD.json")
    try:
        with open(out_path) as fh:
            old = json.load(fh)
        for key in ("round", "config"):
            if key in old and key not in rec:
                rec[key] = old[key]
    except Exception:  # noqa: BLE001 - first write: no fields to carry
        pass
    with open(out_path, "w") as fh:
        json.dump(rec, fh)
        fh.write("\n")
    print(
        f"# bench: encfold A/B off={off_s:.2f}s on={on_s:.2f}s "
        f"(+{100.0 * (off_s - on_s) / off_s:.1f}%), decode stage "
        f"{stage_s_off:.2f}s -> {stage_s_on:.2f}s "
        f"({speedup_x:.2f}x, -{reduction:.1f}%), "
        f"{cols_enc}/{cols_total} cols folded, "
        f"{values}/{runs} values/runs "
        f"({(values / runs if runs else 0):.1f}x), "
        f"gen={gen_s:.1f}s",
        file=sys.stderr,
    )
    print(json.dumps(rec))


def write_incremental_dataset(n_rows: int, n_parts: int, dir_path: str) -> None:
    """A partitioned dataset (one parquet file per partition) with
    deterministic per-partition contents: two doubles (one with NaN
    holes), one long. Partition i is a pure function of i, so appending
    part N later never perturbs parts 0..N-1."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    os.makedirs(dir_path, exist_ok=True)
    per_part = max(1, n_rows // n_parts)
    for i in range(n_parts):
        path = os.path.join(dir_path, f"part-{i:04d}.parquet")
        if os.path.exists(path):
            continue
        rng = np.random.default_rng(1_000 + i)
        x = rng.normal(float(i), 10.0, per_part)
        x[rng.random(per_part) < 0.05] = np.nan
        table = pa.table(
            {
                "x": x,
                "y": x * 0.5 + rng.normal(0.0, 1.0, per_part),
                "g": rng.integers(0, 10_000, per_part),
            }
        )
        pq.write_table(table, path, row_group_size=max(4096, per_part // 8))


def incremental_analyzers():
    """Every cacheable scan family: counts, moments, HLL, KLL."""
    from deequ_tpu.analyzers import (
        ApproxCountDistinct,
        ApproxQuantile,
        Completeness,
        Maximum,
        Mean,
        Minimum,
        Size,
        StandardDeviation,
    )

    return [
        Size(),
        Completeness("x"),
        Mean("x"),
        StandardDeviation("x"),
        Minimum("x"),
        Maximum("y"),
        ApproxCountDistinct("g"),
        ApproxQuantile("x", 0.5),
    ]


def run_incremental_bench(n_rows: int) -> None:
    """BENCH_MODE=incremental: A/B the persistent partition-state cache
    (ISSUE 10) on an N-partition dataset. Cold pass: full scan with an
    empty state repository (fills it). Then ONE partition is appended
    and the warm incremental pass — which loads N cached partition
    states and scans only the new file — races a cache-off full rescan
    of the same N+1 partitions. A separate traced pass (against a
    pristine copy of the cold cache) proves partitions_scanned == 1;
    all timed passes are warm-jit, cold-IO, untraced. Aborts on any
    metric mismatch between the incremental merge and the full rescan.
    Refreshes BENCH_INCREMENTAL.json (round/config preserved)."""
    import shutil

    from deequ_tpu import observe
    from deequ_tpu.data.table import Table
    from deequ_tpu.repository.states import FileSystemStateRepository
    from deequ_tpu.runners.analysis_runner import AnalysisRunner

    n_parts = max(10, int(os.environ.get("BENCH_INCR_PARTS", "12")))
    data_dir = os.environ.get("BENCH_INCR_DIR", "/tmp/bench_incremental")
    appended = os.path.join(data_dir, f"part-{n_parts:04d}.parquet")

    t_gen = time.perf_counter()
    if os.path.exists(appended):
        os.remove(appended)  # a previous run's appended partition
    write_incremental_dataset(n_rows, n_parts, data_dir)
    gen_s = time.perf_counter() - t_gen

    analyzers = incremental_analyzers()
    os.environ["DEEQU_TPU_PLACEMENT"] = "device"
    os.environ.pop("DEEQU_TPU_STATE_CACHE", None)

    cache_dir = os.path.join(data_dir, "state-cache")
    proof_dir = os.path.join(data_dir, "state-cache-proof")
    for d in (cache_dir, proof_dir):
        shutil.rmtree(d, ignore_errors=True)

    def run_once(repository=None, tracing=None):
        context = AnalysisRunner.do_analysis_run(
            Table.scan_parquet_dataset(data_dir, batch_rows=1 << 20),
            analyzers,
            state_repository=repository,
            dataset_name="bench",
            tracing=tracing,
        )
        snapshot = {}
        for analyzer, metric in context.metric_map.items():
            v = (
                metric.value.get()
                if metric.value.is_success
                else type(metric.value.exception).__name__
            )
            if isinstance(v, float) and v != v:
                v = "nan"  # nan != nan would defeat the A/B comparison
            snapshot[repr(analyzer)] = v
        return snapshot, context

    # warm-up (no repository): jit + imports, never timed
    warm_snapshot, _ = run_once()

    # cold pass: full scan, fills the empty repository
    repo = FileSystemStateRepository(cache_dir)
    cache_dropped = _drop_page_cache()
    t0 = time.perf_counter()
    cold_snapshot, _ = run_once(repository=repo)
    cold_s = time.perf_counter() - t0

    # the increment: ONE new partition appears
    write_incremental_dataset(
        n_rows + max(1, n_rows // n_parts), n_parts + 1, data_dir
    )
    # pristine copy of the cold cache for the traced proof pass, so the
    # timed incremental pass still sees the appended partition as new
    shutil.copytree(cache_dir, proof_dir)

    # cache-off full rescan of the grown dataset (the A side)
    _drop_page_cache()
    t0 = time.perf_counter()
    full_snapshot, _ = run_once()
    full_s = time.perf_counter() - t0

    # warm incremental pass (the B side): N cached loads + 1 scan
    _drop_page_cache()
    t0 = time.perf_counter()
    incr_snapshot, _ = run_once(repository=repo)
    incr_s = time.perf_counter() - t0

    # traced proof pass against the pristine cache copy
    proof_snapshot, proof_context = run_once(
        repository=FileSystemStateRepository(proof_dir), tracing=True
    )
    counters = proof_context.run_trace.counters

    if not (
        warm_snapshot == cold_snapshot
        and full_snapshot == incr_snapshot == proof_snapshot
    ):
        raise SystemExit(
            "incremental A/B: metric mismatch between the cached merge "
            f"and the full rescan\nfull: {full_snapshot}\nincr: {incr_snapshot}"
        )
    if counters.get("partitions_scanned") != 1:
        raise SystemExit(
            "incremental A/B: expected exactly 1 partition scanned, "
            f"trace says {dict(counters)}"
        )

    speedup = full_s / incr_s if incr_s > 0 else float("inf")
    rec = {
        "metric": "incremental_speedup",
        "value": round(speedup, 1),
        "unit": "x",
        "rows": n_rows,
        "incremental_ab": {
            "n_partitions": n_parts + 1,
            "partitions_scanned": int(counters.get("partitions_scanned", 0)),
            "partitions_cached": int(counters.get("partitions_cached", 0)),
            "cold_s": round(cold_s, 2),
            "full_rescan_s": round(full_s, 2),
            "incremental_s": round(incr_s, 2),
            "speedup_vs_full_rescan": round(speedup, 1),
            "speedup_vs_cold": round(cold_s / incr_s, 1) if incr_s > 0 else None,
            "bit_identical": True,
            "page_cache_dropped": cache_dropped,
            "passes": (
                "untimed warm-up; cold fill pass; append 1 partition; "
                "cache-off full rescan vs warm incremental, both "
                "warm-jit cold-IO untraced; traced proof pass against "
                "a pristine cache copy pins partitions_scanned == 1"
            ),
        },
    }
    here = os.path.dirname(os.path.abspath(__file__))
    out_path = os.path.join(here, "BENCH_INCREMENTAL.json")
    try:
        with open(out_path) as fh:
            old = json.load(fh)
        for key in ("round", "config"):
            if key in old and key not in rec:
                rec[key] = old[key]
    except Exception:  # noqa: BLE001 - first write: no fields to carry
        pass
    with open(out_path, "w") as fh:
        json.dump(rec, fh)
        fh.write("\n")
    print(
        f"# bench: incremental A/B full={full_s:.2f}s incr={incr_s:.2f}s "
        f"({speedup:.1f}x), scanned {counters.get('partitions_scanned')}/"
        f"{n_parts + 1} partitions (cold fill {cold_s:.2f}s); gen={gen_s:.1f}s",
        file=sys.stderr,
    )
    print(json.dumps(rec))


def write_window_dataset(n_rows: int, n_parts: int, dir_path: str) -> None:
    """A daily-partitioned dataset (one parquet file per calendar day,
    date-named so windows/spec.py derives the time axis from the
    layout). Partition i is a pure function of i, like the incremental
    dataset, so re-running never perturbs existing days."""
    import datetime

    import pyarrow as pa
    import pyarrow.parquet as pq

    os.makedirs(dir_path, exist_ok=True)
    per_part = max(1, n_rows // n_parts)
    day0 = datetime.date(2026, 1, 1)
    for i in range(n_parts):
        day = day0 + datetime.timedelta(days=i)
        path = os.path.join(dir_path, f"part-{day.isoformat()}.parquet")
        if os.path.exists(path):
            continue
        rng = np.random.default_rng(2_000 + i)
        x = rng.normal(50.0 + 0.1 * i, 10.0, per_part)
        x[rng.random(per_part) < 0.05] = np.nan
        table = pa.table(
            {
                "x": x,
                "y": x * 0.5 + rng.normal(0.0, 1.0, per_part),
                "g": rng.integers(0, 10_000, per_part),
            }
        )
        pq.write_table(table, path, row_group_size=max(4096, per_part // 8))


def run_window_bench(n_rows: int) -> None:
    """BENCH_MODE=window: A/B the windowed state algebra (windows/) on a
    30-partition daily dataset. Cold fill commits per-partition states;
    an untimed first window query publishes the DQSG segment covers.
    Then the warm B side — a 7-day sliding-window metrics query PLUS a
    week-over-week drift check, all from segment merges — races the A
    side: cache-off full rescans of the same current-week and
    prior-week partitions. A traced proof pass pins
    partitions_scanned == 0 (zero data rows read warm) and every cover
    span a segment hit; aborts on any metric mismatch between the
    window merge and the full rescan. Refreshes BENCH_WINDOW.json
    (round/config preserved)."""
    import shutil

    from deequ_tpu.checks import CheckLevel, CheckStatus, DriftCheck
    from deequ_tpu.data.table import Table
    from deequ_tpu.repository.states import FileSystemStateRepository
    from deequ_tpu.runners.analysis_runner import AnalysisRunner
    from deequ_tpu.windows import Sliding, WindowQuery

    n_parts = max(30, int(os.environ.get("BENCH_WINDOW_PARTS", "30")))
    data_dir = os.environ.get("BENCH_WINDOW_DIR", "/tmp/bench_window")

    t_gen = time.perf_counter()
    write_window_dataset(n_rows, n_parts, data_dir)
    gen_s = time.perf_counter() - t_gen

    analyzers = incremental_analyzers()
    os.environ["DEEQU_TPU_PLACEMENT"] = "device"
    os.environ.pop("DEEQU_TPU_STATE_CACHE", None)

    cache_dir = os.path.join(data_dir, "state-cache")
    shutil.rmtree(cache_dir, ignore_errors=True)
    repo = FileSystemStateRepository(cache_dir)

    def snapshot_of(context):
        snap = {}
        for analyzer, metric in context.metric_map.items():
            v = (
                metric.value.get()
                if metric.value.is_success
                else type(metric.value.exception).__name__
            )
            if isinstance(v, float) and v != v:
                v = "nan"
            snap[repr(analyzer)] = v
        return snap

    source = Table.scan_parquet_dataset(data_dir, batch_rows=1 << 20)

    # cold fill: one full scan commits every partition's states
    t0 = time.perf_counter()
    AnalysisRunner.do_analysis_run(
        source, analyzers, state_repository=repo, dataset_name="bench",
    )
    cold_s = time.perf_counter() - t0

    query = WindowQuery(
        source, analyzers, repository=repo, dataset="bench",
    )
    timeline = query.timeline()
    current = Sliding(7).resolve(timeline)
    baseline = current.shifted(7, timeline)
    parts = source.partitions()

    drift_check = (
        DriftCheck(CheckLevel.ERROR, "week-over-week")
        .has_no_drift(
            "x",
            max_quantile_shift=0.2,
            max_mean_delta=0.2,
            max_completeness_delta=0.05,
        )
        .has_no_cardinality_drift("g", max_ratio_drift=0.5)
    )

    # untimed first query: publishes the segment covers (warm=True)
    query.run(current)
    query.run(baseline)

    # A side: answer the same question by rescanning — cache-off full
    # scans of the current-week and prior-week partitions
    def subset_for(frame):
        return source.subset([parts[i].path for i in frame.indices])

    _drop_page_cache()
    t0 = time.perf_counter()
    rescan_cur = AnalysisRunner.do_analysis_run(subset_for(current), analyzers)
    rescan_base = AnalysisRunner.do_analysis_run(subset_for(baseline), analyzers)
    rescan_s = time.perf_counter() - t0

    # B side: warm window metrics + week-over-week drift, segment merges
    # only (zero data rows)
    cache_dropped = _drop_page_cache()
    t0 = time.perf_counter()
    window_ctx = query.run(current)
    cur_bag = query.states(current)
    base_bag = query.states(baseline)
    drift_result = drift_check.evaluate(current=cur_bag, baseline=base_bag)
    window_s = time.perf_counter() - t0

    # traced proof pass: zero partitions scanned, every span a hit
    proof_ctx = query.run(current, tracing=True)
    counters = proof_ctx.run_trace.counters

    if snapshot_of(window_ctx) != snapshot_of(rescan_cur):
        raise SystemExit(
            "window A/B: metric mismatch between the segment merge and "
            f"the full rescan\nrescan: {snapshot_of(rescan_cur)}\n"
            f"window: {snapshot_of(window_ctx)}"
        )
    if snapshot_of(proof_ctx) != snapshot_of(rescan_cur):
        raise SystemExit("window A/B: traced proof pass diverged")
    # the drift inputs' provenance: the prior-week window merge must
    # also match ITS full rescan bit-for-bit
    if snapshot_of(query.run(baseline)) != snapshot_of(rescan_base):
        raise SystemExit(
            "window A/B: baseline-week metric mismatch between the "
            "segment merge and the full rescan"
        )
    if counters.get("partitions_scanned", 0) != 0:
        raise SystemExit(
            "window A/B: warm window query scanned data rows, "
            f"trace says {dict(counters)}"
        )
    if counters.get("window.segment_hits", 0) != counters.get(
        "window.spans", -1
    ):
        raise SystemExit(
            "window A/B: warm query missed segment covers, "
            f"trace says {dict(counters)}"
        )
    if drift_result.status != CheckStatus.SUCCESS:
        raise SystemExit(
            "window A/B: drift check failed on the stable dataset: "
            + "; ".join(
                str(r.message)
                for r in drift_result.constraint_results
                if r.message
            )
        )

    speedup = rescan_s / window_s if window_s > 0 else float("inf")
    rec = {
        "metric": "window_speedup",
        "value": round(speedup, 1),
        "unit": "x",
        "rows": n_rows,
        "window_ab": {
            "n_partitions": n_parts,
            "window": "sliding(7) + week-over-week drift",
            "segment_merges": int(counters.get("window.segments_merged", 0)),
            "segment_hits": int(counters.get("window.segment_hits", 0)),
            "partitions_scanned": int(counters.get("partitions_scanned", 0)),
            "cold_fill_s": round(cold_s, 2),
            "full_rescan_s": round(rescan_s, 3),
            "window_query_s": round(window_s, 3),
            "speedup_vs_full_rescan": round(speedup, 1),
            "drift_status": drift_result.status.value,
            "bit_identical": True,
            "page_cache_dropped": cache_dropped,
            "passes": (
                "cold fill commits per-partition states; untimed first "
                "queries publish segment covers; cache-off rescans of "
                "current+prior week vs warm window metrics + drift "
                "check; traced proof pass pins partitions_scanned == 0 "
                "and all covers hit"
            ),
        },
    }
    here = os.path.dirname(os.path.abspath(__file__))
    out_path = os.path.join(here, "BENCH_WINDOW.json")
    try:
        with open(out_path) as fh:
            old = json.load(fh)
        for key in ("round", "config"):
            if key in old and key not in rec:
                rec[key] = old[key]
    except Exception:  # noqa: BLE001 - first write: no fields to carry
        pass
    with open(out_path, "w") as fh:
        json.dump(rec, fh)
        fh.write("\n")
    print(
        f"# bench: window A/B rescan={rescan_s:.3f}s window={window_s:.3f}s "
        f"({speedup:.1f}x), {counters.get('window.segments_merged')} segment "
        f"merges, 0 rows read (cold fill {cold_s:.2f}s); gen={gen_s:.1f}s",
        file=sys.stderr,
    )
    print(json.dumps(rec))


def _stream_shape() -> str:
    return os.environ.get("BENCH_STREAM_SHAPE", "default")


def _builder_for_mode(mode: str):
    if mode == "stream" and _stream_shape() == "wide":
        return build_wide_stream_table
    return {
        "wide": build_wide_table,
        "lineitem": build_lineitem_table,
    }.get(mode, build_table)


def measure_reference_profile_rows_per_sec(
    probe_rows: int = 2_000_000, mode: str = "profiler"
) -> float:
    """Measured baseline denominator: a straightforward single-core
    pandas/numpy implementation of the SAME 3-pass profile deequ runs
    (pass 1: size/completeness/distinct/row-level regex DataType; pass 2:
    min/max/mean/std/sum + 100 percentiles per numeric column incl. cast
    numeric-string columns; pass 3: exact value counts for low-card
    columns), over the SAME table shape as the benched mode (schema
    discovered generically by dtype, so the wide/lineitem modes get a
    same-shape denominator). This is what a competent engineer gets from
    the standard Python stack on this box — a measured stand-in for
    "Spark local on this machine", which a JVM + row-shuffle engine
    would not beat on a single core. bench uses max(this, the
    documented 2.0M proxy) as the denominator so the ratio is never
    inflated by a slow box."""
    import re
    import pandas as pd

    df = _builder_for_mode(mode)(probe_rows).to_pandas()
    t0 = time.perf_counter()

    # ---- pass 1: size, completeness, distinct, DataType inference ----
    n = len(df)
    _ = df.notna().mean()
    nuniques = {c: df[c].nunique() for c in df.columns}
    frac = re.compile(r"^(-|\+)? ?\d*\.\d*$")
    integ = re.compile(r"^(-|\+)? ?\d*$")
    boolean = re.compile(r"^(true|false)$")
    string_cols = [
        c
        for c in df.columns
        if df[c].dtype == object and not isinstance(df[c].iloc[0], (bool, np.bool_))
    ]
    type_counts = {}
    numeric_casts = {}
    for c in string_cols:
        s = df[c].dropna().astype(str)
        matches_int = s.str.fullmatch(integ)
        type_counts[c] = (
            s.str.fullmatch(frac).sum(),
            matches_int.sum(),
            s.str.fullmatch(boolean).sum(),
        )
        if len(s) and bool(matches_int.all()):
            # inferred-numeric string column: pass 2 will cast it
            numeric_casts[c] = pd.to_numeric(df[c], errors="coerce")

    # ---- pass 2: numeric stats + percentiles (incl. cast strings) ----
    numeric = {
        c: df[c]
        for c in df.columns
        if df[c].dtype.kind in "if" and df[c].dtype != bool
    }
    numeric.update(numeric_casts)
    qs = np.arange(1, 101) / 100.0
    for c, s in numeric.items():
        _ = (s.min(), s.max(), s.mean(), s.std(), s.sum())
        vals = s.dropna().to_numpy(dtype=np.float64)
        if len(vals):
            _ = np.quantile(vals, qs)

    # ---- pass 3: exact histograms for low-cardinality columns ----
    for c in df.columns:
        if df[c].dtype == bool or (c in string_cols and nuniques[c] <= 120):
            _ = df[c].value_counts(dropna=False)

    elapsed = max(time.perf_counter() - t0, 1e-9)
    return probe_rows / elapsed


def measure_arrow_profile_rows_per_sec(probe_rows: int = 2_000_000) -> float:
    """Measured baseline denominator #2: the SAME 3-pass profile through
    pyarrow's C++ compute engine pinned to ONE thread — the strongest
    columnar engine available in this image, and a stricter stand-in for
    "Spark local on this box" than pandas.

    Provenance of the engine choice: the reference's own perf substrate
    is Spark local mode (SparkContextSpec.scala:25-95). Running actual
    Spark here was attempted and is impossible offline: pyspark is not
    installed, `pip install` is disallowed in this image, and there is
    no JRE (`java` not on PATH) to run it against. DuckDB and Polars are
    absent too. pyarrow 25's kernels (count_distinct, tdigest,
    value_counts, re2 regex match) cover the whole profile workload in
    vectorized C++, which a JVM row-engine would not beat single-core.
    """
    import pyarrow as pa
    import pyarrow.compute as pc

    old_cpu = pa.cpu_count()
    pa.set_cpu_count(1)  # single core, like our engine on this box
    try:
        df = build_table(probe_rows).to_pandas()
        at = pa.table(
            {name: pa.array(df[name]) for name in df.columns}
        )
        t0 = time.perf_counter()

        # ---- pass 1: size, completeness, distinct, DataType regexes ---
        _ = at.num_rows
        for name in at.column_names:
            col = at.column(name)
            _ = pc.count(col, mode="only_valid")
            _ = pc.count_distinct(col)
        for name in ("category", "code"):
            col = pc.cast(at.column(name), pa.string())
            _ = pc.sum(pc.match_substring_regex(col, r"^(-|\+)? ?\d*\.\d*$"))
            _ = pc.sum(pc.match_substring_regex(col, r"^(-|\+)? ?\d*$"))
            _ = pc.sum(pc.match_substring_regex(col, r"^(true|false)$"))

        # ---- pass 2: numeric stats + 100 approximate percentiles ------
        qs = [i / 100 for i in range(1, 101)]
        numeric = {
            "price": at.column("price"),
            "discount": at.column("discount"),
            "qty": at.column("qty"),
            "code": pc.cast(at.column("code"), pa.float64()),
        }
        for name, col in numeric.items():
            _ = pc.min_max(col)
            _ = pc.mean(col)
            _ = pc.stddev(col)
            _ = pc.sum(col)
            _ = pc.tdigest(col, q=qs)

        # ---- pass 3: exact histograms for low-cardinality columns -----
        for name in ("category", "flag"):
            _ = pc.value_counts(at.column(name))

        elapsed = max(time.perf_counter() - t0, 1e-9)
        return probe_rows / elapsed
    finally:
        pa.set_cpu_count(old_cpu)


def _measure_baseline_subprocess(mode: str = "profiler") -> float:
    """Run the reference profiles (pandas AND single-thread pyarrow
    Acero; the denominator takes the max) in a SUBPROCESS so their
    transient working sets never pollute the bench process's peak-RSS
    report and their wall time never mixes into the engine's timings.
    `mode` selects the table SHAPE the probe profiles (wide/lineitem
    must be measured against their own shape, not the 6-col table)."""
    import subprocess

    env = dict(os.environ, BENCH_MODE=mode)
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--measure-baseline"],
            capture_output=True,
            text=True,
            timeout=600,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            env=env,
        )
        return float(out.stdout.strip().splitlines()[-1])
    except Exception:  # noqa: BLE001 - fall back to the in-process probe
        return measure_reference_profile_rows_per_sec(mode=mode)


def _refresh_shape_json(shape: str, n_rows: int) -> None:
    """Re-run one north-star shape (wide/lineitem) in a subprocess and
    refresh its BENCH_<SHAPE>.json next to this file, preserving the
    hand-written "config"/"round" fields. Part of the per-round
    regression loop: the headline profiler number and the shape numbers
    move together, so a regression in the batched family kernels shows
    up in the tracked artifacts, not just the default 6-col table.
    Failures leave the old file untouched (stderr note only) — the
    headline JSON line must stay the last stdout line either way."""
    import subprocess

    here = os.path.dirname(os.path.abspath(__file__))
    out_path = os.path.join(here, f"BENCH_{shape.upper()}.json")
    env = dict(
        os.environ, BENCH_MODE=shape, BENCH_ROWS=str(n_rows), BENCH_SHAPES="0"
    )
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            capture_output=True,
            text=True,
            timeout=900,
            cwd=here,
            env=env,
        )
        rec = json.loads(out.stdout.strip().splitlines()[-1])
    except Exception as exc:  # noqa: BLE001 - keep the old artifact
        print(f"# bench: shape refresh {shape} FAILED: {exc}", file=sys.stderr)
        return
    try:
        with open(out_path) as fh:
            old = json.load(fh)
        for key in ("round", "config"):
            if key in old and key not in rec:
                rec[key] = old[key]
    except Exception:  # noqa: BLE001 - first write: no fields to carry
        pass
    with open(out_path, "w") as fh:
        json.dump(rec, fh)
        fh.write("\n")
    print(
        f"# bench: refreshed {os.path.basename(out_path)}: "
        f"{rec['value'] / 1e6:.2f}M rows/s, {rec['vs_baseline']}x",
        file=sys.stderr,
    )


def write_parquet(
    n_rows: int, path: str, chunk: int = 2_000_000, builder=build_table
) -> None:
    """Stream-generate the bench table to disk in chunks (bounded memory),
    so stream mode can exceed host RAM."""
    import pyarrow.parquet as pq

    writer = None
    done = 0
    seed = 0
    while done < n_rows:
        rows = min(chunk, n_rows - done)
        at = builder(rows, seed=seed).to_arrow()
        if writer is None:
            writer = pq.ParquetWriter(path, at.schema)
        writer.write_table(at)
        done += rows
        seed += 1
    if writer is not None:
        writer.close()


def _drop_page_cache() -> bool:
    """Best-effort OS page-cache drop (needs root) so a cold stream pass
    pays real disk IO instead of reading the just-written file from the
    125GB host RAM. Returns whether it worked — recorded in the JSON so
    a cached run is never mistaken for a disk-bound one."""
    try:
        with open("/proc/sys/vm/drop_caches", "w") as fh:
            fh.write("3\n")
        return True
    except OSError:
        return False


def pallas_onchip_check() -> str:
    """Run the Pallas HLL register-max kernel ON THE ATTACHED TPU and
    compare it against the XLA scatter path on the same device — the
    driver-visible proof that the Pallas kernel produced correct
    registers on real silicon this round (round-3 verdict: the kernel
    was CI-tested only in interpret mode). Returns 'ok', 'MISMATCH',
    or 'skipped:<reason>' — recorded in the bench JSON either way."""
    try:
        import jax
        import jax.numpy as jnp

        from deequ_tpu.ops import pallas_kernels
        from deequ_tpu.ops.sketches import hll

        device = jax.devices()[0]
        if device.platform != "tpu":
            return f"skipped:platform={device.platform}"
        if not pallas_kernels.usable():
            return "skipped:kernel-not-usable-on-this-chip"
        rng = np.random.default_rng(7)
        n = 1 << 16
        values = rng.integers(-(1 << 40), 1 << 40, n)
        valid = rng.random(n) > 0.1
        packed = jnp.asarray(hll.pack_codes(values, valid))
        on_chip = np.asarray(
            jax.jit(pallas_kernels.hll_register_max)(packed)
        ).astype(np.int32)
        idx = packed >> 6
        rank = packed & 0x3F
        xla = np.asarray(
            jax.jit(
                lambda i, r: jnp.zeros(hll.M, dtype=r.dtype).at[i].max(r)
            )(idx, rank)
        ).astype(np.int32)
        host = np.zeros(hll.M, dtype=np.int32)
        packed_np = np.asarray(packed)
        np.maximum.at(host, packed_np >> 6, packed_np & 0x3F)
        if not (np.array_equal(on_chip, xla) and np.array_equal(on_chip, host)):
            return "MISMATCH:hll"
        # the MXU hist16 radix-select kernel, also on silicon: counts
        # must match a host bincount of the same sortable-key bins
        x32 = rng.lognormal(0.0, 2.0, n).astype(np.float32)
        live = rng.random(n) > 0.1
        bins = jax.jit(pallas_kernels.f32_sortable_bin16)(
            jnp.asarray(x32), jnp.asarray(live)
        )
        hist_chip = np.asarray(jax.jit(pallas_kernels.hist16)(bins)).reshape(
            65536
        )
        host_hist = np.bincount(
            np.asarray(bins).astype(np.int64) & 0xFFFF, minlength=65536
        )
        if not np.array_equal(hist_chip.astype(np.int64), host_hist):
            return "MISMATCH:hist16"
        return "ok"
    except Exception as e:  # noqa: BLE001 - report, never break the bench
        return f"skipped:{type(e).__name__}"


def run_forensics_bench(n_rows: int, reps: int) -> None:
    """BENCH_MODE=forensics: A/B row-level failure-forensics capture
    (ISSUE 12) on the decode bench's 50-column wide-stream shape. The
    check mixes a completeness constraint that FAILS on ~3% of rows in
    a hot column (every batch carries violations, so the capture side
    pays mask rebuild + reservoir churn on every batch — the worst
    case) with passing bound/compliance constraints (their capture is
    pure mask work). Both sides run the identical VerificationSuite;
    the run aborts unless statuses and metrics are bit-identical
    (forensics must be provably inert). Wall times are warm-jit
    best-of-reps, forensics OFF first. Refreshes BENCH_FORENSICS.json
    (round/config preserved)."""
    import pyarrow.parquet as pq

    from deequ_tpu.checks.check import Check, CheckLevel
    from deequ_tpu.data.table import Table
    from deequ_tpu.verification.suite import VerificationSuite

    path = os.environ.get("BENCH_PARQUET", "/tmp/bench_decode.parquet")
    t_gen = time.perf_counter()
    if not (
        os.path.exists(path) and pq.ParquetFile(path).metadata.num_rows == n_rows
    ):
        write_decode_parquet(n_rows, path)
    gen_s = time.perf_counter() - t_gen

    check = (
        Check(CheckLevel.ERROR, "forensics bench")
        # ~3% nulls: FAILS, violations in every batch (capture-heavy)
        .is_complete("f00")
        .is_complete("i00")
        .has_min("f01", lambda v: v >= 0.0)  # passes: bound capture only
        .has_max("f02", lambda v: v <= 1e6)  # passes
        .satisfies("f03 >= 0", "f03 nonneg", lambda r: r >= 0.9)  # passes
    )

    def run_once(forensics: bool):
        builder = (
            VerificationSuite()
            .on_data(Table.scan_parquet(path, batch_rows=1 << 20))
            .add_check(check)
        )
        if forensics:
            builder = builder.with_forensics()
        result = builder.run()
        snapshot = {}
        for analyzer, metric in result.metrics.items():
            value = metric.value
            v = value.get() if value.is_success else type(value.exception).__name__
            if isinstance(v, float) and v != v:
                v = "nan"
            snapshot[repr(analyzer)] = v
        statuses = tuple(
            (cr.status.name)
            for cres in result.check_results.values()
            for cr in cres.constraint_results
        )
        return (statuses, snapshot), result

    warm_key, _ = run_once(False)  # warm-up: jit + imports

    off_s = float("inf")
    off_key = None
    for _ in range(reps):
        t0 = time.perf_counter()
        off_key, _ = run_once(False)
        off_s = min(off_s, time.perf_counter() - t0)

    on_s = float("inf")
    on_key = None
    sampled = violations = 0
    for _ in range(reps):
        t0 = time.perf_counter()
        on_key, result = run_once(True)
        on_s = min(on_s, time.perf_counter() - t0)
        report = result.forensics()
        sampled = sum(len(c.samples) for c in report.constraints)
        violations = sum(c.violations_seen for c in report.constraints)

    if not (warm_key == off_key == on_key):
        raise SystemExit(
            "forensics A/B: result mismatch between capture-on and "
            f"capture-off sides\noff: {off_key}\non:  {on_key}"
        )

    overhead_pct = 100.0 * (on_s - off_s) / off_s if off_s > 0 else 0.0
    rec = {
        "metric": "forensics_overhead_pct",
        "value": round(overhead_pct, 1),
        "unit": "%",
        "rows": n_rows,
        "forensics_ab": {
            "off_s": round(off_s, 2),
            "on_s": round(on_s, 2),
            "overhead_pct": round(overhead_pct, 1),
            "rows_per_sec_off": round(n_rows / off_s, 1),
            "rows_per_sec_on": round(n_rows / on_s, 1),
            "violations_seen": violations,
            "rows_sampled": sampled,
            "constraints": 5,
            "failing_constraints": 2,
            "bit_identical": True,
            "reps": reps,
            "passes": (
                "one warm-up (off), then best-of-reps warm-jit timed "
                "passes per side, forensics OFF first"
            ),
        },
    }
    here = os.path.dirname(os.path.abspath(__file__))
    out_path = os.path.join(here, "BENCH_FORENSICS.json")
    try:
        with open(out_path) as fh:
            old = json.load(fh)
        for key in ("round", "config"):
            if key in old and key not in rec:
                rec[key] = old[key]
    except Exception:  # noqa: BLE001 - first write: no fields to carry
        pass
    with open(out_path, "w") as fh:
        json.dump(rec, fh)
        fh.write("\n")
    print(
        f"# bench: forensics A/B off={off_s:.2f}s on={on_s:.2f}s "
        f"(+{overhead_pct:.1f}%), {violations} violations seen, "
        f"{sampled} rows sampled; gen={gen_s:.1f}s",
        file=sys.stderr,
    )
    print(json.dumps(rec))


def run_chaos_bench(n_rows: int, reps: int) -> None:
    """BENCH_MODE=chaos: the resilience machinery's clean-path cost and
    its fault-mode correctness (ISSUE 13), on the decode bench's
    50-column wide-stream shape.

    A/B: the identical verification run PLAIN (no controller, chaos
    harness disarmed) vs ARMED — a RunController with a generous
    deadline doing per-batch checks/beats, plus an installed fault plan
    whose rates are all 0.0, so every `fault_point` seam takes the full
    decide() path (lock + counter + hash) without injecting. The armed
    side must stay within 2% of plain (the analytic companion bound
    lives in tests/test_observe_overhead.py).

    Then a seeded FAULT pass: transient pread errors, short reads,
    corrupt pages, decode failures and a stage fault all injected in
    one run — the bench aborts unless statuses and metrics are
    bit-identical to the plain side (containment never changes an
    answer). Refreshes BENCH_CHAOS.json (round/config preserved)."""
    import pyarrow.parquet as pq

    from deequ_tpu.checks.check import Check, CheckLevel
    from deequ_tpu.core.controller import RunController
    from deequ_tpu.data.table import Table
    from deequ_tpu.testing import faults
    from deequ_tpu.verification.suite import VerificationSuite

    path = os.environ.get("BENCH_PARQUET", "/tmp/bench_decode.parquet")
    t_gen = time.perf_counter()
    if not (
        os.path.exists(path) and pq.ParquetFile(path).metadata.num_rows == n_rows
    ):
        write_decode_parquet(n_rows, path)
    gen_s = time.perf_counter() - t_gen

    check = (
        Check(CheckLevel.ERROR, "chaos bench")
        .is_complete("f00")
        .has_min("f01", lambda v: v >= 0.0)
        .has_max("f02", lambda v: v <= 1e6)
        .satisfies("f03 >= 0", "f03 nonneg", lambda r: r >= 0.9)
    )

    def run_once(controller=None):
        builder = (
            VerificationSuite()
            .on_data(Table.scan_parquet(path, batch_rows=1 << 20))
            .add_check(check)
        )
        if controller is not None:
            builder = builder.with_controller(controller)
        result = builder.run()
        snapshot = {}
        for analyzer, metric in result.metrics.items():
            value = metric.value
            v = value.get() if value.is_success else type(value.exception).__name__
            if isinstance(v, float) and v != v:
                v = "nan"
            snapshot[repr(analyzer)] = v
        statuses = tuple(
            (cr.status.name)
            for cres in result.check_results.values()
            for cr in cres.constraint_results
        )
        return (statuses, snapshot)

    warm_key = run_once()  # warm-up: jit + imports

    plain_s = float("inf")
    plain_key = None
    for _ in range(reps):
        t0 = time.perf_counter()
        plain_key = run_once()
        plain_s = min(plain_s, time.perf_counter() - t0)

    # armed-but-quiet: every fault seam decides (rate 0), the controller
    # checks and beats every batch against a deadline that never trips
    quiet_spec = "seed=1," + ",".join(
        f"{point}:0.0" for point in sorted(faults.FAULT_POINTS)
    )
    armed_s = float("inf")
    armed_key = None
    with faults.install(quiet_spec):
        for _ in range(reps):
            t0 = time.perf_counter()
            armed_key = run_once(RunController(deadline_s=3600.0))
            armed_s = min(armed_s, time.perf_counter() - t0)

    # seeded fault pass: inject for real, demand the same bits
    fault_spec = (
        "seed=13,read.pread:0.3:5,read.short:0.3:3,read.corrupt:0.5:2,"
        "decode.chunk:0.5:4,pipeline.stage:1.0:1"
    )
    with faults.install(fault_spec) as plan:
        t0 = time.perf_counter()
        faulted_key = run_once(RunController(deadline_s=3600.0))
        faulted_s = time.perf_counter() - t0
        injected = dict(plan.injected)

    if not (warm_key == plain_key == armed_key == faulted_key):
        raise SystemExit(
            "chaos A/B: result mismatch across plain/armed/faulted sides\n"
            f"plain:   {plain_key}\narmed:   {armed_key}\n"
            f"faulted: {faulted_key}"
        )

    overhead_pct = (
        100.0 * (armed_s - plain_s) / plain_s if plain_s > 0 else 0.0
    )
    rec = {
        "metric": "chaos_overhead_pct",
        "value": round(overhead_pct, 1),
        "unit": "%",
        "rows": n_rows,
        "chaos_ab": {
            "plain_s": round(plain_s, 2),
            "armed_s": round(armed_s, 2),
            "overhead_pct": round(overhead_pct, 1),
            "rows_per_sec_plain": round(n_rows / plain_s, 1),
            "rows_per_sec_armed": round(n_rows / armed_s, 1),
            "bit_identical": True,
            "reps": reps,
            "passes": (
                "one warm-up (plain), then best-of-reps warm-jit timed "
                "passes per side, plain first; armed = RunController "
                "with a 3600s deadline + every fault point at rate 0"
            ),
        },
        "fault_pass": {
            "spec": fault_spec,
            "injected": injected,
            "wall_s": round(faulted_s, 2),
            "bit_identical": True,
        },
    }
    here = os.path.dirname(os.path.abspath(__file__))
    out_path = os.path.join(here, "BENCH_CHAOS.json")
    try:
        with open(out_path) as fh:
            old = json.load(fh)
        for key in ("round", "config"):
            if key in old and key not in rec:
                rec[key] = old[key]
    except Exception:  # noqa: BLE001 - first write: no fields to carry
        pass
    with open(out_path, "w") as fh:
        json.dump(rec, fh)
        fh.write("\n")
    total_injected = sum(injected.values())
    print(
        f"# bench: chaos A/B plain={plain_s:.2f}s armed={armed_s:.2f}s "
        f"(+{overhead_pct:.1f}%); fault pass {faulted_s:.2f}s with "
        f"{total_injected} injections, bit-identical; gen={gen_s:.1f}s",
        file=sys.stderr,
    )
    print(json.dumps(rec))


def main() -> None:
    platform = os.environ.get("BENCH_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    n_rows = int(os.environ.get("BENCH_ROWS", "10000000"))
    mode = os.environ.get("BENCH_MODE", "profiler")
    reps = max(1, int(os.environ.get("BENCH_TIMED", "5")))
    trace_enabled = "--trace" in sys.argv or os.environ.get(
        "BENCH_TRACE", ""
    ).lower() not in ("", "0", "false")
    if trace_enabled:
        # shape-regression subprocesses inherit the flag through env
        os.environ["BENCH_TRACE"] = "1"

    if mode == "pushdown":
        # self-contained A/B with its own JSON record and artifact;
        # none of the baseline machinery below applies
        run_pushdown_bench(n_rows)
        return

    if mode == "decode":
        # self-contained A/B with its own JSON record and artifact
        run_decode_bench(n_rows)
        return

    if mode == "wire":
        # self-contained A/B with its own JSON record and artifact
        run_wire_bench(n_rows)
        return

    if mode == "incremental":
        # self-contained A/B with its own JSON record and artifact
        run_incremental_bench(n_rows)
        return

    if mode == "window":
        # self-contained A/B with its own JSON record and artifact
        run_window_bench(n_rows)
        return

    if mode == "reader":
        # self-contained A/B with its own JSON record and artifact
        run_reader_bench(n_rows)
        return

    if mode == "encfold":
        # self-contained A/B with its own JSON record and artifact
        run_encfold_bench(n_rows)
        return

    if mode == "forensics":
        # self-contained A/B with its own JSON record and artifact
        run_forensics_bench(n_rows, reps)
        return

    if mode == "chaos":
        # self-contained A/B with its own JSON record and artifact
        run_chaos_bench(n_rows, reps)
        return

    t_gen = time.perf_counter()
    if mode == "stream":
        import pyarrow.parquet as pq

        from deequ_tpu.data.table import Table

        default_path = (
            "/tmp/bench_wide.parquet"
            if _stream_shape() == "wide"
            else "/tmp/bench.parquet"
        )
        path = os.environ.get("BENCH_PARQUET", default_path)
        if not (
            os.path.exists(path) and pq.ParquetFile(path).metadata.num_rows == n_rows
        ):
            write_parquet(n_rows, path, builder=_builder_for_mode("stream"))
        table = Table.scan_parquet(path)
    elif mode == "wide":
        table = build_wide_table(n_rows)
    elif mode == "lineitem":
        table = build_lineitem_table(n_rows)
    else:
        table = build_table(n_rows)
    gen_s = time.perf_counter() - t_gen

    run = run_scan if mode == "scan" else run_profiler
    if mode == "scan":
        baseline = SPARK_LOCAL_SCAN_ROWS_PER_SEC
        baseline_note = "proxy"
    else:
        # measured denominator (BENCH_BASELINE=proxy skips; a float
        # overrides): single-core pandas/numpy equivalent profile,
        # floored at the documented proxy so a slow box can't inflate
        # the ratio
        baseline_env = os.environ.get("BENCH_BASELINE", "measured")
        if baseline_env == "proxy":
            baseline = SPARK_LOCAL_PROFILE_ROWS_PER_SEC
            baseline_note = "proxy"
        elif baseline_env == "measured":
            measured = _measure_baseline_subprocess(mode)
            if mode in ("wide", "lineitem") or (
                mode == "stream" and _stream_shape() == "wide"
            ):
                # same-shape measured denominator; the 2.0M floor was
                # calibrated for the 6-col table and would be absurdly
                # generous per-row at 16-50 columns
                baseline = measured
                baseline_note = (
                    f"measured same-shape single-core pandas profile "
                    f"{measured / 1e6:.2f}M rows/s (6-col 2.0M floor not "
                    "applied: calibrated for the default shape)"
                )
            else:
                baseline = max(measured, SPARK_LOCAL_PROFILE_ROWS_PER_SEC)
                baseline_note = (
                    f"max(measured best-of(pandas, 1-thread pyarrow Acero) "
                    f"{measured / 1e6:.2f}M rows/s, "
                    f"{SPARK_LOCAL_PROFILE_ROWS_PER_SEC / 1e6:.1f}M proxy; "
                    "Spark-local itself unmeasurable offline: no pyspark/JRE)"
                )
        else:
            baseline = float(baseline_env)
            baseline_note = "override"

    cold = mode == "stream" and os.environ.get("BENCH_COLD", "") in (
        "1",
        "true",
    )
    ab = cold and os.environ.get("BENCH_PIPELINE_AB", "") in ("1", "true")
    extra = {}
    if ab:
        # pipeline A/B: the SAME cold pass twice — fully serial
        # (DEEQU_TPU_PIPELINE=0: synchronous decode, inline prep) vs the
        # staged pipeline — page cache dropped before each so both pay
        # real disk IO. NEITHER timed pass is traced: tracing only the
        # pipelined side was measured as a multi-percent thumb on the
        # scale; the per-stage occupancy instead comes from the traced
        # warm-up pass that runs before the timing. With
        # BENCH_SOURCE_STALL_MS set, a per-row-group source stall
        # (object-store latency model, deequ_tpu.ops.runtime
        # .source_stall_s) applies identically to BOTH sides, measuring
        # how much source wait the pipeline hides.
        from deequ_tpu import observe

        stall_ms = os.environ.get("BENCH_SOURCE_STALL_MS", "")
        if stall_ms:
            os.environ["DEEQU_TPU_SOURCE_STALL_MS"] = stall_ms
        # warm-up pass FIRST (traced, pipelined): compiles every program
        # and pays the one-time imports so neither timed pass rides the
        # other's caches (serial-first was measured gifting the pipelined
        # side ~0.7s of jit/import at 4M rows), and its span tree yields
        # the per-stage occupancy rows. Both timed passes below are
        # warm-jit, cold-IO, untraced.
        with observe.tracing() as tracer:
            run(table)
        occupancy = observe.pipeline_occupancy(tracer.roots)
        os.environ["DEEQU_TPU_PIPELINE"] = "0"
        cache_dropped = _drop_page_cache()
        t0 = time.perf_counter()
        run(table)
        serial_s = time.perf_counter() - t0
        os.environ["DEEQU_TPU_PIPELINE"] = "1"
        _drop_page_cache()
        t0 = time.perf_counter()
        run(table)
        best = time.perf_counter() - t0
        best_cpu = None
        extra["pipeline_ab"] = {
            "serial_s": round(serial_s, 1),
            "pipelined_s": round(best, 1),
            "speedup_pct": round(100.0 * (serial_s - best) / serial_s, 1),
            "page_cache_dropped": cache_dropped,
            **(
                {"source_stall_ms": float(stall_ms)} if stall_ms else {}
            ),
            "occupancy_pass": (
                "from the traced warm-up pass; both timed passes are "
                "warm-jit, cold-IO, untraced"
            ),
            "occupancy": [
                {
                    "stage": row["stage"],
                    "occupancy_pct": round(row["occupancy"] * 100, 1),
                    "busy_s": round(row["busy_s"], 1),
                    "stall_s": round(row["stall_s"], 1),
                    "items": row["items"],
                }
                for row in occupancy
            ],
            "bottleneck": occupancy[0]["stage"] if occupancy else None,
        }
        print(
            f"# bench: pipeline A/B serial={serial_s:.1f}s "
            f"pipelined={best:.1f}s "
            f"(+{100.0 * (serial_s - best) / serial_s:.1f}%), "
            f"bottleneck={extra['pipeline_ab']['bottleneck']}",
            file=sys.stderr,
        )
    elif cold:
        # the BENCH_STREAM_*.json methodology: ONE cold end-to-end pass
        # incl. jit compile; every stream batch decodes fresh either way
        _drop_page_cache()
        t0 = time.perf_counter()
        run(table)
        best = time.perf_counter() - t0
        best_cpu = None
    else:
        # warmup: compiles every (analyzer-set, padded-shape) program
        t_warm = time.perf_counter()
        run(table)
        warm_s = time.perf_counter() - t_warm

        times = []
        cpu_times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            c0 = time.process_time()
            run(table)
            cpu_times.append(time.process_time() - c0)
            times.append(time.perf_counter() - t0)
        best = min(times)
        # CPU-seconds where wall-clock would mislead (shared-vCPU boxes)
        best_cpu = min(cpu_times)
    rows_per_sec = n_rows / best

    # --trace / BENCH_TRACE: one EXTRA traced pass after the timed reps
    # (tracing never overlaps the timed loop, so the headline numbers
    # are identical with and without it); phase self-time buckets from
    # the span tree land in the JSON record next to the trace path
    trace_fields = {}
    if trace_enabled:
        from deequ_tpu import observe

        trace_out = (
            os.environ.get(observe.ENV_OUT, "").strip()
            or observe.default_trace_path()
        )
        with observe.traced_run(
            f"bench_{mode}", enable=trace_out, rows=n_rows
        ) as traced:
            run(table)
        phases = traced.trace.phase_seconds()
        trace_fields = {
            "trace_file": traced.trace.path,
            "trace_phases_s": {
                phase: round(phases.get(phase, 0.0), 4)
                for phase in observe.PHASES
            },
        }

    # /proc-based accounting (observe.telemetry): peak RSS and major
    # page faults come from the process itself, not external measurement
    from deequ_tpu.observe import telemetry

    resources = telemetry.proc_resources()
    peak_rss_mb = resources.get("peak_rss_mb", 0.0)
    if cold:
        extra.update(
            rows=n_rows,
            elapsed_s=round(best, 1),
            peak_rss_mb=round(peak_rss_mb),
            major_faults=int(resources.get("major_faults", 0)),
        )
    # append this run to the engine-telemetry time series so
    # `make sentinel` can watch throughput/phase shares across rounds
    # (BENCH.md). BENCH_ENGINE_REPO overrides the path; 0/off disables.
    engine_repo_env = os.environ.get("BENCH_ENGINE_REPO", "")
    if engine_repo_env.lower() not in ("0", "off", "none"):
        engine_repo_path = engine_repo_env or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "ENGINE_METRICS.json"
        )
        try:
            from deequ_tpu.repository import engine as engine_telemetry
            from deequ_tpu.repository.fs import FileSystemMetricsRepository

            engine_record = {
                "engine.rows_per_s": rows_per_sec,
                "engine.wall_s": best,
                "engine.rows": float(n_rows),
                "engine.peak_rss_mb": peak_rss_mb,
                "engine.major_faults": resources.get("major_faults", 0.0),
            }
            if best_cpu is not None:
                engine_record["engine.cpu_s"] = best_cpu
            for phase, secs in trace_fields.get("trace_phases_s", {}).items():
                engine_record[f"engine.phase.{phase}_s"] = secs
            engine_telemetry.persist_engine_record(
                FileSystemMetricsRepository(engine_repo_path),
                engine_record,
                engine_telemetry.engine_result_key(
                    suite="bench", dataset=f"{mode}:{n_rows}"
                ),
            )
            print(f"# bench: engine series -> {engine_repo_path}", file=sys.stderr)
        except Exception as e:  # noqa: BLE001 - telemetry must never fail the bench
            print(f"# bench: engine series persist failed: {e}", file=sys.stderr)

    warm_note = "none (single cold pass)" if cold else f"{warm_s:.1f}s"
    print(
        f"# bench: mode={mode}{' (cold)' if cold else ''} rows={n_rows} "
        f"gen={gen_s:.1f}s warmup={warm_note} timed={best:.2f}s "
        f"peak_rss={peak_rss_mb:.0f}MB "
        f"baseline={baseline / 1e6:.2f}M rows/s [{baseline_note}]",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": f"{mode}_rows_per_sec_per_chip",
                "value": round(rows_per_sec, 1),
                "unit": "rows/s",
                "vs_baseline": round(rows_per_sec / baseline, 3),
                **({"cpu_s": round(best_cpu, 3)} if best_cpu is not None else {}),
                **extra,
                **trace_fields,
                "pallas_onchip": pallas_onchip_check(),
            }
        )
    )

    # per-round regression loop: the default (headline) run also
    # refreshes the north-star shape artifacts so regressions in wider
    # tables are tracked, not just the 6-col headline. BENCH_SHAPES=0
    # skips; shape/child runs never recurse (env set by the parent).
    if mode == "profiler" and os.environ.get("BENCH_SHAPES", "1") not in (
        "0",
        "false",
    ):
        _refresh_shape_json("wide", 4_000_000)
        _refresh_shape_json("lineitem", 10_000_000)


if __name__ == "__main__":
    if "--measure-baseline" in sys.argv:
        probe_mode = os.environ.get("BENCH_MODE", "profiler")
        wide_shape = probe_mode == "wide" or (
            probe_mode == "stream" and _stream_shape() == "wide"
        )
        probe_rows = 500_000 if wide_shape else 2_000_000
        # best-of-3: the engine side is best-of-N timed reps, so the
        # baseline gets its best box phase too — a single-shot probe on
        # a drifting shared vCPU would randomly deflate the denominator
        # and inflate the ratio
        pandas_rate = max(
            measure_reference_profile_rows_per_sec(probe_rows, mode=probe_mode)
            for _ in range(3)
        )
        arrow_rate = 0.0
        # the Acero probe profiles the fixed 6-col shape: only a valid
        # denominator when that IS the benched shape
        if probe_mode not in ("wide", "lineitem") and not wide_shape:
            for _ in range(3):
                try:
                    arrow_rate = max(
                        arrow_rate, measure_arrow_profile_rows_per_sec()
                    )
                except Exception:  # noqa: BLE001 - acero is best-effort
                    pass  # keep any reps that already succeeded
        print(
            f"# pandas {pandas_rate / 1e6:.2f}M rows/s, "
            f"pyarrow-acero(1 thread) {arrow_rate / 1e6:.2f}M rows/s",
            file=sys.stderr,
        )
        print(max(pandas_rate, arrow_rate))
    else:
        main()

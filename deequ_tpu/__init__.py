"""deequ_tpu: a TPU-native data-quality framework.

Declarative "unit tests for data" with the capabilities of the reference
(deequ @ /root/reference): checks/constraints over tabular data, a metrics
engine built on mergeable sufficient statistics, single-pass scan-shared
metric computation, approximate sketches, a three-pass column profiler,
constraint suggestion, metric repositories and anomaly detection.

Execution engine: JAX/XLA. Columnar batches stream to device; all requested
analyzers lower to one fused masked-reduction computation per pass
(the analogue of the reference's Catalyst scan sharing,
reference: analyzers/runners/AnalysisRunner.scala:98-193), and the semigroup
state merge (reference: analyzers/Analyzer.scala:34-48) maps to collective
reductions across a TPU mesh.
"""

from deequ_tpu.core.maybe import Try, Success, Failure
from deequ_tpu.core.metrics import (
    Entity,
    Metric,
    DoubleMetric,
    KeyedDoubleMetric,
    HistogramMetric,
    Distribution,
    DistributionValue,
)
from deequ_tpu.data.table import Table, Column, ColumnType
from deequ_tpu.checks.check import Check, CheckLevel, CheckStatus
from deequ_tpu.verification.suite import VerificationSuite
from deequ_tpu.verification.result import VerificationResult
from deequ_tpu.constraints.constrainable_data_types import ConstrainableDataTypes
from deequ_tpu.lint.explain import explain_plan

__version__ = "0.1.0"

__all__ = [
    "Try",
    "Success",
    "Failure",
    "Entity",
    "Metric",
    "DoubleMetric",
    "KeyedDoubleMetric",
    "HistogramMetric",
    "Distribution",
    "DistributionValue",
    "Table",
    "Column",
    "ColumnType",
    "Check",
    "CheckLevel",
    "CheckStatus",
    "VerificationSuite",
    "VerificationResult",
    "ConstrainableDataTypes",
    "explain_plan",
]

"""Deprecated `Analysis` container — kept for API-surface parity with
the reference (reference: analyzers/Analysis.scala:29-63, deprecated
there since 2019 in favor of AnalysisRunner.onData)."""

from __future__ import annotations

import warnings
from dataclasses import dataclass
from typing import Sequence, Tuple

from deequ_tpu.analyzers.base import Analyzer


@dataclass(frozen=True)
class Analysis:
    """Immutable bag of analyzers with a deprecated `run`.

    Prefer `AnalysisRunner.on_data(table).add_analyzers(...).run()`."""

    analyzers: Tuple[Analyzer, ...] = ()

    def add_analyzer(self, analyzer: Analyzer) -> "Analysis":
        return Analysis(tuple(self.analyzers) + (analyzer,))

    def add_analyzers(self, other_analyzers: Sequence[Analyzer]) -> "Analysis":
        return Analysis(tuple(self.analyzers) + tuple(other_analyzers))

    def run(
        self,
        data,
        aggregate_with=None,
        save_states_with=None,
    ):
        """Deprecated: use AnalysisRunner.on_data instead
        (reference: Analysis.scala:52 carries the same deprecation)."""
        warnings.warn(
            "Analysis.run is deprecated; use AnalysisRunner.on_data "
            "(the on_data method there)",
            DeprecationWarning,
            stacklevel=2,
        )
        from deequ_tpu.runners.analysis_runner import AnalysisRunner

        return AnalysisRunner.do_analysis_run(
            data,
            list(self.analyzers),
            aggregate_with=aggregate_with,
            save_states_with=save_states_with,
        )

"""Analyzer core: compute State from data, Metric from State.

reference: analyzers/Analyzer.scala:56-272. The TPU twist
(SURVEY.md §7): a scan-shareable analyzer declares
  * host-prep  — which named arrays it needs (columns/masks/match codes),
  * device_reduce — a traced function turning those arrays into a partial
    state pytree for one batch,
  * device_merge  — a traced semigroup combine for cross-device merging,
and the planner fuses every requested analyzer's reduce into ONE compiled
XLA computation per pass (the analogue of the reference's single
`df.agg(...)` with offset bookkeeping, runners/AnalysisRunner.scala:279-326;
offsets become pytree structure here).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deequ_tpu.core.exceptions import (
    EmptyStateException,
    NoColumnsSpecifiedException,
    NoSuchColumnException,
    NumberOfSpecifiedColumnsException,
    WrongColumnTypeException,
    wrap_if_necessary,
)
from deequ_tpu.core.metrics import DoubleMetric, Entity, Metric
from deequ_tpu.core.maybe import Failure
from deequ_tpu.analyzers.states import State
from deequ_tpu.data.expr import Predicate
from deequ_tpu.data.table import ColumnType, Table

COUNT_COL = "com_amazon_deequ_dq_metrics_count"


def render_where(where: Optional[str]) -> str:
    """Scala Option rendering — part of the analyzer identity string used
    in EmptyStateException messages and state-provider keys
    (reference: NullHandlingTests.scala:131-140)."""
    return f"Some({where})" if where is not None else "None"


def entity_from(columns: Sequence[str]) -> Entity:
    """reference: analyzers/Analyzer.scala:381-382."""
    return Entity.COLUMN if len(columns) == 1 else Entity.MULTICOLUMN


# ---------------------------------------------------------------------------
# Preconditions (reference: analyzers/Analyzer.scala:275-335)
# ---------------------------------------------------------------------------

NUMERIC_TYPES = (ColumnType.LONG, ColumnType.DOUBLE, ColumnType.DECIMAL)


class Preconditions:
    @staticmethod
    def has_column(column: str) -> Callable[[Table], None]:
        def check(table: Table) -> None:
            if not table.has_column(column):
                raise NoSuchColumnException(
                    f"Input data does not include column {column}!"
                )

        return check

    @staticmethod
    def is_numeric(column: str) -> Callable[[Table], None]:
        def check(table: Table) -> None:
            ctype = table.column(column).ctype
            if ctype not in NUMERIC_TYPES:
                raise WrongColumnTypeException(
                    f"Expected type of column {column} to be one of "
                    f"(ByteType,ShortType,IntegerType,LongType,FloatType,"
                    f"DoubleType,DecimalType), but found {ctype.value} instead!"
                )

        return check

    @staticmethod
    def is_string(column: str) -> Callable[[Table], None]:
        def check(table: Table) -> None:
            ctype = table.column(column).ctype
            if ctype != ColumnType.STRING:
                raise WrongColumnTypeException(
                    f"Expected type of column {column} to be StringType, "
                    f"but found {ctype.value} instead!"
                )

        return check

    @staticmethod
    def at_least_one(columns: Sequence[str]) -> Callable[[Table], None]:
        def check(table: Table) -> None:
            if len(columns) == 0:
                raise NoColumnsSpecifiedException(
                    "At least one column needs to be specified!"
                )

        return check

    @staticmethod
    def exactly_n_columns(columns: Sequence[str], n: int) -> Callable[[Table], None]:
        def check(table: Table) -> None:
            if len(columns) != n:
                raise NumberOfSpecifiedColumnsException(
                    f"{n} columns have to be specified! "
                    f"Currently, columns contains only {len(columns)} column(s): "
                    f"{','.join(columns)}!"
                )

        return check

    @staticmethod
    def find_first_failing(
        table: Table, checks: Sequence[Callable[[Table], None]]
    ) -> Optional[BaseException]:
        for check in checks:
            try:
                check(table)
            except Exception as e:  # noqa: BLE001
                return e
        return None


# ---------------------------------------------------------------------------
# Analyzer
# ---------------------------------------------------------------------------


class Analyzer:
    """Computes a State from data and a Metric from the State
    (reference: analyzers/Analyzer.scala:56-155)."""

    # -- identity ------------------------------------------------------------

    @property
    def name(self) -> str:
        raise NotImplementedError

    @property
    def instance(self) -> str:
        raise NotImplementedError

    @property
    def entity(self) -> Entity:
        return Entity.COLUMN

    # -- contract ------------------------------------------------------------

    def preconditions(self) -> List[Callable[[Table], None]]:
        return []

    def compute_state_from(self, table: Table) -> Optional[State]:
        raise NotImplementedError

    def compute_metric_from(self, state: Optional[State]) -> Metric:
        raise NotImplementedError

    def to_failure_metric(self, exception: BaseException) -> Metric:
        return DoubleMetric(
            self.entity, self.name, self.instance,
            Failure(wrap_if_necessary(exception)),
        )

    # -- orchestration (reference: Analyzer.scala:88-153) --------------------

    def calculate(
        self,
        table: Table,
        aggregate_with: Optional["StateLoader"] = None,
        save_states_with: Optional["StatePersister"] = None,
    ) -> Metric:
        failing = Preconditions.find_first_failing(table, self.preconditions())
        if failing is not None:
            return self.to_failure_metric(failing)
        try:
            state = self.compute_state_from(table)
        except Exception as e:  # noqa: BLE001
            return self.to_failure_metric(e)
        return self.calculate_metric(state, aggregate_with, save_states_with)

    def calculate_metric(
        self,
        state: Optional[State],
        aggregate_with: Optional["StateLoader"] = None,
        save_states_with: Optional["StatePersister"] = None,
    ) -> Metric:
        if aggregate_with is not None:
            loaded = aggregate_with.load(self)
            if loaded is not None:
                state = loaded if state is None else loaded.merge(state)
        if save_states_with is not None and state is not None:
            save_states_with.persist(self, state)
        return self.compute_metric_from(state)

    def aggregate_state_to(
        self,
        source_a: "StateLoader",
        source_b: "StateLoader",
        target: "StatePersister",
    ) -> None:
        """reference: Analyzer.scala:130-147."""
        a = source_a.load(self)
        b = source_b.load(self)
        merged = a.merge(b) if (a is not None and b is not None) else (a or b)
        if merged is not None:
            target.persist(self, merged)

    def load_state_and_compute_metric(self, source: "StateLoader") -> Metric:
        return self.compute_metric_from(source.load(self))

    def empty_state_failure(self) -> Metric:
        return self.to_failure_metric(
            EmptyStateException(
                f"Empty state for analyzer {self!r}, all input values were NULL."
            )
        )

    # analyzers are used as dict keys; identity is their repr
    def __eq__(self, other) -> bool:
        return type(self) is type(other) and repr(self) == repr(other)

    def __hash__(self) -> int:
        return hash(repr(self))


# ---------------------------------------------------------------------------
# Scan-shareable analyzers: the fused-pass device protocol
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class InputSpec:
    """One named host-prepped array. Keys are globally deduplicated across
    all analyzers in a pass: two analyzers over the same column share one
    device array (the offset-bookkeeping analogue, but by name).

    `columns` names the table columns the build reads — the pass unions
    them for column pruning, so a streaming source only decodes what the
    pass actually consumes (the Parquet analogue of Spark's column
    pruning). None = unknown reads; pruning is disabled for the pass."""

    key: str
    build: Callable[[Table], np.ndarray]
    columns: Optional[Tuple[str, ...]] = None


def col_values_spec(column: str) -> InputSpec:
    return InputSpec(
        key=f"num:{column}",
        build=lambda t: t.column(column).numeric_values()[0],
        columns=(column,),
    )


def col_valid_spec(column: str) -> InputSpec:
    return InputSpec(
        key=f"valid:{column}",
        build=lambda t: t.column(column).valid,
        columns=(column,),
    )


def where_key(where: Optional[str]) -> str:
    """Input key for a where mask — no predicate parsing, safe to call
    inside traced code."""
    return f"where:{where}" if where is not None else "where:<all>"


_ALL_TRUE_CACHE: dict = {}


def _all_true(n: int) -> np.ndarray:
    """Shared all-true mask per batch length (READ-ONLY: consumers treat
    masks as immutable); saves one 1-byte-per-row allocation per batch."""
    mask = _ALL_TRUE_CACHE.get(n)
    if mask is None:
        mask = np.ones(n, dtype=np.bool_)
        mask.setflags(write=False)
        if len(_ALL_TRUE_CACHE) >= 4:  # a scan sees at most a few sizes
            _ALL_TRUE_CACHE.pop(next(iter(_ALL_TRUE_CACHE)))
        _ALL_TRUE_CACHE[n] = mask
    return mask


def where_spec(where: Optional[str]) -> InputSpec:
    """Row mask for an optional filter; None = all (real) rows. Padding rows
    are False either way (the conditionalSelection analogue,
    reference: Analyzer.scala:385-402)."""
    if where is None:
        return InputSpec(
            key=where_key(None),
            build=lambda t: _all_true(t.num_rows),
            columns=(),
        )
    pred = Predicate(where)
    return InputSpec(
        key=where_key(where),
        build=lambda t: pred.eval_mask(t),
        columns=tuple(sorted(set(pred.referenced_columns()))),
    )


class ScanShareableAnalyzer(Analyzer):
    """An analyzer whose per-batch work is expressible as a masked reduction
    that can be fused with others into one compiled pass
    (reference: analyzers/Analyzer.scala:159-216).

    Two flavors share the single scan: device-REDUCED analyzers contribute
    traced reductions whose outputs merge in-graph / cross-batch via
    `merge_agg`; device-ASSISTED analyzers (``device_assisted = True``,
    e.g. quantile sketches) contribute a traced per-batch computation
    (`device_batch` — the heavy part, e.g. the sort) whose fixed-size
    output is consumed on the host each batch (`host_consume`) instead of
    being merged in-graph — the host keeps only the sketch fold."""

    device_assisted = False

    def device_batch(self, inputs: Dict[str, Any], xp) -> Any:
        """Per-batch traced computation for a device-assisted analyzer.
        Output leaves must be 1-D arrays (scalars as shape-(1,)) so the
        mesh pass can gather per-device outputs along axis 0. Only called
        when device_assisted is True."""
        raise NotImplementedError

    def host_consume(self, state: Optional[State], batch_output: Any) -> Optional[State]:
        """Fold one batch's (or one device shard's) device_batch output
        into the running State. Only called when device_assisted."""
        raise NotImplementedError

    def input_specs(self) -> List[InputSpec]:
        raise NotImplementedError

    def device_reduce(self, inputs: Dict[str, Any], xp) -> Any:
        """Named arrays -> partial-state pytree for one batch. `xp` is the
        array namespace: jnp when traced into the fused XLA pass, numpy for
        host-side evaluation."""
        raise NotImplementedError

    def merge_agg(self, a: Any, b: Any, xp) -> Any:
        """Semigroup combine of two aggregate pytrees. Same function serves
        the traced cross-device mesh merge (xp=jnp) and the driver-side
        float64 cross-batch fold (xp=numpy)."""
        raise NotImplementedError

    def unshift_agg(self, agg: Any, shifts: Dict[str, float]) -> Any:
        """Undo the f32 wire's per-column pre-centering (the engine ships
        x - shift so a float32 device resolves clustered data, e.g. mean
        ~1e7 with variance ~1e-2 — without the shift the variance signal
        is destroyed by f32 quantization before any kernel runs). Called
        once on the final aggregate; `shifts` maps input keys
        ("num:<col>") to the scan-constant shift. Default: no numeric
        value inputs, nothing to undo."""
        return agg

    def unshift_batch(self, out: Any, shifts: Dict[str, float]) -> Any:
        """Same, for a device-assisted member's per-batch output (applied
        before host_consume)."""
        return out

    def host_finish_batch(
        self, out: Any, host_inputs: Dict[str, Any], shifts: Dict[str, float]
    ) -> Any:
        """Optional single-device hook: turn a device-produced SUMMARY
        (e.g. the pallas hist16 radix histogram) into the regular
        per-batch output using the batch's host-resident inputs. Called
        before unshift_batch; default: pass through."""
        return out

    def state_from_aggregates(self, agg: Any) -> Optional[State]:
        """Folded (host, float64) pytree -> State; None = empty state."""
        raise NotImplementedError

    def compute_state_from(self, table: Table) -> Optional[State]:
        from deequ_tpu.ops.fused import FusedScanPass

        return FusedScanPass([self]).run(table)[0].state_or_raise()


# late import hook for typing only
from deequ_tpu.analyzers.state_provider import StateLoader, StatePersister  # noqa: E402,F401

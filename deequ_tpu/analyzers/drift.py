"""Two-sample drift statistics computed state-vs-state.

Every function here compares two analyzer STATES (the mergeable
sufficient statistics the repository persists) without touching a row
of either sample: KLL sketches answer two-sample KS distance through
their rank functions, HLL registers answer cardinality ratios through
their estimates, frequency tables answer a chi-square homogeneity test
over the union of keys, and the scalar states (completeness, mean,
stddev) answer delta checks directly. This is what makes
week-over-week and train-vs-serve comparisons free on a warm
repository: both sides are O(log n) state merges (windows/query.py),
and the comparison itself is host-side arithmetic.

Import discipline (WINDOWS lint rule, tools/lint.py): numpy and the
stdlib only — no jax, no pyarrow, no `deequ_tpu.ops` imports. Sketch
behavior is reached through the state objects' own methods
(`digest.rank`, `metric_value`), never by importing kernel code.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "ChiSquareResult",
    "StateBag",
    "cardinality_drift",
    "completeness_drift",
    "frequency_chi_square",
    "mean_drift",
    "quantile_drift",
    "regularized_gamma_q",
    "stddev_drift",
]

_EPS = 1e-12


# ---------------------------------------------------------------------------
# StateBag — one side of a two-sample comparison
# ---------------------------------------------------------------------------


@dataclass
class StateBag:
    """One sample's analyzer states, keyed by analyzer repr — the unit a
    drift check compares. `signature` carries the plan signature the
    states were committed under (when known), so a baseline produced by
    a different plan flags DQ324 instead of silently comparing
    incompatible sketches. `label` names the sample in messages
    ("sliding(7)[...]", "baseline week 31")."""

    states: Dict[str, Tuple[Any, Any]] = field(default_factory=dict)
    signature: Optional[str] = None
    label: str = ""

    @classmethod
    def from_pairs(
        cls,
        pairs: Sequence[Tuple[Any, Any]],
        *,
        signature: Optional[str] = None,
        label: str = "",
    ) -> "StateBag":
        bag = cls(signature=signature, label=label)
        for analyzer, state in pairs:
            bag.states[repr(analyzer)] = (analyzer, state)
        return bag

    @classmethod
    def from_provider(
        cls,
        provider: Any,
        analyzers: Sequence[Any],
        *,
        signature: Optional[str] = None,
        label: str = "",
    ) -> "StateBag":
        """From an `InMemoryStateProvider` (or anything with
        `load(analyzer)`) — the path grouping analyzers take, since
        their states ride the provider rather than the partitioned
        repository."""
        return cls.from_pairs(
            [(a, provider.load(a)) for a in analyzers],
            signature=signature,
            label=label,
        )

    def get(self, analyzer: Any) -> Optional[Any]:
        entry = self.states.get(repr(analyzer))
        return entry[1] if entry is not None else None

    def __contains__(self, analyzer: Any) -> bool:
        return (
            repr(analyzer) in self.states
            and self.states[repr(analyzer)][1] is not None
        )

    def __len__(self) -> int:
        return len(self.states)


# ---------------------------------------------------------------------------
# sketch-backed statistics
# ---------------------------------------------------------------------------


def _sketch_of(state: Any) -> Any:
    """The KLL sketch inside an ApproxQuantileState (or a raw sketch)."""
    return getattr(state, "digest", state)


def quantile_drift(a: Any, b: Any) -> float:
    """Two-sample Kolmogorov–Smirnov distance from two KLL sketches:
    ``max |F_a(v) - F_b(v)|`` over the union of both sketches' retained
    items — scale-free, in [0, 1], and exact over the sketches'
    empirical CDFs (the sketch error is the only approximation). Two
    sketches over identically distributed data sit near 0; a shifted
    or reshaped distribution pushes toward 1."""
    sa, sb = _sketch_of(a), _sketch_of(b)
    ka, na, levels_a = sa.to_arrays()
    kb, nb, levels_b = sb.to_arrays()
    if na == 0 or nb == 0:
        return 0.0 if na == nb else 1.0
    values = np.unique(
        np.concatenate(
            [lv for lv in levels_a if len(lv)]
            + [lv for lv in levels_b if len(lv)]
        )
    )
    worst = 0.0
    for v in values.tolist():
        worst = max(worst, abs(sa.rank(v) - sb.rank(v)))
    return float(worst)


def cardinality_drift(a: Any, b: Any) -> float:
    """Symmetric cardinality ratio drift from two HLL states:
    ``max(r, 1/r) - 1`` with ``r = est_a / est_b`` — 0 when the two
    sides agree, 1.0 when one side holds twice the distincts of the
    other, scale-free in between."""
    ca = float(a.metric_value())
    cb = float(b.metric_value())
    if ca <= 0.0 and cb <= 0.0:
        return 0.0
    if ca <= 0.0 or cb <= 0.0:
        return float("inf")
    r = ca / cb
    return float(max(r, 1.0 / r) - 1.0)


# ---------------------------------------------------------------------------
# frequency chi-square (homogeneity over the union of keys)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ChiSquareResult:
    statistic: float
    dof: int
    p_value: float


def _gamma_q_series(a: float, x: float) -> float:
    """Lower-series evaluation of P(a, x), returned as Q = 1 - P.
    Converges fast for x < a + 1 (Numerical Recipes §6.2 `gser`)."""
    term = 1.0 / a
    total = term
    ap = a
    for _ in range(500):
        ap += 1.0
        term *= x / ap
        total += term
        if abs(term) < abs(total) * 1e-15:
            break
    return 1.0 - total * math.exp(-x + a * math.log(x) - math.lgamma(a))

def _gamma_q_cf(a: float, x: float) -> float:
    """Continued-fraction evaluation of Q(a, x) by the modified Lentz
    method. Converges fast for x >= a + 1 (Numerical Recipes `gcf`)."""
    tiny = 1e-300
    b = x + 1.0 - a
    c = 1.0 / tiny
    d = 1.0 / b
    h = d
    for i in range(1, 500):
        an = -i * (i - a)
        b += 2.0
        d = an * d + b
        if abs(d) < tiny:
            d = tiny
        c = b + an / c
        if abs(c) < tiny:
            c = tiny
        d = 1.0 / d
        delta = d * c
        h *= delta
        if abs(delta - 1.0) < 1e-15:
            break
    return h * math.exp(-x + a * math.log(x) - math.lgamma(a))


def regularized_gamma_q(a: float, x: float) -> float:
    """Regularized upper incomplete gamma Q(a, x) = Γ(a, x)/Γ(a) — the
    chi-square survival function is ``Q(dof/2, stat/2)``. Stdlib-only
    (no scipy in this container), validated against scipy values in
    tests/test_drift.py."""
    if a <= 0.0:
        raise ValueError(f"gamma Q needs a > 0, got {a}")
    if x < 0.0:
        raise ValueError(f"gamma Q needs x >= 0, got {x}")
    if x == 0.0:
        return 1.0
    if x < a + 1.0:
        return min(1.0, max(0.0, _gamma_q_series(a, x)))
    return min(1.0, max(0.0, _gamma_q_cf(a, x)))


def frequency_chi_square(a: Any, b: Any) -> ChiSquareResult:
    """Two-sample chi-square test of homogeneity over the union of two
    frequency tables (`FrequenciesAndNumRows` states): expected count
    of key i in sample A is ``(a_i + b_i) * A / (A + B)``, dof =
    #union-keys - 1, p-value from the chi-square survival function. A
    small p-value means the two categorical distributions differ."""
    counts_a = {k: int(c) for k, c in zip(a.keys, a.counts.tolist())}
    counts_b = {k: int(c) for k, c in zip(b.keys, b.counts.tolist())}
    union = sorted(set(counts_a) | set(counts_b))
    total_a = float(sum(counts_a.values()))
    total_b = float(sum(counts_b.values()))
    if not union or total_a <= 0.0 or total_b <= 0.0:
        return ChiSquareResult(0.0, 0, 1.0)
    grand = total_a + total_b
    stat = 0.0
    for key in union:
        ca = float(counts_a.get(key, 0))
        cb = float(counts_b.get(key, 0))
        pooled = ca + cb
        ea = pooled * total_a / grand
        eb = pooled * total_b / grand
        if ea > 0.0:
            stat += (ca - ea) ** 2 / ea
        if eb > 0.0:
            stat += (cb - eb) ** 2 / eb
    dof = len(union) - 1
    if dof <= 0:
        return ChiSquareResult(float(stat), 0, 1.0)
    p = regularized_gamma_q(dof / 2.0, stat / 2.0)
    return ChiSquareResult(float(stat), int(dof), float(p))


# ---------------------------------------------------------------------------
# scalar-state deltas
# ---------------------------------------------------------------------------


def completeness_drift(a: Any, b: Any) -> float:
    """Absolute completeness-ratio difference between two
    `NumMatchesAndCount` states; an empty side counts as drift 0 only
    against another empty side."""
    ra = float(a.metric_value())
    rb = float(b.metric_value())
    if math.isnan(ra) and math.isnan(rb):
        return 0.0
    if math.isnan(ra) or math.isnan(rb):
        return float("inf")
    return abs(ra - rb)


def _relative_delta(va: float, vb: float) -> float:
    if math.isnan(va) and math.isnan(vb):
        return 0.0
    if math.isnan(va) or math.isnan(vb):
        return float("inf")
    scale = max(abs(va), abs(vb))
    if scale < _EPS:
        return 0.0
    return abs(va - vb) / scale


def mean_drift(a: Any, b: Any) -> float:
    """Relative mean delta ``|m_a - m_b| / max(|m_a|, |m_b|)`` between
    two `MeanState`s — scale-free, 0 when equal."""
    return _relative_delta(float(a.metric_value()), float(b.metric_value()))


def stddev_drift(a: Any, b: Any) -> float:
    """Relative standard-deviation delta between two
    `StandardDeviationState`s."""
    return _relative_delta(float(a.metric_value()), float(b.metric_value()))

"""Disk-spilled group frequencies: bounded-memory high-cardinality group-by.

The reference keeps its frequencies table as a Spark DataFrame cached at
MEMORY_AND_DISK (reference: runners/AnalysisRunner.scala:75,479-483), so
Uniqueness/Entropy/CountDistinct over a near-unique key at a billion rows
spills instead of OOMing. This module is the engine-level equivalent:

  * `GroupCountAccumulator` folds per-batch `FrequenciesAndNumRows`
    partials in RAM until the accumulated group count crosses a cap
    (DEEQU_TPU_MAX_GROUPS_IN_MEMORY, default 2M groups), then switches
    to hash-partitioned disk spill: each partial's groups are routed by
    a stable 64-bit key hash into one of N partition files.
  * `finalize()` compacts each partition once (all chunks of a
    partition merge together; a partition holds ~#groups/N distinct
    keys, so peak memory is O(cap + batch + groups/N), never O(groups))
    and returns a `SpilledFrequencies` state.
  * `SpilledFrequencies` satisfies the same consumer contracts as the
    in-memory state — additive `freq_reduce` aggregation (streamed
    per partition by ops/freq_agg), exact Histogram top-N (per-partition
    top-N then global), MutualInformation marginals, semigroup `merge` —
    without ever materializing the full key set.

Every `freq_reduce` in the frequency family is a sum over groups of
f(count_g, num_rows), which is what makes streaming per-partition
evaluation exact, not approximate.
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
import weakref
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from deequ_tpu.analyzers.states import State

def default_max_groups_in_memory() -> int:
    """Group cap before the fold spills to disk; env-tunable so memory-
    constrained deployments (and tests) can lower it."""
    return int(os.environ.get("DEEQU_TPU_MAX_GROUPS_IN_MEMORY", 2_000_000))


N_SPILL_PARTITIONS = 64
# routing works in row chunks so the stringify/hash temporaries stay
# O(chunk), not O(partial)
_ROUTE_CHUNK = 1 << 18


def _hash_key_rows(key_columns: Sequence[np.ndarray]) -> np.ndarray:
    """Stable uint64 hash per group row (combines all key columns).
    Stability across batches/processes matters: the same key must land
    in the same partition everywhere, so merges stay partition-local."""
    from deequ_tpu.ops.strings import hash_strings

    acc = np.full(len(key_columns[0]), np.uint64(0x9E3779B97F4A7C15))
    for kc in key_columns:
        h = hash_strings(np.asarray(kc).astype(str).astype(object))
        acc = (acc * np.uint64(0xC2B2AE3D27D4EB4F)) ^ h
    return acc


class _SpillWriter:
    """Appends (key_columns, counts) chunks hash-partitioned on disk."""

    def __init__(self, columns: List[str], n_partitions: int = N_SPILL_PARTITIONS):
        self.columns = list(columns)
        self.n_partitions = n_partitions
        self.directory = tempfile.mkdtemp(prefix="deequ_tpu_spill_")
        self._seq = 0
        self.num_rows = 0
        # a fold that dies mid-stream must not leak GBs of spill chunks:
        # the writer owns the directory until finalize() hands it over
        self._cleanup = weakref.finalize(
            self, shutil.rmtree, self.directory, ignore_errors=True
        )

    def append(self, partial, include_rows: bool = True) -> None:
        """Route a FrequenciesAndNumRows partial's groups to partitions,
        in row chunks so the hash/sort temporaries stay O(chunk).
        `include_rows=False` spills the groups without adding the
        partial's num_rows (used when the caller accounts rows itself);
        the partial is never mutated."""
        if include_rows:
            self.num_rows += partial.num_rows
        if partial.num_groups == 0:
            return
        key_columns = partial.key_columns
        if partial.columns != self.columns:
            key_columns = [
                partial.key_columns[partial.columns.index(c)] for c in self.columns
            ]
        # hash/sort in row chunks (temporaries stay O(chunk)); buffer each
        # partition's selections across chunks and write ONE file per
        # partition per append — 64 files instead of 64 x n_chunks
        per_part_keys: List[List[List[np.ndarray]]] = [
            [] for _ in range(self.n_partitions)
        ]
        per_part_counts: List[List[np.ndarray]] = [
            [] for _ in range(self.n_partitions)
        ]
        for start in range(0, len(partial.counts), _ROUTE_CHUNK):
            stop = min(start + _ROUTE_CHUNK, len(partial.counts))
            kcs = [kc[start:stop] for kc in key_columns]
            counts = partial.counts[start:stop]
            parts = (
                _hash_key_rows(kcs) % np.uint64(self.n_partitions)
            ).astype(np.int64)
            order = np.argsort(parts, kind="stable")
            sorted_parts = parts[order]
            boundaries = np.searchsorted(
                sorted_parts, np.arange(self.n_partitions + 1)
            )
            for p in range(self.n_partitions):
                lo, hi = boundaries[p], boundaries[p + 1]
                if lo == hi:
                    continue
                sel = order[lo:hi]
                per_part_keys[p].append([kc[sel] for kc in kcs])
                per_part_counts[p].append(counts[sel])
        self._seq += 1
        for p in range(self.n_partitions):
            if not per_part_counts[p]:
                continue
            chunk = (
                [
                    np.concatenate([kcs[j] for kcs in per_part_keys[p]])
                    for j in range(len(key_columns))
                ],
                np.concatenate(per_part_counts[p]),
            )
            path = os.path.join(self.directory, f"p{p:03d}_{self._seq:06d}.pkl")
            with open(path, "wb") as f:
                pickle.dump(chunk, f, protocol=pickle.HIGHEST_PROTOCOL)

    def finalize(self) -> "SpilledFrequencies":
        """Compact each partition to one chunk; record exact group count."""
        from deequ_tpu.analyzers.frequency import FrequenciesAndNumRows

        num_groups = 0
        # one directory scan, bucketed by partition prefix
        by_partition: dict = {}
        for fn in os.listdir(self.directory):
            if fn.startswith("p") and fn.endswith(".pkl") and "_" in fn:
                by_partition.setdefault(fn[: fn.index("_")], []).append(fn)
        for p in range(self.n_partitions):
            chunk_files = sorted(by_partition.get(f"p{p:03d}", []))
            if not chunk_files:
                continue
            key_chunks: List[List[np.ndarray]] = []
            count_chunks: List[np.ndarray] = []
            for fn in chunk_files:
                with open(os.path.join(self.directory, fn), "rb") as f:
                    kcs, counts = pickle.load(f)
                key_chunks.append(kcs)
                count_chunks.append(counts)
            merged = FrequenciesAndNumRows(
                self.columns,
                [
                    np.concatenate([kc[j] for kc in key_chunks])
                    for j in range(len(self.columns))
                ],
                np.concatenate(count_chunks),
                0,
            )
            if len(chunk_files) > 1:
                merged = merged.compacted()
            num_groups += merged.num_groups
            with open(
                os.path.join(self.directory, f"part{p:03d}.pkl"), "wb"
            ) as f:
                pickle.dump(
                    (merged.key_columns, merged.counts),
                    f,
                    protocol=pickle.HIGHEST_PROTOCOL,
                )
            for fn in chunk_files:
                os.unlink(os.path.join(self.directory, fn))
        # ownership of the directory passes to the state object
        self._cleanup.detach()
        return SpilledFrequencies(
            self.columns, self.directory, self.n_partitions, self.num_rows, num_groups
        )


class SpilledFrequencies(State):
    """Disk-backed group frequencies (hash-partitioned, compacted).

    Quacks like FrequenciesAndNumRows for every consumer that can stream
    (freq aggregation, Histogram top-N, MutualInformation, merge); it
    deliberately does NOT expose a whole-table ``counts`` array."""

    is_spilled = True

    def __init__(
        self,
        columns: List[str],
        directory: str,
        n_partitions: int,
        num_rows: int,
        num_groups: int,
    ):
        self.columns = list(columns)
        self.directory = directory
        self.n_partitions = n_partitions
        self.num_rows = int(num_rows)
        self.num_groups = int(num_groups)
        self._cleanup = weakref.finalize(
            self, shutil.rmtree, directory, ignore_errors=True
        )

    def partitions(self) -> Iterator["object"]:
        """Yield each partition as an in-memory FrequenciesAndNumRows
        (groups are disjoint across partitions)."""
        from deequ_tpu.analyzers.frequency import FrequenciesAndNumRows

        for p in range(self.n_partitions):
            path = os.path.join(self.directory, f"part{p:03d}.pkl")
            if not os.path.exists(path):
                continue
            with open(path, "rb") as f:
                key_columns, counts = pickle.load(f)
            yield FrequenciesAndNumRows(self.columns, key_columns, counts, 0)

    def top_n(self, n: int) -> Tuple[List[np.ndarray], np.ndarray]:
        """Exact global top-n groups by (count desc, key asc):
        per-partition top-n, then top-n of the union (each partition
        holds its keys' FULL counts; the deterministic tie-break matches
        the in-memory path, analyzers/frequency.py:top_n_order).

        SINGLE-COLUMN states only (the key-ascending tie-break is over
        the first key column; Histogram — the one consumer — always
        groups one column)."""
        from deequ_tpu.analyzers.frequency import top_n_order

        if len(self.columns) != 1:
            raise ValueError(
                "top_n's deterministic tie-break is defined for "
                f"single-column states, got {self.columns}"
            )

        best_keys: List[List[np.ndarray]] = []
        best_counts: List[np.ndarray] = []
        for part in self.partitions():
            order = top_n_order(part.key_columns[0], part.counts, n)
            best_keys.append([kc[order] for kc in part.key_columns])
            best_counts.append(part.counts[order])
        if not best_counts:
            return (
                [np.array([], dtype=object) for _ in self.columns],
                np.array([], dtype=np.int64),
            )
        counts = np.concatenate(best_counts)
        keys = [
            np.concatenate([bk[j] for bk in best_keys])
            for j in range(len(self.columns))
        ]
        order = top_n_order(keys[0], counts, n)
        return [kc[order] for kc in keys], counts[order]

    def merge(self, other) -> "SpilledFrequencies":
        """Semigroup merge with either state flavor: re-partition both
        sides into a fresh spill (partition-local compaction keeps the
        memory bound). Neither operand is mutated."""
        writer = _SpillWriter(self.columns, self.n_partitions)
        for part in self.partitions():
            writer.append(part, include_rows=False)
        if getattr(other, "is_spilled", False):
            for part in other.partitions():
                writer.append(part, include_rows=False)
        else:
            writer.append(_reorder(other, self.columns), include_rows=False)
        writer.num_rows = self.num_rows + other.num_rows
        return writer.finalize()

    def __repr__(self) -> str:
        return (
            f"SpilledFrequencies({self.columns}, groups={self.num_groups}, "
            f"num_rows={self.num_rows}, partitions={self.n_partitions})"
        )


def _reorder(state, columns: List[str]):
    from deequ_tpu.analyzers.frequency import FrequenciesAndNumRows

    if state.columns == list(columns):
        return state
    if sorted(state.columns) != sorted(columns):
        raise ValueError(
            f"cannot merge frequencies over {state.columns} with {columns}"
        )
    return FrequenciesAndNumRows(
        list(columns),
        [state.key_columns[state.columns.index(c)] for c in columns],
        state.counts,
        state.num_rows,
    )


class GroupCountAccumulator:
    """Cross-batch fold of frequency partials with a group-count cap.

    Below the cap this is the plain in-memory merge chain; above it,
    partials spill to hash partitions and merging is deferred to the
    per-partition compaction in finalize()."""

    def __init__(
        self,
        columns: Sequence[str],
        max_groups_in_memory: Optional[int] = None,
        n_partitions: int = N_SPILL_PARTITIONS,
    ):
        self.columns = list(columns)
        self.max_groups = (
            default_max_groups_in_memory()
            if max_groups_in_memory is None
            else max_groups_in_memory
        )
        self.n_partitions = n_partitions
        self._buffer = None
        self._writer: Optional[_SpillWriter] = None

    def add(self, partial) -> None:
        if self._writer is not None:
            self._writer.append(partial)  # num_rows accumulates in append
            return
        combined = (
            partial.num_groups
            if self._buffer is None
            else self._buffer.num_groups + partial.num_groups
        )
        if combined > self.max_groups:
            # spill both sides UNMERGED: running the O(groups) hash merge
            # on a buffer that's about to spill anyway would make peak
            # memory ~3x the cap for near-unique keys (low reduction
            # factor — the same reason Spark skips map-side combine there);
            # partition-local compaction in finalize() dedups instead
            self._writer = _SpillWriter(self.columns, self.n_partitions)
            if self._buffer is not None:
                self._writer.append(self._buffer)
                self._buffer = None
            self._writer.append(partial)
            return
        self._buffer = (
            partial if self._buffer is None else self._buffer.merge(partial)
        )

    def finalize(self):
        from deequ_tpu.analyzers.frequency import FrequenciesAndNumRows

        if self._writer is not None:
            return self._writer.finalize()
        if self._buffer is None:
            return FrequenciesAndNumRows(
                self.columns, [], np.array([], dtype=np.int64), 0
            )
        return self._buffer

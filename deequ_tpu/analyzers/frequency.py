"""Frequency-based (grouping) analyzers.

The frequency computation is the engine's group-by:
  SELECT cols, COUNT(*) FROM data WHERE all cols NOT NULL GROUP BY cols
(reference: analyzers/GroupingAnalyzers.scala:44-81). Host-side, columns
are dictionary-encoded and combined with ravel_multi_index, so the group-by
is one vectorized np.unique over dense codes; the aggregations over the
resulting counts array (uniqueness/distinctness/entropy/...) fuse into one
device reduction shared by every analyzer on the same grouping columns
(reference: AnalysisRunner.scala:466-534).

State merge is a key-aligned counts sum — the dict analogue of the
reference's null-safe outer join (GroupingAnalyzers.scala:128-148).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deequ_tpu.analyzers.base import COUNT_COL, Analyzer, Preconditions, entity_from
from deequ_tpu.analyzers.grouping import GroupingAnalyzer
from deequ_tpu.analyzers.states import State
from deequ_tpu.core.maybe import Success
from deequ_tpu.core.metrics import DoubleMetric, Entity, Metric
from deequ_tpu.data.table import ColumnType, Table


# ---------------------------------------------------------------------------
# State
# ---------------------------------------------------------------------------


@dataclass
class FrequenciesAndNumRows(State):
    """Group keys + counts + overall #rows
    (reference: GroupingAnalyzers.scala:124-157)."""

    columns: List[str]
    keys: List[Tuple]  # one tuple of group-key values per group
    counts: np.ndarray  # int64, aligned with keys
    num_rows: int

    @property
    def num_groups(self) -> int:
        return len(self.keys)

    def merge(self, other: "FrequenciesAndNumRows") -> "FrequenciesAndNumRows":
        other_keys = other.keys
        if self.columns != other.columns:
            # align by column name (the dict analogue of the reference's
            # name-based outer join); declared order may differ from the
            # runner's sorted sharing order
            if sorted(self.columns) != sorted(other.columns):
                raise ValueError(
                    f"cannot merge frequencies over {self.columns} with {other.columns}"
                )
            perm = [other.columns.index(c) for c in self.columns]
            other_keys = [tuple(k[i] for i in perm) for k in other.keys]
        combined: Dict[Tuple, int] = {}
        for key, count in zip(self.keys, self.counts):
            combined[key] = combined.get(key, 0) + int(count)
        for key, count in zip(other_keys, other.counts):
            combined[key] = combined.get(key, 0) + int(count)
        keys = list(combined.keys())
        counts = np.array([combined[k] for k in keys], dtype=np.int64)
        return FrequenciesAndNumRows(
            list(self.columns), keys, counts, self.num_rows + other.num_rows
        )

    def __eq__(self, other) -> bool:
        if not isinstance(other, FrequenciesAndNumRows):
            return False
        return (
            self.columns == other.columns
            and self.num_rows == other.num_rows
            and dict(zip(self.keys, self.counts.tolist()))
            == dict(zip(other.keys, other.counts.tolist()))
        )


def _column_key_values(col) -> Tuple[np.ndarray, np.ndarray]:
    """(codes, uniques) with uniques as python-friendly scalars."""
    codes, uniques = col.dict_encode()
    if col.ctype == ColumnType.LONG:
        uniques = np.array([int(u) for u in uniques], dtype=object)
    elif col.ctype in (ColumnType.DOUBLE, ColumnType.DECIMAL):
        uniques = np.array([float(u) for u in uniques], dtype=object)
    elif col.ctype == ColumnType.BOOLEAN:
        uniques = np.array([bool(u) for u in uniques], dtype=object)
    else:
        uniques = np.asarray(uniques, dtype=object)
    return codes, uniques


def compute_frequencies(
    data: Table, grouping_columns: Sequence[str], num_rows: Optional[int] = None
) -> FrequenciesAndNumRows:
    """reference: GroupingAnalyzers.scala:53-80. Rows where ANY grouping
    column is NULL are excluded from groups; num_rows counts all rows."""
    from deequ_tpu.ops import runtime

    runtime.record_group_pass(",".join(grouping_columns))

    cols = [data.column(name) for name in grouping_columns]
    valid = np.ones(data.num_rows, dtype=np.bool_)
    for col in cols:
        valid &= col.valid

    encoded = [_column_key_values(col) for col in cols]
    dims = [max(len(u), 1) for _, u in encoded]

    if valid.any():
        code_arrays = [np.where(valid, c, 0) for c, _ in encoded]
        combined = np.ravel_multi_index(code_arrays, dims)[valid]
        unique_codes, counts = np.unique(combined, return_counts=True)
        unraveled = np.unravel_index(unique_codes, dims)
        keys = [
            tuple(encoded[j][1][unraveled[j][i]] for j in range(len(cols)))
            for i in range(len(unique_codes))
        ]
        counts = counts.astype(np.int64)
    else:
        keys = []
        counts = np.array([], dtype=np.int64)

    total = num_rows if num_rows is not None else data.num_rows
    return FrequenciesAndNumRows(list(grouping_columns), keys, counts, total)


# ---------------------------------------------------------------------------
# Analyzer bases
# ---------------------------------------------------------------------------


class FrequencyBasedAnalyzer(GroupingAnalyzer):
    """reference: GroupingAnalyzers.scala:28-41."""

    def grouping_columns(self) -> List[str]:
        return list(self.columns)

    @property
    def instance(self) -> str:
        return ",".join(self.columns)

    @property
    def entity(self) -> Entity:
        return entity_from(self.columns)

    def preconditions(self) -> List[Callable[[Table], None]]:
        return [Preconditions.at_least_one(self.columns)] + [
            Preconditions.has_column(c) for c in self.columns
        ]

    def compute_state_from(self, table: Table) -> Optional[FrequenciesAndNumRows]:
        return compute_frequencies(table, self.grouping_columns())


class ScanShareableFrequencyBasedAnalyzer(FrequencyBasedAnalyzer):
    """Aggregations over the shared frequencies table
    (reference: GroupingAnalyzers.scala:84-121). `freq_reduce` is generic
    over the array namespace so it fuses into one device program per
    grouping set and also serves host evaluation."""

    def freq_reduce(self, counts, num_rows: int, xp) -> Any:
        raise NotImplementedError

    def metric_from_freq_agg(self, agg: Any, state: FrequenciesAndNumRows) -> Metric:
        raise NotImplementedError

    def compute_metric_from(self, state: Optional[FrequenciesAndNumRows]) -> Metric:
        if state is None:
            return self.empty_state_failure()
        from deequ_tpu.ops.freq_agg import run_shared_freq_agg

        return run_shared_freq_agg(state, [self])[0]

    def to_success_metric(self, value: float) -> DoubleMetric:
        return DoubleMetric(self.entity, self.name, self.instance, Success(value))


# ---------------------------------------------------------------------------
# Concrete frequency analyzers
# ---------------------------------------------------------------------------


def _single_or_seq(columns) -> List[str]:
    if isinstance(columns, str):
        return [columns]
    return list(columns)


def _scala_list_repr(columns: Sequence[str]) -> str:
    return f"List({', '.join(columns)})"


class Uniqueness(ScanShareableFrequencyBasedAnalyzer):
    """Fraction of values occurring exactly once
    (reference: analyzers/Uniqueness.scala:26)."""

    def __init__(self, columns):
        self.columns = _single_or_seq(columns)

    @property
    def name(self) -> str:
        return "Uniqueness"

    def freq_reduce(self, counts, num_rows: int, xp) -> Any:
        return {"unique": xp.sum(xp.asarray(counts == 1, dtype=counts.dtype))}

    def metric_from_freq_agg(self, agg: Any, state: FrequenciesAndNumRows) -> Metric:
        if state.num_groups == 0:
            return self.empty_state_failure()  # SQL sum over empty -> NULL
        return self.to_success_metric(float(agg["unique"]) / state.num_rows)

    def __repr__(self) -> str:
        return f"Uniqueness({_scala_list_repr(self.columns)})"


class Distinctness(ScanShareableFrequencyBasedAnalyzer):
    """Fraction of distinct values (reference: analyzers/Distinctness.scala:29)."""

    def __init__(self, columns):
        self.columns = _single_or_seq(columns)

    @property
    def name(self) -> str:
        return "Distinctness"

    def freq_reduce(self, counts, num_rows: int, xp) -> Any:
        return {"distinct": xp.sum(xp.asarray(counts >= 1, dtype=counts.dtype))}

    def metric_from_freq_agg(self, agg: Any, state: FrequenciesAndNumRows) -> Metric:
        if state.num_groups == 0:
            return self.empty_state_failure()
        return self.to_success_metric(float(agg["distinct"]) / state.num_rows)

    def __repr__(self) -> str:
        return f"Distinctness({_scala_list_repr(self.columns)})"


class UniqueValueRatio(ScanShareableFrequencyBasedAnalyzer):
    """#unique / #distinct groups (reference: analyzers/UniqueValueRatio.scala:25)."""

    def __init__(self, columns):
        self.columns = _single_or_seq(columns)

    @property
    def name(self) -> str:
        return "UniqueValueRatio"

    def freq_reduce(self, counts, num_rows: int, xp) -> Any:
        return {
            "unique": xp.sum(xp.asarray(counts == 1, dtype=counts.dtype)),
            "groups": xp.sum(xp.asarray(counts >= 1, dtype=counts.dtype)),
        }

    def metric_from_freq_agg(self, agg: Any, state: FrequenciesAndNumRows) -> Metric:
        if state.num_groups == 0:
            return self.empty_state_failure()
        return self.to_success_metric(float(agg["unique"]) / float(agg["groups"]))

    def __repr__(self) -> str:
        return f"UniqueValueRatio({_scala_list_repr(self.columns)})"


class CountDistinct(ScanShareableFrequencyBasedAnalyzer):
    """#groups; count(*) never nulls, so empty -> 0.0
    (reference: analyzers/CountDistinct.scala:24)."""

    def __init__(self, columns):
        self.columns = _single_or_seq(columns)

    @property
    def name(self) -> str:
        return "CountDistinct"

    def freq_reduce(self, counts, num_rows: int, xp) -> Any:
        return {"groups": xp.sum(xp.asarray(counts >= 1, dtype=counts.dtype))}

    def metric_from_freq_agg(self, agg: Any, state: FrequenciesAndNumRows) -> Metric:
        return self.to_success_metric(float(agg["groups"]))

    def __repr__(self) -> str:
        return f"CountDistinct({_scala_list_repr(self.columns)})"


class Entropy(ScanShareableFrequencyBasedAnalyzer):
    """-Σ (c/N)·ln(c/N) with N = total rows incl. nulls, exactly like the
    reference's UDF over group counts (reference: analyzers/Entropy.scala:28-41)."""

    def __init__(self, column: str):
        self.columns = [column]

    @property
    def name(self) -> str:
        return "Entropy"

    def freq_reduce(self, counts, num_rows, xp) -> Any:
        n = xp.maximum(xp.asarray(num_rows, dtype=counts.dtype), 1)
        p = counts / n
        safe_p = xp.where(p > 0, p, 1.0)
        return {"entropy": xp.sum(xp.where(p > 0, -safe_p * xp.log(safe_p), 0.0))}

    def metric_from_freq_agg(self, agg: Any, state: FrequenciesAndNumRows) -> Metric:
        if state.num_groups == 0:
            return self.empty_state_failure()
        return self.to_success_metric(float(agg["entropy"]))

    def __repr__(self) -> str:
        # Scala: case class Entropy(column: String)
        return f"Entropy({self.columns[0]})"


class MutualInformation(FrequencyBasedAnalyzer):
    """Σ pxy·ln(pxy/(px·py)) over the joint frequencies; NOT shareable
    (joins marginals — reference: analyzers/MutualInformation.scala:35-90)."""

    def __init__(self, column_a, column_b=None):
        if column_b is None:
            self.columns = _single_or_seq(column_a)
        else:
            self.columns = [column_a, column_b]

    @property
    def name(self) -> str:
        return "MutualInformation"

    @property
    def entity(self) -> Entity:
        return Entity.MULTICOLUMN

    def preconditions(self) -> List[Callable[[Table], None]]:
        return [Preconditions.exactly_n_columns(self.columns, 2)] + super().preconditions()

    def compute_metric_from(self, state: Optional[FrequenciesAndNumRows]) -> Metric:
        if state is None or state.num_groups == 0:
            return self.empty_state_failure()
        from deequ_tpu.ops import runtime

        runtime.record_pass("freq-agg:MutualInformation")
        total = state.num_rows
        # state columns may be sorted differently than self.columns
        ia = state.columns.index(self.columns[0])
        ib = state.columns.index(self.columns[1])
        keys_a = [k[ia] for k in state.keys]
        keys_b = [k[ib] for k in state.keys]
        counts = state.counts.astype(np.float64)

        _, codes_a = np.unique(np.array(keys_a, dtype=object), return_inverse=True)
        _, codes_b = np.unique(np.array(keys_b, dtype=object), return_inverse=True)
        marg_a = np.bincount(codes_a, weights=counts)
        marg_b = np.bincount(codes_b, weights=counts)

        pxy = counts / total
        px = marg_a[codes_a] / total
        py = marg_b[codes_b] / total
        value = float(np.sum(pxy * np.log(pxy / (px * py))))
        return DoubleMetric(self.entity, self.name, self.instance, Success(value))

    def __repr__(self) -> str:
        return f"MutualInformation({_scala_list_repr(self.columns)})"

"""Frequency-based (grouping) analyzers.

The frequency computation is the engine's group-by:
  SELECT cols, COUNT(*) FROM data WHERE all cols NOT NULL GROUP BY cols
(reference: analyzers/GroupingAnalyzers.scala:44-81). Host-side, columns
are dictionary-encoded and combined with ravel_multi_index, so the group-by
is one vectorized np.unique over dense codes; the aggregations over the
resulting counts array (uniqueness/distinctness/entropy/...) fuse into one
device reduction shared by every analyzer on the same grouping columns
(reference: AnalysisRunner.scala:466-534).

State merge is a key-aligned counts sum — the dict analogue of the
reference's null-safe outer join (GroupingAnalyzers.scala:128-148).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deequ_tpu.analyzers.base import Preconditions, entity_from
from deequ_tpu.analyzers.grouping import GroupingAnalyzer
from deequ_tpu.analyzers.states import State
from deequ_tpu.core.maybe import Success
from deequ_tpu.core.metrics import DoubleMetric, Entity, Metric
from deequ_tpu.data.table import ColumnType, Table


# ---------------------------------------------------------------------------
# State
# ---------------------------------------------------------------------------


class FrequenciesAndNumRows(State):
    """Group keys + counts + overall #rows
    (reference: GroupingAnalyzers.scala:124-157).

    Keys are stored columnar (one object array per grouping column,
    aligned with ``counts``) so merges stay vectorized; ``keys`` exposes
    the row-tuple view lazily for consumers that want it.
    """

    __slots__ = ("columns", "key_columns", "counts", "num_rows", "_keys")

    def __init__(self, columns, keys, counts, num_rows: int):
        """`keys` is either a list of per-group tuples or a list of
        per-COLUMN arrays (len == len(columns)); both are accepted so
        construction sites build whichever is natural."""
        self.columns: List[str] = list(columns)
        counts = np.asarray(counts, dtype=np.int64)
        if len(keys) == len(self.columns) and all(
            isinstance(k, np.ndarray) for k in keys
        ):
            self.key_columns = [np.asarray(k, dtype=object) for k in keys]
        else:
            n = len(keys)
            self.key_columns = [
                np.array([k[j] for k in keys], dtype=object)
                for j in range(len(self.columns))
            ]
            assert all(len(kc) == n for kc in self.key_columns)
        self.counts = counts
        self.num_rows = int(num_rows)
        self._keys: Optional[List[Tuple]] = None

    @property
    def keys(self) -> List[Tuple]:
        if self._keys is None:
            self._keys = (
                list(zip(*[kc.tolist() for kc in self.key_columns]))
                if len(self.counts)
                else []
            )
        return self._keys

    @property
    def num_groups(self) -> int:
        return len(self.counts)

    def merge(self, other) -> "FrequenciesAndNumRows":
        if getattr(other, "is_spilled", False):
            # spilled ⊕ in-memory commutes; the spilled side knows how
            return other.merge(self)
        other_cols = other.key_columns
        if self.columns != other.columns:
            # align by column name (the columnar analogue of the
            # reference's name-based outer join); declared order may
            # differ from the runner's sorted sharing order
            if sorted(self.columns) != sorted(other.columns):
                raise ValueError(
                    f"cannot merge frequencies over {self.columns} with {other.columns}"
                )
            other_cols = [
                other.key_columns[other.columns.index(c)] for c in self.columns
            ]
        key_columns, counts = _group_sum(
            [
                np.concatenate([self.key_columns[j], other_cols[j]])
                for j in range(len(self.columns))
            ],
            np.concatenate([self.counts, other.counts]),
        )
        return FrequenciesAndNumRows(
            list(self.columns),
            key_columns,
            counts,
            self.num_rows + other.num_rows,
        )

    def compacted(self) -> "FrequenciesAndNumRows":
        """Re-group duplicate key rows (spill-partition compaction)."""
        key_columns, counts = _group_sum(self.key_columns, self.counts)
        return FrequenciesAndNumRows(
            list(self.columns), key_columns, counts, self.num_rows
        )

    def __eq__(self, other) -> bool:
        if not isinstance(other, FrequenciesAndNumRows):
            return False
        return (
            self.columns == other.columns
            and self.num_rows == other.num_rows
            and dict(zip(self.keys, self.counts.tolist()))
            == dict(zip(other.keys, other.counts.tolist()))
        )

    def __repr__(self) -> str:
        return (
            f"FrequenciesAndNumRows({self.columns}, groups={self.num_groups}, "
            f"num_rows={self.num_rows})"
        )


def _group_sum(
    key_columns: List[np.ndarray], counts: np.ndarray
) -> Tuple[List[np.ndarray], np.ndarray]:
    """C-hash group-by summing counts over identical key rows — the
    vectorized form of the reference's null-safe outer join + count sum
    (GroupingAnalyzers.scala:128-148); no Python loop over groups."""
    import pandas as pd

    n_cols = len(key_columns)
    frame = {f"k{j}": key_columns[j] for j in range(n_cols)}
    frame["__count"] = counts
    grouped = (
        pd.DataFrame(frame)
        .groupby(
            [f"k{j}" for j in range(n_cols)],
            sort=False,
            dropna=False,  # NaN/None group keys are real groups
        )["__count"]
        .sum()
    )
    index = grouped.index
    if n_cols == 1:
        out_keys = [index.to_numpy(dtype=object)]
    else:
        out_keys = [
            index.get_level_values(j).to_numpy(dtype=object)
            for j in range(n_cols)
        ]
    return out_keys, grouped.to_numpy(dtype=np.int64)


def top_n_order(keys: np.ndarray, counts: np.ndarray, n: int) -> np.ndarray:
    """Indices of the top-n groups by (count desc, key asc) — the
    deterministic tie-break shared by Histogram's in-memory selection
    and SpilledFrequencies.top_n (the reference's rdd.top leaves tie
    order partition-dependent; a total order keeps the detail-bin set
    identical across execution paths).

    Groups strictly above the n-th count sort fully; the boundary tie
    group only pays an O(|ties|) key partition for its n-fill smallest
    keys, so an all-tied high-cardinality column never string-sorts
    every group."""
    counts = np.asarray(counts)
    m = len(counts)
    if m == 0 or n <= 0:
        return np.array([], dtype=np.int64)
    if m <= n:
        keys_u = np.asarray(keys).astype(str)  # U-dtype: vectorized sort
        return np.lexsort((keys_u, -counts))
    kth = np.partition(counts, m - n)[m - n]
    above = np.nonzero(counts > kth)[0]
    above_keys = np.asarray(keys)[above].astype(str)
    above_order = np.lexsort((above_keys, -counts[above]))
    n_fill = n - len(above)
    if n_fill <= 0:
        return above[above_order][:n]
    tie = np.nonzero(counts == kth)[0]
    tie_keys = np.asarray(keys)[tie].astype(str)
    if len(tie) > n_fill:
        part = np.argpartition(tie_keys, n_fill - 1)[:n_fill]
        tie, tie_keys = tie[part], tie_keys[part]
    fill = tie[np.argsort(tie_keys)]
    return np.concatenate([above[above_order], fill])


def _column_key_values(col) -> Tuple[np.ndarray, np.ndarray]:
    """(codes, uniques) with uniques as python-friendly scalars."""
    codes, uniques = col.dict_encode()
    if col.ctype == ColumnType.LONG:
        uniques = np.array([int(u) for u in uniques], dtype=object)
    elif col.ctype in (ColumnType.DOUBLE, ColumnType.DECIMAL):
        uniques = np.array([float(u) for u in uniques], dtype=object)
    elif col.ctype == ColumnType.BOOLEAN:
        uniques = np.array([bool(u) for u in uniques], dtype=object)
    else:
        uniques = np.asarray(uniques, dtype=object)
    return codes, uniques


def compute_frequencies(
    data: Table,
    grouping_columns: Sequence[str],
    num_rows: Optional[int] = None,
    mesh=None,
) -> FrequenciesAndNumRows:
    """reference: GroupingAnalyzers.scala:53-80. Rows where ANY grouping
    column is NULL are excluded from groups; num_rows counts all rows.

    Streaming sources are folded batch-by-batch with the vectorized
    state merge — bounded host memory at O(#groups), never O(#rows).
    With a mesh, the count aggregation runs row-sharded on the devices
    (psum merge); the host keeps dict-encode and key bookkeeping."""
    from deequ_tpu import observe
    from deequ_tpu.ops import runtime

    with observe.span(
        "group_pass", cat="group", columns=",".join(grouping_columns)
    ):
        runtime.record_group_pass(",".join(grouping_columns))
        return _compute_frequencies(data, grouping_columns, num_rows, mesh)


def _compute_frequencies(
    data: Table,
    grouping_columns: Sequence[str],
    num_rows: Optional[int] = None,
    mesh=None,
) -> FrequenciesAndNumRows:
    if hasattr(data, "with_columns"):
        data = data.with_columns(list(grouping_columns))
    if getattr(data, "is_streaming", False):
        # bounded-memory fold: in-RAM merges below the group cap, hash-
        # partitioned disk spill above it (the MEMORY_AND_DISK escape
        # hatch, reference: AnalysisRunner.scala:75,479-483)
        from deequ_tpu.analyzers.freq_spill import GroupCountAccumulator

        acc = GroupCountAccumulator(grouping_columns)
        for batch in data.batches(getattr(data, "batch_rows", 1 << 22)):
            acc.add(_frequencies_of_batch(batch, grouping_columns, mesh))
        state = acc.finalize()
        if num_rows is not None:
            state.num_rows = num_rows
        return state

    state = _frequencies_of_batch(data, grouping_columns, mesh)
    if num_rows is not None:
        state.num_rows = num_rows
    return state


# raveled group-code spaces larger than this spill to the host np.unique
# path (the analogue of the reference's cache-grouped-data escape hatch)
_MAX_DEVICE_BINS = 1 << 20


def _frequencies_of_batch(
    data: Table, grouping_columns: Sequence[str], mesh=None
) -> FrequenciesAndNumRows:
    cols = [data.column(name) for name in grouping_columns]
    valid = np.ones(data.num_rows, dtype=np.bool_)
    for col in cols:
        valid &= col.valid

    encoded = [_column_key_values(col) for col in cols]
    dims = [max(len(u), 1) for _, u in encoded]

    if not valid.any():
        return FrequenciesAndNumRows(
            list(grouping_columns),
            [np.array([], dtype=object) for _ in cols],
            np.array([], dtype=np.int64),
            data.num_rows,
        )

    code_arrays = [np.where(valid, c, 0) for c, _ in encoded]
    combined_all = np.ravel_multi_index(code_arrays, dims)
    total_bins = int(np.prod(dims))

    if mesh is not None and total_bins <= _MAX_DEVICE_BINS:
        from deequ_tpu.parallel.distributed import sharded_bincount

        combined_signed = np.where(valid, combined_all, -1)
        bin_counts = sharded_bincount(combined_signed, total_bins, mesh)
        unique_codes = np.nonzero(bin_counts)[0]
        counts = bin_counts[unique_codes]
    else:
        combined = combined_all[valid]
        unique_codes, counts = np.unique(combined, return_counts=True)
        counts = counts.astype(np.int64)

    unraveled = np.unravel_index(unique_codes, dims)
    # per-column gather of group-key values: one fancy-index per column,
    # no Python loop over groups
    key_columns = [encoded[j][1][unraveled[j]] for j in range(len(cols))]

    return FrequenciesAndNumRows(
        list(grouping_columns), key_columns, counts, data.num_rows
    )


# ---------------------------------------------------------------------------
# Analyzer bases
# ---------------------------------------------------------------------------


class FrequencyBasedAnalyzer(GroupingAnalyzer):
    """reference: GroupingAnalyzers.scala:28-41."""

    def grouping_columns(self) -> List[str]:
        return list(self.columns)

    @property
    def instance(self) -> str:
        return ",".join(self.columns)

    @property
    def entity(self) -> Entity:
        return entity_from(self.columns)

    def preconditions(self) -> List[Callable[[Table], None]]:
        return [Preconditions.at_least_one(self.columns)] + [
            Preconditions.has_column(c) for c in self.columns
        ]

    def compute_state_from(self, table: Table) -> Optional[FrequenciesAndNumRows]:
        return compute_frequencies(table, self.grouping_columns())


class ScanShareableFrequencyBasedAnalyzer(FrequencyBasedAnalyzer):
    """Aggregations over the shared frequencies table
    (reference: GroupingAnalyzers.scala:84-121). `freq_reduce` is generic
    over the array namespace so it fuses into one device program per
    grouping set and also serves host evaluation."""

    def freq_reduce(self, counts, num_rows: int, xp) -> Any:
        raise NotImplementedError

    def metric_from_freq_agg(self, agg: Any, state: FrequenciesAndNumRows) -> Metric:
        raise NotImplementedError

    def compute_metric_from(self, state: Optional[FrequenciesAndNumRows]) -> Metric:
        if state is None:
            return self.empty_state_failure()
        from deequ_tpu.ops.freq_agg import run_shared_freq_agg

        return run_shared_freq_agg(state, [self])[0]

    def to_success_metric(self, value: float) -> DoubleMetric:
        return DoubleMetric(self.entity, self.name, self.instance, Success(value))


# ---------------------------------------------------------------------------
# Concrete frequency analyzers
# ---------------------------------------------------------------------------


def _single_or_seq(columns) -> List[str]:
    if isinstance(columns, str):
        return [columns]
    return list(columns)


def _scala_list_repr(columns: Sequence[str]) -> str:
    return f"List({', '.join(columns)})"


class Uniqueness(ScanShareableFrequencyBasedAnalyzer):
    """Fraction of values occurring exactly once
    (reference: analyzers/Uniqueness.scala:26)."""

    def __init__(self, columns):
        self.columns = _single_or_seq(columns)

    @property
    def name(self) -> str:
        return "Uniqueness"

    def freq_reduce(self, counts, num_rows: int, xp) -> Any:
        return {"unique": xp.sum(xp.asarray(counts == 1, dtype=counts.dtype))}

    def metric_from_freq_agg(self, agg: Any, state: FrequenciesAndNumRows) -> Metric:
        if state.num_groups == 0:
            return self.empty_state_failure()  # SQL sum over empty -> NULL
        return self.to_success_metric(float(agg["unique"]) / state.num_rows)

    def __repr__(self) -> str:
        return f"Uniqueness({_scala_list_repr(self.columns)})"


class Distinctness(ScanShareableFrequencyBasedAnalyzer):
    """Fraction of distinct values (reference: analyzers/Distinctness.scala:29)."""

    def __init__(self, columns):
        self.columns = _single_or_seq(columns)

    @property
    def name(self) -> str:
        return "Distinctness"

    def freq_reduce(self, counts, num_rows: int, xp) -> Any:
        return {"distinct": xp.sum(xp.asarray(counts >= 1, dtype=counts.dtype))}

    def metric_from_freq_agg(self, agg: Any, state: FrequenciesAndNumRows) -> Metric:
        if state.num_groups == 0:
            return self.empty_state_failure()
        return self.to_success_metric(float(agg["distinct"]) / state.num_rows)

    def __repr__(self) -> str:
        return f"Distinctness({_scala_list_repr(self.columns)})"


class UniqueValueRatio(ScanShareableFrequencyBasedAnalyzer):
    """#unique / #distinct groups (reference: analyzers/UniqueValueRatio.scala:25)."""

    def __init__(self, columns):
        self.columns = _single_or_seq(columns)

    @property
    def name(self) -> str:
        return "UniqueValueRatio"

    def freq_reduce(self, counts, num_rows: int, xp) -> Any:
        return {
            "unique": xp.sum(xp.asarray(counts == 1, dtype=counts.dtype)),
            "groups": xp.sum(xp.asarray(counts >= 1, dtype=counts.dtype)),
        }

    def metric_from_freq_agg(self, agg: Any, state: FrequenciesAndNumRows) -> Metric:
        if state.num_groups == 0:
            return self.empty_state_failure()
        return self.to_success_metric(float(agg["unique"]) / float(agg["groups"]))

    def __repr__(self) -> str:
        return f"UniqueValueRatio({_scala_list_repr(self.columns)})"


class CountDistinct(ScanShareableFrequencyBasedAnalyzer):
    """#groups; count(*) never nulls, so empty -> 0.0
    (reference: analyzers/CountDistinct.scala:24)."""

    def __init__(self, columns):
        self.columns = _single_or_seq(columns)

    @property
    def name(self) -> str:
        return "CountDistinct"

    def freq_reduce(self, counts, num_rows: int, xp) -> Any:
        return {"groups": xp.sum(xp.asarray(counts >= 1, dtype=counts.dtype))}

    def metric_from_freq_agg(self, agg: Any, state: FrequenciesAndNumRows) -> Metric:
        return self.to_success_metric(float(agg["groups"]))

    def __repr__(self) -> str:
        return f"CountDistinct({_scala_list_repr(self.columns)})"


class Entropy(ScanShareableFrequencyBasedAnalyzer):
    """-Σ (c/N)·ln(c/N) with N = total rows incl. nulls, exactly like the
    reference's UDF over group counts (reference: analyzers/Entropy.scala:28-41)."""

    def __init__(self, column: str):
        self.columns = [column]

    @property
    def name(self) -> str:
        return "Entropy"

    def freq_reduce(self, counts, num_rows, xp) -> Any:
        n = xp.maximum(xp.asarray(num_rows, dtype=counts.dtype), 1)
        p = counts / n
        safe_p = xp.where(p > 0, p, 1.0)
        return {"entropy": xp.sum(xp.where(p > 0, -safe_p * xp.log(safe_p), 0.0))}

    def metric_from_freq_agg(self, agg: Any, state: FrequenciesAndNumRows) -> Metric:
        if state.num_groups == 0:
            return self.empty_state_failure()
        return self.to_success_metric(float(agg["entropy"]))

    def __repr__(self) -> str:
        # Scala: case class Entropy(column: String)
        return f"Entropy({self.columns[0]})"


class MutualInformation(FrequencyBasedAnalyzer):
    """Σ pxy·ln(pxy/(px·py)) over the joint frequencies; NOT shareable
    (joins marginals — reference: analyzers/MutualInformation.scala:35-90)."""

    def __init__(self, column_a, column_b=None):
        if column_b is None:
            self.columns = _single_or_seq(column_a)
        else:
            self.columns = [column_a, column_b]

    @property
    def name(self) -> str:
        return "MutualInformation"

    @property
    def entity(self) -> Entity:
        return Entity.MULTICOLUMN

    def preconditions(self) -> List[Callable[[Table], None]]:
        return [Preconditions.exactly_n_columns(self.columns, 2)] + super().preconditions()

    def compute_metric_from(self, state: Optional[FrequenciesAndNumRows]) -> Metric:
        if state is None or state.num_groups == 0:
            return self.empty_state_failure()
        from deequ_tpu.ops import runtime

        runtime.record_pass("freq-agg:MutualInformation")
        total = state.num_rows
        # state columns may be sorted differently than self.columns
        ia = state.columns.index(self.columns[0])
        ib = state.columns.index(self.columns[1])

        if getattr(state, "is_spilled", False):
            # two streamed passes over the partitions: marginal counts
            # (memory O(|A| + |B|), typically << O(|A×B|) joint groups),
            # then the joint sum
            marg_a: Dict[str, float] = {}
            marg_b: Dict[str, float] = {}
            for part in state.partitions():
                counts = part.counts.astype(np.float64)
                for keys, marg in (
                    (part.key_columns[ia], marg_a),
                    (part.key_columns[ib], marg_b),
                ):
                    uniq, inv = np.unique(keys.astype(str), return_inverse=True)
                    sums = np.bincount(inv, weights=counts)
                    for u, s in zip(uniq, sums):
                        marg[u] = marg.get(u, 0.0) + s
            value = 0.0
            for part in state.partitions():
                counts = part.counts.astype(np.float64)
                pxy = counts / total
                # dict lookups per UNIQUE key, gathers per row (inverse
                # codes) — same vectorization as the in-memory branch
                ua, inv_a = np.unique(
                    part.key_columns[ia].astype(str), return_inverse=True
                )
                ub, inv_b = np.unique(
                    part.key_columns[ib].astype(str), return_inverse=True
                )
                px = np.array([marg_a[u] for u in ua])[inv_a] / total
                py = np.array([marg_b[u] for u in ub])[inv_b] / total
                value += float(np.sum(pxy * np.log(pxy / (px * py))))
            return DoubleMetric(
                self.entity, self.name, self.instance, Success(value)
            )

        keys_a = state.key_columns[ia]
        keys_b = state.key_columns[ib]
        counts = state.counts.astype(np.float64)

        _, codes_a = np.unique(keys_a.astype(str), return_inverse=True)
        _, codes_b = np.unique(keys_b.astype(str), return_inverse=True)
        marg_a = np.bincount(codes_a, weights=counts)
        marg_b = np.bincount(codes_b, weights=counts)

        pxy = counts / total
        px = marg_a[codes_a] / total
        py = marg_b[codes_b] / total
        value = float(np.sum(pxy * np.log(pxy / (px * py))))
        return DoubleMetric(self.entity, self.name, self.instance, Success(value))

    def __repr__(self) -> str:
        return f"MutualInformation({_scala_list_repr(self.columns)})"

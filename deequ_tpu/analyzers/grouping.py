"""Grouping (frequency-based) analyzers — marker + shared state.

reference: analyzers/GroupingAnalyzers.scala, analyzers/Analyzer.scala:263-272.
Concrete frequency analyzers land with the grouping milestone; the marker
class exists so the runner can partition analyzer sets.
"""

from __future__ import annotations

from typing import List

from deequ_tpu.analyzers.base import Analyzer


class GroupingAnalyzer(Analyzer):
    """Marker: analyzers that need a group-by over some column set.
    Analyzers with the same (sorted) grouping columns share one frequency
    computation (reference: AnalysisRunner.scala:164-180)."""

    def grouping_columns(self) -> List[str]:
        raise NotImplementedError

"""Histogram analyzer: full value distribution with top-N detail bins.

reference: analyzers/Histogram.scala:38-116. Unlike the grouping analyzers
it keeps NULL rows (as the "NullValue" bin) and stringifies values the way
Spark's cast-to-string does.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from deequ_tpu.analyzers.base import Analyzer, Preconditions
from deequ_tpu.analyzers.frequency import FrequenciesAndNumRows, top_n_order
from deequ_tpu.core.exceptions import IllegalAnalyzerParameterException, wrap_if_necessary
from deequ_tpu.core.maybe import Failure, Try
from deequ_tpu.core.metrics import (
    Distribution,
    DistributionValue,
    Entity,
    HistogramMetric,
    Metric,
)
from deequ_tpu.data.table import ColumnType, Table

NULL_FIELD_REPLACEMENT = "NullValue"
MAXIMUM_ALLOWED_DETAIL_BINS = 1000


def _stringify(value, ctype: ColumnType) -> str:
    """Spark cast-to-string conventions for typed column values."""
    if ctype == ColumnType.BOOLEAN:
        return "true" if value else "false"
    if ctype == ColumnType.LONG:
        return str(int(value))
    if ctype in (ColumnType.DOUBLE, ColumnType.DECIMAL):
        return str(float(value))
    return str(value)


def _stringify_any(value) -> str:
    """Stringify by the VALUE's type — binning udfs may map numeric input
    to arbitrary labels."""
    if isinstance(value, bool) or isinstance(value, np.bool_):
        return "true" if value else "false"
    if isinstance(value, (int, np.integer)):
        return str(int(value))
    if isinstance(value, (float, np.floating)):
        return str(float(value))
    return str(value)


class Histogram(Analyzer):
    def __init__(
        self,
        column: str,
        binning_udf: Optional[Callable] = None,
        max_detail_bins: int = MAXIMUM_ALLOWED_DETAIL_BINS,
    ):
        self.column = column
        self.binning_udf = binning_udf
        self.max_detail_bins = max_detail_bins

    @property
    def name(self) -> str:
        return "Histogram"

    @property
    def instance(self) -> str:
        return self.column

    @property
    def entity(self) -> Entity:
        return Entity.COLUMN

    def preconditions(self) -> List[Callable[[Table], None]]:
        def param_check(table: Table) -> None:
            if self.max_detail_bins > MAXIMUM_ALLOWED_DETAIL_BINS:
                raise IllegalAnalyzerParameterException(
                    "Cannot return histogram values for more than "
                    f"{MAXIMUM_ALLOWED_DETAIL_BINS} values"
                )

        return [param_check, Preconditions.has_column(self.column)]

    def compute_state_from(self, table: Table) -> Optional[FrequenciesAndNumRows]:
        from deequ_tpu.ops import runtime

        runtime.record_group_pass(f"histogram:{self.column}")
        if hasattr(table, "with_columns"):
            table = table.with_columns([self.column])
        if getattr(table, "is_streaming", False):
            # bounded-memory fold with the same spill escape hatch as
            # compute_frequencies: a high-cardinality histogram column
            # must not hold every group in RAM
            from deequ_tpu.analyzers.freq_spill import GroupCountAccumulator

            acc = GroupCountAccumulator([self.column])
            saw_batch = False
            for batch in table.batches(getattr(table, "batch_rows", 1 << 22)):
                saw_batch = True
                acc.add(self._state_of_batch(batch))
            return acc.finalize() if saw_batch else None
        return self._state_of_batch(table)

    def _state_of_batch(self, table: Table) -> FrequenciesAndNumRows:
        col = table.column(self.column)
        if self.binning_udf is None:
            # vectorized fast path: group on dictionary codes, stringify
            # only the (few) unique values
            from deequ_tpu.ops import native

            codes, uniques = col.dict_encode()
            group_counts = native.bincount(codes, len(uniques) + 1, base=1)
            if group_counts is None:
                group_counts = np.bincount(codes + 1, minlength=len(uniques) + 1)
            labels = [NULL_FIELD_REPLACEMENT] + [
                _stringify(u, col.ctype) for u in uniques
            ]
            keys: List[tuple] = []
            counts_list: List[int] = []
            label_totals: Dict[str, int] = {}
            for label, count in zip(labels, group_counts):
                if count > 0:
                    label_totals[label] = label_totals.get(label, 0) + int(count)
            keys = [(label,) for label in label_totals]
            counts = np.array(list(label_totals.values()), dtype=np.int64)
        else:
            values = np.empty(len(col), dtype=object)
            for i in range(len(col)):
                if not col.valid[i]:
                    values[i] = NULL_FIELD_REPLACEMENT
                else:
                    values[i] = _stringify_any(self.binning_udf(col.values[i]))
            if len(values):
                uniques, ucounts = np.unique(values.astype(str), return_counts=True)
            else:
                uniques, ucounts = np.array([], dtype=str), np.array([], dtype=np.int64)
            keys = [(str(u),) for u in uniques]
            counts = ucounts.astype(np.int64)
        return FrequenciesAndNumRows([self.column], keys, counts, table.num_rows)

    def compute_metric_from(self, state: Optional[FrequenciesAndNumRows]) -> Metric:
        if state is None:
            from deequ_tpu.core.exceptions import EmptyStateException

            return HistogramMetric(
                Entity.COLUMN,
                self.name,
                self.column,
                Failure(
                    EmptyStateException(
                        f"Empty state for analyzer {self!r}, all input values were NULL."
                    )
                ),
            )

        def build() -> Distribution:
            bin_count = state.num_groups
            if getattr(state, "is_spilled", False):
                # exact global top-N from per-partition top-Ns (each
                # partition holds its keys' full counts)
                top_keys, top_counts = state.top_n(self.max_detail_bins)
                keys_arr, counts_arr = top_keys[0], top_counts
            else:
                # (count desc, key asc): deterministic tie-break, see
                # frequency.top_n_order
                order = top_n_order(
                    state.key_columns[0], state.counts, self.max_detail_bins
                )
                keys_arr = state.key_columns[0][order]
                counts_arr = state.counts[order]
            details = {}
            for value, absolute in zip(keys_arr, counts_arr):
                absolute = int(absolute)
                details[value] = DistributionValue(
                    absolute, absolute / state.num_rows
                )
            return Distribution(details, number_of_bins=bin_count)

        return HistogramMetric(Entity.COLUMN, self.name, self.column, Try.of(build))

    def to_failure_metric(self, exception: BaseException) -> Metric:
        return HistogramMetric(
            Entity.COLUMN, self.name, self.column, Failure(wrap_if_necessary(exception))
        )

    def __repr__(self) -> str:
        udf = "None" if self.binning_udf is None else f"Some({self.binning_udf})"
        return f"Histogram({self.column},{udf},{self.max_detail_bins})"

"""Scan-shareable analyzers: single-pass masked reductions.

Each analyzer's heavy work is a per-batch reduction expressed once, generic
over the array namespace (jnp on device, numpy float64 on the host fold) —
the same code path serves the fused XLA pass, the cross-device collective
merge, and the driver-side cross-batch fold. This replaces the reference's
Catalyst aggregate kernels (reference: analyzers/catalyst/, SURVEY.md §2.6)
and its per-analyzer `aggregationFunctions()` offsets
(reference: analyzers/Analyzer.scala:159-216).

Aggregate pytrees are dicts of scalars; all masks enter reductions as
multiplicative 0/1 factors so padded rows and filtered rows contribute
exactly nothing.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from deequ_tpu.analyzers.base import (
    InputSpec,
    Preconditions,
    ScanShareableAnalyzer,
    col_valid_spec,
    col_values_spec,
    render_where,
    where_key,
    where_spec,
)
from deequ_tpu.analyzers.states import (
    CorrelationState,
    DataTypeHistogram,
    MaxState,
    MeanState,
    MinState,
    NumMatches,
    NumMatchesAndCount,
    State,
    StandardDeviationState,
    SumState,
)
from deequ_tpu.core.maybe import Success
from deequ_tpu.core.metrics import (
    Distribution,
    DistributionValue,
    DoubleMetric,
    Entity,
    HistogramMetric,
    Metric,
)
from deequ_tpu.data.table import ColumnType, Table


def _f(xp, x):
    """Cast mask/ints to the float dtype reductions run in (no copy when
    already that dtype on the host path)."""
    if xp is np:
        return np.asarray(x).astype(np.result_type(0.0), copy=False)
    return xp.asarray(x).astype(xp.result_type(0.0))


# ---------------------------------------------------------------------------
# Size
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Size(ScanShareableAnalyzer):
    """# rows, optionally filtered (reference: analyzers/Size.scala:36)."""

    discrete_inputs = True  # mask-only: host-foldable under placement
    where: Optional[str] = None

    @property
    def name(self) -> str:
        return "Size"

    @property
    def instance(self) -> str:
        return "*"

    @property
    def entity(self) -> Entity:
        return Entity.DATASET

    def input_specs(self) -> List[InputSpec]:
        return [where_spec(self.where)]

    def device_reduce(self, inputs: Dict[str, Any], xp) -> Any:
        if xp is np and self.where is None:
            # host fold: unfiltered size is the (unpadded) batch length
            return {"n": float(inputs[where_key(None)].shape[0])}
        w = inputs[where_key(self.where)]
        if xp is np and np.asarray(w).dtype == np.bool_:
            return {"n": float(np.count_nonzero(w))}  # host fold fast path
        return {"n": xp.sum(_f(xp, w))}

    def merge_agg(self, a: Any, b: Any, xp) -> Any:
        return {"n": a["n"] + b["n"]}

    def state_from_aggregates(self, agg: Any) -> Optional[State]:
        return NumMatches(int(agg["n"]))

    def compute_metric_from(self, state: Optional[State]) -> Metric:
        if state is None:
            return self.empty_state_failure()
        return DoubleMetric(
            self.entity, self.name, self.instance, Success(state.metric_value())
        )

    def __repr__(self) -> str:
        return f"Size({render_where(self.where)})"


# ---------------------------------------------------------------------------
# Ratio analyzers: Completeness / Compliance / PatternMatch
# ---------------------------------------------------------------------------


class _RatioAnalyzer(ScanShareableAnalyzer):
    """matches/count with a guard leaf for the empty-state rule.

    The guard mirrors SQL `sum` nullability in the reference's aggregation
    expressions: the state is empty (None -> EmptyStateException) exactly
    when every row's criterion was NULL. For Completeness the criterion
    (`isNotNull(...)`) is never NULL, so the guard is "any row scanned"; for
    Compliance/PatternMatch non-matching `where` rows and NULL inputs make
    the criterion NULL, so the guard is "any row with where ∧ non-null
    input" (reference: analyzers/Completeness.scala:36-41,
    Compliance.scala:50, PatternMatch.scala:42-50)."""

    discrete_inputs = True  # mask-only: host-foldable under placement

    def _match_mask_key(self) -> str:
        raise NotImplementedError

    def _extra_specs(self) -> List[InputSpec]:
        raise NotImplementedError

    def _guard(self, inputs: Dict[str, Any], xp):
        """Mask of rows whose criterion is non-NULL."""
        raise NotImplementedError

    def input_specs(self) -> List[InputSpec]:
        return self._extra_specs() + [where_spec(self.where), where_spec(None)]

    def device_reduce(self, inputs: Dict[str, Any], xp) -> Any:
        w_raw = inputs[where_key(self.where)]
        m_raw = inputs[self._match_mask_key()]
        if (
            xp is np
            and np.asarray(w_raw).dtype == np.bool_
            and np.asarray(m_raw).dtype == np.bool_
        ):
            # host fold fast path: popcounts, no float materialization
            w_b = np.asarray(w_raw)
            guard = np.asarray(self._guard(inputs, np), dtype=bool)
            return {
                "matches": float(np.count_nonzero(np.asarray(m_raw) & w_b)),
                "count": float(np.count_nonzero(w_b)),
                "guard": float(np.count_nonzero(guard)),
            }
        w = _f(xp, w_raw)
        m = _f(xp, m_raw)
        return {
            "matches": xp.sum(m * w),
            "count": xp.sum(w),
            "guard": xp.sum(_f(xp, self._guard(inputs, xp))),
        }

    def merge_agg(self, a: Any, b: Any, xp) -> Any:
        return {k: a[k] + b[k] for k in ("matches", "count", "guard")}

    def state_from_aggregates(self, agg: Any) -> Optional[State]:
        if int(agg["guard"]) == 0:
            return None
        return NumMatchesAndCount(int(agg["matches"]), int(agg["count"]))

    def compute_metric_from(self, state: Optional[State]) -> Metric:
        if state is None:
            return self.empty_state_failure()
        return DoubleMetric(
            self.entity, self.name, self.instance, Success(state.metric_value())
        )


@dataclass(frozen=True)
class Completeness(_RatioAnalyzer):
    """Fraction non-NULL (reference: analyzers/Completeness.scala:26)."""

    column: str
    where: Optional[str] = None

    @property
    def name(self) -> str:
        return "Completeness"

    @property
    def instance(self) -> str:
        return self.column

    def preconditions(self) -> List[Callable[[Table], None]]:
        return [Preconditions.has_column(self.column)]

    def _match_mask_key(self) -> str:
        return f"valid:{self.column}"

    def _extra_specs(self) -> List[InputSpec]:
        return [col_valid_spec(self.column)]

    def _guard(self, inputs: Dict[str, Any], xp):
        # isNotNull(...) is never NULL: empty only when nothing was scanned
        return inputs[where_key(None)]

    def device_reduce(self, inputs: Dict[str, Any], xp) -> Any:
        if xp is np:
            # Completeness's counts are exactly the (column, where)
            # family's fused-moment counts: matches = valid∧where,
            # count = where-true, guard = rows scanned — free when a
            # quantile sketch already ran the combined family kernel
            mom = inputs.get(f"__moments:{self.column}:{where_key(self.where)}")
            if mom is not None and "n_rows" in mom:
                return {
                    "matches": mom["count"],
                    "count": mom["n_where"],
                    "guard": mom["n_rows"],
                }
            if self.where is None:
                # string/bool column counted by _LowCardCounts this
                # batch: null count is already known
                nulls = inputs.get(f"__lccnulls:{self.column}")
                if nulls is not None:
                    null_count, n = nulls
                    return {
                        "matches": float(n - null_count),
                        "count": float(n),
                        "guard": float(n),
                    }
        return super().device_reduce(inputs, xp)

    def __repr__(self) -> str:
        return f"Completeness({self.column},{render_where(self.where)})"


def _pred_spec(predicate: str) -> InputSpec:
    from deequ_tpu.data.expr import Predicate

    pred = Predicate(predicate)
    return InputSpec(
        key=f"pred:{predicate}",
        build=lambda t: pred.eval_mask(t),
        columns=tuple(sorted(set(pred.referenced_columns()))),
    )


def _pred_nonnull_spec(predicate: str) -> InputSpec:
    from deequ_tpu.data.expr import Predicate

    pred = Predicate(predicate)

    def build(t: Table) -> np.ndarray:
        _, null, _ = pred.eval(t)
        return ~null

    return InputSpec(
        key=f"prednn:{predicate}",
        build=build,
        columns=tuple(sorted(set(pred.referenced_columns()))),
    )


@dataclass(frozen=True)
class Compliance(_RatioAnalyzer):
    """Fraction of rows satisfying an arbitrary SQL predicate
    (reference: analyzers/Compliance.scala:37)."""

    instance_name: str
    predicate: str
    where: Optional[str] = None

    @property
    def name(self) -> str:
        return "Compliance"

    @property
    def instance(self) -> str:
        return self.instance_name

    @property
    def entity(self) -> Entity:
        return Entity.COLUMN

    def _match_mask_key(self) -> str:
        return f"pred:{self.predicate}"

    def _extra_specs(self) -> List[InputSpec]:
        return [_pred_spec(self.predicate), _pred_nonnull_spec(self.predicate)]

    def _guard(self, inputs: Dict[str, Any], xp):
        # criterion NULL on where-misses and NULL predicate results
        return xp.logical_and(
            xp.asarray(inputs[where_key(self.where)]),
            xp.asarray(inputs[f"prednn:{self.predicate}"]),
        )

    def __repr__(self) -> str:
        return f"Compliance({self.instance_name},{self.predicate},{render_where(self.where)})"


class Patterns:
    """Built-in patterns (reference: analyzers/PatternMatch.scala:57-70;
    the regexes are cited third-party public constants)."""

    # http://emailregex.com
    EMAIL = (
        r"""(?:[a-z0-9!#$%&'*+/=?^_`{|}~-]+(?:\.[a-z0-9!#$%&'*+/=?^_`{|}~-]+)*"""
        r"""|"(?:[\x01-\x08\x0b\x0c\x0e-\x1f\x21\x23-\x5b\x5d-\x7f]|\\[\x01-\x09\x0b\x0c\x0e-\x7f])*")"""
        r"""@(?:(?:[a-z0-9](?:[a-z0-9-]*[a-z0-9])?\.)+[a-z0-9](?:[a-z0-9-]*[a-z0-9])?"""
        r"""|\[(?:(?:25[0-5]|2[0-4][0-9]|[01]?[0-9][0-9]?)\.){3}"""
        r"""(?:25[0-5]|2[0-4][0-9]|[01]?[0-9][0-9]?|[a-z0-9-]*[a-z0-9]:"""
        r"""(?:[\x01-\x08\x0b\x0c\x0e-\x1f\x21-\x5a\x53-\x7f]|\\[\x01-\x09\x0b\x0c\x0e-\x7f])+)\])"""
    )

    # https://mathiasbynens.be/demo/url-regex (@stephenhay)
    URL = r"""(https?|ftp)://[^\s/$.?#].[^\s]*"""

    SOCIAL_SECURITY_NUMBER_US = (
        r"""((?!219-09-9999|078-05-1120)(?!666|000|9\d{2})\d{3}-(?!00)\d{2}-(?!0{4})\d{4})"""
        r"""|((?!219 09 9999|078 05 1120)(?!666|000|9\d{2})\d{3} (?!00)\d{2} (?!0{4})\d{4})"""
        r"""|((?!219099999|078051120)(?!666|000|9\d{2})\d{3}(?!00)\d{2}(?!0{4})\d{4})"""
    )

    # http://www.richardsramblings.com/regex/credit-card-numbers/
    CREDITCARD = (
        r"""\b(?:3[47]\d{2}([\ \-]?)\d{6}\1\d|(?:(?:4\d|5[1-5]|65)\d{2}|6011)"""
        r"""([\ \-]?)\d{4}\2\d{4}\2)\d{4}\b"""
    )


def _match_spec(column: str, pattern: str) -> InputSpec:
    re.compile(pattern)  # fail fast on a bad pattern, at spec-build time

    def compute(col) -> np.ndarray:
        from deequ_tpu.data.table import gather_with_null
        from deequ_tpu.ops.strings import match_pattern

        # regex only the unique values (typically << rows), gather to
        # rows; null rows map to False
        codes, uniques = col.dict_encode()
        return gather_with_null(match_pattern(uniques, pattern), codes, False)

    def build(t: Table) -> np.ndarray:
        from deequ_tpu.data.table import cached_column_encode

        return cached_column_encode(
            t.column(column), f"match:{pattern}", compute
        )

    return InputSpec(key=f"match:{column}:{pattern}", build=build, columns=(column,))


@dataclass(frozen=True)
class PatternMatch(_RatioAnalyzer):
    """Fraction of values matching a regex
    (reference: analyzers/PatternMatch.scala:37)."""

    column: str
    pattern: str
    where: Optional[str] = None

    @property
    def name(self) -> str:
        return "PatternMatch"

    @property
    def instance(self) -> str:
        return self.column

    def preconditions(self) -> List[Callable[[Table], None]]:
        return [
            Preconditions.has_column(self.column),
            Preconditions.is_string(self.column),
        ]

    def _match_mask_key(self) -> str:
        return f"match:{self.column}:{self.pattern}"

    def _extra_specs(self) -> List[InputSpec]:
        return [_match_spec(self.column, self.pattern), col_valid_spec(self.column)]

    def _guard(self, inputs: Dict[str, Any], xp):
        # regexp_extract(NULL) is NULL: criterion non-NULL iff where ∧ value present
        return xp.logical_and(
            xp.asarray(inputs[where_key(self.where)]),
            xp.asarray(inputs[f"valid:{self.column}"]),
        )

    def __repr__(self) -> str:
        return f"PatternMatch({self.column},{self.pattern},{render_where(self.where)})"


# ---------------------------------------------------------------------------
# Numeric moments: Mean / Min / Max / Sum / StdDev / Correlation
# ---------------------------------------------------------------------------


class _NumericScanAnalyzer(ScanShareableAnalyzer):
    def preconditions(self) -> List[Callable[[Table], None]]:
        return [
            Preconditions.has_column(self.column),
            Preconditions.is_numeric(self.column),
        ]

    @property
    def instance(self) -> str:
        return self.column

    def input_specs(self) -> List[InputSpec]:
        return [
            col_values_spec(self.column),
            col_valid_spec(self.column),
            where_spec(self.where),
        ]

    def _masked(self, inputs: Dict[str, Any], xp):
        if xp is np:
            # host fold: several analyzers share (column, where) — memo
            # the mask product in the per-batch inputs dict
            memo_key = f"__masked:{self.column}:{where_key(self.where)}"
            cached = inputs.get(memo_key)
            if cached is None:
                x = np.asarray(inputs[f"num:{self.column}"])
                m = _f(np, inputs[f"valid:{self.column}"]) * _f(
                    np, inputs[where_key(self.where)]
                )
                cached = (x, m)
                inputs[memo_key] = cached
            return cached
        x = xp.asarray(inputs[f"num:{self.column}"])
        m = _f(xp, inputs[f"valid:{self.column}"]) * _f(
            xp, inputs[where_key(self.where)]
        )
        return x, m

    def _moments(self, inputs: Dict[str, Any]) -> Dict[str, float]:
        """Host-fold fast path: ONE fused traversal per (column, where)
        family per batch computes count/sum/min/max/m2, shared by
        Mean/Sum/Minimum/Maximum/StandardDeviation via a per-batch memo —
        the host analogue of the device pass where XLA CSE shares the
        masked subexpressions. Native C when available, compacted numpy
        otherwise; both match the generic formulas within 1e-12."""
        memo_key = f"__moments:{self.column}:{where_key(self.where)}"
        cached = inputs.get(memo_key)
        if cached is None:
            from deequ_tpu.ops import native

            x = np.asarray(inputs[f"num:{self.column}"])
            valid = np.asarray(inputs[f"valid:{self.column}"])
            where = (
                None
                if self.where is None
                else np.asarray(inputs[where_key(self.where)])
            )
            out = None
            if x.dtype == np.float64 and valid.dtype == np.bool_ and (
                where is None or where.dtype == np.bool_
            ):
                out = native.masked_moments(x, valid, where)
            if out is not None:
                cached = {
                    "count": float(out[0]),
                    "sum": float(out[1]),
                    "min": float(out[2]),
                    "max": float(out[3]),
                    "m2": float(out[4]),
                    "n_where": float(out[5]),
                    "n_rows": float(len(x)),
                }
            else:
                mask = (
                    valid.astype(bool)
                    if where is None
                    else (valid.astype(bool) & where.astype(bool))
                )
                xm = np.asarray(x, dtype=np.float64)[mask]
                count = float(xm.size)
                total = float(xm.sum()) if xm.size else 0.0
                avg = total / max(count, 1.0)
                cached = {
                    "count": count,
                    "sum": total,
                    "min": float(xm.min()) if xm.size else float("inf"),
                    "max": float(xm.max()) if xm.size else float("-inf"),
                    "m2": float(((xm - avg) ** 2).sum()) if xm.size else 0.0,
                }
            inputs[memo_key] = cached
        return cached

    def compute_metric_from(self, state: Optional[State]) -> Metric:
        if state is None:
            return self.empty_state_failure()
        return DoubleMetric(
            self.entity, self.name, self.instance, Success(state.metric_value())
        )


def _pallas_moments(x, m):
    """(count, sum, min, max) via the single-HBM-pass pallas fold when
    the knob/platform/shape allow, else None — the caller then runs its
    XLA fold. Blocked summation is a different float order, so whenever
    this fires the plan signature carries the "pallas-folds" variant
    (runtime.fold_variant()) and cached states never cross arithmetics."""
    from deequ_tpu.ops import pallas_kernels

    return pallas_kernels.fold_moments_or_none(x, m)


@dataclass(frozen=True)
class Mean(_NumericScanAnalyzer):
    """reference: analyzers/Mean.scala:36."""

    column: str
    where: Optional[str] = None

    @property
    def name(self) -> str:
        return "Mean"

    def device_reduce(self, inputs: Dict[str, Any], xp) -> Any:
        if xp is np:
            mom = self._moments(inputs)
            return {"total": mom["sum"], "count": mom["count"]}
        x, m = self._masked(inputs, xp)
        folded = _pallas_moments(x, m)
        if folded is not None:
            count, total, _mn, _mx = folded
            return {"total": total, "count": count}
        return {"total": xp.sum(x * m), "count": xp.sum(m)}

    def merge_agg(self, a: Any, b: Any, xp) -> Any:
        return {"total": a["total"] + b["total"], "count": a["count"] + b["count"]}

    def unshift_agg(self, agg: Any, shifts: Dict[str, float]) -> Any:
        s = shifts.get(f"num:{self.column}", 0.0)
        if s == 0.0:
            return agg
        return {"total": agg["total"] + s * agg["count"], "count": agg["count"]}

    def state_from_aggregates(self, agg: Any) -> Optional[State]:
        if int(agg["count"]) == 0:
            return None
        return MeanState(float(agg["total"]), int(agg["count"]))

    def __repr__(self) -> str:
        return f"Mean({self.column},{render_where(self.where)})"


@dataclass(frozen=True)
class Sum(_NumericScanAnalyzer):
    """reference: analyzers/Sum.scala:36."""

    column: str
    where: Optional[str] = None

    @property
    def name(self) -> str:
        return "Sum"

    def device_reduce(self, inputs: Dict[str, Any], xp) -> Any:
        if xp is np:
            mom = self._moments(inputs)
            return {"sum": mom["sum"], "count": mom["count"]}
        x, m = self._masked(inputs, xp)
        folded = _pallas_moments(x, m)
        if folded is not None:
            count, total, _mn, _mx = folded
            return {"sum": total, "count": count}
        return {"sum": xp.sum(x * m), "count": xp.sum(m)}

    def merge_agg(self, a: Any, b: Any, xp) -> Any:
        return {"sum": a["sum"] + b["sum"], "count": a["count"] + b["count"]}

    def unshift_agg(self, agg: Any, shifts: Dict[str, float]) -> Any:
        s = shifts.get(f"num:{self.column}", 0.0)
        if s == 0.0:
            return agg
        return {"sum": agg["sum"] + s * agg["count"], "count": agg["count"]}

    def state_from_aggregates(self, agg: Any) -> Optional[State]:
        if int(agg["count"]) == 0:
            return None
        return SumState(float(agg["sum"]))

    def __repr__(self) -> str:
        return f"Sum({self.column},{render_where(self.where)})"


@dataclass(frozen=True)
class Minimum(_NumericScanAnalyzer):
    """reference: analyzers/Minimum.scala:36."""

    column: str
    where: Optional[str] = None

    @property
    def name(self) -> str:
        return "Minimum"

    def device_reduce(self, inputs: Dict[str, Any], xp) -> Any:
        if xp is np:
            mom = self._moments(inputs)
            return {"min": mom["min"], "count": mom["count"]}
        x, m = self._masked(inputs, xp)
        folded = _pallas_moments(x, m)
        if folded is not None:
            count, _total, mn, _mx = folded
            return {"min": mn, "count": count}
        masked = xp.where(m > 0, x, xp.inf)
        return {"min": xp.min(masked), "count": xp.sum(m)}

    def merge_agg(self, a: Any, b: Any, xp) -> Any:
        return {"min": xp.minimum(a["min"], b["min"]), "count": a["count"] + b["count"]}

    def unshift_agg(self, agg: Any, shifts: Dict[str, float]) -> Any:
        s = shifts.get(f"num:{self.column}", 0.0)
        if s == 0.0:
            return agg
        return {"min": agg["min"] + s, "count": agg["count"]}

    def state_from_aggregates(self, agg: Any) -> Optional[State]:
        if int(agg["count"]) == 0:
            return None
        return MinState(float(agg["min"]))

    def __repr__(self) -> str:
        return f"Minimum({self.column},{render_where(self.where)})"


@dataclass(frozen=True)
class Maximum(_NumericScanAnalyzer):
    """reference: analyzers/Maximum.scala:36."""

    column: str
    where: Optional[str] = None

    @property
    def name(self) -> str:
        return "Maximum"

    def device_reduce(self, inputs: Dict[str, Any], xp) -> Any:
        if xp is np:
            mom = self._moments(inputs)
            return {"max": mom["max"], "count": mom["count"]}
        x, m = self._masked(inputs, xp)
        folded = _pallas_moments(x, m)
        if folded is not None:
            count, _total, _mn, mx = folded
            return {"max": mx, "count": count}
        masked = xp.where(m > 0, x, -xp.inf)
        return {"max": xp.max(masked), "count": xp.sum(m)}

    def merge_agg(self, a: Any, b: Any, xp) -> Any:
        return {"max": xp.maximum(a["max"], b["max"]), "count": a["count"] + b["count"]}

    def unshift_agg(self, agg: Any, shifts: Dict[str, float]) -> Any:
        s = shifts.get(f"num:{self.column}", 0.0)
        if s == 0.0:
            return agg
        return {"max": agg["max"] + s, "count": agg["count"]}

    def state_from_aggregates(self, agg: Any) -> Optional[State]:
        if int(agg["count"]) == 0:
            return None
        return MaxState(float(agg["max"]))

    def __repr__(self) -> str:
        return f"Maximum({self.column},{render_where(self.where)})"


@dataclass(frozen=True)
class StandardDeviation(_NumericScanAnalyzer):
    """Population stddev via per-batch centered moments + Chan merge
    (reference: analyzers/StandardDeviation.scala:47, kernel
    catalyst/StatefulStdDevPop.scala:24). The batch pass computes the mean
    first, then sums centered squares — two reads of HBM, full accuracy in
    f32."""

    column: str
    where: Optional[str] = None

    @property
    def name(self) -> str:
        return "StandardDeviation"

    def device_reduce(self, inputs: Dict[str, Any], xp) -> Any:
        if xp is np:
            mom = self._moments(inputs)
            n = mom["count"]
            return {
                "n": n,
                "avg": mom["sum"] / n if n > 0 else 0.0,
                "m2": mom["m2"],
            }
        x, m = self._masked(inputs, xp)
        folded = _pallas_moments(x, m)
        if folded is not None:
            from deequ_tpu.ops import pallas_kernels

            n, total, _mn, _mx = folded
            safe_n = xp.maximum(n, 1.0)
            avg = total / safe_n
            m2 = pallas_kernels.masked_centered_sumsq(x, m, avg)
            return {"n": n, "avg": xp.where(n > 0, avg, 0.0), "m2": m2}
        n = xp.sum(m)
        safe_n = xp.maximum(n, 1.0)
        avg = xp.sum(x * m) / safe_n
        m2 = xp.sum(((x - avg) * m) ** 2)
        return {"n": n, "avg": xp.where(n > 0, avg, 0.0), "m2": m2}

    def merge_agg(self, a: Any, b: Any, xp) -> Any:
        n = a["n"] + b["n"]
        safe_n = xp.maximum(n, 1.0)
        delta = b["avg"] - a["avg"]
        avg = (a["n"] * a["avg"] + b["n"] * b["avg"]) / safe_n
        m2 = a["m2"] + b["m2"] + delta * delta * a["n"] * b["n"] / safe_n
        return {"n": n, "avg": xp.where(n > 0, avg, 0.0), "m2": m2}

    def unshift_agg(self, agg: Any, shifts: Dict[str, float]) -> Any:
        s = shifts.get(f"num:{self.column}", 0.0)
        if s == 0.0:
            return agg
        return {"n": agg["n"], "avg": agg["avg"] + s, "m2": agg["m2"]}

    def state_from_aggregates(self, agg: Any) -> Optional[State]:
        if float(agg["n"]) == 0:
            return None
        return StandardDeviationState(float(agg["n"]), float(agg["avg"]), float(agg["m2"]))

    def __repr__(self) -> str:
        return f"StandardDeviation({self.column},{render_where(self.where)})"


@dataclass(frozen=True)
class Correlation(ScanShareableAnalyzer):
    """Pearson r via per-batch centered co-moments + pairwise merge
    (reference: analyzers/Correlation.scala:65, kernel
    catalyst/StatefulCorrelation.scala:24). Rows enter only when BOTH
    columns are non-null."""

    first_column: str
    second_column: str
    where: Optional[str] = None

    @property
    def name(self) -> str:
        return "Correlation"

    @property
    def instance(self) -> str:
        return f"{self.first_column},{self.second_column}"

    @property
    def entity(self) -> Entity:
        return Entity.MULTICOLUMN

    def preconditions(self) -> List[Callable[[Table], None]]:
        return [
            Preconditions.has_column(self.first_column),
            Preconditions.is_numeric(self.first_column),
            Preconditions.has_column(self.second_column),
            Preconditions.is_numeric(self.second_column),
        ]

    def input_specs(self) -> List[InputSpec]:
        return [
            col_values_spec(self.first_column),
            col_valid_spec(self.first_column),
            col_values_spec(self.second_column),
            col_valid_spec(self.second_column),
            where_spec(self.where),
        ]

    def device_reduce(self, inputs: Dict[str, Any], xp) -> Any:
        x = xp.asarray(inputs[f"num:{self.first_column}"])
        y = xp.asarray(inputs[f"num:{self.second_column}"])
        m = (
            _f(xp, inputs[f"valid:{self.first_column}"])
            * _f(xp, inputs[f"valid:{self.second_column}"])
            * _f(xp, inputs[where_key(self.where)])
        )
        n = xp.sum(m)
        safe_n = xp.maximum(n, 1.0)
        x_avg = xp.sum(x * m) / safe_n
        y_avg = xp.sum(y * m) / safe_n
        xc = (x - x_avg) * m
        yc = (y - y_avg) * m
        return {
            "n": n,
            "x_avg": xp.where(n > 0, x_avg, 0.0),
            "y_avg": xp.where(n > 0, y_avg, 0.0),
            "ck": xp.sum(xc * yc),
            "x_mk": xp.sum(xc * xc),
            "y_mk": xp.sum(yc * yc),
        }

    def merge_agg(self, a: Any, b: Any, xp) -> Any:
        n = a["n"] + b["n"]
        safe_n = xp.maximum(n, 1.0)
        dx = b["x_avg"] - a["x_avg"]
        dy = b["y_avg"] - a["y_avg"]
        frac = b["n"] / safe_n
        cross = a["n"] * b["n"] / safe_n
        return {
            "n": n,
            "x_avg": a["x_avg"] + dx * frac,
            "y_avg": a["y_avg"] + dy * frac,
            "ck": a["ck"] + b["ck"] + dx * dy * cross,
            "x_mk": a["x_mk"] + b["x_mk"] + dx * dx * cross,
            "y_mk": a["y_mk"] + b["y_mk"] + dy * dy * cross,
        }

    def unshift_agg(self, agg: Any, shifts: Dict[str, float]) -> Any:
        sx = shifts.get(f"num:{self.first_column}", 0.0)
        sy = shifts.get(f"num:{self.second_column}", 0.0)
        if sx == 0.0 and sy == 0.0:
            return agg
        out = dict(agg)
        out["x_avg"] = agg["x_avg"] + sx
        out["y_avg"] = agg["y_avg"] + sy
        return out

    def state_from_aggregates(self, agg: Any) -> Optional[State]:
        if float(agg["n"]) == 0:
            return None
        return CorrelationState(
            float(agg["n"]),
            float(agg["x_avg"]),
            float(agg["y_avg"]),
            float(agg["ck"]),
            float(agg["x_mk"]),
            float(agg["y_mk"]),
        )

    def compute_metric_from(self, state: Optional[State]) -> Metric:
        if state is None:
            return self.empty_state_failure()
        return DoubleMetric(
            self.entity, self.name, self.instance, Success(state.metric_value())
        )

    def __repr__(self) -> str:
        return (
            f"Correlation({self.first_column},{self.second_column},"
            f"{render_where(self.where)})"
        )


# ---------------------------------------------------------------------------
# DataType
# ---------------------------------------------------------------------------


class DataTypeInstances:
    UNKNOWN = "Unknown"
    FRACTIONAL = "Fractional"
    INTEGRAL = "Integral"
    BOOLEAN = "Boolean"
    STRING = "String"


# class codes used on device: order matches DataTypeHistogram fields
# (value classification itself — the reference's regexes
# catalyst/StatefulDataType.scala:36-38 — is the vectorized kernel in
# deequ_tpu/ops/strings.py:classify, run over unique values only)
from deequ_tpu.ops.strings import (  # noqa: E402
    CODE_BOOLEAN as _CODE_BOOLEAN,
    CODE_FRACTIONAL as _CODE_FRACTIONAL,
    CODE_INTEGRAL as _CODE_INTEGRAL,
    CODE_NULL as _CODE_NULL,
    CODE_STRING as _CODE_STRING,
)


def _classified_dict(col) -> np.ndarray:
    """int8 class code per dictionary entry, memoized on the ROOT column
    and across stream batches via the dictionary content digest (one
    classify pass per distinct dictionary — consumed by both the
    per-row dtclass codes and the counts-based DataType shortcut)."""
    from deequ_tpu.data.table import cached_dictionary_encode
    from deequ_tpu.ops.strings import classify

    return cached_dictionary_encode(
        col,
        "dtclassdict",
        lambda c: classify(np.asarray(c.dict_encode()[1])).astype(np.int8),
    )


def _dtclass_spec(column: str) -> InputSpec:
    def compute(col) -> np.ndarray:
        from deequ_tpu.ops.strings import classify

        if col.ctype == ColumnType.STRING:
            # classify unique strings only; null rows map to the NULL
            # class. int8: 5 classes, and the narrow dtype is both the
            # wire format and the host bincount fast path
            from deequ_tpu.data.table import gather_with_null

            dict_codes, _uniques = col.dict_encode()
            return gather_with_null(
                _classified_dict(col), dict_codes, _CODE_NULL
            )
        # typed columns classify statically from the stringified form
        static = {
            ColumnType.LONG: _CODE_INTEGRAL,
            ColumnType.DOUBLE: _CODE_FRACTIONAL,
            ColumnType.DECIMAL: _CODE_FRACTIONAL,
            ColumnType.BOOLEAN: _CODE_BOOLEAN,
            ColumnType.TIMESTAMP: _CODE_STRING,
        }[col.ctype]
        return np.where(col.valid, np.int8(static), np.int8(_CODE_NULL))

    def build(t: Table) -> np.ndarray:
        from deequ_tpu.data.table import cached_column_encode

        # column-deterministic: memoized per table, sliced per batch
        return cached_column_encode(t.column(column), "dtclass", compute)

    return InputSpec(key=f"dtclass:{column}", build=build, columns=(column,))


@dataclass(frozen=True)
class DataType(ScanShareableAnalyzer):
    """Histogram over inferred value types + majority-type inference
    (reference: analyzers/DataType.scala:32-183). Rows excluded by `where`
    become NULL before classification (exactly like conditionalSelection
    feeding the reference UDAF), so they count as Unknown."""

    discrete_inputs = True  # code-only: host-foldable under placement
    column: str
    where: Optional[str] = None

    @property
    def name(self) -> str:
        return "Histogram"

    @property
    def instance(self) -> str:
        return self.column

    def preconditions(self) -> List[Callable[[Table], None]]:
        return [Preconditions.has_column(self.column)]

    def input_specs(self) -> List[InputSpec]:
        return [_dtclass_spec(self.column), where_spec(self.where), where_spec(None)]

    def device_reduce(self, inputs: Dict[str, Any], xp) -> Any:
        labels = ("null", "fractional", "integral", "boolean", "string")
        if xp is np and self.where is None:
            # a _LowCardCounts member counted this column's dictionary
            # this batch: classify the DICTIONARY and weigh the classes
            # by the per-entry counts — O(#uniques), and the per-row
            # class-code input is never built at all (lazy HostInputs)
            from deequ_tpu.ops import counts_family

            lcc = inputs.get(f"__lcccounts:{self.column}")
            if lcc is not None and counts_family.enabled():
                counts, uniques, n_batch = lcc
                rows_arr = np.asarray(inputs[where_key(None)], dtype=bool)
                if n_batch == len(rows_arr) and bool(rows_arr.all()):
                    cls = self._classified_dictionary(inputs, uniques)
                    counts_vec = np.zeros(len(labels), dtype=np.int64)
                    np.add.at(counts_vec, cls, np.asarray(counts[1:]))
                    counts_vec[_CODE_NULL] += int(counts[0])
                    return {
                        label: float(counts_vec[code])
                        for code, label in enumerate(labels)
                    }
        codes = xp.asarray(inputs[f"dtclass:{self.column}"])
        w = inputs[where_key(self.where)]
        rows = inputs[where_key(None)]
        if xp is np:
            # host fold: one bincount pass instead of 5 comparison scans;
            # where-filtered rows count as NULL class (conditionalSelection
            # semantics), padded rows (rows=False) drop out entirely
            from deequ_tpu.ops import native

            sel_codes = np.asarray(codes)
            w_arr = np.asarray(w, dtype=bool)
            rows_arr = np.asarray(rows, dtype=bool)
            w_all = bool(w_arr.all())
            rows_all = bool(rows_arr.all())
            if w_all and rows_all:
                mask = None
            elif w_all:
                mask = rows_arr
            elif rows_all:
                mask = w_arr
            else:
                mask = w_arr & rows_arr
            counts_vec = native.bincount(sel_codes, len(labels), where=mask)
            if counts_vec is None:
                if mask is not None:
                    sel_codes = sel_codes[mask]
                counts_vec = np.bincount(sel_codes, minlength=len(labels))
            if not w_all:
                # rows present but excluded by `where` classify as NULL
                n_rows = int(np.count_nonzero(rows_arr)) if not rows_all else len(rows_arr)
                n_in = int(counts_vec.sum())
                counts_vec = counts_vec.copy()
                counts_vec[_CODE_NULL] += n_rows - n_in
            return {
                label: float(counts_vec[code]) for code, label in enumerate(labels)
            }
        rows_f = _f(xp, rows)
        # where-filtered rows -> NULL class; padded rows excluded via `rows`
        codes = xp.where(xp.asarray(w), codes, _CODE_NULL)
        counts = {}
        for code, label in enumerate(labels):
            counts[label] = xp.sum(_f(xp, codes == code) * rows_f)
        return counts

    def _classified_dictionary(self, inputs, uniques) -> np.ndarray:
        """int8 class code per dictionary entry via the shared
        `_classified_dict` memo when the batch is reachable (one
        classify per table, shared with the per-row dtclass spec);
        plain classify otherwise."""
        from deequ_tpu.ops.strings import classify

        batch = getattr(inputs, "batch", None)
        if batch is not None:
            try:
                cls = _classified_dict(batch.column(self.column))
                if len(cls) == len(uniques):
                    return cls
            except Exception:  # noqa: BLE001 - fall back to direct classify
                pass
        return classify(np.asarray(uniques)).astype(np.int8)

    def merge_agg(self, a: Any, b: Any, xp) -> Any:
        return {k: a[k] + b[k] for k in a}

    def state_from_aggregates(self, agg: Any) -> Optional[State]:
        return DataTypeHistogram(
            int(agg["null"]),
            int(agg["fractional"]),
            int(agg["integral"]),
            int(agg["boolean"]),
            int(agg["string"]),
        )

    def compute_metric_from(self, state: Optional[State]) -> Metric:
        if state is None:
            return self.to_failure_metric_histogram()
        return HistogramMetric(
            Entity.COLUMN,
            self.name,
            self.column,
            Success(to_distribution(state)),
        )

    def to_failure_metric(self, exception: BaseException) -> Metric:
        from deequ_tpu.core.exceptions import wrap_if_necessary
        from deequ_tpu.core.maybe import Failure

        return HistogramMetric(
            Entity.COLUMN, self.name, self.column, Failure(wrap_if_necessary(exception))
        )

    def to_failure_metric_histogram(self) -> Metric:
        from deequ_tpu.core.exceptions import EmptyStateException

        return self.to_failure_metric(
            EmptyStateException(
                f"Empty state for analyzer {self!r}, all input values were NULL."
            )
        )

    def __repr__(self) -> str:
        return f"DataType({self.column},{render_where(self.where)})"


def to_distribution(hist: DataTypeHistogram) -> Distribution:
    """reference: analyzers/DataType.scala:100-115."""
    total = hist.total
    ratio = (lambda c: c / total) if total > 0 else (lambda c: float("nan"))
    return Distribution(
        {
            DataTypeInstances.UNKNOWN: DistributionValue(hist.num_null, ratio(hist.num_null)),
            DataTypeInstances.FRACTIONAL: DistributionValue(
                hist.num_fractional, ratio(hist.num_fractional)
            ),
            DataTypeInstances.INTEGRAL: DistributionValue(
                hist.num_integral, ratio(hist.num_integral)
            ),
            DataTypeInstances.BOOLEAN: DistributionValue(
                hist.num_boolean, ratio(hist.num_boolean)
            ),
            DataTypeInstances.STRING: DistributionValue(
                hist.num_string, ratio(hist.num_string)
            ),
        },
        number_of_bins=5,
    )


def determine_type(dist: Distribution) -> str:
    """Majority-type decision tree (reference: analyzers/DataType.scala:116-146)."""

    def ratio_of(key: str) -> float:
        v = dist.values.get(key)
        return v.ratio if v is not None else 0.0

    if ratio_of(DataTypeInstances.UNKNOWN) == 1.0:
        return DataTypeInstances.UNKNOWN
    if ratio_of(DataTypeInstances.STRING) > 0.0 or (
        ratio_of(DataTypeInstances.BOOLEAN) > 0.0
        and (
            ratio_of(DataTypeInstances.INTEGRAL) > 0.0
            or ratio_of(DataTypeInstances.FRACTIONAL) > 0.0
        )
    ):
        return DataTypeInstances.STRING
    if ratio_of(DataTypeInstances.BOOLEAN) > 0.0:
        return DataTypeInstances.BOOLEAN
    if ratio_of(DataTypeInstances.FRACTIONAL) > 0.0:
        return DataTypeInstances.FRACTIONAL
    return DataTypeInstances.INTEGRAL

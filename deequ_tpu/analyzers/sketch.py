"""Sketch-based analyzers: bounded-memory approximations.

ApproxCountDistinct: host hashes values (vectorized xxhash64), the device
scatter-maxes HLL registers inside the fused pass, merges are register-wise
max (reference: analyzers/ApproxCountDistinct.scala:47 + catalyst kernel).

ApproxQuantile(s): per-batch KLL partial sketches folded on the host —
the host-reduce stage of the fused pass (same single logical scan;
reference: analyzers/ApproxQuantile.scala:49, ApproxQuantiles.scala:39).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deequ_tpu.analyzers.base import (
    InputSpec,
    Preconditions,
    ScanShareableAnalyzer,
    col_valid_spec,
    col_values_spec,
    render_where,
    where_key,
    where_spec,
)
from deequ_tpu.analyzers.states import DoubleValuedState, State
from deequ_tpu.core.exceptions import IllegalAnalyzerParameterException
from deequ_tpu.core.maybe import Success
from deequ_tpu.core.metrics import DoubleMetric, Entity, KeyedDoubleMetric, Metric
from deequ_tpu.data.table import Table
from deequ_tpu.ops.sketches import hll
from deequ_tpu.ops.sketches.kll import KLLSketch, k_for_error


# ---------------------------------------------------------------------------
# ApproxCountDistinct
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ApproxCountDistinctState(DoubleValuedState):
    """HLL registers (reference: ApproxCountDistinct.scala:26 — merge is
    register-wise max)."""

    registers: np.ndarray

    def merge(self, other: "ApproxCountDistinctState") -> "ApproxCountDistinctState":
        return ApproxCountDistinctState(hll.merge_registers(self.registers, other.registers))

    def metric_value(self) -> float:
        return hll.estimate(self.registers)

    def words(self) -> np.ndarray:
        return hll.pack_words(self.registers)

    def __eq__(self, other) -> bool:
        return isinstance(other, ApproxCountDistinctState) and np.array_equal(
            self.registers, other.registers
        )

    def __hash__(self) -> int:
        return hash(self.registers.tobytes())


def _hll_spec(column: str) -> InputSpec:
    """One int32 per row packing (register idx << 6 | rank) so the column
    is hashed exactly once per batch; invalid rows pack to 0 (idx 0,
    rank 0 — a no-op for the scatter-max)."""

    def build(t: Table) -> np.ndarray:
        col = t.column(column)
        if col.values.dtype == object:
            # share the batch's dict-encode; hash unique strings only
            from deequ_tpu.ops.strings import hash_strings

            codes, uniques = col.dict_encode()
            idx_u, rank_u = hll.registers_from_hashes(hash_strings(uniques))
            packed = np.zeros(len(col), dtype=np.int32)
            sel = codes >= 0
            packed[sel] = ((idx_u << 6) | rank_u)[codes[sel]]
            return packed
        hashes = hll.hash_column(col.values, col.valid)
        idx_v, rank_v = hll.registers_from_hashes(hashes)
        packed = np.zeros(len(col), dtype=np.int32)
        packed[col.valid] = (idx_v << 6) | rank_v
        return packed

    return InputSpec(key=f"hll:{column}", build=build)


@dataclass(frozen=True)
class ApproxCountDistinct(ScanShareableAnalyzer):
    """HLL++ distinct estimate (reference: analyzers/ApproxCountDistinct.scala:47)."""

    column: str
    where: Optional[str] = None

    @property
    def name(self) -> str:
        return "ApproxCountDistinct"

    @property
    def instance(self) -> str:
        return self.column

    def preconditions(self) -> List[Callable[[Table], None]]:
        return [Preconditions.has_column(self.column)]

    def input_specs(self) -> List[InputSpec]:
        return [_hll_spec(self.column), where_spec(self.where)]

    def device_reduce(self, inputs: Dict[str, Any], xp) -> Any:
        packed = xp.asarray(inputs[f"hll:{self.column}"])
        w = inputs[where_key(self.where)]
        idx = packed >> 6
        rank = packed & 0x3F
        masked_rank = xp.where(xp.asarray(w), rank, 0)
        if xp is np:
            registers = np.zeros(hll.M, dtype=np.int32)
            np.maximum.at(registers, np.asarray(idx), masked_rank)
            return {"registers": registers}
        registers = xp.zeros(hll.M, dtype=masked_rank.dtype).at[idx].max(masked_rank)
        return {"registers": registers}

    def merge_agg(self, a: Any, b: Any, xp) -> Any:
        return {"registers": xp.maximum(a["registers"], b["registers"])}

    def state_from_aggregates(self, agg: Any) -> Optional[State]:
        return ApproxCountDistinctState(
            np.asarray(agg["registers"]).astype(np.int32)
        )

    def compute_metric_from(self, state: Optional[State]) -> Metric:
        if state is None:
            return self.empty_state_failure()
        return DoubleMetric(
            self.entity, self.name, self.instance, Success(state.metric_value())
        )

    def __repr__(self) -> str:
        return f"ApproxCountDistinct({self.column},{render_where(self.where)})"


# ---------------------------------------------------------------------------
# ApproxQuantile(s) — host-reduced members of the fused pass
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ApproxQuantileState(State):
    """Mergeable quantile digest (reference: ApproxQuantile.scala:28-35)."""

    digest: KLLSketch

    def merge(self, other: "ApproxQuantileState") -> "ApproxQuantileState":
        return ApproxQuantileState(self.digest.merge(other.digest))

    def __eq__(self, other) -> bool:
        if not isinstance(other, ApproxQuantileState):
            return False
        k1, n1, l1 = self.digest.to_arrays()
        k2, n2, l2 = other.digest.to_arrays()
        return (
            k1 == k2
            and n1 == n2
            and len(l1) == len(l2)
            and all(np.array_equal(a, b) for a, b in zip(l1, l2))
        )

    def __hash__(self) -> int:
        return hash((self.digest.k, self.digest.n))


def _quantile_param_check(quantile: float) -> Callable[[Table], None]:
    def check(table: Table) -> None:
        if not (0.0 <= quantile <= 1.0):
            raise IllegalAnalyzerParameterException(
                "Quantile parameter must be in the closed interval [0, 1]. "
                f"Currently, the value is: {quantile}!"
            )

    return check


def _relative_error_param_check(relative_error: float) -> Callable[[Table], None]:
    def check(table: Table) -> None:
        if not (0.0 <= relative_error <= 1.0):
            raise IllegalAnalyzerParameterException(
                "Relative error parameter must be in the closed interval [0, 1]. "
                f"Currently, the value is: {relative_error}!"
            )

    return check


import itertools

# itertools.count.__next__ is atomic under the GIL: shard reducers may
# run concurrently in the distributed pass's thread pool
_BATCH_SEED_COUNTER = itertools.count(1)


def _next_batch_seed() -> int:
    """Distinct seed per batch sketch: KLL's error bound needs independent
    compaction offsets across merged partials."""
    return next(_BATCH_SEED_COUNTER)


class _QuantileAnalyzerBase(ScanShareableAnalyzer):
    """Shared host-reduce machinery: one KLL partial per batch."""

    host_reduced = True

    def input_specs(self) -> List[InputSpec]:
        return []

    def host_prepare(self) -> Callable[[Table], Optional[State]]:
        """Per-pass setup: parse the filter once; a bad predicate fails this
        analyzer alone (matching the device path's spec isolation)."""
        where = getattr(self, "where", None)
        predicate = None
        if where is not None:
            from deequ_tpu.data.expr import Predicate

            predicate = Predicate(where)
        k = k_for_error(self.relative_error)

        def reduce(batch: Table) -> Optional[State]:
            col = batch.column(self.column)
            values, valid = col.numeric_values()
            mask = valid if predicate is None else valid & predicate.eval_mask(batch)
            selected = values[mask]
            if len(selected) == 0:
                return None
            sketch = KLLSketch(k=k, seed=_next_batch_seed())
            sketch.update_batch(selected)
            return ApproxQuantileState(sketch)

        return reduce


@dataclass(frozen=True)
class ApproxQuantile(_QuantileAnalyzerBase):
    """Single quantile (reference: analyzers/ApproxQuantile.scala:49)."""

    column: str
    quantile: float
    relative_error: float = 0.01
    where: Optional[str] = None

    @property
    def name(self) -> str:
        return "ApproxQuantile"

    @property
    def instance(self) -> str:
        return self.column

    def preconditions(self) -> List[Callable[[Table], None]]:
        return [
            _quantile_param_check(self.quantile),
            _relative_error_param_check(self.relative_error),
            Preconditions.has_column(self.column),
            Preconditions.is_numeric(self.column),
        ]

    def compute_metric_from(self, state: Optional[State]) -> Metric:
        if state is None:
            return self.empty_state_failure()
        return DoubleMetric(
            self.entity,
            self.name,
            self.instance,
            Success(state.digest.quantile(self.quantile)),
        )

    def __repr__(self) -> str:
        # `where` is our extension over the reference signature
        # (reference: ApproxQuantile.scala:49 has no filter); render it only
        # when set so the default matches the reference toString
        base = f"ApproxQuantile({self.column},{self.quantile},{self.relative_error}"
        if self.where is not None:
            return base + f",{render_where(self.where)})"
        return base + ")"


@dataclass(frozen=True)
class ApproxQuantiles(_QuantileAnalyzerBase):
    """Many quantiles from one digest -> KeyedDoubleMetric
    (reference: analyzers/ApproxQuantiles.scala:39)."""

    column: str
    quantiles: Tuple[float, ...]
    relative_error: float = 0.01

    def __init__(self, column: str, quantiles, relative_error: float = 0.01):
        object.__setattr__(self, "column", column)
        object.__setattr__(self, "quantiles", tuple(quantiles))
        object.__setattr__(self, "relative_error", relative_error)

    @property
    def name(self) -> str:
        return "ApproxQuantiles"

    @property
    def instance(self) -> str:
        return self.column

    def preconditions(self) -> List[Callable[[Table], None]]:
        return (
            [_quantile_param_check(q) for q in self.quantiles]
            + [
                _relative_error_param_check(self.relative_error),
                Preconditions.has_column(self.column),
                Preconditions.is_numeric(self.column),
            ]
        )

    def compute_metric_from(self, state: Optional[State]) -> Metric:
        if state is None:
            from deequ_tpu.core.exceptions import EmptyStateException
            from deequ_tpu.core.maybe import Failure

            return KeyedDoubleMetric(
                self.entity,
                self.name,
                self.instance,
                Failure(
                    EmptyStateException(
                        f"Empty state for analyzer {self!r}, all input values were NULL."
                    )
                ),
            )
        values = state.digest.quantiles(list(self.quantiles))
        keyed = {_format_quantile(q): v for q, v in zip(self.quantiles, values)}
        return KeyedDoubleMetric(self.entity, self.name, self.instance, Success(keyed))

    def to_failure_metric(self, exception: BaseException) -> Metric:
        from deequ_tpu.core.exceptions import wrap_if_necessary
        from deequ_tpu.core.maybe import Failure

        return KeyedDoubleMetric(
            self.entity, self.name, self.instance, Failure(wrap_if_necessary(exception))
        )

    def __repr__(self) -> str:
        qs = ", ".join(_format_quantile(q) for q in self.quantiles)
        return f"ApproxQuantiles({self.column},List({qs}),{self.relative_error})"


def _format_quantile(q: float) -> str:
    return repr(float(q))

"""Sketch-based analyzers: bounded-memory approximations.

ApproxCountDistinct: host hashes values (vectorized xxhash64), the device
scatter-maxes HLL registers inside the fused pass, merges are register-wise
max (reference: analyzers/ApproxCountDistinct.scala:47 + catalyst kernel).

ApproxQuantile(s): per-batch KLL partial sketches folded on the host —
the host-reduce stage of the fused pass (same single logical scan;
reference: analyzers/ApproxQuantile.scala:49, ApproxQuantiles.scala:39).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from deequ_tpu.analyzers.base import (
    InputSpec,
    Preconditions,
    ScanShareableAnalyzer,
    col_valid_spec,
    col_values_spec,
    render_where,
    where_key,
    where_spec,
)
from deequ_tpu.analyzers.states import DoubleValuedState, State
from deequ_tpu.core.exceptions import IllegalAnalyzerParameterException
from deequ_tpu.core.maybe import Success
from deequ_tpu.core.metrics import DoubleMetric, KeyedDoubleMetric, Metric
from deequ_tpu.data.table import Table
from deequ_tpu.ops.sketches import hll
from deequ_tpu.ops.sketches.kll import KLLSketch, k_for_error


# ---------------------------------------------------------------------------
# ApproxCountDistinct
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ApproxCountDistinctState(DoubleValuedState):
    """HLL registers (reference: ApproxCountDistinct.scala:26 — merge is
    register-wise max)."""

    registers: np.ndarray

    def merge(self, other: "ApproxCountDistinctState") -> "ApproxCountDistinctState":
        return ApproxCountDistinctState(hll.merge_registers(self.registers, other.registers))

    def metric_value(self) -> float:
        return hll.estimate(self.registers)

    def words(self) -> np.ndarray:
        return hll.pack_words(self.registers)

    def __eq__(self, other) -> bool:
        return isinstance(other, ApproxCountDistinctState) and np.array_equal(
            self.registers, other.registers
        )

    def __hash__(self) -> int:
        return hash(self.registers.tobytes())


def _hist16_available(n: int) -> bool:
    """Pallas hist16 usable for this batch shape (TPU platform + block
    multiple); interpret-mode tests monkeypatch this.

    The n <= 2^24 cap keeps the kernel exact: hist16 accumulates bin
    counts in float32 (MXU tiles), which counts exactly only up to
    2^24 per bin. A low-cardinality column in an oversized explicit
    FusedScanPass(batch_size=...) batch could push one bin past that
    and silently corrupt counts/ranks, so such batches fall back to
    the sort path instead."""
    from deequ_tpu.ops import pallas_kernels

    return (
        n <= (1 << 24)
        and pallas_kernels.shape_supported(n)
        and pallas_kernels.usable()
    )


_BOOL_HLL = None


def _bool_hll_identities():
    """(idx, rank, packed) for the two canonical boolean identities
    (int64 0/1) — ONE definition shared by the per-row gather spec and
    the _LowCardCounts presence shortcut, computed once."""
    global _BOOL_HLL
    if _BOOL_HLL is None:
        from deequ_tpu.ops.sketches.hll import xxhash64_u64

        idx, rank = hll.registers_from_hashes(
            xxhash64_u64(np.array([0, 1], dtype=np.int64))
        )
        packed = ((idx << 6) | rank).astype(np.int32)
        _BOOL_HLL = (idx, rank, packed)
    return _BOOL_HLL


def _hll_spec(column: str) -> InputSpec:
    """One int32 per row packing (register idx << 6 | rank) so the column
    is hashed exactly once per batch; invalid rows pack to 0 (idx 0,
    rank 0 — a no-op for the scatter-max)."""

    def compute(col) -> np.ndarray:
        from deequ_tpu.data.table import ColumnType

        if col.ctype == ColumnType.STRING:
            # share the batch's dict-encode; hash unique strings only
            # (cross-batch dictionary memo); null rows map to packed
            # code 0 (idx 0, rank 0 — a no-op for the scatter-max)
            from deequ_tpu.data.table import (
                gather_with_null,
                hashed_dictionary,
            )

            codes, _uniques = col.dict_encode()
            idx_u, rank_u = hll.registers_from_hashes(hashed_dictionary(col))
            return gather_with_null(
                ((idx_u << 6) | rank_u).astype(np.int32), codes, 0
            )
        if col.ctype == ColumnType.BOOLEAN:
            # two possible identities (canonical int64 0/1): hash them
            # once and gather — no per-row hashing
            _idx, _rank, packed_u = _bool_hll_identities()
            return np.where(
                col.valid, packed_u[col.values.view(np.uint8)], np.int32(0)
            )
        # one-pass C kernel when available, identical numpy codes otherwise
        return hll.pack_codes(col.values, col.valid)

    def build(t: Table) -> np.ndarray:
        from deequ_tpu.data.table import cached_column_encode

        # column-deterministic: memoized per table, sliced per batch
        return cached_column_encode(t.column(column), "hll_packed", compute)

    return InputSpec(key=f"hll:{column}", build=build, columns=(column,))


@dataclass(frozen=True)
class ApproxCountDistinct(ScanShareableAnalyzer):
    """HLL++ distinct estimate (reference: analyzers/ApproxCountDistinct.scala:47)."""

    discrete_inputs = True  # packed idx|rank codes: host-foldable
    column: str
    where: Optional[str] = None

    @property
    def name(self) -> str:
        return "ApproxCountDistinct"

    @property
    def instance(self) -> str:
        return self.column

    def preconditions(self) -> List[Callable[[Table], None]]:
        return [Preconditions.has_column(self.column)]

    def input_specs(self) -> List[InputSpec]:
        return [_hll_spec(self.column), where_spec(self.where)]

    def device_reduce(self, inputs: Dict[str, Any], xp) -> Any:
        if xp is np:
            # fused-family kernel already produced this column's
            # registers this batch? (checked BEFORE touching the packed
            # hash input, which then never gets built under HostInputs)
            regs = inputs.get(f"__hllregs:{self.column}:{where_key(self.where)}")
            if regs is not None:
                return {"registers": np.asarray(regs)}
            if self.where is None:
                # a bool column counted this batch (_LowCardCounts):
                # registers from the ≤2 present canonical identities
                pres_bool = inputs.get(f"__lccbool:{self.column}")
                if pres_bool is not None:
                    idx, rank, _packed = _bool_hll_identities()
                    registers = np.zeros(hll.M, dtype=np.int32)
                    for value, present in enumerate(pres_bool):
                        if present:
                            registers[idx[value]] = max(
                                registers[idx[value]], int(rank[value])
                            )
                    return {"registers": registers}
                # a string column whose dictionary presence was counted
                # this batch (_LowCardCounts): hash only the PRESENT
                # uniques — identical registers, no full-row scatter
                pres = inputs.get(f"__lccpresence:{self.column}")
                if pres is not None:
                    from deequ_tpu.ops.strings import hash_strings

                    present, uniques = pres
                    present = np.asarray(present)
                    # hash the FULL dictionary through the cross-batch
                    # memo when reachable (stream batches rebuild equal
                    # dictionaries), then select the present entries
                    hashes = None
                    batch = getattr(inputs, "batch", None)
                    if batch is not None:
                        try:
                            from deequ_tpu.data.table import (
                                hashed_dictionary,
                            )

                            full = hashed_dictionary(
                                batch.column(self.column)
                            )
                            if len(full) == len(present):
                                hashes = full[present]
                        except Exception:  # noqa: BLE001 - direct hash
                            hashes = None
                    if hashes is None:
                        hashes = hash_strings(
                            np.asarray(uniques, dtype=object)[present]
                        )
                    idx, rank = hll.registers_from_hashes(hashes)
                    registers = np.zeros(hll.M, dtype=np.int32)
                    np.maximum.at(registers, idx, rank.astype(np.int32))
                    return {"registers": registers}
        packed = xp.asarray(inputs[f"hll:{self.column}"])
        w = inputs[where_key(self.where)]
        if xp is np:
            from deequ_tpu.ops import native

            registers = np.zeros(hll.M, dtype=np.int32)
            where = np.asarray(w)
            if native.hll_update_registers(
                np.asarray(packed), None if where.all() else where, registers
            ):
                return {"registers": registers}
            masked_rank = np.where(where, packed & 0x3F, 0)
            np.maximum.at(registers, np.asarray(packed >> 6), masked_rank)
            return {"registers": registers}
        from deequ_tpu.ops import pallas_kernels

        if pallas_kernels.shape_supported(
            int(packed.shape[0])
        ) and pallas_kernels.usable():
            # pallas path: XLA serializes the 512-register scatter-max on
            # TPU; the blockwise one-hot kernel keeps it on the VPU
            masked_codes = xp.where(xp.asarray(w), packed, 0)
            return {
                "registers": pallas_kernels.hll_register_max(masked_codes)
            }
        idx = packed >> 6
        rank = packed & 0x3F
        masked_rank = xp.where(xp.asarray(w), rank, 0)
        registers = xp.zeros(hll.M, dtype=masked_rank.dtype).at[idx].max(masked_rank)
        return {"registers": registers}

    def merge_agg(self, a: Any, b: Any, xp) -> Any:
        return {"registers": xp.maximum(a["registers"], b["registers"])}

    def state_from_aggregates(self, agg: Any) -> Optional[State]:
        return ApproxCountDistinctState(
            np.asarray(agg["registers"]).astype(np.int32)
        )

    def compute_metric_from(self, state: Optional[State]) -> Metric:
        if state is None:
            return self.empty_state_failure()
        return DoubleMetric(
            self.entity, self.name, self.instance, Success(state.metric_value())
        )

    def __repr__(self) -> str:
        return f"ApproxCountDistinct({self.column},{render_where(self.where)})"


# ---------------------------------------------------------------------------
# ApproxQuantile(s) — host-reduced members of the fused pass
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ApproxQuantileState(State):
    """Mergeable quantile digest (reference: ApproxQuantile.scala:28-35)."""

    digest: KLLSketch

    def merge(self, other: "ApproxQuantileState") -> "ApproxQuantileState":
        return ApproxQuantileState(self.digest.merge(other.digest))

    def __eq__(self, other) -> bool:
        if not isinstance(other, ApproxQuantileState):
            return False
        k1, n1, l1 = self.digest.to_arrays()
        k2, n2, l2 = other.digest.to_arrays()
        return (
            k1 == k2
            and n1 == n2
            and len(l1) == len(l2)
            and all(np.array_equal(a, b) for a, b in zip(l1, l2))
        )

    def __hash__(self) -> int:
        return hash((self.digest.k, self.digest.n))


def _quantile_param_check(quantile: float) -> Callable[[Table], None]:
    def check(table: Table) -> None:
        if not (0.0 <= quantile <= 1.0):
            raise IllegalAnalyzerParameterException(
                "Quantile parameter must be in the closed interval [0, 1]. "
                f"Currently, the value is: {quantile}!"
            )

    return check


def _relative_error_param_check(relative_error: float) -> Callable[[Table], None]:
    def check(table: Table) -> None:
        if not (0.0 <= relative_error <= 1.0):
            raise IllegalAnalyzerParameterException(
                "Relative error parameter must be in the closed interval [0, 1]. "
                f"Currently, the value is: {relative_error}!"
            )

    return check


import zlib


def _batch_seed(sample: np.ndarray, n: int, level: int) -> int:
    """Deterministic per-batch sketch seed: KLL's error bound wants
    compaction offsets that decorrelate across merged partials, and the
    engine's differential contracts (pipeline on/off, engine parity,
    repeated runs in one process) need bit-identical results. Hashing
    the batch's own decimated sample gives both — distinct batches get
    distinct offsets, while a scan's outcome depends only on its inputs
    and fold order, never on which scans ran earlier in the process
    (the old global counter made every run order-sensitive). Pure
    function of the arguments: safe from concurrent shard reducers."""
    h = zlib.crc32(np.ascontiguousarray(sample, dtype=np.float64).tobytes())
    return (h ^ (int(n) * 0x9E3779B1) ^ (int(level) << 17)) & 0x7FFFFFFF


class _QuantileAnalyzerBase(ScanShareableAnalyzer):
    """Device-assisted member of the fused scan: the DEVICE does the
    heavy per-batch work — sort the masked column and stride-decimate to
    a fixed-size sample at a power-of-two level — inside the same XLA
    program as every other analyzer (sharing the column transfer); the
    HOST only merges each shard's decimated sample into the KLL at that
    level (exactly the `_bulk_insert` law whose rank-error bound is
    tested). This lowers the sketch's compactor work to an XLA sort, the
    north-star requirement, and makes quantiles scale with mesh devices
    via shard_map like every device-reduced analyzer.

    Precision note: on a float32 device engine (TPU with x64 off) the
    column is sorted in float32, so quantile RESULTS are quantized to one
    float32 ulp of the value's magnitude (e.g. ~2.7e8 for
    microsecond-epoch timestamps ~1.7e15). The rank error bound is
    unaffected. The CPU/x64 engine sketches exact float64.
    (reference: catalyst/StatefulApproxQuantile.scala:28 — the mergeable
    digest role; the sort+decimate replaces its per-row GK updates.)"""

    device_assisted = True

    def _sample_size(self) -> int:
        # one level's worth: n/stride lands in (k, 2k]
        return 2 * k_for_error(self.relative_error)

    def input_specs(self) -> List[InputSpec]:
        return [
            col_values_spec(self.column),
            col_valid_spec(self.column),
            where_spec(getattr(self, "where", None)),
        ]

    def device_batch(self, inputs: Dict[str, Any], xp) -> Any:
        if xp is np:
            # fused family kernel already ran for this batch? (fold_host_batch
            # precomputes moments+sample in one C traversal)
            memo = inputs.get(
                f"__qsample:{self.column}:"
                f"{where_key(getattr(self, 'where', None))}:{self._sample_size()}"
            )
            if memo is not None:
                return memo
        x = xp.asarray(inputs[f"num:{self.column}"])
        if xp is np:
            valid = np.asarray(inputs[f"valid:{self.column}"])
            where = inputs.get(where_key(getattr(self, "where", None)))
            if getattr(self, "where", None) is None:
                where = None
            from deequ_tpu.ops import native

            # host fold fastest path: C histogram-assisted selection
            # extracts the decimated sample (identical values) without
            # sorting the whole batch — ~10x less work than sort
            res = native.masked_select_decimate(
                x, valid, where, self._sample_size()
            )
            if res is not None:
                sample, n_valid, level = res
                return {
                    "sample": sample,
                    "n": np.asarray([n_valid], dtype=np.float64),
                    "level": np.asarray([level], dtype=np.int32),
                }
            # no native library: compact the masked rows ONCE and sort
            # only them (the generic path pays two float-mask temps plus a
            # full-length sort with +inf fillers — ~2x the work); the
            # decimated sample is identical because masked rows sort to
            # the tail either way
            mask = np.asarray(valid, dtype=bool)
            if where is not None:
                mask = mask & np.asarray(where, dtype=bool)
            xm = np.asarray(x, dtype=np.float64)[mask]
            n = xm.size
            if n == 0:
                return {
                    "sample": np.zeros(0, dtype=np.float64),
                    "n": np.zeros(1, dtype=np.float64),
                    "level": np.zeros(1, dtype=np.int32),
                }
            cap = self._sample_size()
            level = max(0, int(np.ceil(np.log2(max(n, 1) / cap))))
            stride = 1 << level
            offset = stride // 2
            kept = max(0, -(-(n - offset) // stride))
            # full sort of the compacted rows: numpy's vectorized introsort
            # beats a scalar C multiselect by ~5x here (measured), so the
            # "only k order statistics" trick does NOT pay on this host
            xm.sort()
            sample = xm[offset::stride][:kept]
            return {
                "sample": sample,
                "n": np.asarray([n], dtype=np.float64),
                "level": np.asarray([level], dtype=np.int32),
            }
        live = xp.asarray(inputs[f"valid:{self.column}"]).astype(bool) & xp.asarray(
            inputs[where_key(getattr(self, "where", None))]
        ).astype(bool)
        if (
            inputs.get("__single_device")
            and x.dtype == xp.float32
            and _hist16_available(int(x.shape[0]))
        ):
            # TPU radix-select: the MXU builds the full 16-bit histogram
            # of the sortable-key space (one-hot matmuls, ~1ns/row) and
            # the HOST walks the 65536 counts, gathering only the bins
            # that own a decimation rank (host_finish_batch) — replaces
            # the O(n log^2 n) bitonic device sort entirely.
            from deequ_tpu.ops import pallas_kernels

            bins = pallas_kernels.f32_sortable_bin16(x, live)
            return {
                "hist16": pallas_kernels.hist16(bins),
                "n": xp.sum(live.astype(x.dtype))[None],
            }
        m = live.astype(x.dtype)
        big = xp.asarray(xp.inf, dtype=x.dtype)
        vals = xp.where(m > 0, x, big)
        sorted_vals = xp.sort(vals)
        n = xp.sum(m)
        cap = self._sample_size()
        # stride = 2^ceil(log2(n/cap)) so the kept sample has <= cap items;
        # all index math in int32 (native on TPU; batches are < 2^31 rows)
        level = xp.maximum(
            0.0, xp.ceil(xp.log2(xp.maximum(n, 1.0) / cap))
        ).astype(xp.int32)
        stride = xp.asarray(1, dtype=xp.int32) << level
        offset = stride // 2  # midpoint decimation (deterministic)
        idx = xp.minimum(
            offset + stride * xp.arange(cap, dtype=xp.int32), len(vals) - 1
        )
        sample = sorted_vals[idx]
        return {
            "sample": sample,
            "n": n[None] if hasattr(n, "shape") else xp.asarray([n]),
            "level": level[None].astype(xp.int32),
        }

    def unshift_batch(self, out: Any, shifts) -> Any:
        s = shifts.get(f"num:{self.column}", 0.0)
        if s == 0.0 or "sample" not in out:
            return out
        return {**out, "sample": np.asarray(out["sample"], dtype=np.float64) + s}

    def host_finish_batch(self, out: Any, host_inputs, shifts) -> Any:
        """Finish the TPU hist16 radix-select: walk the 65536 counts to
        the wanted decimation ranks, gather ONLY the owning bins from the
        host-resident column, sort that sliver, read the samples off.
        Exactly the decimated sample the device sort path would produce
        (in the same float32 value space)."""
        if "hist16" not in out:
            return out
        counts = np.asarray(out["hist16"], dtype=np.float64).reshape(65536)
        # bins 65409..65535: positive-NaN key region (impossible for
        # valid rows under the NaN==NULL contract) + the mask sentinel —
        # never ranked. Bin 65408 is exactly +inf: kept.
        counts[65409:] = 0.0
        counts = counts.astype(np.int64)
        n = int(counts.sum())
        if n <= 0:
            return {
                "sample": np.zeros(0, dtype=np.float64),
                "n": np.zeros(1, dtype=np.float64),
                "level": np.zeros(1, dtype=np.int32),
            }
        cap = self._sample_size()
        level = max(0, int(np.ceil(np.log2(max(n, 1) / cap))))
        stride = 1 << level
        offset = stride // 2
        kept = max(0, -(-(n - offset) // stride))
        ranks = offset + stride * np.arange(kept, dtype=np.int64)

        cum = np.cumsum(counts)
        bins_of_rank = np.searchsorted(cum, ranks, side="right")
        wanted = np.zeros(65536, dtype=bool)
        wanted[bins_of_rank] = True

        # reproduce the wire's value space host-side: shifted float32
        x = np.asarray(host_inputs[f"num:{self.column}"], dtype=np.float64)
        valid = np.asarray(host_inputs[f"valid:{self.column}"], dtype=bool)
        where = getattr(self, "where", None)
        live = valid
        if where is not None:
            live = live & np.asarray(host_inputs[where_key(where)], dtype=bool)
        shift = shifts.get(f"num:{self.column}", 0.0)
        xs32 = (x - shift).astype(np.float32) if shift != 0.0 else x.astype(
            np.float32
        )
        u = xs32.view(np.int32)
        key = np.where(u < 0, ~u, u | np.int32(-(1 << 31)))
        bin16 = (key >> 16) & 0xFFFF
        sel = live & wanted[bin16]
        gathered = np.sort(xs32[sel].astype(np.float64))

        # rank within the gathered (wanted-bins-only) ordering: subtract
        # the mass of NON-wanted bins below each rank's bin
        unwanted_cum = np.cumsum(counts * ~wanted)
        below = np.where(
            bins_of_rank > 0, unwanted_cum[bins_of_rank - 1], 0
        )
        idx = ranks - below
        sample = gathered[idx]
        return {
            "sample": sample,
            "n": np.asarray([n], dtype=np.float64),
            "level": np.asarray([level], dtype=np.int32),
        }

    def host_consume(self, state: Optional[State], out: Any) -> Optional[State]:
        n = int(round(float(np.asarray(out["n"]).reshape(-1)[0])))
        if n <= 0:
            return state
        level = int(np.asarray(out["level"]).reshape(-1)[0])
        stride = 1 << level
        offset = stride // 2
        kept = max(0, -(-(n - offset) // stride))  # ceil((n-offset)/stride)
        sample = np.asarray(out["sample"], dtype=np.float64).reshape(-1)[:kept]
        k = k_for_error(self.relative_error)
        sketch = KLLSketch(k=k, seed=_batch_seed(sample, n, level))
        sketch.insert_level(sample, level, true_count=n)
        partial = ApproxQuantileState(sketch)
        return partial if state is None else state.merge(partial)


@dataclass(frozen=True)
class ApproxQuantile(_QuantileAnalyzerBase):
    """Single quantile (reference: analyzers/ApproxQuantile.scala:49)."""

    column: str
    quantile: float
    relative_error: float = 0.01
    where: Optional[str] = None

    @property
    def name(self) -> str:
        return "ApproxQuantile"

    @property
    def instance(self) -> str:
        return self.column

    def preconditions(self) -> List[Callable[[Table], None]]:
        return [
            _quantile_param_check(self.quantile),
            _relative_error_param_check(self.relative_error),
            Preconditions.has_column(self.column),
            Preconditions.is_numeric(self.column),
        ]

    def compute_metric_from(self, state: Optional[State]) -> Metric:
        if state is None:
            return self.empty_state_failure()
        return DoubleMetric(
            self.entity,
            self.name,
            self.instance,
            Success(state.digest.quantile(self.quantile)),
        )

    def __repr__(self) -> str:
        # `where` is our extension over the reference signature
        # (reference: ApproxQuantile.scala:49 has no filter); render it only
        # when set so the default matches the reference toString
        base = f"ApproxQuantile({self.column},{self.quantile},{self.relative_error}"
        if self.where is not None:
            return base + f",{render_where(self.where)})"
        return base + ")"


@dataclass(frozen=True)
class ApproxQuantiles(_QuantileAnalyzerBase):
    """Many quantiles from one digest -> KeyedDoubleMetric
    (reference: analyzers/ApproxQuantiles.scala:39)."""

    column: str
    quantiles: Tuple[float, ...]
    relative_error: float = 0.01

    def __init__(self, column: str, quantiles, relative_error: float = 0.01):
        object.__setattr__(self, "column", column)
        object.__setattr__(self, "quantiles", tuple(quantiles))
        object.__setattr__(self, "relative_error", relative_error)

    @property
    def name(self) -> str:
        return "ApproxQuantiles"

    @property
    def instance(self) -> str:
        return self.column

    def preconditions(self) -> List[Callable[[Table], None]]:
        return (
            [_quantile_param_check(q) for q in self.quantiles]
            + [
                _relative_error_param_check(self.relative_error),
                Preconditions.has_column(self.column),
                Preconditions.is_numeric(self.column),
            ]
        )

    def compute_metric_from(self, state: Optional[State]) -> Metric:
        if state is None:
            from deequ_tpu.core.exceptions import EmptyStateException
            from deequ_tpu.core.maybe import Failure

            return KeyedDoubleMetric(
                self.entity,
                self.name,
                self.instance,
                Failure(
                    EmptyStateException(
                        f"Empty state for analyzer {self!r}, all input values were NULL."
                    )
                ),
            )
        values = state.digest.quantiles(list(self.quantiles))
        keyed = {_format_quantile(q): v for q, v in zip(self.quantiles, values)}
        return KeyedDoubleMetric(self.entity, self.name, self.instance, Success(keyed))

    def to_failure_metric(self, exception: BaseException) -> Metric:
        from deequ_tpu.core.exceptions import wrap_if_necessary
        from deequ_tpu.core.maybe import Failure

        return KeyedDoubleMetric(
            self.entity, self.name, self.instance, Failure(wrap_if_necessary(exception))
        )

    def __repr__(self) -> str:
        qs = ", ".join(_format_quantile(q) for q in self.quantiles)
        return f"ApproxQuantiles({self.column},List({qs}),{self.relative_error})"


def _format_quantile(q: float) -> str:
    return repr(float(q))

"""State checkpoint layer: load/persist analyzer states.

reference: analyzers/StateProvider.scala:36-295. The filesystem provider
keeps the reference's binary layouts (big-endian, Java DataOutputStream
conventions) per analyzer type, so the *payload* of a state file is
format-compatible where the underlying sketch is. File *naming* defaults
to SHA-1[:16] of repr(analyzer) (this build's stable scheme);
`naming="reference"` switches to the reference's
MurmurHash3(analyzer.toString) scheme (StateProvider.scala:81-83) so the
two implementations can discover each other's files — see README
'State-file interop' for the JVM-validation caveat.

CAUTION on sketch states across engine versions: HLL registers are a
function of the engine's value hash. If the hash changes between builds
(it did when string hashing moved from per-row blake2b to the vectorized
bucket hash), persisted ApproxCountDistinct states from the older build
merge incorrectly with new ones — the same value lands in different
registers and is double-counted. Invalidate persisted HLL states when
upgrading across a hash change.
"""

from __future__ import annotations

import hashlib
import struct
import threading
from typing import TYPE_CHECKING, Dict, Optional

import numpy as np

from deequ_tpu.analyzers.states import State

if TYPE_CHECKING:
    from deequ_tpu.analyzers.base import Analyzer


class StateLoader:
    def load(self, analyzer: "Analyzer") -> Optional[State]:
        raise NotImplementedError


class StatePersister:
    def persist(self, analyzer: "Analyzer", state: State) -> None:
        raise NotImplementedError


class InMemoryStateProvider(StateLoader, StatePersister):
    """Keyed by analyzer identity (reference: StateProvider.scala:46-69)."""

    def __init__(self) -> None:
        self._states: Dict["Analyzer", State] = {}
        self._lock = threading.Lock()

    def load(self, analyzer: "Analyzer") -> Optional[State]:
        with self._lock:
            return self._states.get(analyzer)

    def persist(self, analyzer: "Analyzer", state: State) -> None:
        with self._lock:
            self._states[analyzer] = state

    def __repr__(self) -> str:
        with self._lock:
            keys = ", ".join(repr(k) for k in self._states)
        return f"InMemoryStateProvider({keys})"


_MM3_C1 = 0xCC9E2D51
_MM3_C2 = 0x1B873593
_MASK32 = 0xFFFFFFFF


def _mm3_rotl(value: int, amount: int) -> int:
    return ((value << amount) | ((value & _MASK32) >> (32 - amount))) & _MASK32


def _mm3_mix_k(k: int) -> int:
    """The murmur3 x86_32 block premix: k*c1, rotl15, k*c2."""
    k = (k * _MM3_C1) & _MASK32
    k = _mm3_rotl(k, 15)
    return (k * _MM3_C2) & _MASK32


def _mm3_mix(h: int, data: int) -> int:
    """One full murmur3 x86_32 mix round (MurmurHash3.mix)."""
    h ^= _mm3_mix_k(data)
    h = _mm3_rotl(h, 13)
    return (h * 5 + 0xE6546B64) & _MASK32


def _mm3_mix_last(h: int, data: int) -> int:
    """Tail mix without the h-side rotation (MurmurHash3.mixLast)."""
    return h ^ _mm3_mix_k(data)


def _mm3_finalize(h: int, length: int) -> int:
    """MurmurHash3.finalizeHash: xor in the length, then avalanche."""
    h ^= length
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _MASK32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _MASK32
    h ^= h >> 16
    return h


def _scala_murmur3_string_hash(s: str, seed: int = 42) -> int:
    """scala.util.hashing.MurmurHash3.stringHash(s, seed) — the hash the
    reference uses to name state files, with the explicit seed 42 from
    its call site (reference: analyzers/StateProvider.scala:81-83,
    ``MurmurHash3.stringHash(analyzer.toString, 42)``). Characters are
    consumed in UTF-16 code-unit pairs ((c[i] << 16) + c[i+1]) through
    the standard murmur3 x86_32 mix rounds; an odd final unit goes
    through mixLast; finalizeHash xors in the code-unit count. The mix/
    finalize primitives are validated against published murmur3 x86_32
    test vectors and hand-derived stringHash values in
    tests/test_persistence.py; there is no JVM in this image, so a
    one-time reference-side smoke test is still documented in README
    ('State-file interop')."""
    h = seed & _MASK32
    # Java charAt/length operate on UTF-16 CODE UNITS: derive them
    # explicitly so non-BMP characters (surrogate pairs on the JVM)
    # hash identically
    raw = s.encode("utf-16-be", "surrogatepass")
    units = [
        (raw[i] << 8) | raw[i + 1] for i in range(0, len(raw), 2)
    ]
    i = 0
    while i + 1 < len(units):
        h = _mm3_mix(h, ((units[i] << 16) + units[i + 1]) & _MASK32)
        i += 2
    if i < len(units):
        h = _mm3_mix_last(h, units[i])
    h = _mm3_finalize(h, len(units))
    # Scala's Int is signed
    return h - (1 << 32) if h >= (1 << 31) else h


class FileSystemStateProvider(StateLoader, StatePersister):
    """Binary per-analyzer state files
    (reference: HdfsStateProvider, StateProvider.scala:72-295).

    `filesystem` selects the storage backend (core/fsio.py — local disk,
    in-memory object-store fake, or any fsspec store). `naming` selects
    the file-name scheme: 'sha1' (default, this build's own stable
    naming) or 'reference' (MurmurHash3 of the analyzer's toString, the
    reference's scheme — lets the two implementations discover each
    other's state files when the payload layouts already match
    byte-for-byte)."""

    def __init__(
        self,
        location_prefix: str,
        allow_overwrite: bool = False,
        filesystem=None,
        naming: str = "sha1",
    ):
        from deequ_tpu.core.fsio import resolve_filesystem

        if naming not in ("sha1", "reference"):
            raise ValueError(f"naming must be 'sha1' or 'reference', got {naming!r}")
        self.location_prefix = location_prefix
        self.allow_overwrite = allow_overwrite
        self.filesystem = resolve_filesystem(filesystem)
        self.naming = naming

    def _identifier(self, analyzer: "Analyzer") -> str:
        if self.naming == "reference":
            return str(_scala_murmur3_string_hash(repr(analyzer)))
        digest = hashlib.sha1(repr(analyzer).encode("utf-8")).hexdigest()[:16]
        return digest

    def _path(self, identifier: str, suffix: str = ".bin") -> str:
        return f"{self.location_prefix}-{identifier}{suffix}"

    # -- persist -------------------------------------------------------------

    def persist(self, analyzer: "Analyzer", state: State) -> None:
        from deequ_tpu.analyzers.frequency import FrequencyBasedAnalyzer
        from deequ_tpu.analyzers.histogram import Histogram

        identifier = self._identifier(analyzer)
        if isinstance(analyzer, (FrequencyBasedAnalyzer, Histogram)):
            # keep the reference's 3-file on-disk layout
            # (parquet + numRows + columns)
            self._persist_frequencies(identifier, state)
        else:
            self._write(identifier, serialize_state(analyzer, state))

    # -- load ----------------------------------------------------------------

    def load(self, analyzer: "Analyzer") -> Optional[State]:
        from deequ_tpu.analyzers.frequency import FrequencyBasedAnalyzer
        from deequ_tpu.analyzers.histogram import Histogram

        identifier = self._identifier(analyzer)
        if isinstance(analyzer, (FrequencyBasedAnalyzer, Histogram)):
            return self._load_frequencies(identifier)
        data = self._read(identifier)
        if data is None:
            return None
        return deserialize_state(analyzer, data)

    # -- io ------------------------------------------------------------------

    def _write(self, identifier: str, payload: bytes) -> None:
        path = self._path(identifier)
        if self.filesystem.exists(path) and not self.allow_overwrite:
            raise FileExistsError(f"File {path} already exists and overwrite disabled")
        self.filesystem.write_bytes(path, payload)

    def _read(self, identifier: str) -> Optional[bytes]:
        path = self._path(identifier)
        if not self.filesystem.exists(path):
            return None
        return self.filesystem.read_bytes(path)

    def _persist_frequencies(self, identifier: str, state) -> None:
        """Frequencies as Parquet + numRows binary
        (reference: StateProvider.scala:211-223)."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        from deequ_tpu.analyzers.base import COUNT_COL

        paths = {
            suffix: self._path(identifier, suffix)
            for suffix in ("-frequencies.pqt", "-num_rows.bin", "-columns.txt")
        }
        if not self.allow_overwrite:
            for path in paths.values():
                if self.filesystem.exists(path):
                    raise FileExistsError(
                        f"File {path} already exists and overwrite disabled"
                    )

        # write siblings first, parquet last with atomic publish: load()
        # keys on the .pqt, so a crash mid-persist leaves a state that
        # reads as absent, never corrupt
        self.filesystem.write_bytes(
            paths["-num_rows.bin"], struct.pack(">q", state.num_rows)
        )
        self.filesystem.write_bytes(
            paths["-columns.txt"], "\n".join(state.columns).encode("utf-8")
        )
        with self.filesystem.open_write(paths["-frequencies.pqt"]) as sink:
            if getattr(state, "is_spilled", False):
                # disk-spilled state streams partition by partition into
                # the same Parquet layout (one row group per partition) —
                # persist never materializes the full key set
                writer = None
                for part in state.partitions():
                    at = pa.table(_frequencies_to_columns(part))
                    if writer is None:
                        writer = pq.ParquetWriter(sink, at.schema)
                    writer.write_table(at)
                if writer is None:
                    pq.write_table(
                        pa.table(
                            {
                                **{name: [] for name in state.columns},
                                COUNT_COL: np.array([], dtype=np.int64),
                            }
                        ),
                        sink,
                    )
                else:
                    writer.close()
            else:
                pq.write_table(pa.table(_frequencies_to_columns(state)), sink)

    def _load_frequencies(self, identifier: str):
        import pyarrow.parquet as pq

        pqt_path = self._path(identifier, "-frequencies.pqt")
        if not self.filesystem.exists(pqt_path):
            return None
        columns_payload = self.filesystem.read_bytes(
            self._path(identifier, "-columns.txt")
        ).decode("utf-8")
        columns = [line for line in columns_payload.split("\n") if line]
        (num_rows,) = struct.unpack(
            ">q", self.filesystem.read_bytes(self._path(identifier, "-num_rows.bin"))
        )
        # load row group by row group through the group-cap accumulator:
        # a persisted high-cardinality state comes back SPILLED, keeping
        # the persist/load round trip bounded-memory on both halves
        from deequ_tpu.analyzers.freq_spill import GroupCountAccumulator

        acc = GroupCountAccumulator(columns)
        with self.filesystem.open_read(pqt_path) as source, pq.ParquetFile(
            source
        ) as pf:
            for g in range(pf.metadata.num_row_groups):
                partial = _frequencies_from_table(
                    pf.read_row_group(g), columns, 0
                )
                acc.add(partial)
        state = acc.finalize()
        state.num_rows = int(num_rows)
        return state


def serialize_state(analyzer: "Analyzer", state: State) -> bytes:
    """State -> reference-layout bytes (per-type big-endian formats,
    reference: StateProvider.scala:85-134). Frequency states get a
    self-contained envelope (column names + numRows + in-memory Parquet)
    so they can cross DCN, not just the filesystem."""
    from deequ_tpu.analyzers.frequency import FrequencyBasedAnalyzer
    from deequ_tpu.analyzers.histogram import Histogram
    from deequ_tpu.analyzers.scan import (
        Completeness,
        Compliance,
        Correlation,
        DataType,
        Maximum,
        Mean,
        Minimum,
        PatternMatch,
        Size,
        StandardDeviation,
        Sum,
    )
    from deequ_tpu.analyzers.sketch import ApproxCountDistinct, ApproxQuantile, ApproxQuantiles

    if isinstance(analyzer, Size):
        return struct.pack(">q", state.num_matches)
    if isinstance(analyzer, (Completeness, Compliance, PatternMatch)):
        return struct.pack(">qq", state.num_matches, state.count)
    if isinstance(analyzer, Sum):
        return struct.pack(">d", state.sum_value)
    if isinstance(analyzer, Mean):
        return struct.pack(">dq", state.total, state.count)
    if isinstance(analyzer, Minimum):
        return struct.pack(">d", state.min_value)
    if isinstance(analyzer, Maximum):
        return struct.pack(">d", state.max_value)
    if isinstance(analyzer, (FrequencyBasedAnalyzer, Histogram)):
        return _serialize_frequencies_bytes(state)
    if isinstance(analyzer, DataType):
        payload = struct.pack(
            ">qqqqq",
            state.num_null,
            state.num_fractional,
            state.num_integral,
            state.num_boolean,
            state.num_string,
        )
        return struct.pack(">i", len(payload)) + payload
    if isinstance(analyzer, ApproxCountDistinct):
        words = state.words()
        payload = struct.pack(f">{len(words)}q", *[int(w) for w in words])
        return struct.pack(">i", len(payload)) + payload
    if isinstance(analyzer, Correlation):
        return struct.pack(
            ">dddddd",
            state.n, state.x_avg, state.y_avg, state.ck, state.x_mk, state.y_mk,
        )
    if isinstance(analyzer, StandardDeviation):
        return struct.pack(">ddd", state.n, state.avg, state.m2)
    if isinstance(analyzer, (ApproxQuantile, ApproxQuantiles)):
        return _serialize_kll(state.digest)
    raise ValueError(f"Unable to persist state for analyzer {analyzer!r}.")


def deserialize_state(analyzer: "Analyzer", data: bytes) -> State:
    """Inverse of serialize_state (reference: StateProvider.scala:136-174)."""
    from deequ_tpu.analyzers.frequency import FrequencyBasedAnalyzer
    from deequ_tpu.analyzers.histogram import Histogram
    from deequ_tpu.analyzers.scan import (
        Completeness,
        Compliance,
        Correlation,
        DataType,
        Maximum,
        Mean,
        Minimum,
        PatternMatch,
        Size,
        StandardDeviation,
        Sum,
    )
    from deequ_tpu.analyzers.sketch import (
        ApproxCountDistinct,
        ApproxCountDistinctState,
        ApproxQuantile,
        ApproxQuantiles,
        ApproxQuantileState,
    )
    from deequ_tpu.analyzers import states as S
    from deequ_tpu.ops.sketches import hll as hll_mod

    if isinstance(analyzer, Size):
        return S.NumMatches(struct.unpack(">q", data)[0])
    if isinstance(analyzer, (Completeness, Compliance, PatternMatch)):
        matches, count = struct.unpack(">qq", data)
        return S.NumMatchesAndCount(matches, count)
    if isinstance(analyzer, Sum):
        return S.SumState(struct.unpack(">d", data)[0])
    if isinstance(analyzer, Mean):
        total, count = struct.unpack(">dq", data)
        return S.MeanState(total, count)
    if isinstance(analyzer, Minimum):
        return S.MinState(struct.unpack(">d", data)[0])
    if isinstance(analyzer, Maximum):
        return S.MaxState(struct.unpack(">d", data)[0])
    if isinstance(analyzer, (FrequencyBasedAnalyzer, Histogram)):
        return _deserialize_frequencies_bytes(data)
    if isinstance(analyzer, DataType):
        (length,) = struct.unpack(">i", data[:4])
        values = struct.unpack(">qqqqq", data[4 : 4 + length])
        return S.DataTypeHistogram(*values)
    if isinstance(analyzer, ApproxCountDistinct):
        (length,) = struct.unpack(">i", data[:4])
        words = np.array(
            struct.unpack(f">{length // 8}q", data[4 : 4 + length]), dtype=np.int64
        )
        return ApproxCountDistinctState(hll_mod.unpack_words(words))
    if isinstance(analyzer, Correlation):
        return S.CorrelationState(*struct.unpack(">dddddd", data))
    if isinstance(analyzer, StandardDeviation):
        return S.StandardDeviationState(*struct.unpack(">ddd", data))
    if isinstance(analyzer, (ApproxQuantile, ApproxQuantiles)):
        return ApproxQuantileState(_deserialize_kll(data))
    raise ValueError(f"Unable to load state for analyzer {analyzer!r}.")


def _frequencies_to_columns(state) -> dict:
    """State -> the {key columns..., COUNT_COL} dict both the on-disk
    Parquet layout and the DCN envelope serialize."""
    from deequ_tpu.analyzers.base import COUNT_COL

    columns = {
        name: state.key_columns[i].tolist() for i, name in enumerate(state.columns)
    }
    columns[COUNT_COL] = [int(c) for c in state.counts]
    return columns


def _frequencies_from_table(table, columns, num_rows):
    """Arrow table (+ declared key-column order, numRows) -> state."""
    from deequ_tpu.analyzers.base import COUNT_COL
    from deequ_tpu.analyzers.frequency import FrequenciesAndNumRows

    counts = np.asarray(table.column(COUNT_COL).to_pylist(), dtype=np.int64)
    key_columns = [
        np.array(table.column(c).to_pylist(), dtype=object) for c in columns
    ]
    return FrequenciesAndNumRows(list(columns), key_columns, counts, int(num_rows))


def _serialize_frequencies_bytes(state) -> bytes:
    """Envelope: ncols, utf8 names, numRows, in-memory Parquet payload.

    Spilled states stream partition by partition into the payload (one
    row group each) — the bytes themselves are necessarily materialized
    (they're about to cross DCN), but the object key set never is."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from deequ_tpu.analyzers.base import COUNT_COL

    sink = pa.BufferOutputStream()
    if getattr(state, "is_spilled", False):
        writer = None
        for part in state.partitions():
            at = pa.table(_frequencies_to_columns(part))
            if writer is None:
                writer = pq.ParquetWriter(sink, at.schema)
            writer.write_table(at)
        if writer is None:
            pq.write_table(
                pa.table(
                    {
                        **{name: [] for name in state.columns},
                        COUNT_COL: np.array([], dtype=np.int64),
                    }
                ),
                sink,
            )
        else:
            writer.close()
    else:
        pq.write_table(pa.table(_frequencies_to_columns(state)), sink)
    parquet = sink.getvalue().to_pybytes()

    parts = [struct.pack(">i", len(state.columns))]
    for name in state.columns:
        encoded = name.encode("utf-8")
        parts.append(struct.pack(">i", len(encoded)))
        parts.append(encoded)
    parts.append(struct.pack(">qi", state.num_rows, len(parquet)))
    parts.append(parquet)
    return b"".join(parts)


def _deserialize_frequencies_bytes(data: bytes):
    import pyarrow.parquet as pq
    import pyarrow as pa

    (ncols,) = struct.unpack(">i", data[:4])
    offset = 4
    columns = []
    for _ in range(ncols):
        (length,) = struct.unpack(">i", data[offset : offset + 4])
        offset += 4
        columns.append(data[offset : offset + length].decode("utf-8"))
        offset += length
    num_rows, parquet_len = struct.unpack(">qi", data[offset : offset + 12])
    offset += 12
    # row-group-wise through the group-cap accumulator: a high-cardinality
    # envelope re-spills on the receiving host instead of materializing
    from deequ_tpu.analyzers.freq_spill import GroupCountAccumulator

    acc = GroupCountAccumulator(columns)
    with pq.ParquetFile(
        pa.BufferReader(data[offset : offset + parquet_len])
    ) as pf:
        for g in range(pf.metadata.num_row_groups):
            acc.add(_frequencies_from_table(pf.read_row_group(g), columns, 0))
    state = acc.finalize()
    state.num_rows = int(num_rows)
    return state


def _serialize_kll(digest) -> bytes:
    """Our own digest layout (KLL, not the reference's GK digest — the
    sketch algorithms differ; see BASELINE.md parity notes)."""
    k, n, levels = digest.to_arrays()
    parts = [struct.pack(">iqi", k, n, len(levels))]
    for level in levels:
        parts.append(struct.pack(">i", len(level)))
        parts.append(np.asarray(level, dtype=">f8").tobytes())
    # trailing generator position: KLL merges draw compaction offsets
    # from the sketch's own rng, so restoring it is what makes a
    # deserialized partial merge bit-identically to the live sketch
    parts.append(digest.rng_state_bytes())
    return b"".join(parts)


def _deserialize_kll(data: bytes):
    from deequ_tpu.ops.sketches.kll import KLLSketch

    k, n, depth = struct.unpack(">iqi", data[:16])
    offset = 16
    levels = []
    for _ in range(depth):
        (length,) = struct.unpack(">i", data[offset : offset + 4])
        offset += 4
        level = np.frombuffer(data[offset : offset + 8 * length], dtype=">f8").astype(
            np.float64
        )
        offset += 8 * length
        levels.append(level)
    sketch = KLLSketch.from_arrays(k, n, levels)
    tail = data[offset:]
    if len(tail) == KLLSketch.RNG_STATE_LEN:
        sketch.set_rng_state_bytes(tail)
    return sketch

"""State checkpoint layer: load/persist analyzer states.

reference: analyzers/StateProvider.scala:36-69 (traits + in-memory
provider). The filesystem provider with binary per-analyzer formats is in
deequ_tpu/repository (added with the persistence milestone).
"""

from __future__ import annotations

import threading
from typing import TYPE_CHECKING, Dict, Optional

from deequ_tpu.analyzers.states import State

if TYPE_CHECKING:
    from deequ_tpu.analyzers.base import Analyzer


class StateLoader:
    def load(self, analyzer: "Analyzer") -> Optional[State]:
        raise NotImplementedError


class StatePersister:
    def persist(self, analyzer: "Analyzer", state: State) -> None:
        raise NotImplementedError


class InMemoryStateProvider(StateLoader, StatePersister):
    """Keyed by analyzer identity (reference: StateProvider.scala:46-69)."""

    def __init__(self) -> None:
        self._states: Dict["Analyzer", State] = {}
        self._lock = threading.Lock()

    def load(self, analyzer: "Analyzer") -> Optional[State]:
        with self._lock:
            return self._states.get(analyzer)

    def persist(self, analyzer: "Analyzer", state: State) -> None:
        with self._lock:
            self._states[analyzer] = state

    def __repr__(self) -> str:
        with self._lock:
            keys = ", ".join(repr(k) for k in self._states)
        return f"InMemoryStateProvider({keys})"

"""State checkpoint layer: load/persist analyzer states.

reference: analyzers/StateProvider.scala:36-295. The filesystem provider
keeps the reference's binary layouts (big-endian, Java DataOutputStream
conventions) per analyzer type, so the *payload* of a state file is
format-compatible where the underlying sketch is. File *naming* is not
interoperable: files are keyed by SHA-1[:16] of repr(analyzer), whereas
the reference keys by MurmurHash3(analyzer.toString)
(StateProvider.scala:81-83) — a state written by one implementation is
not discovered by the other without renaming.

CAUTION on sketch states across engine versions: HLL registers are a
function of the engine's value hash. If the hash changes between builds
(it did when string hashing moved from per-row blake2b to the vectorized
bucket hash), persisted ApproxCountDistinct states from the older build
merge incorrectly with new ones — the same value lands in different
registers and is double-counted. Invalidate persisted HLL states when
upgrading across a hash change.
"""

from __future__ import annotations

import hashlib
import os
import struct
import threading
from typing import TYPE_CHECKING, Dict, Optional

import numpy as np

from deequ_tpu.analyzers.states import State

if TYPE_CHECKING:
    from deequ_tpu.analyzers.base import Analyzer


class StateLoader:
    def load(self, analyzer: "Analyzer") -> Optional[State]:
        raise NotImplementedError


class StatePersister:
    def persist(self, analyzer: "Analyzer", state: State) -> None:
        raise NotImplementedError


class InMemoryStateProvider(StateLoader, StatePersister):
    """Keyed by analyzer identity (reference: StateProvider.scala:46-69)."""

    def __init__(self) -> None:
        self._states: Dict["Analyzer", State] = {}
        self._lock = threading.Lock()

    def load(self, analyzer: "Analyzer") -> Optional[State]:
        with self._lock:
            return self._states.get(analyzer)

    def persist(self, analyzer: "Analyzer", state: State) -> None:
        with self._lock:
            self._states[analyzer] = state

    def __repr__(self) -> str:
        with self._lock:
            keys = ", ".join(repr(k) for k in self._states)
        return f"InMemoryStateProvider({keys})"


class FileSystemStateProvider(StateLoader, StatePersister):
    """Binary per-analyzer state files
    (reference: HdfsStateProvider, StateProvider.scala:72-295)."""

    def __init__(self, location_prefix: str, allow_overwrite: bool = False):
        self.location_prefix = location_prefix
        self.allow_overwrite = allow_overwrite

    def _identifier(self, analyzer: "Analyzer") -> str:
        digest = hashlib.sha1(repr(analyzer).encode("utf-8")).hexdigest()[:16]
        return digest

    def _path(self, identifier: str, suffix: str = ".bin") -> str:
        return f"{self.location_prefix}-{identifier}{suffix}"

    # -- persist -------------------------------------------------------------

    def persist(self, analyzer: "Analyzer", state: State) -> None:
        from deequ_tpu.analyzers.frequency import FrequencyBasedAnalyzer
        from deequ_tpu.analyzers.histogram import Histogram

        identifier = self._identifier(analyzer)
        if isinstance(analyzer, (FrequencyBasedAnalyzer, Histogram)):
            # keep the reference's 3-file on-disk layout
            # (parquet + numRows + columns)
            self._persist_frequencies(identifier, state)
        else:
            self._write(identifier, serialize_state(analyzer, state))

    # -- load ----------------------------------------------------------------

    def load(self, analyzer: "Analyzer") -> Optional[State]:
        from deequ_tpu.analyzers.frequency import FrequencyBasedAnalyzer
        from deequ_tpu.analyzers.histogram import Histogram

        identifier = self._identifier(analyzer)
        if isinstance(analyzer, (FrequencyBasedAnalyzer, Histogram)):
            return self._load_frequencies(identifier)
        data = self._read(identifier)
        if data is None:
            return None
        return deserialize_state(analyzer, data)

    # -- io ------------------------------------------------------------------

    def _write(self, identifier: str, payload: bytes) -> None:
        path = self._path(identifier)
        if os.path.exists(path) and not self.allow_overwrite:
            raise FileExistsError(f"File {path} already exists and overwrite disabled")
        directory = os.path.dirname(os.path.abspath(path)) or "."
        os.makedirs(directory, exist_ok=True)
        with open(path, "wb") as f:
            f.write(payload)

    def _read(self, identifier: str) -> Optional[bytes]:
        path = self._path(identifier)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def _persist_frequencies(self, identifier: str, state) -> None:
        """Frequencies as Parquet + numRows binary
        (reference: StateProvider.scala:211-223)."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        from deequ_tpu.analyzers.base import COUNT_COL

        paths = {
            suffix: self._path(identifier, suffix)
            for suffix in ("-frequencies.pqt", "-num_rows.bin", "-columns.txt")
        }
        if not self.allow_overwrite:
            for path in paths.values():
                if os.path.exists(path):
                    raise FileExistsError(
                        f"File {path} already exists and overwrite disabled"
                    )
        directory = os.path.dirname(os.path.abspath(paths["-frequencies.pqt"])) or "."
        os.makedirs(directory, exist_ok=True)

        # write siblings first, parquet last via tmp+rename: load() keys on
        # the .pqt, so a crash mid-persist leaves a state that reads as
        # absent, never corrupt
        with open(paths["-num_rows.bin"], "wb") as f:
            f.write(struct.pack(">q", state.num_rows))
        with open(paths["-columns.txt"], "w", encoding="utf-8") as f:
            f.write("\n".join(state.columns))
        tmp = paths["-frequencies.pqt"] + ".tmp"
        if getattr(state, "is_spilled", False):
            # disk-spilled state streams partition by partition into the
            # same Parquet layout (one row group per partition) — persist
            # never materializes the full key set
            writer = None
            for part in state.partitions():
                at = pa.table(_frequencies_to_columns(part))
                if writer is None:
                    writer = pq.ParquetWriter(tmp, at.schema)
                writer.write_table(at)
            if writer is None:
                pq.write_table(
                    pa.table(
                        {
                            **{name: [] for name in state.columns},
                            COUNT_COL: np.array([], dtype=np.int64),
                        }
                    ),
                    tmp,
                )
            else:
                writer.close()
        else:
            pq.write_table(pa.table(_frequencies_to_columns(state)), tmp)
        os.replace(tmp, paths["-frequencies.pqt"])

    def _load_frequencies(self, identifier: str):
        import pyarrow.parquet as pq

        pqt_path = self._path(identifier, "-frequencies.pqt")
        if not os.path.exists(pqt_path):
            return None
        with open(self._path(identifier, "-columns.txt"), encoding="utf-8") as f:
            columns = [line for line in f.read().split("\n") if line]
        with open(self._path(identifier, "-num_rows.bin"), "rb") as f:
            (num_rows,) = struct.unpack(">q", f.read())
        # load row group by row group through the group-cap accumulator:
        # a persisted high-cardinality state comes back SPILLED, keeping
        # the persist/load round trip bounded-memory on both halves
        from deequ_tpu.analyzers.freq_spill import GroupCountAccumulator

        acc = GroupCountAccumulator(columns)
        with pq.ParquetFile(pqt_path) as pf:
            for g in range(pf.metadata.num_row_groups):
                partial = _frequencies_from_table(
                    pf.read_row_group(g), columns, 0
                )
                acc.add(partial)
        state = acc.finalize()
        state.num_rows = int(num_rows)
        return state


def serialize_state(analyzer: "Analyzer", state: State) -> bytes:
    """State -> reference-layout bytes (per-type big-endian formats,
    reference: StateProvider.scala:85-134). Frequency states get a
    self-contained envelope (column names + numRows + in-memory Parquet)
    so they can cross DCN, not just the filesystem."""
    from deequ_tpu.analyzers.frequency import FrequencyBasedAnalyzer
    from deequ_tpu.analyzers.histogram import Histogram
    from deequ_tpu.analyzers.scan import (
        Completeness,
        Compliance,
        Correlation,
        DataType,
        Maximum,
        Mean,
        Minimum,
        PatternMatch,
        Size,
        StandardDeviation,
        Sum,
    )
    from deequ_tpu.analyzers.sketch import ApproxCountDistinct, ApproxQuantile, ApproxQuantiles

    if isinstance(analyzer, Size):
        return struct.pack(">q", state.num_matches)
    if isinstance(analyzer, (Completeness, Compliance, PatternMatch)):
        return struct.pack(">qq", state.num_matches, state.count)
    if isinstance(analyzer, Sum):
        return struct.pack(">d", state.sum_value)
    if isinstance(analyzer, Mean):
        return struct.pack(">dq", state.total, state.count)
    if isinstance(analyzer, Minimum):
        return struct.pack(">d", state.min_value)
    if isinstance(analyzer, Maximum):
        return struct.pack(">d", state.max_value)
    if isinstance(analyzer, (FrequencyBasedAnalyzer, Histogram)):
        return _serialize_frequencies_bytes(state)
    if isinstance(analyzer, DataType):
        payload = struct.pack(
            ">qqqqq",
            state.num_null,
            state.num_fractional,
            state.num_integral,
            state.num_boolean,
            state.num_string,
        )
        return struct.pack(">i", len(payload)) + payload
    if isinstance(analyzer, ApproxCountDistinct):
        words = state.words()
        payload = struct.pack(f">{len(words)}q", *[int(w) for w in words])
        return struct.pack(">i", len(payload)) + payload
    if isinstance(analyzer, Correlation):
        return struct.pack(
            ">dddddd",
            state.n, state.x_avg, state.y_avg, state.ck, state.x_mk, state.y_mk,
        )
    if isinstance(analyzer, StandardDeviation):
        return struct.pack(">ddd", state.n, state.avg, state.m2)
    if isinstance(analyzer, (ApproxQuantile, ApproxQuantiles)):
        return _serialize_kll(state.digest)
    raise ValueError(f"Unable to persist state for analyzer {analyzer!r}.")


def deserialize_state(analyzer: "Analyzer", data: bytes) -> State:
    """Inverse of serialize_state (reference: StateProvider.scala:136-174)."""
    from deequ_tpu.analyzers.frequency import FrequencyBasedAnalyzer
    from deequ_tpu.analyzers.histogram import Histogram
    from deequ_tpu.analyzers.scan import (
        Completeness,
        Compliance,
        Correlation,
        DataType,
        Maximum,
        Mean,
        Minimum,
        PatternMatch,
        Size,
        StandardDeviation,
        Sum,
    )
    from deequ_tpu.analyzers.sketch import (
        ApproxCountDistinct,
        ApproxCountDistinctState,
        ApproxQuantile,
        ApproxQuantiles,
        ApproxQuantileState,
    )
    from deequ_tpu.analyzers import states as S
    from deequ_tpu.ops.sketches import hll as hll_mod

    if isinstance(analyzer, Size):
        return S.NumMatches(struct.unpack(">q", data)[0])
    if isinstance(analyzer, (Completeness, Compliance, PatternMatch)):
        matches, count = struct.unpack(">qq", data)
        return S.NumMatchesAndCount(matches, count)
    if isinstance(analyzer, Sum):
        return S.SumState(struct.unpack(">d", data)[0])
    if isinstance(analyzer, Mean):
        total, count = struct.unpack(">dq", data)
        return S.MeanState(total, count)
    if isinstance(analyzer, Minimum):
        return S.MinState(struct.unpack(">d", data)[0])
    if isinstance(analyzer, Maximum):
        return S.MaxState(struct.unpack(">d", data)[0])
    if isinstance(analyzer, (FrequencyBasedAnalyzer, Histogram)):
        return _deserialize_frequencies_bytes(data)
    if isinstance(analyzer, DataType):
        (length,) = struct.unpack(">i", data[:4])
        values = struct.unpack(">qqqqq", data[4 : 4 + length])
        return S.DataTypeHistogram(*values)
    if isinstance(analyzer, ApproxCountDistinct):
        (length,) = struct.unpack(">i", data[:4])
        words = np.array(
            struct.unpack(f">{length // 8}q", data[4 : 4 + length]), dtype=np.int64
        )
        return ApproxCountDistinctState(hll_mod.unpack_words(words))
    if isinstance(analyzer, Correlation):
        return S.CorrelationState(*struct.unpack(">dddddd", data))
    if isinstance(analyzer, StandardDeviation):
        return S.StandardDeviationState(*struct.unpack(">ddd", data))
    if isinstance(analyzer, (ApproxQuantile, ApproxQuantiles)):
        return ApproxQuantileState(_deserialize_kll(data))
    raise ValueError(f"Unable to load state for analyzer {analyzer!r}.")


def _frequencies_to_columns(state) -> dict:
    """State -> the {key columns..., COUNT_COL} dict both the on-disk
    Parquet layout and the DCN envelope serialize."""
    from deequ_tpu.analyzers.base import COUNT_COL

    columns = {
        name: state.key_columns[i].tolist() for i, name in enumerate(state.columns)
    }
    columns[COUNT_COL] = [int(c) for c in state.counts]
    return columns


def _frequencies_from_table(table, columns, num_rows):
    """Arrow table (+ declared key-column order, numRows) -> state."""
    from deequ_tpu.analyzers.base import COUNT_COL
    from deequ_tpu.analyzers.frequency import FrequenciesAndNumRows

    counts = np.asarray(table.column(COUNT_COL).to_pylist(), dtype=np.int64)
    key_columns = [
        np.array(table.column(c).to_pylist(), dtype=object) for c in columns
    ]
    return FrequenciesAndNumRows(list(columns), key_columns, counts, int(num_rows))


def _serialize_frequencies_bytes(state) -> bytes:
    """Envelope: ncols, utf8 names, numRows, in-memory Parquet payload.

    Spilled states stream partition by partition into the payload (one
    row group each) — the bytes themselves are necessarily materialized
    (they're about to cross DCN), but the object key set never is."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from deequ_tpu.analyzers.base import COUNT_COL

    sink = pa.BufferOutputStream()
    if getattr(state, "is_spilled", False):
        writer = None
        for part in state.partitions():
            at = pa.table(_frequencies_to_columns(part))
            if writer is None:
                writer = pq.ParquetWriter(sink, at.schema)
            writer.write_table(at)
        if writer is None:
            pq.write_table(
                pa.table(
                    {
                        **{name: [] for name in state.columns},
                        COUNT_COL: np.array([], dtype=np.int64),
                    }
                ),
                sink,
            )
        else:
            writer.close()
    else:
        pq.write_table(pa.table(_frequencies_to_columns(state)), sink)
    parquet = sink.getvalue().to_pybytes()

    parts = [struct.pack(">i", len(state.columns))]
    for name in state.columns:
        encoded = name.encode("utf-8")
        parts.append(struct.pack(">i", len(encoded)))
        parts.append(encoded)
    parts.append(struct.pack(">qi", state.num_rows, len(parquet)))
    parts.append(parquet)
    return b"".join(parts)


def _deserialize_frequencies_bytes(data: bytes):
    import pyarrow.parquet as pq
    import pyarrow as pa

    (ncols,) = struct.unpack(">i", data[:4])
    offset = 4
    columns = []
    for _ in range(ncols):
        (length,) = struct.unpack(">i", data[offset : offset + 4])
        offset += 4
        columns.append(data[offset : offset + length].decode("utf-8"))
        offset += length
    num_rows, parquet_len = struct.unpack(">qi", data[offset : offset + 12])
    offset += 12
    # row-group-wise through the group-cap accumulator: a high-cardinality
    # envelope re-spills on the receiving host instead of materializing
    from deequ_tpu.analyzers.freq_spill import GroupCountAccumulator

    acc = GroupCountAccumulator(columns)
    with pq.ParquetFile(
        pa.BufferReader(data[offset : offset + parquet_len])
    ) as pf:
        for g in range(pf.metadata.num_row_groups):
            acc.add(_frequencies_from_table(pf.read_row_group(g), columns, 0))
    state = acc.finalize()
    state.num_rows = int(num_rows)
    return state


def _serialize_kll(digest) -> bytes:
    """Our own digest layout (KLL, not the reference's GK digest — the
    sketch algorithms differ; see BASELINE.md parity notes)."""
    k, n, levels = digest.to_arrays()
    parts = [struct.pack(">iqi", k, n, len(levels))]
    for level in levels:
        parts.append(struct.pack(">i", len(level)))
        parts.append(np.asarray(level, dtype=">f8").tobytes())
    return b"".join(parts)


def _deserialize_kll(data: bytes):
    from deequ_tpu.ops.sketches.kll import KLLSketch

    k, n, depth = struct.unpack(">iqi", data[:16])
    offset = 16
    levels = []
    for _ in range(depth):
        (length,) = struct.unpack(">i", data[offset : offset + 4])
        offset += 4
        level = np.frombuffer(data[offset : offset + 8 * length], dtype=">f8").astype(
            np.float64
        )
        offset += 8 * length
        levels.append(level)
    return KLLSketch.from_arrays(k, n, levels)

"""Mergeable sufficient statistics — the state semigroup.

THE enabling abstraction (reference: analyzers/Analyzer.scala:29-53,
`State[S].sum`): every metric is computed from a state that merges
associatively+commutatively, which is what makes computation incremental
(per-batch), partition-parallel (per-device partial states combined by
collectives) and resumable (states persist; metrics recompute from merged
states without rescanning data).

Host-side states are plain float64/int dataclasses. The device-side pytree
counterparts live with each analyzer's `device_reduce` (analyzers/scan.py);
the formulas here are the driver-side merge path (numpy float64).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import TypeVar

S = TypeVar("S", bound="State")


class State:
    """A commutative-semigroup element."""

    def merge(self: S, other: S) -> S:
        raise NotImplementedError

    def __add__(self: S, other: S) -> S:
        return self.merge(other)


class DoubleValuedState(State):
    def metric_value(self) -> float:
        raise NotImplementedError


@dataclass(frozen=True)
class NumMatches(DoubleValuedState):
    """reference: analyzers/Size.scala:23"""

    num_matches: int

    def merge(self, other: "NumMatches") -> "NumMatches":
        return NumMatches(self.num_matches + other.num_matches)

    def metric_value(self) -> float:
        return float(self.num_matches)


@dataclass(frozen=True)
class NumMatchesAndCount(DoubleValuedState):
    """Ratio state; NaN when count == 0
    (reference: analyzers/Analyzer.scala:220-234)."""

    num_matches: int
    count: int

    def merge(self, other: "NumMatchesAndCount") -> "NumMatchesAndCount":
        return NumMatchesAndCount(
            self.num_matches + other.num_matches, self.count + other.count
        )

    def metric_value(self) -> float:
        if self.count == 0:
            return float("nan")
        return self.num_matches / self.count


@dataclass(frozen=True)
class MeanState(DoubleValuedState):
    """reference: analyzers/Mean.scala:25"""

    total: float
    count: int

    def merge(self, other: "MeanState") -> "MeanState":
        return MeanState(self.total + other.total, self.count + other.count)

    def metric_value(self) -> float:
        if self.count == 0:
            return float("nan")
        return self.total / self.count


@dataclass(frozen=True)
class MinState(DoubleValuedState):
    min_value: float

    def merge(self, other: "MinState") -> "MinState":
        return MinState(min(self.min_value, other.min_value))

    def metric_value(self) -> float:
        return self.min_value


@dataclass(frozen=True)
class MaxState(DoubleValuedState):
    max_value: float

    def merge(self, other: "MaxState") -> "MaxState":
        return MaxState(max(self.max_value, other.max_value))

    def metric_value(self) -> float:
        return self.max_value


@dataclass(frozen=True)
class SumState(DoubleValuedState):
    sum_value: float

    def merge(self, other: "SumState") -> "SumState":
        return SumState(self.sum_value + other.sum_value)

    def metric_value(self) -> float:
        return self.sum_value


@dataclass(frozen=True)
class StandardDeviationState(DoubleValuedState):
    """(n, avg, m2) — parallel variance via the Chan et al. pairwise update
    (reference: analyzers/StandardDeviation.scala:25-44)."""

    n: float
    avg: float
    m2: float

    def merge(self, other: "StandardDeviationState") -> "StandardDeviationState":
        if self.n == 0:
            return other
        if other.n == 0:
            return self
        n = self.n + other.n
        delta = other.avg - self.avg
        avg = (self.n * self.avg + other.n * other.avg) / n
        m2 = self.m2 + other.m2 + delta * delta * self.n * other.n / n
        return StandardDeviationState(n, avg, m2)

    def metric_value(self) -> float:
        if self.n == 0:
            return float("nan")
        return math.sqrt(self.m2 / self.n)


@dataclass(frozen=True)
class CorrelationState(DoubleValuedState):
    """(n, xAvg, yAvg, ck, xMk, yMk) — pairwise co-moment merge
    (reference: analyzers/Correlation.scala:26-52)."""

    n: float
    x_avg: float
    y_avg: float
    ck: float
    x_mk: float
    y_mk: float

    def merge(self, other: "CorrelationState") -> "CorrelationState":
        if self.n == 0:
            return other
        if other.n == 0:
            return self
        n1, n2 = self.n, other.n
        n = n1 + n2
        dx = other.x_avg - self.x_avg
        dy = other.y_avg - self.y_avg
        x_avg = self.x_avg + dx * n2 / n
        y_avg = self.y_avg + dy * n2 / n
        ck = self.ck + other.ck + dx * dy * n1 * n2 / n
        x_mk = self.x_mk + other.x_mk + dx * dx * n1 * n2 / n
        y_mk = self.y_mk + other.y_mk + dy * dy * n1 * n2 / n
        return CorrelationState(n, x_avg, y_avg, ck, x_mk, y_mk)

    def metric_value(self) -> float:
        if self.n == 0 or self.x_mk == 0 or self.y_mk == 0:
            return float("nan")
        return self.ck / math.sqrt(self.x_mk * self.y_mk)


@dataclass(frozen=True)
class DataTypeHistogram(State):
    """Counts per inferred value class
    (reference: analyzers/DataType.scala:40-100)."""

    num_null: int
    num_fractional: int
    num_integral: int
    num_boolean: int
    num_string: int

    def merge(self, other: "DataTypeHistogram") -> "DataTypeHistogram":
        return DataTypeHistogram(
            self.num_null + other.num_null,
            self.num_fractional + other.num_fractional,
            self.num_integral + other.num_integral,
            self.num_boolean + other.num_boolean,
            self.num_string + other.num_string,
        )

    @property
    def total(self) -> int:
        return (
            self.num_null
            + self.num_fractional
            + self.num_integral
            + self.num_boolean
            + self.num_string
        )

from deequ_tpu.anomaly.base import (
    Anomaly,
    AnomalyDetectionStrategy,
    DetectionResult,
)
from deequ_tpu.anomaly.detector import AnomalyDetector, DataPoint
from deequ_tpu.anomaly.strategies import (
    BatchNormalStrategy,
    OnlineNormalStrategy,
    RateOfChangeStrategy,
    SimpleThresholdStrategy,
)
from deequ_tpu.anomaly.holt_winters import HoltWinters, MetricInterval, SeriesSeasonality

__all__ = [
    "Anomaly",
    "AnomalyDetectionStrategy",
    "DetectionResult",
    "AnomalyDetector",
    "DataPoint",
    "SimpleThresholdStrategy",
    "RateOfChangeStrategy",
    "OnlineNormalStrategy",
    "BatchNormalStrategy",
    "HoltWinters",
    "MetricInterval",
    "SeriesSeasonality",
]

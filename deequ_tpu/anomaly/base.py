"""Anomaly-detection data model.

reference: anomalydetection/AnomalyDetectionStrategy.scala:20-27,
anomalydetection/DetectionResult.scala:19-56 (equality ignores detail).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple


@dataclass
class Anomaly:
    value: Optional[float]
    confidence: float
    detail: Optional[str] = None

    def __eq__(self, other) -> bool:
        # reference: equality ignores detail (DetectionResult.scala:19-56)
        return (
            isinstance(other, Anomaly)
            and self.value == other.value
            and self.confidence == other.confidence
        )

    def __hash__(self) -> int:
        return hash((self.value, self.confidence))


@dataclass
class DetectionResult:
    anomalies: List[Tuple[int, Anomaly]] = field(default_factory=list)


class AnomalyDetectionStrategy:
    def detect(
        self, data_series: Sequence[float], search_interval: Tuple[int, int]
    ) -> List[Tuple[int, Anomaly]]:
        """Indices of anomalies in [a, b) and their wrapper objects."""
        raise NotImplementedError

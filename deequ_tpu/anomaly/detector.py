"""AnomalyDetector: time-series preprocessing around a strategy.

reference: anomalydetection/AnomalyDetector.scala:29-102,
anomalydetection/HistoryUtils.scala:24-48.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

from deequ_tpu.anomaly.base import AnomalyDetectionStrategy, DetectionResult

_LONG_MAX = (1 << 63) - 1
_LONG_MIN = -(1 << 63)


@dataclass
class DataPoint:
    time: int
    metric_value: Optional[float]


@dataclass
class AnomalyDetector:
    strategy: AnomalyDetectionStrategy

    def is_new_point_anomalous(
        self,
        historical_data_points: Sequence[DataPoint],
        new_point,
    ) -> DetectionResult:
        """reference: AnomalyDetector.scala:39-66. `new_point` may be a
        DataPoint or a bare value (then stamped after the newest history
        time, as the repository-backed check closure needs)."""
        if not historical_data_points:
            raise ValueError("historicalDataPoints must not be empty!")

        sorted_points = sorted(historical_data_points, key=lambda p: p.time)
        first_time = sorted_points[0].time
        last_time = sorted_points[-1].time

        if not isinstance(new_point, DataPoint):
            new_point = DataPoint(last_time + 1, float(new_point))

        if last_time >= new_point.time:
            raise ValueError(
                "Can't decide which range to use for anomaly detection. New "
                f"data point with time {new_point.time} is in history range "
                f"({first_time} - {last_time})!"
            )

        all_points = list(sorted_points) + [new_point]
        anomalies = self.detect_anomalies_in_history(
            all_points, (new_point.time, _LONG_MAX)
        ).anomalies
        return DetectionResult(anomalies)

    def detect_anomalies_in_history(
        self,
        data_series: Sequence[DataPoint],
        search_interval: Tuple[int, int] = (_LONG_MIN, _LONG_MAX),
    ) -> DetectionResult:
        """reference: AnomalyDetector.scala:68-102: drop missing values,
        sort by time, binary-search the time bounds into index bounds,
        delegate to the strategy, map indices back to timestamps."""
        search_start, search_end = search_interval
        if search_start > search_end:
            raise ValueError(
                "The first interval element has to be smaller or equal to the last."
            )
        present = [p for p in data_series if p.metric_value is not None]
        sorted_series = sorted(present, key=lambda p: p.time)
        timestamps = [p.time for p in sorted_series]

        lower = bisect.bisect_left(timestamps, search_start)
        upper = bisect.bisect_left(timestamps, search_end)

        values = [p.metric_value for p in sorted_series]
        anomalies = self.strategy.detect(values, (lower, upper))
        return DetectionResult(
            [(timestamps[index], anomaly) for index, anomaly in anomalies]
        )

"""Holt-Winters seasonal anomaly detection: additive triple exponential
smoothing ETS(A,A).

reference: anomalydetection/seasonal/HoltWinters.scala:60-249. The
smoothing recursion runs as a jax.lax.scan (compiled, differentiable) and
the (alpha, beta, gamma) fit minimizes RSS with L-BFGS-B over [0,1]^3 using
EXACT jax gradients — where the reference needed breeze's
ApproximateGradientFunction, autodiff gives the real thing.
"""

from __future__ import annotations

import enum
from functools import partial
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deequ_tpu.anomaly.base import Anomaly, AnomalyDetectionStrategy


class MetricInterval(enum.Enum):
    DAILY = "Daily"
    MONTHLY = "Monthly"


class SeriesSeasonality(enum.Enum):
    WEEKLY = "Weekly"
    YEARLY = "Yearly"


@partial(jax.jit, static_argnums=(1, 2))
def _holt_winters_fit(series, periodicity: int, num_forecasts: int, params):
    """Run the ETS(A,A) recursion; returns (forecasts, residuals).

    reference: HoltWinters.scala:86-135 — initial level = mean of first
    period, initial trend = (mean2 - mean1)/periodicity, initial seasonal
    components = first period minus level.
    """
    alpha, beta, gamma = params[0], params[1], params[2]
    n = series.shape[0]

    first = jnp.mean(series[:periodicity])
    second = jnp.mean(series[periodicity : 2 * periodicity])
    level0 = first
    trend0 = (second - first) / periodicity
    season0 = series[:periodicity] - level0

    # state: (level, trend, season buffer of length `periodicity` where
    # season[0] is the component for the CURRENT step, last_level_trend sum)
    def step(state, y_t):
        level, trend, season = state
        s_t = season[0]
        new_level = alpha * (y_t - s_t) + (1 - alpha) * (level + trend)
        new_trend = beta * (new_level - level) + (1 - beta) * trend
        new_s = gamma * (y_t - level - trend) + (1 - gamma) * s_t
        season = jnp.concatenate([season[1:], jnp.array([new_s])])
        forecast_next = new_level + new_trend + season[0]
        return (new_level, new_trend, season), (level + trend + s_t, forecast_next)

    (level_n, trend_n, season_n), (fitted, _) = jax.lax.scan(
        step, (level0, trend0, season0), series
    )
    residuals = series - fitted

    # out-of-sample forecasts
    def forecast_step(state, _):
        level, trend, season = state
        y_hat = level + trend + season[0]
        new_level = alpha * (y_hat - season[0]) + (1 - alpha) * (level + trend)
        new_trend = beta * (new_level - level) + (1 - beta) * trend
        new_s = gamma * (y_hat - level - trend) + (1 - gamma) * season[0]
        season = jnp.concatenate([season[1:], jnp.array([new_s])])
        return (new_level, new_trend, season), y_hat

    _, forecasts = jax.lax.scan(
        forecast_step, (level_n, trend_n, season_n), None, length=num_forecasts
    )
    return forecasts, residuals


class HoltWinters(AnomalyDetectionStrategy):
    def __init__(self, metrics_interval: MetricInterval, seasonality: SeriesSeasonality):
        key = (seasonality, metrics_interval)
        periodicity = {
            (SeriesSeasonality.WEEKLY, MetricInterval.DAILY): 7,
            (SeriesSeasonality.YEARLY, MetricInterval.MONTHLY): 12,
        }.get(key)
        if periodicity is None:
            raise ValueError(
                f"Unsupported seasonality/interval combination: {key}"
            )
        self.series_periodicity = periodicity

    def _fit_params(self, training: np.ndarray, num_forecasts: int) -> np.ndarray:
        """L-BFGS-B over RSS with exact jax gradients
        (reference: HoltWinters.scala:138-174)."""
        from scipy.optimize import minimize

        from deequ_tpu.ops import runtime

        # the engine's compute dtype: float64 with x64, float32 on bare
        # TPU engines — requesting f64 there only produces truncation
        # warnings, not precision
        dtype = runtime.compute_dtype()
        series = jnp.asarray(training, dtype=dtype)

        def rss(params_np: np.ndarray):
            _, residuals = _holt_winters_fit(
                series, self.series_periodicity, num_forecasts, jnp.asarray(params_np)
            )
            return jnp.sum(residuals**2)

        value_and_grad = jax.value_and_grad(lambda p: rss(p))

        def objective(p):
            value, grad = value_and_grad(jnp.asarray(p, dtype=dtype))
            return float(value), np.asarray(grad, dtype=np.float64)

        # scipy's default ftol/gtol assume f64-accurate objectives; under
        # an f32 engine the evaluation noise (~1e-7 relative) would make
        # the line search terminate abnormally, so loosen the tolerances
        # to sit above that noise floor
        options = (
            {"ftol": 1e-6, "gtol": 1e-4}
            if np.dtype(dtype) == np.float32
            else {}
        )
        result = minimize(
            objective,
            x0=np.array([0.3, 0.1, 0.1]),
            jac=True,
            method="L-BFGS-B",
            bounds=[(0.0, 1.0)] * 3,
            options=options,
        )
        return result.x

    def detect(
        self, data_series: Sequence[float], search_interval: Tuple[int, int] = (0, 1 << 62)
    ) -> List[Tuple[int, Anomaly]]:
        if len(data_series) == 0:
            raise ValueError("Provided data series is empty")
        start, end = search_interval
        if start >= end:
            raise ValueError("Start must be before end")
        if start < 0 or end < 0:
            raise ValueError("The search interval needs to be strictly positive")
        if start < self.series_periodicity * 2:
            raise ValueError("Need at least two full cycles of data to estimate model")

        if start >= len(data_series):
            num_forecasts = 1
        else:
            num_forecasts = min(end, len(data_series)) - start

        training = np.asarray(data_series[:start], dtype=np.float64)
        params = self._fit_params(training, num_forecasts)

        forecasts, residuals = _holt_winters_fit(
            jnp.asarray(training), self.series_periodicity, num_forecasts, jnp.asarray(params)
        )
        forecasts = np.asarray(forecasts)
        # reference: stddev of |residuals| (HoltWinters.scala:236-237),
        # breeze stddev = sample stddev
        abs_residuals = np.abs(np.asarray(residuals))
        residual_sd = float(np.std(abs_residuals, ddof=1)) if len(abs_residuals) > 1 else 0.0

        test_series = np.asarray(data_series[start:], dtype=np.float64)
        out: List[Tuple[int, Anomaly]] = []
        for i in range(min(len(test_series), len(forecasts))):
            observed = float(test_series[i])
            forecasted = float(forecasts[i])
            if abs(observed - forecasted) > 1.96 * residual_sd:
                out.append(
                    (
                        i + start,
                        Anomaly(
                            observed,
                            1.0,
                            f"Forecasted {forecasted} for observed value {observed}",
                        ),
                    )
                )
        return out

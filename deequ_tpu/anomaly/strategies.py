"""Non-seasonal anomaly-detection strategies — vectorized numpy.

reference: anomalydetection/SimpleThresholdStrategy.scala:25,
RateOfChangeStrategy.scala:35-104, OnlineNormalStrategy.scala:39-155,
BatchNormalStrategy.scala:33-95. Detail strings mirror the reference.
"""

from __future__ import annotations

import math
import sys
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from deequ_tpu.anomaly.base import Anomaly, AnomalyDetectionStrategy

# the reference uses Double.MinValue/MaxValue, NOT infinities — the
# distinction matters: a one-sided normal strategy multiplies the
# missing side's factor by the stddev, and `inf * 0.0` is nan (which
# poisons the bounds check and flags every point of a zero-variance
# series), while `MaxValue * 0.0` is 0.
_DBL_MIN = -sys.float_info.max
_DBL_MAX = sys.float_info.max


@dataclass
class SimpleThresholdStrategy(AnomalyDetectionStrategy):
    """Out-of-[lower, upper] bounds."""

    upper_bound: float
    lower_bound: float = _DBL_MIN

    def __post_init__(self):
        if self.lower_bound > self.upper_bound:
            raise ValueError(
                "The lower bound must be smaller or equal to the upper bound."
            )

    def detect(self, data_series, search_interval) -> List[Tuple[int, Anomaly]]:
        start, end = search_interval
        if start > end:
            raise ValueError("The start of the interval can't be larger than the end.")
        out = []
        for index in range(start, min(end, len(data_series))):
            value = data_series[index]
            if value < self.lower_bound or value > self.upper_bound:
                detail = (
                    f"[SimpleThresholdStrategy]: Value {value} is not in "
                    f"bounds [{self.lower_bound}, {self.upper_bound}]"
                )
                out.append((index, Anomaly(value, 1.0, detail)))
        return out


@dataclass
class RateOfChangeStrategy(AnomalyDetectionStrategy):
    """Order-k discrete differences out of bounds."""

    max_rate_decrease: Optional[float] = None
    max_rate_increase: Optional[float] = None
    order: int = 1

    def __post_init__(self):
        if self.max_rate_decrease is None and self.max_rate_increase is None:
            raise ValueError(
                "At least one of the two limits (maxRateDecrease or "
                "maxRateIncrease) has to be specified."
            )
        lower = self.max_rate_decrease if self.max_rate_decrease is not None else _DBL_MIN
        upper = self.max_rate_increase if self.max_rate_increase is not None else _DBL_MAX
        if lower > upper:
            raise ValueError(
                "The maximal rate of increase has to be bigger than the "
                "maximal rate of decrease."
            )
        if self.order < 0:
            raise ValueError("Order of derivative cannot be negative.")

    def detect(self, data_series, search_interval) -> List[Tuple[int, Anomaly]]:
        start, end = search_interval
        if start > end:
            raise ValueError("The start of the interval cannot be larger than the end.")
        lower = self.max_rate_decrease if self.max_rate_decrease is not None else _DBL_MIN
        upper = self.max_rate_increase if self.max_rate_increase is not None else _DBL_MAX

        start_point = max(start - self.order, 0)
        data = np.asarray(data_series[start_point : min(end, len(data_series))], dtype=float)
        diffed = np.diff(data, n=self.order) if len(data) else data
        out = []
        for i, change in enumerate(diffed):
            if change < lower or change > upper:
                index = i + start_point + self.order
                detail = (
                    f"[RateOfChangeStrategy]: Change of {change} is not in bounds ["
                    f"{lower}, {upper}]. Order={self.order}"
                )
                out.append((index, Anomaly(data_series[index], 1.0, detail)))
        return out


@dataclass
class OnlineNormalStrategy(AnomalyDetectionStrategy):
    """Streaming Welford mean/stddev, optionally excluding detected
    anomalies from the stats, with a warm-up fraction."""

    lower_deviation_factor: Optional[float] = 3.0
    upper_deviation_factor: Optional[float] = 3.0
    ignore_start_percentage: float = 0.1
    ignore_anomalies: bool = True

    def __post_init__(self):
        if self.lower_deviation_factor is None and self.upper_deviation_factor is None:
            raise ValueError("At least one factor has to be specified.")
        if (self.lower_deviation_factor or 1.0) < 0 or (
            self.upper_deviation_factor or 1.0
        ) < 0:
            raise ValueError("Factors cannot be smaller than zero.")
        if not (0.0 <= self.ignore_start_percentage <= 1.0):
            raise ValueError(
                "Percentage of start values to ignore must be in interval [0, 1]."
            )

    def compute_stats_and_anomalies(
        self, data_series, search_interval=(0, 1 << 62)
    ) -> List[Tuple[float, float, bool]]:
        """reference: OnlineNormalStrategy.scala:70-121 — returns
        (mean, stddev, is_anomaly) per point."""
        results: List[Tuple[float, float, bool]] = []
        current_mean = 0.0
        current_variance = 0.0
        sn = 0.0
        num_to_skip = len(data_series) * self.ignore_start_percentage
        search_start, search_end = search_interval
        upper_factor = (
            self.upper_deviation_factor
            if self.upper_deviation_factor is not None
            else _DBL_MAX
        )
        lower_factor = (
            self.lower_deviation_factor
            if self.lower_deviation_factor is not None
            else _DBL_MAX
        )

        for index, value in enumerate(data_series):
            last_mean, last_variance, last_sn = current_mean, current_variance, sn
            if index == 0:
                current_mean = value
            else:
                current_mean = last_mean + (1.0 / (index + 1)) * (value - last_mean)
            sn += (value - last_mean) * (value - current_mean)
            current_variance = sn / (index + 1)
            std_dev = math.sqrt(current_variance)

            upper_bound = current_mean + upper_factor * std_dev
            lower_bound = current_mean - lower_factor * std_dev

            if (
                index < num_to_skip
                or index < search_start
                or index >= search_end
                or (lower_bound <= value <= upper_bound)
            ):
                results.append((current_mean, std_dev, False))
            else:
                if self.ignore_anomalies:
                    current_mean, current_variance, sn = last_mean, last_variance, last_sn
                results.append((current_mean, std_dev, True))
        return results

    def detect(self, data_series, search_interval) -> List[Tuple[int, Anomaly]]:
        start, end = search_interval
        if start > end:
            raise ValueError("The start of the interval can't be larger than the end.")
        upper_factor = (
            self.upper_deviation_factor
            if self.upper_deviation_factor is not None
            else _DBL_MAX
        )
        lower_factor = (
            self.lower_deviation_factor
            if self.lower_deviation_factor is not None
            else _DBL_MAX
        )
        stats = self.compute_stats_and_anomalies(data_series, search_interval)
        out = []
        for index in range(start, min(end, len(data_series))):
            mean, std_dev, is_anomaly = stats[index]
            if is_anomaly:
                lower_bound = mean - lower_factor * std_dev
                upper_bound = mean + upper_factor * std_dev
                detail = (
                    f"[OnlineNormalStrategy]: Value {data_series[index]} is not in "
                    f"bounds [{lower_bound}, {upper_bound}]."
                )
                out.append((index, Anomaly(data_series[index], 1.0, detail)))
        return out


@dataclass
class BatchNormalStrategy(AnomalyDetectionStrategy):
    """mean ± k·stddev computed from points outside (or including) the
    search interval."""

    lower_deviation_factor: Optional[float] = 3.0
    upper_deviation_factor: Optional[float] = 3.0
    include_interval: bool = False

    def __post_init__(self):
        if self.lower_deviation_factor is None and self.upper_deviation_factor is None:
            raise ValueError("At least one factor has to be specified.")
        if (self.lower_deviation_factor or 1.0) < 0 or (
            self.upper_deviation_factor or 1.0
        ) < 0:
            raise ValueError("Factors cannot be smaller than zero.")

    def detect(self, data_series, search_interval) -> List[Tuple[int, Anomaly]]:
        start, end = search_interval
        if start > end:
            raise ValueError("The start of the interval can't be larger than the end.")
        if len(data_series) == 0:
            raise ValueError("Data series is empty. Can't calculate mean/ stdDev.")
        interval_length = end - start
        if not self.include_interval and interval_length >= len(data_series):
            raise ValueError(
                "Excluding values in searchInterval from calculation but not "
                "enough values remain to calculate mean and stdDev."
            )
        series = np.asarray(data_series, dtype=float)
        if self.include_interval:
            basis = series
        else:
            basis = np.concatenate([series[:start], series[min(end, len(series)):]])
        mean = float(np.mean(basis))
        # sample stddev like breeze's meanAndVariance
        std_dev = float(np.std(basis, ddof=1)) if len(basis) > 1 else 0.0

        upper_factor = (
            self.upper_deviation_factor
            if self.upper_deviation_factor is not None
            else _DBL_MAX
        )
        lower_factor = (
            self.lower_deviation_factor
            if self.lower_deviation_factor is not None
            else _DBL_MAX
        )
        upper_bound = mean + upper_factor * std_dev
        lower_bound = mean - lower_factor * std_dev

        out = []
        for index in range(start, min(end, len(series))):
            value = float(series[index])
            if value > upper_bound or value < lower_bound:
                detail = (
                    f"[BatchNormalStrategy]: Value {value} is not in "
                    f"bounds [{lower_bound}, {upper_bound}]."
                )
                out.append((index, Anomaly(value, 1.0, detail)))
        return out

from deequ_tpu.applicability.applicability import (
    Applicability,
    AnalyzersApplicability,
    CheckApplicability,
    generate_random_data,
)

__all__ = [
    "Applicability",
    "AnalyzersApplicability",
    "CheckApplicability",
    "generate_random_data",
]

"""Applicability checker: dry-run constraints/analyzers on generated random
data matching a schema.

reference: analyzers/applicability/Applicability.scala:40-273 — 1000 rows,
~1% nulls for nullable fields, typed random generators. This doubles as the
framework's schema-level fake backend.
"""

from __future__ import annotations

import string
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deequ_tpu.checks.check import Check
from deequ_tpu.constraints.constraint import (
    AnalysisBasedConstraint,
    Constraint,
    ConstraintDecorator,
)
from deequ_tpu.data.table import Column, ColumnType, Table


@dataclass
class SchemaField:
    name: str
    ctype: ColumnType
    nullable: bool = True
    precision: int = 10
    scale: int = 2


@dataclass
class CheckApplicability:
    is_applicable: bool
    failures: List[Tuple[str, BaseException]]
    constraint_applicabilities: Dict[Constraint, bool]


@dataclass
class AnalyzersApplicability:
    is_applicable: bool
    failures: List[Tuple[str, BaseException]]


def generate_random_data(
    schema: Sequence[SchemaField], num_records: int = 1000, seed: Optional[int] = None
) -> Table:
    """reference: Applicability.scala:46-155 — ~1% nulls when nullable."""
    rng = np.random.default_rng(seed)
    columns = []
    for fld in schema:
        null_mask = (
            rng.random(num_records) < 0.01
            if fld.nullable
            else np.zeros(num_records, dtype=bool)
        )
        valid = ~null_mask
        if fld.ctype == ColumnType.BOOLEAN:
            values = rng.random(num_records) > 0.5
        elif fld.ctype == ColumnType.LONG:
            values = rng.integers(-(2**31), 2**31, num_records, dtype=np.int64)
        elif fld.ctype == ColumnType.DOUBLE:
            values = rng.random(num_records)
        elif fld.ctype == ColumnType.DECIMAL:
            digits = fld.precision - fld.scale
            # precision == scale means no whole digits: whole part is 0
            # (10**(digits-1) would be the float 0.1 and rng.integers
            # rejects it)
            lo = 10 ** (digits - 1) if digits > 0 else 0
            hi = 10**digits if digits > 0 else 1
            whole = rng.integers(lo, hi, num_records)
            frac = rng.integers(0, 10**fld.scale, num_records) if fld.scale > 0 else 0
            values = whole + (frac / (10**fld.scale) if fld.scale > 0 else 0.0)
            values = values.astype(np.float64)
        elif fld.ctype == ColumnType.TIMESTAMP:
            values = rng.integers(0, 2**41, num_records).astype("datetime64[ms]").astype(
                "datetime64[us]"
            )
        else:  # STRING: alphanumeric, length 1..20
            alphabet = np.array(list(string.ascii_letters + string.digits))
            values = np.empty(num_records, dtype=object)
            lengths = rng.integers(1, 21, num_records)
            for i in range(num_records):
                values[i] = "".join(rng.choice(alphabet, lengths[i]))
        if fld.ctype != ColumnType.STRING:
            values = np.asarray(values)
        columns.append(Column(fld.name, fld.ctype, values, valid))
    return Table(columns)


def _statically_decidable(analyzer) -> bool:
    """True when the static pass alone decides this analyzer's
    applicability: its failure modes are all plan-time facts
    (preconditions, expression parsing, column resolution, regex
    validity). User-supplied callables (Histogram binning UDFs) can fail
    in ways no static pass sees, so they keep the dynamic dry-run."""
    from deequ_tpu.analyzers import (
        ApproxCountDistinct,
        ApproxQuantile,
        ApproxQuantiles,
        Completeness,
        Compliance,
        Correlation,
        CountDistinct,
        DataType,
        Distinctness,
        Entropy,
        Histogram,
        Maximum,
        Mean,
        Minimum,
        MutualInformation,
        PatternMatch,
        Size,
        StandardDeviation,
        Sum,
        UniqueValueRatio,
        Uniqueness,
    )

    if isinstance(analyzer, Histogram):
        return analyzer.binning_udf is None
    return isinstance(
        analyzer,
        (
            ApproxCountDistinct,
            ApproxQuantile,
            ApproxQuantiles,
            Completeness,
            Compliance,
            Correlation,
            CountDistinct,
            DataType,
            Distinctness,
            Entropy,
            Maximum,
            Mean,
            Minimum,
            MutualInformation,
            PatternMatch,
            Size,
            StandardDeviation,
            Sum,
            UniqueValueRatio,
            Uniqueness,
        ),
    )


def _static_failure(analyzer, schema_info) -> Optional[BaseException]:
    """The exception a dry-run would surface for this analyzer, determined
    with zero data scans; None when the static pass finds no problem.
    Conservative: only failure modes that a real run would DEFINITELY hit
    (missing columns, wrong types, bad parameters, unparseable
    expressions, invalid regexes) are reported — a typecheck warning like
    a numeric comparison against a string literal does not fail a scan
    and must not fail applicability."""
    import re

    from deequ_tpu.analyzers.base import Preconditions
    from deequ_tpu.core.exceptions import NoSuchColumnException
    from deequ_tpu.data.expr import ExpressionParseError, Predicate

    err = Preconditions.find_first_failing(
        schema_info.empty_table(), analyzer.preconditions()
    )
    if err is not None:
        return err

    for attr in ("predicate", "where"):
        expression = getattr(analyzer, attr, None)
        if not isinstance(expression, str):
            continue
        try:
            predicate = Predicate(expression)
        except ExpressionParseError as e:
            return e
        for col in predicate.referenced_columns():
            if not schema_info.has(col):
                return NoSuchColumnException(
                    f"Input data does not include column {col}!"
                )

    pattern = getattr(analyzer, "pattern", None)
    if isinstance(pattern, str):
        try:
            re.compile(pattern)
        except re.error as e:
            return e

    return None


class Applicability:
    """reference: Applicability.scala:172-237 — but STATIC-FIRST: the
    plan-time analyzer (deequ_tpu/lint) decides whatever it can with zero
    scans; random data is generated and dry-run only for analyzers whose
    failure modes statics cannot rule out."""

    def is_applicable(
        self, check: Check, schema: Sequence[SchemaField], num_records: int = 1000
    ) -> CheckApplicability:
        from deequ_tpu.core.exceptions import wrap_if_necessary
        from deequ_tpu.lint import SchemaInfo

        schema_info = SchemaInfo.from_schema_fields(schema)
        constraint_applicabilities: Dict[Constraint, bool] = {}
        failures: List[Tuple[str, BaseException]] = []

        # static pass first; collect the constraints statics can't decide
        dynamic: List[Tuple[Constraint, AnalysisBasedConstraint]] = []
        for constraint in check.constraints:
            inner = (
                constraint.inner
                if isinstance(constraint, ConstraintDecorator)
                else constraint
            )
            if not isinstance(inner, AnalysisBasedConstraint):
                constraint_applicabilities[constraint] = True
                continue
            exc = _static_failure(inner.analyzer, schema_info)
            if exc is not None:
                constraint_applicabilities[constraint] = False
                failures.append((repr(constraint), wrap_if_necessary(exc)))
            elif _statically_decidable(inner.analyzer):
                constraint_applicabilities[constraint] = True
            else:
                dynamic.append((constraint, inner))

        # dynamic fallback only for what statics couldn't decide
        if dynamic:
            data = generate_random_data(schema, num_records)
            for constraint, inner in dynamic:
                metric = inner.analyzer.calculate(data)
                ok = metric.value.is_success
                constraint_applicabilities[constraint] = ok
                if not ok:
                    failures.append((repr(constraint), metric.value.exception))

        return CheckApplicability(
            not failures, failures, constraint_applicabilities
        )

    def are_applicable(
        self,
        analyzers: Sequence,
        schema: Sequence[SchemaField],
        num_records: int = 1000,
    ) -> AnalyzersApplicability:
        from deequ_tpu.core.exceptions import wrap_if_necessary
        from deequ_tpu.lint import SchemaInfo

        schema_info = SchemaInfo.from_schema_fields(schema)
        failures: List[Tuple[str, BaseException]] = []
        dynamic = []
        for analyzer in analyzers:
            exc = _static_failure(analyzer, schema_info)
            if exc is not None:
                failures.append((analyzer.instance, wrap_if_necessary(exc)))
            elif not _statically_decidable(analyzer):
                dynamic.append(analyzer)

        if dynamic:
            data = generate_random_data(schema, num_records)
            for analyzer in dynamic:
                metric = analyzer.calculate(data)
                if metric.value.is_failure:
                    failures.append((metric.instance, metric.value.exception))
        return AnalyzersApplicability(not failures, failures)

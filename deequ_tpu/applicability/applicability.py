"""Applicability checker: dry-run constraints/analyzers on generated random
data matching a schema.

reference: analyzers/applicability/Applicability.scala:40-273 — 1000 rows,
~1% nulls for nullable fields, typed random generators. This doubles as the
framework's schema-level fake backend.
"""

from __future__ import annotations

import string
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deequ_tpu.checks.check import Check
from deequ_tpu.constraints.constraint import (
    AnalysisBasedConstraint,
    Constraint,
    ConstraintDecorator,
)
from deequ_tpu.data.table import Column, ColumnType, Table


@dataclass
class SchemaField:
    name: str
    ctype: ColumnType
    nullable: bool = True
    precision: int = 10
    scale: int = 2


@dataclass
class CheckApplicability:
    is_applicable: bool
    failures: List[Tuple[str, BaseException]]
    constraint_applicabilities: Dict[Constraint, bool]


@dataclass
class AnalyzersApplicability:
    is_applicable: bool
    failures: List[Tuple[str, BaseException]]


def generate_random_data(
    schema: Sequence[SchemaField], num_records: int = 1000, seed: Optional[int] = None
) -> Table:
    """reference: Applicability.scala:46-155 — ~1% nulls when nullable."""
    rng = np.random.default_rng(seed)
    columns = []
    for fld in schema:
        null_mask = (
            rng.random(num_records) < 0.01
            if fld.nullable
            else np.zeros(num_records, dtype=bool)
        )
        valid = ~null_mask
        if fld.ctype == ColumnType.BOOLEAN:
            values = rng.random(num_records) > 0.5
        elif fld.ctype == ColumnType.LONG:
            values = rng.integers(-(2**31), 2**31, num_records, dtype=np.int64)
        elif fld.ctype == ColumnType.DOUBLE:
            values = rng.random(num_records)
        elif fld.ctype == ColumnType.DECIMAL:
            digits = fld.precision - fld.scale
            whole = rng.integers(10 ** (digits - 1), 10**digits, num_records)
            frac = rng.integers(0, 10**fld.scale, num_records) if fld.scale > 0 else 0
            values = whole + (frac / (10**fld.scale) if fld.scale > 0 else 0.0)
            values = values.astype(np.float64)
        elif fld.ctype == ColumnType.TIMESTAMP:
            values = rng.integers(0, 2**41, num_records).astype("datetime64[ms]").astype(
                "datetime64[us]"
            )
        else:  # STRING: alphanumeric, length 1..20
            alphabet = np.array(list(string.ascii_letters + string.digits))
            values = np.empty(num_records, dtype=object)
            lengths = rng.integers(1, 21, num_records)
            for i in range(num_records):
                values[i] = "".join(rng.choice(alphabet, lengths[i]))
        if fld.ctype != ColumnType.STRING:
            values = np.asarray(values)
        columns.append(Column(fld.name, fld.ctype, values, valid))
    return Table(columns)


class Applicability:
    """reference: Applicability.scala:172-237."""

    def is_applicable(
        self, check: Check, schema: Sequence[SchemaField], num_records: int = 1000
    ) -> CheckApplicability:
        data = generate_random_data(schema, num_records)
        constraint_applicabilities: Dict[Constraint, bool] = {}
        failures: List[Tuple[str, BaseException]] = []

        for constraint in check.constraints:
            inner = (
                constraint.inner
                if isinstance(constraint, ConstraintDecorator)
                else constraint
            )
            if not isinstance(inner, AnalysisBasedConstraint):
                constraint_applicabilities[constraint] = True
                continue
            metric = inner.analyzer.calculate(data)
            ok = metric.value.is_success
            constraint_applicabilities[constraint] = ok
            if not ok:
                failures.append((repr(constraint), metric.value.exception))

        return CheckApplicability(
            not failures, failures, constraint_applicabilities
        )

    def are_applicable(
        self,
        analyzers: Sequence,
        schema: Sequence[SchemaField],
        num_records: int = 1000,
    ) -> AnalyzersApplicability:
        data = generate_random_data(schema, num_records)
        failures: List[Tuple[str, BaseException]] = []
        for analyzer in analyzers:
            metric = analyzer.calculate(data)
            if metric.value.is_failure:
                failures.append((metric.instance, metric.value.exception))
        return AnalyzersApplicability(not failures, failures)

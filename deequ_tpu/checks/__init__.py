from deequ_tpu.checks.check import (
    Check,
    CheckLevel,
    CheckResult,
    CheckStatus,
    CheckWithLastConstraintFilterable,
)

__all__ = [
    "Check",
    "CheckLevel",
    "CheckResult",
    "CheckStatus",
    "CheckWithLastConstraintFilterable",
]

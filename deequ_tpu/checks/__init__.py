from deequ_tpu.checks.check import (
    Check,
    CheckLevel,
    CheckResult,
    CheckStatus,
    CheckWithLastConstraintFilterable,
)
from deequ_tpu.checks.drift import (
    DriftCheck,
    DriftCheckResult,
    DriftConstraint,
    DriftConstraintResult,
)

__all__ = [
    "Check",
    "CheckLevel",
    "CheckResult",
    "CheckStatus",
    "CheckWithLastConstraintFilterable",
    "DriftCheck",
    "DriftCheckResult",
    "DriftConstraint",
    "DriftConstraintResult",
]

"""The user-facing Check DSL: a fluent, immutable builder of constraint
groups with severity levels.

reference: checks/Check.scala:30-984 — the full DSL surface listed in
SURVEY.md §2.2 is reproduced method-for-method (Scala overloads become
Python default/keyword arguments).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Set

from deequ_tpu.analyzers import Patterns
from deequ_tpu.analyzers.base import Analyzer
from deequ_tpu.constraints import constraint as C
from deequ_tpu.constraints.constrainable_data_types import ConstrainableDataTypes
from deequ_tpu.constraints.constraint import (
    AnalysisBasedConstraint,
    Constraint,
    ConstraintDecorator,
    ConstraintResult,
    ConstraintStatus,
)


class CheckLevel(enum.Enum):
    ERROR = "Error"
    WARNING = "Warning"


class CheckStatus(enum.Enum):
    SUCCESS = "Success"
    WARNING = "Warning"
    ERROR = "Error"

    @property
    def severity(self) -> int:
        return {"Success": 0, "Warning": 1, "Error": 2}[self.value]


@dataclass
class CheckResult:
    check: "Check"
    status: CheckStatus
    constraint_results: List[ConstraintResult]


def is_one(value: float) -> bool:
    """The default assertion (reference: checks/Check.scala:907)."""
    return value == 1.0


class Check:
    """Immutable list of constraints + severity
    (reference: checks/Check.scala:59)."""

    IsOne = staticmethod(is_one)

    def __init__(
        self,
        level: CheckLevel,
        description: str,
        constraints: Optional[List[Constraint]] = None,
    ):
        self.level = level
        self.description = description
        self.constraints: List[Constraint] = list(constraints or [])

    # -- plumbing ------------------------------------------------------------

    def add_constraint(self, constraint: Constraint) -> "Check":
        """reference: Check.scala:71."""
        return self._copy_with(self.constraints + [constraint])

    def _copy_with(self, constraints: List[Constraint]) -> "Check":
        return Check(self.level, self.description, constraints)

    def _add_filterable_constraint(
        self, creation_func: Callable[[Optional[str]], Constraint]
    ) -> "CheckWithLastConstraintFilterable":
        """reference: Check.scala:76-84."""
        constraint_without_filtering = creation_func(None)
        return CheckWithLastConstraintFilterable(
            self.level,
            self.description,
            self.constraints + [constraint_without_filtering],
            creation_func,
        )

    # -- DSL (reference line numbers from checks/Check.scala) ----------------

    def has_size(self, assertion, hint=None) -> "CheckWithLastConstraintFilterable":
        # :97
        return self._add_filterable_constraint(
            lambda filter_: C.size_constraint(assertion, filter_, hint)
        )

    def is_complete(self, column, hint=None) -> "CheckWithLastConstraintFilterable":
        # :110
        return self._add_filterable_constraint(
            lambda filter_: C.completeness_constraint(column, is_one, filter_, hint)
        )

    def has_completeness(
        self, column, assertion, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        # :124
        return self._add_filterable_constraint(
            lambda filter_: C.completeness_constraint(column, assertion, filter_, hint)
        )

    def is_unique(self, column, hint=None) -> "Check":
        # :139
        return self.add_constraint(C.uniqueness_constraint([column], is_one, hint))

    def is_primary_key(self, column, *columns, hint=None) -> "Check":
        # :151/:164
        return self.add_constraint(
            C.uniqueness_constraint([column] + list(columns), is_one, hint)
        )

    def has_uniqueness(self, columns, assertion, hint=None) -> "Check":
        # :176/:189/:206/:219
        if isinstance(columns, str):
            columns = [columns]
        return self.add_constraint(C.uniqueness_constraint(columns, assertion, hint))

    def has_distinctness(self, columns, assertion, hint=None) -> "Check":
        # :232
        if isinstance(columns, str):
            columns = [columns]
        return self.add_constraint(C.distinctness_constraint(columns, assertion, hint))

    def has_unique_value_ratio(self, columns, assertion, hint=None) -> "Check":
        # :249
        if isinstance(columns, str):
            columns = [columns]
        return self.add_constraint(
            C.unique_value_ratio_constraint(columns, assertion, hint)
        )

    def has_number_of_distinct_values(
        self, column, assertion, binning_udf=None, max_bins=1000, hint=None
    ) -> "Check":
        # :269
        return self.add_constraint(
            C.histogram_bin_constraint(column, assertion, binning_udf, max_bins, hint)
        )

    def has_histogram_values(
        self, column, assertion, binning_udf=None, max_bins=1000, hint=None
    ) -> "Check":
        # :295
        return self.add_constraint(
            C.histogram_constraint(column, assertion, binning_udf, max_bins, hint)
        )

    def is_newest_point_non_anomalous(
        self,
        metrics_repository,
        anomaly_detection_strategy,
        analyzer,
        with_tag_values: Optional[Dict[str, str]] = None,
        after_date: Optional[int] = None,
        before_date: Optional[int] = None,
        hint=None,
    ) -> "Check":
        # :322 — assertion closes over the repository (reference :926-983)
        assertion = _is_newest_point_non_anomalous_assertion(
            metrics_repository,
            anomaly_detection_strategy,
            analyzer,
            with_tag_values or {},
            after_date,
            before_date,
        )
        return self.add_constraint(C.anomaly_constraint(analyzer, assertion, hint))

    def has_entropy(self, column, assertion, hint=None) -> "Check":
        # :353
        return self.add_constraint(C.entropy_constraint(column, assertion, hint))

    def has_mutual_information(self, column_a, column_b, assertion, hint=None) -> "Check":
        # :371
        return self.add_constraint(
            C.mutual_information_constraint(column_a, column_b, assertion, hint)
        )

    def has_approx_quantile(self, column, quantile, assertion, hint=None) -> "Check":
        # :391
        return self.add_constraint(
            C.approx_quantile_constraint(column, quantile, assertion, hint)
        )

    def has_min(self, column, assertion, hint=None) -> "CheckWithLastConstraintFilterable":
        # :409
        return self._add_filterable_constraint(
            lambda filter_: C.min_constraint(column, assertion, filter_, hint)
        )

    def has_max(self, column, assertion, hint=None) -> "CheckWithLastConstraintFilterable":
        # :426
        return self._add_filterable_constraint(
            lambda filter_: C.max_constraint(column, assertion, filter_, hint)
        )

    def has_mean(self, column, assertion, hint=None) -> "CheckWithLastConstraintFilterable":
        # :443
        return self._add_filterable_constraint(
            lambda filter_: C.mean_constraint(column, assertion, filter_, hint)
        )

    def has_sum(self, column, assertion, hint=None) -> "CheckWithLastConstraintFilterable":
        # :460
        return self._add_filterable_constraint(
            lambda filter_: C.sum_constraint(column, assertion, filter_, hint)
        )

    def has_standard_deviation(
        self, column, assertion, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        # :477
        return self._add_filterable_constraint(
            lambda filter_: C.standard_deviation_constraint(
                column, assertion, filter_, hint
            )
        )

    def has_approx_count_distinct(
        self, column, assertion, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        # :495
        return self._add_filterable_constraint(
            lambda filter_: C.approx_count_distinct_constraint(
                column, assertion, filter_, hint
            )
        )

    def has_correlation(
        self, column_a, column_b, assertion, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        # :514
        return self._add_filterable_constraint(
            lambda filter_: C.correlation_constraint(
                column_a, column_b, assertion, filter_, hint
            )
        )

    def satisfies(
        self, column_condition, constraint_name, assertion=None, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        # :538
        assertion = assertion if assertion is not None else is_one
        return self._add_filterable_constraint(
            lambda filter_: C.compliance_constraint(
                constraint_name, column_condition, assertion, filter_, hint
            )
        )

    def has_pattern(
        self, column, pattern, assertion=None, name=None, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        # :560
        assertion = assertion if assertion is not None else is_one
        return self._add_filterable_constraint(
            lambda filter_: C.pattern_match_constraint(
                column, pattern, assertion, filter_, name, hint
            )
        )

    def contains_credit_card_number(self, column, assertion=None, hint=None) -> "Check":
        # :581
        return self.has_pattern(
            column,
            Patterns.CREDITCARD,
            assertion,
            name=f"containsCreditCardNumber({column})",
            hint=hint,
        )

    def contains_email(self, column, assertion=None, hint=None) -> "Check":
        # :599
        return self.has_pattern(
            column, Patterns.EMAIL, assertion, name=f"containsEmail({column})", hint=hint
        )

    def contains_url(self, column, assertion=None, hint=None) -> "Check":
        # :616
        return self.has_pattern(
            column, Patterns.URL, assertion, name=f"containsURL({column})", hint=hint
        )

    def contains_social_security_number(self, column, assertion=None, hint=None) -> "Check":
        # :634
        return self.has_pattern(
            column,
            Patterns.SOCIAL_SECURITY_NUMBER_US,
            assertion,
            name=f"containsSocialSecurityNumber({column})",
            hint=hint,
        )

    def has_data_type(
        self, column, data_type: ConstrainableDataTypes, assertion=None, hint=None
    ) -> "Check":
        # :653
        assertion = assertion if assertion is not None else is_one
        return self.add_constraint(
            C.data_type_constraint(column, data_type, assertion, hint)
        )

    def is_non_negative(self, column, hint=None) -> "CheckWithLastConstraintFilterable":
        # :670 (NULL-coalescing predicate :676)
        return self.satisfies(
            f"COALESCE({column}, 0.0) >= 0", f"{column} is non-negative", hint=hint
        )

    def is_positive(self, column) -> "CheckWithLastConstraintFilterable":
        # :685
        return self.satisfies(f"COALESCE({column}, 1.0) > 0", f"{column} is positive")

    def is_less_than(self, column_a, column_b, hint=None) -> "CheckWithLastConstraintFilterable":
        # :699
        return self.satisfies(
            f"{column_a} < {column_b}", f"{column_a} is less than {column_b}", hint=hint
        )

    def is_less_than_or_equal_to(
        self, column_a, column_b, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        # :717
        return self.satisfies(
            f"{column_a} <= {column_b}",
            f"{column_a} is less than or equal to {column_b}",
            hint=hint,
        )

    def is_greater_than(self, column_a, column_b, hint=None) -> "CheckWithLastConstraintFilterable":
        # :735
        return self.satisfies(
            f"{column_a} > {column_b}", f"{column_a} is greater than {column_b}", hint=hint
        )

    def is_greater_than_or_equal_to(
        self, column_a, column_b, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        # :754
        return self.satisfies(
            f"{column_a} >= {column_b}",
            f"{column_a} is greater than or equal to {column_b}",
            hint=hint,
        )

    def is_contained_in(
        self,
        column,
        allowed_values=None,
        assertion=None,
        hint=None,
        lower_bound=None,
        upper_bound=None,
        include_lower_bound=True,
        include_upper_bound=True,
    ) -> "CheckWithLastConstraintFilterable":
        # values overloads :772-842, numeric range overload :855-871
        if allowed_values is not None:
            assertion = assertion if assertion is not None else is_one
            value_list = ",".join(
                "'" + str(v).replace("'", "''") + "'" for v in allowed_values
            )
            predicate = f"`{column}` IS NULL OR `{column}` IN ({value_list})"
            return self.satisfies(
                predicate,
                f"{column} contained in {','.join(str(v) for v in allowed_values)}",
                assertion,
                hint,
            )
        if lower_bound is None or upper_bound is None:
            raise ValueError(
                "isContainedIn requires allowed_values or lower_bound+upper_bound"
            )
        left_operand = ">=" if include_lower_bound else ">"
        right_operand = "<=" if include_upper_bound else "<"
        predicate = (
            f"`{column}` IS NULL OR "
            f"(`{column}` {left_operand} {lower_bound} AND "
            f"`{column}` {right_operand} {upper_bound})"
        )
        return self.satisfies(
            predicate, f"{column} between {lower_bound} and {upper_bound}", hint=hint
        )

    # -- evaluation ----------------------------------------------------------

    def evaluate(self, context) -> CheckResult:
        """reference: Check.scala:878-890."""
        constraint_results = [c.evaluate(context.metric_map) for c in self.constraints]
        any_failures = any(
            r.status == ConstraintStatus.FAILURE for r in constraint_results
        )
        if any_failures and self.level == CheckLevel.ERROR:
            status = CheckStatus.ERROR
        elif any_failures and self.level == CheckLevel.WARNING:
            status = CheckStatus.WARNING
        else:
            status = CheckStatus.SUCCESS
        return CheckResult(self, status, constraint_results)

    def required_analyzers(self) -> Set[Analyzer]:
        """reference: Check.scala:892-901."""
        out: Set[Analyzer] = set()
        for constraint in self.constraints:
            inner = constraint.inner if isinstance(constraint, ConstraintDecorator) else constraint
            if isinstance(inner, AnalysisBasedConstraint):
                out.add(inner.analyzer)
        return out

    def __repr__(self) -> str:
        return f"Check({self.level.value},{self.description},{len(self.constraints)} constraints)"


class CheckWithLastConstraintFilterable(Check):
    """Allows `.where(filter)` to rebuild the last constraint with a row
    filter (reference: checks/CheckWithLastConstraintFilterable.scala:22-41)."""

    def __init__(
        self,
        level: CheckLevel,
        description: str,
        constraints: List[Constraint],
        create_replacement: Callable[[Optional[str]], Constraint],
    ):
        super().__init__(level, description, constraints)
        self._create_replacement = create_replacement

    def where(self, filter_: str) -> Check:
        adjusted = self.constraints[:-1] + [self._create_replacement(filter_)]
        return Check(self.level, self.description, adjusted)


def _is_newest_point_non_anomalous_assertion(
    metrics_repository,
    anomaly_detection_strategy,
    analyzer,
    with_tag_values: Dict[str, str],
    after_date: Optional[int],
    before_date: Optional[int],
) -> Callable[[float], bool]:
    """Assertion closure that queries the repository for this analyzer's
    metric history and runs the detector on history + current value
    (reference: checks/Check.scala:926-983)."""

    def assertion(current_value: float) -> bool:
        from deequ_tpu.anomaly.detector import AnomalyDetector, DataPoint

        loader = metrics_repository.load()
        if with_tag_values:
            loader = loader.with_tag_values(with_tag_values)
        if after_date is not None:
            loader = loader.after(after_date)
        if before_date is not None:
            loader = loader.before(before_date)
        results = loader.get()

        data_points = []
        for result in results:
            metric = result.analyzer_context.metric_map.get(analyzer)
            value = None
            if metric is not None and metric.value.is_success:
                value = float(metric.value.get())
            data_points.append(DataPoint(result.result_key.data_set_date, value))

        # sort by time; detect on history + new point
        detector = AnomalyDetector(anomaly_detection_strategy)
        detection = detector.is_new_point_anomalous(data_points, current_value)
        return len(detection.anomalies) == 0

    return assertion

"""The drift Check family: two-sample state-vs-state constraints.

A `DriftCheck` compares two `StateBag`s — typically "this window's
merged states" against "the same window a week earlier"
(`WindowQuery.states(...)`) or a pinned training-time baseline — and
never rescans either side. It mirrors the ordinary `Check` builder
(immutable, chainable, CheckLevel severity) but evaluates against two
samples instead of one dataset, with its own result types: a
constraint here has no single-dataset metric, it has a drift measure.

    check = (DriftCheck(CheckLevel.ERROR, "weekly skew")
             .has_no_quantile_drift("latency_ms", max_quantile_shift=0.05)
             .has_no_cardinality_drift("user_id", max_ratio_drift=0.10))
    result = check.evaluate(current=this_week, baseline=last_week)

A missing baseline state or a plan-signature mismatch between the two
bags fails the affected constraints and attaches a DQ324 diagnostic
(caret-rendered over the constraint description) rather than raising —
a drifting dataset and a mis-wired baseline should both be visible in
the same result object.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Tuple

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    ApproxQuantile,
    Completeness,
    CountDistinct,
    Mean,
    StandardDeviation,
)
from deequ_tpu.analyzers.drift import (
    StateBag,
    cardinality_drift,
    completeness_drift,
    frequency_chi_square,
    mean_drift,
    quantile_drift,
    stddev_drift,
)
from deequ_tpu.checks.check import CheckLevel, CheckStatus
from deequ_tpu.constraints.constraint import ConstraintStatus
from deequ_tpu.lint.diagnostics import Diagnostic, Severity

__all__ = [
    "DriftCheck",
    "DriftCheckResult",
    "DriftConstraint",
    "DriftConstraintResult",
]


@dataclass(frozen=True)
class DriftConstraint:
    """One two-sample constraint: which analyzer's states to compare,
    how to turn the pair into a drift measure, and the threshold the
    measure must stay under (or, for p-values, over)."""

    description: str
    analyzer: Any
    measure: Callable[[Any, Any], float]
    threshold: float
    #: 'max' — fail when measure > threshold (distances, ratios);
    #: 'min' — fail when measure < threshold (p-values)
    mode: str = "max"

    def holds(self, value: float) -> bool:
        if value != value:  # NaN never passes
            return False
        if self.mode == "min":
            return value >= self.threshold
        return value <= self.threshold


@dataclass
class DriftConstraintResult:
    constraint: DriftConstraint
    status: ConstraintStatus
    message: Optional[str] = None
    #: the drift measure (None when a side was missing)
    value: Optional[float] = None


@dataclass
class DriftCheckResult:
    check: "DriftCheck"
    status: CheckStatus
    constraint_results: List[DriftConstraintResult]
    #: DQ324 diagnostics for missing/mismatched baselines
    diagnostics: List[Diagnostic] = field(default_factory=list)


class DriftCheck:
    """Immutable chainable builder of two-sample drift constraints,
    `Check`-shaped: every `has_no_*` returns a NEW DriftCheck."""

    def __init__(
        self,
        level: CheckLevel,
        description: str,
        constraints: Optional[List[DriftConstraint]] = None,
    ):
        self.level = level
        self.description = description
        self.constraints: Tuple[DriftConstraint, ...] = tuple(constraints or ())

    def _add(self, constraint: DriftConstraint) -> "DriftCheck":
        return DriftCheck(
            self.level, self.description, list(self.constraints) + [constraint]
        )

    # -- the family ----------------------------------------------------------

    def has_no_quantile_drift(
        self,
        column: str,
        max_quantile_shift: float = 0.05,
        *,
        quantile: float = 0.5,
        relative_error: float = 0.01,
    ) -> "DriftCheck":
        """Two-sample KS distance between the column's KLL sketches must
        stay <= `max_quantile_shift`. The `quantile` parameter only
        names which ApproxQuantile analyzer supplies the sketch — the
        comparison uses the whole sketch, not one quantile point."""
        return self._add(
            DriftConstraint(
                description=(
                    f"quantile drift of {column!r} <= {max_quantile_shift}"
                ),
                analyzer=ApproxQuantile(column, quantile, relative_error),
                measure=quantile_drift,
                threshold=float(max_quantile_shift),
            )
        )

    def has_no_cardinality_drift(
        self, column: str, max_ratio_drift: float = 0.10
    ) -> "DriftCheck":
        """HLL distinct-count ratio drift ``max(r, 1/r) - 1`` must stay
        <= `max_ratio_drift`."""
        return self._add(
            DriftConstraint(
                description=(
                    f"cardinality drift of {column!r} <= {max_ratio_drift}"
                ),
                analyzer=ApproxCountDistinct(column),
                measure=cardinality_drift,
                threshold=float(max_ratio_drift),
            )
        )

    def has_no_frequency_drift(
        self, column: str, min_p_value: float = 0.01
    ) -> "DriftCheck":
        """Two-sample chi-square over the column's frequency tables must
        NOT reject homogeneity: p-value >= `min_p_value`. Rides
        `CountDistinct([column])` states (a grouping analyzer — supply
        its states through `StateBag.from_provider`)."""
        return self._add(
            DriftConstraint(
                description=(
                    f"frequency drift of {column!r}: p >= {min_p_value}"
                ),
                analyzer=CountDistinct([column]),
                measure=lambda a, b: frequency_chi_square(a, b).p_value,
                threshold=float(min_p_value),
                mode="min",
            )
        )

    def has_no_completeness_drift(
        self, column: str, max_delta: float = 0.02
    ) -> "DriftCheck":
        return self._add(
            DriftConstraint(
                description=(
                    f"completeness drift of {column!r} <= {max_delta}"
                ),
                analyzer=Completeness(column),
                measure=completeness_drift,
                threshold=float(max_delta),
            )
        )

    def has_no_mean_drift(
        self, column: str, max_relative_delta: float = 0.05
    ) -> "DriftCheck":
        return self._add(
            DriftConstraint(
                description=f"mean drift of {column!r} <= {max_relative_delta}",
                analyzer=Mean(column),
                measure=mean_drift,
                threshold=float(max_relative_delta),
            )
        )

    def has_no_stddev_drift(
        self, column: str, max_relative_delta: float = 0.05
    ) -> "DriftCheck":
        return self._add(
            DriftConstraint(
                description=(
                    f"stddev drift of {column!r} <= {max_relative_delta}"
                ),
                analyzer=StandardDeviation(column),
                measure=stddev_drift,
                threshold=float(max_relative_delta),
            )
        )

    def has_no_drift(
        self,
        column: str,
        *,
        max_quantile_shift: Optional[float] = 0.05,
        max_cardinality_drift: Optional[float] = None,
        max_completeness_delta: Optional[float] = None,
        max_mean_delta: Optional[float] = None,
    ) -> "DriftCheck":
        """The convenience bundle from the issue's motivating example:
        `has_no_drift(column, against=last_week, max_quantile_shift=...)`
        — each non-None threshold adds its constraint."""
        check = self
        if max_quantile_shift is not None:
            check = check.has_no_quantile_drift(
                column, max_quantile_shift=max_quantile_shift
            )
        if max_cardinality_drift is not None:
            check = check.has_no_cardinality_drift(
                column, max_ratio_drift=max_cardinality_drift
            )
        if max_completeness_delta is not None:
            check = check.has_no_completeness_drift(
                column, max_delta=max_completeness_delta
            )
        if max_mean_delta is not None:
            check = check.has_no_mean_drift(
                column, max_relative_delta=max_mean_delta
            )
        return check

    # -- plumbing ------------------------------------------------------------

    def required_analyzers(self) -> List[Any]:
        """Deduplicated analyzers both samples must carry states for —
        feed these to `WindowQuery` (scan-shareable ones) and/or the
        state provider (grouping ones like CountDistinct)."""
        seen = set()
        out: List[Any] = []
        for c in self.constraints:
            if c.analyzer not in seen:
                seen.add(c.analyzer)
                out.append(c.analyzer)
        return out

    def _dq324(self, description: str, detail: str) -> Diagnostic:
        return Diagnostic(
            code="DQ324",
            severity=Severity.WARNING
            if self.level == CheckLevel.WARNING
            else Severity.ERROR,
            message=f"drift baseline unusable: {detail}",
            source=description,
            span=(0, len(description)),
            subject=f"drift check {self.description!r}",
        )

    def evaluate(
        self, current: StateBag, baseline: StateBag
    ) -> DriftCheckResult:
        """Compare the two samples constraint by constraint. Missing
        states on either side and bag-level plan-signature mismatches
        fail the affected constraints with DQ324 attached — never an
        exception, so a sentinel loop can keep watching a broken
        baseline wire-up."""
        diagnostics: List[Diagnostic] = []
        signature_ok = True
        if (
            current.signature is not None
            and baseline.signature is not None
            and current.signature != baseline.signature
        ):
            signature_ok = False
        results: List[DriftConstraintResult] = []
        for constraint in self.constraints:
            desc = constraint.description
            if not signature_ok:
                detail = (
                    f"plan signature mismatch: current "
                    f"{current.signature!r} vs baseline "
                    f"{baseline.signature!r}"
                )
                diagnostics.append(self._dq324(desc, detail))
                results.append(
                    DriftConstraintResult(
                        constraint, ConstraintStatus.FAILURE, detail
                    )
                )
                continue
            cur_state = current.get(constraint.analyzer)
            base_state = baseline.get(constraint.analyzer)
            if cur_state is None or base_state is None:
                side = "current" if cur_state is None else "baseline"
                label = (
                    getattr(
                        current if side == "current" else baseline, "label", ""
                    )
                    or side
                )
                detail = (
                    f"no {side} state for {constraint.analyzer!r} "
                    f"(sample {label!r})"
                )
                diagnostics.append(self._dq324(desc, detail))
                results.append(
                    DriftConstraintResult(
                        constraint, ConstraintStatus.FAILURE, detail
                    )
                )
                continue
            value = float(constraint.measure(cur_state, base_state))
            if constraint.holds(value):
                results.append(
                    DriftConstraintResult(
                        constraint,
                        ConstraintStatus.SUCCESS,
                        None,
                        value,
                    )
                )
            else:
                op = ">=" if constraint.mode == "min" else "<="
                results.append(
                    DriftConstraintResult(
                        constraint,
                        ConstraintStatus.FAILURE,
                        f"drift measure {value:.6g} violates "
                        f"{op} {constraint.threshold} ({desc})",
                        value,
                    )
                )
        if all(r.status == ConstraintStatus.SUCCESS for r in results):
            status = CheckStatus.SUCCESS
        elif self.level == CheckLevel.ERROR:
            status = CheckStatus.ERROR
        else:
            status = CheckStatus.WARNING
        return DriftCheckResult(self, status, results, diagnostics)

from deequ_tpu.constraints.constraint import (
    AnalysisBasedConstraint,
    Constraint,
    ConstraintDecorator,
    ConstraintResult,
    ConstraintStatus,
    NamedConstraint,
)
from deequ_tpu.constraints.constrainable_data_types import ConstrainableDataTypes

__all__ = [
    "AnalysisBasedConstraint",
    "Constraint",
    "ConstraintDecorator",
    "ConstraintResult",
    "ConstraintStatus",
    "NamedConstraint",
    "ConstrainableDataTypes",
]

"""reference: constraints/ConstrainableDataTypes.scala:19."""

import enum


class ConstrainableDataTypes(enum.Enum):
    NULL = "Null"
    FRACTIONAL = "Fractional"
    INTEGRAL = "Integral"
    BOOLEAN = "Boolean"
    STRING = "String"
    NUMERIC = "Numeric"

"""Constraints: bind an analyzer + value picker + assertion into a
pass/fail evaluation over a precomputed metric map.

reference: constraints/Constraint.scala:25-615,
constraints/AnalysisBasedConstraint.scala:42-122. Error-message texts are
part of the user-facing contract and mirror the reference.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Sequence

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    ApproxQuantile,
    Completeness,
    Compliance,
    Correlation,
    DataType,
    Distinctness,
    Entropy,
    Histogram,
    Maximum,
    Mean,
    Minimum,
    MutualInformation,
    PatternMatch,
    Size,
    StandardDeviation,
    Sum,
    UniqueValueRatio,
    Uniqueness,
)
from deequ_tpu.analyzers.base import Analyzer
from deequ_tpu.analyzers.scan import DataTypeInstances
from deequ_tpu.constraints.constrainable_data_types import ConstrainableDataTypes
from deequ_tpu.core.metrics import Distribution, Metric


class ConstraintStatus(enum.Enum):
    SUCCESS = "Success"
    FAILURE = "Failure"


@dataclass
class ConstraintResult:
    constraint: "Constraint"
    status: ConstraintStatus
    message: Optional[str] = None
    metric: Optional[Metric] = None


class Constraint:
    """reference: constraints/Constraint.scala:36-38."""

    def evaluate(self, analysis_results: Dict[Analyzer, Metric]) -> ConstraintResult:
        raise NotImplementedError


class ConstraintDecorator(Constraint):
    """reference: constraints/Constraint.scala:41-58."""

    def __init__(self, inner: Constraint):
        self._inner = inner

    @property
    def inner(self) -> Constraint:
        if isinstance(self._inner, ConstraintDecorator):
            return self._inner.inner
        return self._inner

    def evaluate(self, analysis_results: Dict[Analyzer, Metric]) -> ConstraintResult:
        result = self._inner.evaluate(analysis_results)
        result.constraint = self
        return result


class NamedConstraint(ConstraintDecorator):
    """Readable toString wrapper (reference: constraints/Constraint.scala:66)."""

    def __init__(self, constraint: Constraint, name: str):
        super().__init__(constraint)
        self._name = name

    def __repr__(self) -> str:
        return self._name


MISSING_ANALYSIS = "Missing Analysis, can't run the constraint!"
PROBLEMATIC_METRIC_PICKER = "Can't retrieve the value to assert on"
ASSERTION_EXCEPTION = "Can't execute the assertion"


class _ValuePickerException(Exception):
    pass


class _AssertionException(Exception):
    pass


class AnalysisBasedConstraint(Constraint):
    """The single generic evaluation engine
    (reference: constraints/AnalysisBasedConstraint.scala:42-122)."""

    def __init__(
        self,
        analyzer: Analyzer,
        assertion: Callable[[Any], bool],
        value_picker: Optional[Callable[[Any], Any]] = None,
        hint: Optional[str] = None,
    ):
        self.analyzer = analyzer
        self.assertion = assertion
        self.value_picker = value_picker
        self.hint = hint

    def calculate_and_evaluate(self, data) -> ConstraintResult:
        metric = self.analyzer.calculate(data)
        return self.evaluate({self.analyzer: metric})

    def evaluate(self, analysis_results: Dict[Analyzer, Metric]) -> ConstraintResult:
        metric = analysis_results.get(self.analyzer)
        if metric is None:
            return ConstraintResult(
                self, ConstraintStatus.FAILURE, MISSING_ANALYSIS, None
            )
        return self._pick_value_and_assert(metric)

    def _pick_value_and_assert(self, metric: Metric) -> ConstraintResult:
        if metric.value.is_failure:
            return ConstraintResult(
                self,
                ConstraintStatus.FAILURE,
                str(metric.value.exception),
                metric,
            )
        try:
            assert_on = self._run_picker(metric.value.get())
            assertion_ok = self._run_assertion(assert_on)
        except _AssertionException as e:
            return ConstraintResult(
                self,
                ConstraintStatus.FAILURE,
                f"{ASSERTION_EXCEPTION}: {e}!",
                metric,
            )
        except _ValuePickerException as e:
            return ConstraintResult(
                self,
                ConstraintStatus.FAILURE,
                f"{PROBLEMATIC_METRIC_PICKER}: {e}!",
                metric,
            )
        if assertion_ok:
            return ConstraintResult(self, ConstraintStatus.SUCCESS, metric=metric)
        message = f"Value: {_render_value(assert_on)} does not meet the constraint requirement!"
        if self.hint is not None:
            message += f" {self.hint}"
        return ConstraintResult(self, ConstraintStatus.FAILURE, message, metric)

    def _run_picker(self, metric_value):
        try:
            if self.value_picker is not None:
                return self.value_picker(metric_value)
            return metric_value
        except Exception as e:  # noqa: BLE001
            raise _ValuePickerException(str(e)) from e

    def _run_assertion(self, assert_on) -> bool:
        try:
            return bool(self.assertion(assert_on))
        except Exception as e:  # noqa: BLE001
            raise _AssertionException(str(e)) from e

    def __repr__(self) -> str:
        return f"AnalysisBasedConstraint({self.analyzer!r})"


def _render_value(value) -> str:
    """Scala renders doubles as e.g. 0.8 — Python float repr matches."""
    return str(value)


# ---------------------------------------------------------------------------
# Factories (reference: constraints/Constraint.scala:83-613)
# ---------------------------------------------------------------------------


def size_constraint(
    assertion: Callable[[int], bool],
    where: Optional[str] = None,
    hint: Optional[str] = None,
) -> Constraint:
    size = Size(where)
    constraint = AnalysisBasedConstraint(
        size, assertion, value_picker=lambda d: int(d), hint=hint
    )
    return NamedConstraint(constraint, f"SizeConstraint({size!r})")


def completeness_constraint(
    column: str,
    assertion: Callable[[float], bool],
    where: Optional[str] = None,
    hint: Optional[str] = None,
) -> Constraint:
    completeness = Completeness(column, where)
    constraint = AnalysisBasedConstraint(completeness, assertion, hint=hint)
    return NamedConstraint(constraint, f"CompletenessConstraint({completeness!r})")


def anomaly_constraint(
    analyzer: Analyzer,
    anomaly_assertion: Callable[[float], bool],
    hint: Optional[str] = None,
) -> Constraint:
    constraint = AnalysisBasedConstraint(analyzer, anomaly_assertion, hint=hint)
    return NamedConstraint(constraint, f"AnomalyConstraint({analyzer!r})")


def uniqueness_constraint(
    columns: Sequence[str],
    assertion: Callable[[float], bool],
    hint: Optional[str] = None,
) -> Constraint:
    uniqueness = Uniqueness(list(columns))
    constraint = AnalysisBasedConstraint(uniqueness, assertion, hint=hint)
    return NamedConstraint(constraint, f"UniquenessConstraint({uniqueness!r})")


def distinctness_constraint(
    columns: Sequence[str],
    assertion: Callable[[float], bool],
    hint: Optional[str] = None,
) -> Constraint:
    distinctness = Distinctness(list(columns))
    constraint = AnalysisBasedConstraint(distinctness, assertion, hint=hint)
    return NamedConstraint(constraint, f"DistinctnessConstraint({distinctness!r})")


def unique_value_ratio_constraint(
    columns: Sequence[str],
    assertion: Callable[[float], bool],
    hint: Optional[str] = None,
) -> Constraint:
    ratio = UniqueValueRatio(list(columns))
    constraint = AnalysisBasedConstraint(ratio, assertion, hint=hint)
    # missing ")" is deliberate: mirrors the reference's own toString typo
    # (reference: constraints/Constraint.scala:254) for output parity
    return NamedConstraint(constraint, f"UniqueValueRatioConstraint({ratio!r}")


def compliance_constraint(
    name: str,
    column_condition: str,
    assertion: Callable[[float], bool],
    where: Optional[str] = None,
    hint: Optional[str] = None,
) -> Constraint:
    compliance = Compliance(name, column_condition, where)
    constraint = AnalysisBasedConstraint(compliance, assertion, hint=hint)
    return NamedConstraint(constraint, f"ComplianceConstraint({compliance!r})")


def pattern_match_constraint(
    column: str,
    pattern: str,
    assertion: Callable[[float], bool],
    where: Optional[str] = None,
    name: Optional[str] = None,
    hint: Optional[str] = None,
) -> Constraint:
    pattern_match = PatternMatch(column, pattern, where)
    constraint = AnalysisBasedConstraint(pattern_match, assertion, hint=hint)
    constraint_name = (
        name if name is not None else f"PatternMatchConstraint({column}, {pattern})"
    )
    return NamedConstraint(constraint, constraint_name)


def entropy_constraint(
    column: str,
    assertion: Callable[[float], bool],
    hint: Optional[str] = None,
) -> Constraint:
    entropy = Entropy(column)
    constraint = AnalysisBasedConstraint(entropy, assertion, hint=hint)
    return NamedConstraint(constraint, f"EntropyConstraint({entropy!r})")


def mutual_information_constraint(
    column_a: str,
    column_b: str,
    assertion: Callable[[float], bool],
    hint: Optional[str] = None,
) -> Constraint:
    mutual_information = MutualInformation(column_a, column_b)
    constraint = AnalysisBasedConstraint(mutual_information, assertion, hint=hint)
    return NamedConstraint(
        constraint, f"MutualInformationConstraint({mutual_information!r})"
    )


def approx_quantile_constraint(
    column: str,
    quantile: float,
    assertion: Callable[[float], bool],
    hint: Optional[str] = None,
) -> Constraint:
    approx_quantile = ApproxQuantile(column, quantile)
    constraint = AnalysisBasedConstraint(approx_quantile, assertion, hint=hint)
    return NamedConstraint(constraint, f"ApproxQuantileConstraint({approx_quantile!r})")


def min_constraint(
    column: str,
    assertion: Callable[[float], bool],
    where: Optional[str] = None,
    hint: Optional[str] = None,
) -> Constraint:
    minimum = Minimum(column, where)
    constraint = AnalysisBasedConstraint(minimum, assertion, hint=hint)
    return NamedConstraint(constraint, f"MinimumConstraint({minimum!r})")


def max_constraint(
    column: str,
    assertion: Callable[[float], bool],
    where: Optional[str] = None,
    hint: Optional[str] = None,
) -> Constraint:
    maximum = Maximum(column, where)
    constraint = AnalysisBasedConstraint(maximum, assertion, hint=hint)
    return NamedConstraint(constraint, f"MaximumConstraint({maximum!r})")


def mean_constraint(
    column: str,
    assertion: Callable[[float], bool],
    where: Optional[str] = None,
    hint: Optional[str] = None,
) -> Constraint:
    mean = Mean(column, where)
    constraint = AnalysisBasedConstraint(mean, assertion, hint=hint)
    return NamedConstraint(constraint, f"MeanConstraint({mean!r})")


def sum_constraint(
    column: str,
    assertion: Callable[[float], bool],
    where: Optional[str] = None,
    hint: Optional[str] = None,
) -> Constraint:
    sum_analyzer = Sum(column, where)
    constraint = AnalysisBasedConstraint(sum_analyzer, assertion, hint=hint)
    return NamedConstraint(constraint, f"SumConstraint({sum_analyzer!r})")


def standard_deviation_constraint(
    column: str,
    assertion: Callable[[float], bool],
    where: Optional[str] = None,
    hint: Optional[str] = None,
) -> Constraint:
    std = StandardDeviation(column, where)
    constraint = AnalysisBasedConstraint(std, assertion, hint=hint)
    return NamedConstraint(constraint, f"StandardDeviationConstraint({std!r})")


def approx_count_distinct_constraint(
    column: str,
    assertion: Callable[[float], bool],
    where: Optional[str] = None,
    hint: Optional[str] = None,
) -> Constraint:
    approx = ApproxCountDistinct(column, where)
    constraint = AnalysisBasedConstraint(approx, assertion, hint=hint)
    return NamedConstraint(constraint, f"ApproxCountDistinctConstraint({approx!r})")


def correlation_constraint(
    column_a: str,
    column_b: str,
    assertion: Callable[[float], bool],
    where: Optional[str] = None,
    hint: Optional[str] = None,
) -> Constraint:
    correlation = Correlation(column_a, column_b, where)
    constraint = AnalysisBasedConstraint(correlation, assertion, hint=hint)
    return NamedConstraint(constraint, f"CorrelationConstraint({correlation!r})")


def histogram_constraint(
    column: str,
    assertion: Callable[[Distribution], bool],
    binning_udf=None,
    max_bins: int = 1000,
    hint: Optional[str] = None,
) -> Constraint:
    histogram = Histogram(column, binning_udf, max_bins)
    constraint = AnalysisBasedConstraint(histogram, assertion, hint=hint)
    return NamedConstraint(constraint, f"HistogramConstraint({histogram!r})")


def histogram_bin_constraint(
    column: str,
    assertion: Callable[[int], bool],
    binning_udf=None,
    max_bins: int = 1000,
    hint: Optional[str] = None,
) -> Constraint:
    histogram = Histogram(column, binning_udf, max_bins)
    constraint = AnalysisBasedConstraint(
        histogram,
        assertion,
        value_picker=lambda d: d.number_of_bins,
        hint=hint,
    )
    return NamedConstraint(constraint, f"HistogramBinConstraint({histogram!r})")


def data_type_constraint(
    column: str,
    data_type: ConstrainableDataTypes,
    assertion: Callable[[float], bool],
    hint: Optional[str] = None,
) -> Constraint:
    """reference: Constraint.scala:548-613 (ratioTypes value picker)."""

    def ratio_types(ignore_unknown: bool, key_type: str, distribution: Distribution) -> float:
        if ignore_unknown:
            dv = distribution.values.get(key_type)
            absolute = dv.absolute if dv is not None else 0
            if absolute == 0:
                return 0.0
            num_values = sum(v.absolute for v in distribution.values.values())
            unknown = distribution.values.get(DataTypeInstances.UNKNOWN)
            num_unknown = unknown.absolute if unknown is not None else 0
            sum_non_null = num_values - num_unknown
            return absolute / sum_non_null
        dv = distribution.values.get(key_type)
        return dv.ratio if dv is not None else 0.0

    def picker(distribution: Distribution) -> float:
        if data_type == ConstrainableDataTypes.NULL:
            return ratio_types(False, DataTypeInstances.UNKNOWN, distribution)
        if data_type == ConstrainableDataTypes.FRACTIONAL:
            return ratio_types(True, DataTypeInstances.FRACTIONAL, distribution)
        if data_type == ConstrainableDataTypes.INTEGRAL:
            return ratio_types(True, DataTypeInstances.INTEGRAL, distribution)
        if data_type == ConstrainableDataTypes.BOOLEAN:
            return ratio_types(True, DataTypeInstances.BOOLEAN, distribution)
        if data_type == ConstrainableDataTypes.STRING:
            return ratio_types(True, DataTypeInstances.STRING, distribution)
        # NUMERIC = fractional + integral
        return ratio_types(True, DataTypeInstances.FRACTIONAL, distribution) + ratio_types(
            True, DataTypeInstances.INTEGRAL, distribution
        )

    return AnalysisBasedConstraint(
        DataType(column), assertion, value_picker=picker, hint=hint
    )

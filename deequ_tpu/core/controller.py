"""Cooperative run control: cancel tokens, deadlines, bounded retry,
and the stall watchdog (ISSUE 13 tentpole).

A `RunController` threads suite -> runner -> fused scan and is honored
at batch granularity: the fold loops probe `controller.check()` between
batches (an attribute check when no controller is attached), and a
tripped check raises `RunCancelled` carrying the run's progress. The
raise unwinds through `contextlib.closing` around the staged pipeline
and the source's `batches()` generator, so every stage thread, decode
worker, readahead slot and file descriptor joins through the SAME
shutdown contract an exhausted scan uses (pinned by
tests/test_pipeline_shutdown.py) — cancellation is just an early exit,
not a second teardown path.

All clock reads live here (core/), keeping the TIMING lint's ban on
ad-hoc timing in ops/ and runners/ intact: those layers call
`check()` / `beat()` and never read a clock themselves.

The DQ4xx runtime taxonomy (plan-time lints own DQ1xx-DQ3xx):

  * DQ401 — run cancelled by an explicit `cancel()`;
  * DQ402 — run deadline exceeded;
  * DQ403 — reserved: a retry budget exhausted WITHOUT a degrade path
    (every current retry site degrades to the pyarrow fallback instead,
    counted in `engine.retry.*` telemetry — never a wrong answer);
  * DQ404 — run stalled: the watchdog saw no batch progress for the
    stall window and cancelled the run after dumping per-stage state.

Service-era additions (ISSUE 14): the `DQService` scheduler needs to
stop a run WITHOUT losing its committed partition states, so the
controller also carries a *soft* cancel — `cancel_at_boundary()` —
that trips only at checks marked `boundary=True` (the partition
boundaries in `FusedScanPass._run_partitioned`, where every finished
partition has already committed to the StateRepository). In-flight
batches keep folding until the current partition lands; the raise then
unwinds through the same closing() shutdown contract a hard cancel
uses. Reasons/codes for the soft path:

  * DQ405 — run preempted: the scheduler evicted a heavy run so a
    cheaper one could take its worker; the submission is requeued and
    its resume loads the committed partitions (bit-identical — pinned
    by tests/test_service.py);
  * DQ406 — run stopped at a partition boundary because the tenant's
    scan-bytes/disk quota ran out mid-run (admission-time quota
    rejections are the service's DQ411);
  * DQ407 — run stopped by a graceful drain (SIGTERM): the partition
    in flight committed, the rest resumes after restart.

A `boundary_probe` hook — set by the service — runs at every boundary
check with the run's progress dict and may return a soft-cancel reason
(the per-partition quota-charging seam).
"""

from __future__ import annotations

import os
import random
import sys
import threading
import time
import traceback
from typing import Any, Callable, Dict, Optional, Tuple

DQ_CANCELLED = "DQ401"
DQ_DEADLINE = "DQ402"
DQ_RETRIES_EXHAUSTED = "DQ403"  # reserved — see module docstring
DQ_STALLED = "DQ404"
DQ_PREEMPTED = "DQ405"
DQ_QUOTA = "DQ406"
DQ_DRAIN = "DQ407"

_REASON_CODES = {
    "cancelled": DQ_CANCELLED,
    "deadline": DQ_DEADLINE,
    "stalled": DQ_STALLED,
    "preempted": DQ_PREEMPTED,
    "quota": DQ_QUOTA,
    "drain": DQ_DRAIN,
}

#: soft-cancel reasons: these trip only at `boundary=True` checks, so
#: the partition in flight commits its states before the run unwinds
SOFT_REASONS = frozenset({"preempted", "quota", "drain"})


class RunCancelled(RuntimeError):
    """A run ended early on purpose: explicit cancel, deadline, or the
    stall watchdog. Carries the DQ4xx code and a progress dict (batches
    and — for partitioned runs — partitions completed), so the caller
    knows exactly what a rerun will resume from: every partition
    committed to the StateRepository before the cancel loads from cache
    instead of rescanning."""

    def __init__(
        self,
        reason: str,
        *,
        where: str = "",
        progress: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.reason = reason
        self.code = _REASON_CODES.get(reason, DQ_CANCELLED)
        self.where = where
        self.progress = dict(progress or {})
        detail = f" at {where}" if where else ""
        extra = ""
        if self.progress:
            extra = " (" + ", ".join(
                f"{k}={v}" for k, v in sorted(self.progress.items())
            ) + ")"
        super().__init__(f"[{self.code}] run {reason}{detail}{extra}")


class RunController:
    """Cooperative cancel token + optional deadline for one run.

    Thread-safe: any thread may `cancel()`; the run's fold loop calls
    `check()` between batches and raises `RunCancelled` once tripped.
    `beat()` is the watchdog's liveness signal — one call per folded
    batch, a plain int increment on the fold thread."""

    def __init__(self, deadline_s: Optional[float] = None) -> None:
        self.deadline_s = float(deadline_s) if deadline_s is not None else None
        self._deadline_at = (
            time.monotonic() + self.deadline_s
            if self.deadline_s is not None
            else None
        )
        self._cancel = threading.Event()
        self._reason: str = "cancelled"
        self._soft_cancel = threading.Event()
        self._soft_reason: str = "preempted"
        self._boundary_probe: Optional[
            Callable[[Dict[str, Any]], Optional[str]]
        ] = None
        self.beats = 0

    def cancel(self, reason: str = "cancelled") -> None:
        """Trip the token; the run raises RunCancelled at its next
        check. First cancel wins the reason."""
        if not self._cancel.is_set():
            self._reason = reason
            self._cancel.set()

    def cancel_at_boundary(self, reason: str = "preempted") -> None:
        """Soft cancel: trip the run at its next `boundary=True` check
        only — batch-granularity checks pass through, so the partition
        in flight finishes and commits its states before the raise.
        First soft cancel wins the reason; a hard `cancel()` still
        overrides everywhere."""
        if not self._soft_cancel.is_set():
            self._soft_reason = reason
            self._soft_cancel.set()

    def set_boundary_probe(
        self, probe: Optional[Callable[[Dict[str, Any]], Optional[str]]]
    ) -> None:
        """Install a hook run at every boundary check with the progress
        dict; a non-None return soft-cancels with that reason. The
        service charges per-partition quota usage through it."""
        self._boundary_probe = probe

    def bind_shared_cancel(self, token: "SharedCancelToken") -> None:
        """Chain a cross-process `SharedCancelToken` into the boundary
        probe: a token tripped by ANY shard (or the launcher) cancels
        this run at its next partition boundary — the partition in
        flight commits first, exactly like a service preemption. An
        existing probe keeps running and wins on a non-None reason."""
        prev = self._boundary_probe

        def probe(progress: Dict[str, Any]) -> Optional[str]:
            if prev is not None:
                reason = prev(progress)
                if reason:
                    return reason
            return token.reason()

        self._boundary_probe = probe

    @property
    def cancelled(self) -> bool:
        return self._cancel.is_set()

    @property
    def soft_cancelled(self) -> bool:
        return self._soft_cancel.is_set()

    def remaining_s(self) -> Optional[float]:
        """Seconds until the deadline, or None when none is set."""
        if self._deadline_at is None:
            return None
        return self._deadline_at - time.monotonic()

    def beat(self) -> None:
        """One unit of forward progress (a folded batch): feeds the
        stall watchdog. Single-writer (the fold thread)."""
        self.beats += 1

    def check(
        self,
        where: str = "",
        progress: Optional[Dict[str, Any]] = None,
        *,
        boundary: bool = False,
    ) -> None:
        """Raise RunCancelled when cancelled or past the deadline.
        `boundary=True` marks a resume point (a partition boundary:
        everything before it has committed): only there do soft cancels
        trip and the boundary probe run."""
        if self._cancel.is_set():
            raise RunCancelled(self._reason, where=where, progress=progress)
        if self._deadline_at is not None and time.monotonic() > self._deadline_at:
            self._reason = "deadline"
            self._cancel.set()
            raise RunCancelled("deadline", where=where, progress=progress)
        if boundary:
            probe = self._boundary_probe
            if probe is not None:
                reason = probe(dict(progress or {}))
                if reason:
                    self.cancel_at_boundary(reason)
            if self._soft_cancel.is_set():
                raise RunCancelled(
                    self._soft_reason, where=where, progress=progress
                )


class SharedCancelToken:
    """Cross-process boundary-cancel rendezvous for the sharded scan
    (parallel/multihost.py): one file on a filesystem every shard can
    see. Tripping publishes a reason atomically (tmp + rename); every
    shard's boundary probe (`RunController.bind_shared_cancel`) polls
    `reason()` at its partition boundaries — a stat of one path, no
    collective, so a cancel propagates without waiting for the next
    allgather. First trip effectively wins (a near-simultaneous second
    trip may overwrite the reason; ANY published reason cancels).

    All failure modes degrade to "not tripped": a token on a vanished
    directory simply never fires, it cannot wedge or crash a run."""

    def __init__(self, path: str) -> None:
        self.path = str(path)

    def trip(self, reason: str = "cancelled") -> None:
        if os.path.exists(self.path):
            return
        tmp = f"{self.path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as handle:
                handle.write(reason)
            os.replace(tmp, self.path)
        except OSError:  # fault-ok: a failed trip = not tripped
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def reason(self) -> Optional[str]:
        """The published cancel reason, or None while untripped. An
        empty or unreadable file reads as a plain "cancelled"."""
        if not os.path.exists(self.path):
            return None
        try:
            with open(self.path, encoding="utf-8") as handle:
                text = handle.read().strip()
        except OSError:
            return "cancelled"
        return text or "cancelled"

    @property
    def tripped(self) -> bool:
        return self.reason() is not None


class StallWatchdog:
    """Heartbeat-driven stall detector: a timer thread that watches the
    controller's beat counter. One full window with no beat dumps
    per-stage state (the live heartbeat snapshot when one is running,
    else the deequ-* thread stacks) to stderr; a second consecutive
    silent window cancels the run with reason "stalled" (DQ404), so the
    wedged scan fails with forensics instead of hanging forever.

    The dump-then-cancel split is deliberate: a slow batch that recovers
    costs one diagnostic dump, not the run."""

    def __init__(
        self,
        controller: RunController,
        timeout_s: float,
        *,
        out=None,
        snapshot_fn: Optional[Callable[[], Any]] = None,
    ) -> None:
        self.controller = controller
        self.timeout_s = float(timeout_s)
        self.dumps = 0
        self._out = out if out is not None else sys.stderr
        self._snapshot_fn = snapshot_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "StallWatchdog":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="deequ-watchdog"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        last = self.controller.beats
        silent_windows = 0
        while not self._stop.wait(self.timeout_s):
            now = self.controller.beats
            if now != last:
                last = now
                silent_windows = 0
                continue
            silent_windows += 1
            self._dump(now, silent_windows)
            if silent_windows >= 2:
                self.controller.cancel("stalled")
                return

    def _dump(self, beats: int, silent_windows: int) -> None:
        self.dumps += 1
        lines = [
            f"deequ-watchdog: no batch progress for "
            f"{silent_windows * self.timeout_s:g}s "
            f"(beats={beats}, window={self.timeout_s:g}s)"
        ]
        snap = None
        if self._snapshot_fn is not None:
            try:
                snap = self._snapshot_fn()
            except Exception:  # noqa: BLE001 — diagnostics must not kill the run
                snap = None
        if snap:
            lines.append(f"deequ-watchdog: stage state: {snap}")
        else:
            lines.extend(_engine_thread_stacks())
        try:
            self._out.write("\n".join(lines) + "\n")
            self._out.flush()
        except Exception:  # noqa: BLE001
            pass


def _engine_thread_stacks(prefix: str = "deequ-") -> list:
    """One-line-per-frame stacks of the engine's worker threads — the
    per-stage state dump when no heartbeat snapshot is live."""
    frames = sys._current_frames()
    lines = []
    for t in threading.enumerate():
        if not t.name.startswith(prefix) or t.name == "deequ-watchdog":
            continue
        frame = frames.get(t.ident)
        if frame is None:
            continue
        stack = traceback.extract_stack(frame)
        tail = stack[-1] if stack else None
        where = f"{tail.filename}:{tail.lineno} {tail.name}" if tail else "?"
        lines.append(f"deequ-watchdog:   {t.name} @ {where}")
    return lines or ["deequ-watchdog:   (no engine worker threads alive)"]


def backoff_s(base_s: float, attempt: int, key: str = "") -> float:
    """Exponential backoff with deterministic jitter for retry attempt
    `attempt` (0-based): `base * 2^attempt * U`, U in [0.5, 1.5) hashed
    from (key, attempt) — reproducible schedules under a fixed key, no
    thundering herd across readahead slots (each slot keys by unit)."""
    jitter = 0.5 + random.Random(f"{key}:{attempt}").random()
    return base_s * (2.0 ** attempt) * jitter


def retry_call(
    fn: Callable[[], Any],
    *,
    attempts: int,
    base_s: float,
    key: str = "",
    retryable: Tuple[type, ...] = (OSError,),
) -> Tuple[Any, int, bool]:
    """Call `fn` with up to `attempts` retries and exponential backoff.

    A `None` return counts as a transient failure too (the native
    reader's short-read signal). Returns `(result, retries_used,
    recovered)`; exhaustion returns `(None, attempts, False)` — the
    caller degrades (pyarrow fallback), it never re-raises. Exceptions
    outside `retryable` propagate untouched."""
    retries = 0
    for attempt in range(attempts + 1):
        try:
            result = fn()
        except retryable:
            result = None
        if result is not None:
            return result, retries, retries > 0
        if attempt < attempts:
            retries += 1
            time.sleep(backoff_s(base_s, attempt, key))
    return None, retries, False


__all__ = [
    "DQ_CANCELLED",
    "DQ_DEADLINE",
    "DQ_DRAIN",
    "DQ_PREEMPTED",
    "DQ_QUOTA",
    "DQ_RETRIES_EXHAUSTED",
    "DQ_STALLED",
    "SOFT_REASONS",
    "RunCancelled",
    "RunController",
    "SharedCancelToken",
    "StallWatchdog",
    "backoff_s",
    "retry_call",
]

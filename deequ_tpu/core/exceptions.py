"""Typed metric-calculation failures.

Reference: analyzers/runners/MetricCalculationException.scala:19-78.
Failure messages are part of the framework contract (they surface inside
failed metrics and constraint results), so the texts mirror the reference.
"""

from __future__ import annotations


class MetricCalculationException(Exception):
    pass


class MetricCalculationRuntimeException(MetricCalculationException):
    pass


class NoSuchColumnException(MetricCalculationRuntimeException):
    pass


class WrongColumnTypeException(MetricCalculationRuntimeException):
    pass


class NoColumnsSpecifiedException(MetricCalculationRuntimeException):
    pass


class NumberOfSpecifiedColumnsException(MetricCalculationRuntimeException):
    pass


class IllegalAnalyzerParameterException(MetricCalculationRuntimeException):
    pass


class EmptyStateException(MetricCalculationRuntimeException):
    pass


def wrap_if_necessary(exception: BaseException) -> MetricCalculationException:
    """reference: MetricCalculationException.scala wrapIfNecessary."""
    if isinstance(exception, MetricCalculationException):
        return exception
    wrapped = MetricCalculationRuntimeException(str(exception))
    wrapped.__cause__ = exception
    return wrapped

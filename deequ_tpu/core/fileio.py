"""Text-file output with overwrite guard + atomic replace.

The role of the reference's DfsUtils.writeToTextFileOnDfs
(reference: io/DfsUtils.scala:24-84) for the builders' save-JSON-to-path
options: refuse to clobber an existing file unless overwrite was
requested, and never leave a half-written file behind (tmp + rename, the
same atomicity contract as the FS metrics repository,
reference: repository/fs/FileSystemMetricsRepository.scala:167-195).
"""

from __future__ import annotations

import os
import uuid

from deequ_tpu.core.fsio import FileSystem, LocalFileSystem, resolve_filesystem


def write_text_output(
    path: str,
    text: str,
    overwrite: bool = False,
    filesystem: FileSystem = None,
) -> None:
    fs = resolve_filesystem(filesystem)
    if fs.exists(path) and not overwrite:
        raise FileExistsError(
            f"File {path} already exists and overwrite disabled"
        )
    if not text.endswith("\n"):
        text = text + "\n"
    if isinstance(fs, LocalFileSystem):
        # O_CREAT with mode 0o666 lets the KERNEL apply the caller's
        # current umask — no os.umask() global mutation (which would race
        # other threads) and no stale snapshot (the process may tighten
        # its umask after import). O_EXCL + a random suffix keeps the tmp
        # private to us.
        directory = os.path.dirname(os.path.abspath(path)) or "."
        os.makedirs(directory, exist_ok=True)
        tmp = os.path.join(directory, f".{uuid.uuid4().hex}.tmp")
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o666)
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                f.write(text)
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        return
    fs.write_bytes(path, text.encode("utf-8"))

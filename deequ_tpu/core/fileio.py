"""Text-file output with overwrite guard + atomic replace.

The role of the reference's DfsUtils.writeToTextFileOnDfs
(reference: io/DfsUtils.scala:24-84) for the builders' save-JSON-to-path
options: refuse to clobber an existing file unless overwrite was
requested, and never leave a half-written file behind (tmp + rename, the
same atomicity contract as the FS metrics repository,
reference: repository/fs/FileSystemMetricsRepository.scala:167-195).
"""

from __future__ import annotations

import os
import tempfile


def write_text_output(path: str, text: str, overwrite: bool = False) -> None:
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(
            f"File {path} already exists and overwrite disabled"
        )
    directory = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(text)
            if not text.endswith("\n"):
                f.write("\n")
        # mkstemp creates 0600; give the artifact the normal
        # umask-respecting mode a plain open() would have produced
        umask = os.umask(0)
        os.umask(umask)
        os.chmod(tmp, 0o666 & ~umask)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise

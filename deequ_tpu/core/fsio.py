"""Filesystem seam for every persistence path.

The reference runs its repository and state provider against local disk,
HDFS and S3 through the Hadoop FileSystem API with path qualification
(reference: io/DfsUtils.scala:24-84,
repository/fs/FileSystemMetricsRepository.scala:219 `asQualifiedPath`).
This is the TPU build's equivalent: ONE small interface —
exists / read / atomic write / streamed read / streamed atomic write —
behind `repository/fs.py`, `core/fileio.py` and
`analyzers/state_provider.py`, with:

  * `LocalFileSystem` — the default; atomic publish via tmp + rename,
    the same crash-safety contract the reference gets from
    writeToFileOnDfs (FileSystemMetricsRepository.scala:167-195);
  * `MemoryFileSystem` — an object-store-style fake (whole-object puts,
    no partial state ever visible; no real directories). The persistence
    test suite runs against it, proving nothing in the stack depends on
    POSIX semantics beyond the interface;
  * `FsspecFileSystem` — an adapter for any fsspec implementation
    (s3fs, gcsfs, ...) when one is installed; nothing in this package
    imports fsspec itself.

Streamed writes publish atomically on successful close and discard on
error — readers key on the final object, so a crash mid-write leaves a
state that reads as absent, never corrupt.
"""

from __future__ import annotations

import io
import os
import threading
import uuid
from contextlib import contextmanager
from typing import Dict, Iterator, Optional


class FileSystem:
    """Minimal persistence interface; paths are opaque strings."""

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def read_bytes(self, path: str) -> bytes:
        raise NotImplementedError

    def write_bytes(self, path: str, data: bytes) -> None:
        """Atomic whole-object publish."""
        raise NotImplementedError

    @contextmanager
    def open_read(self, path: str) -> Iterator[io.BufferedIOBase]:
        raise NotImplementedError
        yield  # pragma: no cover

    @contextmanager
    def open_write(self, path: str) -> Iterator[io.BufferedIOBase]:
        """Streamed write; atomic publish on successful close, discard on
        error."""
        raise NotImplementedError
        yield  # pragma: no cover

    def delete(self, path: str) -> None:
        raise NotImplementedError


class LocalFileSystem(FileSystem):
    """POSIX-backed default. Atomicity = write to a sibling tmp name,
    fsync-free rename (the same guarantee the reference's tmp+rename
    gives); parent directories are created on demand."""

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def read_bytes(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def _prepare(self, path: str) -> str:
        directory = os.path.dirname(os.path.abspath(path)) or "."
        os.makedirs(directory, exist_ok=True)
        return os.path.join(directory, f".{uuid.uuid4().hex}.tmp")

    def write_bytes(self, path: str, data: bytes) -> None:
        tmp = self._prepare(path)
        try:
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    @contextmanager
    def open_read(self, path: str):
        with open(path, "rb") as f:
            yield f

    @contextmanager
    def open_write(self, path: str):
        tmp = self._prepare(path)
        try:
            with open(tmp, "wb") as f:
                yield f
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def delete(self, path: str) -> None:
        if os.path.exists(path):
            os.unlink(path)


class MemoryFileSystem(FileSystem):
    """Object-store-style fake: a locked dict of whole objects. Puts are
    atomic by construction (single dict assignment); there are no
    directories and no partial reads — exactly the semantics of an S3 /
    GCS bucket, which is why the persistence suite passing against it
    demonstrates object-store readiness."""

    def __init__(self) -> None:
        self._objects: Dict[str, bytes] = {}
        self._lock = threading.Lock()

    def exists(self, path: str) -> bool:
        with self._lock:
            return path in self._objects

    def read_bytes(self, path: str) -> bytes:
        with self._lock:
            if path not in self._objects:
                raise FileNotFoundError(path)
            return self._objects[path]

    def write_bytes(self, path: str, data: bytes) -> None:
        with self._lock:
            self._objects[path] = bytes(data)

    @contextmanager
    def open_read(self, path: str):
        yield io.BytesIO(self.read_bytes(path))

    @contextmanager
    def open_write(self, path: str):
        buffer = io.BytesIO()
        yield buffer
        # only published when the body completed without raising
        self.write_bytes(path, buffer.getvalue())

    def delete(self, path: str) -> None:
        with self._lock:
            self._objects.pop(path, None)


class FsspecFileSystem(FileSystem):
    """Adapter over a user-supplied fsspec filesystem instance (s3fs,
    gcsfs, adlfs, ...). fsspec itself is never imported here — the
    caller passes the instance, this class only calls its standard
    methods.

    Atomicity contract: object stores (s3/gcs/...) publish each object
    atomically, so in-place writes are already crash-safe there. On
    POSIX-like fsspec backends an in-place write that crashes midway
    leaves a TRUNCATED file that later reads as corrupt rather than
    absent — those backends need ``rename_atomic=True`` (tmp file +
    ``fs.mv``). The default (``rename_atomic=None``) auto-detects:
    tmp+mv when the backend's ``protocol`` names a local/posix
    filesystem, plain in-place write otherwise (object-store ``mv`` is
    a non-atomic copy+delete, so forcing it there would make things
    worse, not better)."""

    _POSIX_PROTOCOLS = frozenset({"file", "local"})

    def __init__(self, fs, rename_atomic: "bool | None" = None):
        self._fs = fs
        if rename_atomic is None:
            protocol = getattr(fs, "protocol", ())
            if isinstance(protocol, str):
                protocol = (protocol,)
            rename_atomic = bool(
                set(protocol) & self._POSIX_PROTOCOLS
            ) and hasattr(fs, "mv")
        self._rename_atomic = bool(rename_atomic)

    def exists(self, path: str) -> bool:
        return bool(self._fs.exists(path))

    def read_bytes(self, path: str) -> bytes:
        with self._fs.open(path, "rb") as f:
            return f.read()

    def write_bytes(self, path: str, data: bytes) -> None:
        if self._rename_atomic:
            tmp = f"{path}.{uuid.uuid4().hex}.tmp"
            try:
                with self._fs.open(tmp, "wb") as f:
                    f.write(data)
                self._fs.mv(tmp, path)
            except BaseException:
                try:
                    self._fs.rm(tmp)
                except Exception:  # noqa: BLE001 - best-effort tmp cleanup
                    pass
                raise
        else:
            with self._fs.open(path, "wb") as f:
                f.write(data)

    @contextmanager
    def open_read(self, path: str):
        with self._fs.open(path, "rb") as f:
            yield f

    @contextmanager
    def open_write(self, path: str):
        buffer = io.BytesIO()
        yield buffer
        self.write_bytes(path, buffer.getvalue())

    def delete(self, path: str) -> None:
        self._fs.rm(path)


_LOCAL = LocalFileSystem()


def resolve_filesystem(filesystem: Optional[FileSystem]) -> FileSystem:
    return filesystem if filesystem is not None else _LOCAL

"""Success/Failure result values.

Every metric carries its value as a ``Try``: computation failures are data,
not control flow (reference: metrics/Metric.scala:19-40 — `value: Try[T]`).
"""

from __future__ import annotations

from typing import Callable, Generic, TypeVar

T = TypeVar("T")
U = TypeVar("U")


class Try(Generic[T]):
    """Base of Success/Failure. Mirrors scala.util.Try semantics."""

    @property
    def is_success(self) -> bool:
        raise NotImplementedError

    @property
    def is_failure(self) -> bool:
        return not self.is_success

    def get(self) -> T:
        raise NotImplementedError

    def get_or_else(self, default: T) -> T:
        return self.get() if self.is_success else default

    def map(self, fn: Callable[[T], U]) -> "Try[U]":
        raise NotImplementedError

    def flat_map(self, fn: Callable[[T], "Try[U]"]) -> "Try[U]":
        raise NotImplementedError

    def recover(self, fn: Callable[[BaseException], T]) -> "Try[T]":
        raise NotImplementedError

    @staticmethod
    def of(fn: Callable[[], T]) -> "Try[T]":
        try:
            return Success(fn())
        except Exception as e:  # noqa: BLE001 - Try captures any exception
            return Failure(e)


class Success(Try[T]):
    __slots__ = ("value",)

    def __init__(self, value: T):
        self.value = value

    @property
    def is_success(self) -> bool:
        return True

    def get(self) -> T:
        return self.value

    def map(self, fn):
        return Try.of(lambda: fn(self.value))

    def flat_map(self, fn):
        try:
            return fn(self.value)
        except Exception as e:  # noqa: BLE001
            return Failure(e)

    def recover(self, fn):
        return self

    def __repr__(self):
        return f"Success({self.value!r})"

    def __eq__(self, other):
        return isinstance(other, Success) and self.value == other.value

    def __hash__(self):
        return hash(("Success", self.value))


class Failure(Try[T]):
    __slots__ = ("exception",)

    def __init__(self, exception: BaseException):
        self.exception = exception

    @property
    def is_success(self) -> bool:
        return False

    def get(self) -> T:
        raise self.exception

    def map(self, fn):
        return self

    def flat_map(self, fn):
        return self

    def recover(self, fn):
        return Try.of(lambda: fn(self.exception))

    def __repr__(self):
        return f"Failure({self.exception!r})"

    def __eq__(self, other):
        # failures compare by exception class + message (the contract the
        # reference's AssertionUtils tests: utils/AssertionUtils.scala)
        return (
            isinstance(other, Failure)
            and type(self.exception) is type(other.exception)
            and str(self.exception) == str(other.exception)
        )

    def __hash__(self):
        return hash(("Failure", type(self.exception).__name__, str(self.exception)))

"""Typed metric values.

Reference: metrics/Metric.scala:19-68, metrics/HistogramMetric.scala:18-60.
Pure data layer — no engine dependency.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Dict, Generic, List, Sequence, TypeVar

from deequ_tpu.core.maybe import Failure, Success, Try

T = TypeVar("T")


class Entity(enum.Enum):
    """What a metric is about. The serialized name of MULTICOLUMN keeps the
    reference's load-bearing typo ("Mutlicolumn", metrics/Metric.scala:21)."""

    DATASET = "Dataset"
    COLUMN = "Column"
    MULTICOLUMN = "Mutlicolumn"


@dataclass(frozen=True)
class Metric(Generic[T]):
    entity: Entity
    name: str
    instance: str
    value: Try[T]

    def flatten(self) -> Sequence["DoubleMetric"]:
        raise NotImplementedError


@dataclass(frozen=True)
class DoubleMetric(Metric[float]):
    def flatten(self) -> Sequence["DoubleMetric"]:
        return [self]


@dataclass(frozen=True)
class KeyedDoubleMetric(Metric[Dict[str, float]]):
    """Many named values from one analyzer (e.g. ApproxQuantiles).
    Flatten emits `name-$key` (reference: metrics/Metric.scala:56-66)."""

    def flatten(self) -> Sequence[DoubleMetric]:
        if self.value.is_success:
            return [
                DoubleMetric(self.entity, f"{self.name}-{k}", self.instance, Success(v))
                for k, v in self.value.get().items()
            ]
        return [DoubleMetric(self.entity, self.name, self.instance, self.value)]


@dataclass(frozen=True)
class DistributionValue:
    absolute: int
    ratio: float


@dataclass(frozen=True)
class Distribution:
    values: Dict[str, DistributionValue]
    number_of_bins: int

    def __getitem__(self, key: str) -> DistributionValue:
        return self.values[key]

    def argmax(self) -> str:
        # reference: metrics/HistogramMetric.scala argmax — key of max absolute
        max_count = max(v.absolute for v in self.values.values())
        for k, v in self.values.items():
            if v.absolute == max_count:
                return k
        raise ValueError("empty distribution")


@dataclass(frozen=True)
class HistogramMetric(Metric[Distribution]):
    """Flatten emits Histogram.bins, Histogram.abs.<v>, Histogram.ratio.<v>
    (reference: metrics/HistogramMetric.scala:37-60)."""

    def flatten(self) -> Sequence[DoubleMetric]:
        if not self.value.is_success:
            return [DoubleMetric(self.entity, self.name, self.instance, self.value)]
        dist = self.value.get()
        result: List[DoubleMetric] = [
            DoubleMetric(
                self.entity,
                f"{self.name}.bins",
                self.instance,
                Success(float(dist.number_of_bins)),
            )
        ]
        for k, v in dist.values.items():
            result.append(
                DoubleMetric(
                    self.entity,
                    f"{self.name}.abs.{k}",
                    self.instance,
                    Success(float(v.absolute)),
                )
            )
            result.append(
                DoubleMetric(
                    self.entity,
                    f"{self.name}.ratio.{k}",
                    self.instance,
                    Success(v.ratio),
                )
            )
        return result


def metric_from_value(
    value: float, name: str, instance: str, entity: Entity
) -> DoubleMetric:
    return DoubleMetric(entity, name, instance, Success(value))


def metric_from_failure(
    exception: BaseException, name: str, instance: str, entity: Entity
) -> DoubleMetric:
    return DoubleMetric(entity, name, instance, Failure(exception))

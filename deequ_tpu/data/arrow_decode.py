"""Buffer-level Arrow decode: the fast path behind Table.from_arrow.

For a planner-approved column (ops/fused.py:plan_decode_fastpath) this
module walks the column's chunks and hands each chunk's raw buffers —
values, validity BITMAP, dictionary index buffer — to the C kernels in
ops/native/decode.c, which write the engine Column backing in one pass
(neutral fill in null slots, uint8 mask, NaN fold for floats). No
intermediate numpy arrays, no bitmap byte-expansion, no fill_null copy.

Every function returns None whenever the native route cannot take the
input (library unavailable, unexpected buffer layout, multi-chunk
dictionary); Table.from_arrow then re-decodes the column through the
host fallback chain. Both paths produce bit-identical Columns, so
eligibility is purely a performance decision.

tools/lint.py's DECODE rule bans `.to_numpy(`/`np.frombuffer` copy
idioms in this module — host materialization belongs to the designated
fallbacks in data/table.py.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from deequ_tpu.data.table import (
    Column,
    ColumnType,
    _arrow_dictionary_digest,
    _arrow_logical_decimal,
    dictionary_uniques_fallback,
    gather_with_null,
    pool_empty,
    shared_all_true,
)
from deequ_tpu.ops import native


def decode_fast_column(
    name: str, chunks: List, arrow_table, shared: Dict[str, np.ndarray]
) -> Optional[Column]:
    """Decode one column's chunks through the native kernels.

    `chunks` are the raw (possibly sliced) arrow chunks — never
    combined; each chunk decodes at its row offset into one
    preallocated output, so multi-chunk columns cost no concat copy.
    Returns None to route the column to the host fallback."""
    import pyarrow as pa

    if not chunks or not native.available():
        return None
    t = chunks[0].type
    if pa.types.is_dictionary(t):
        return _decode_dictionary(name, chunks, shared)
    if pa.types.is_boolean(t):
        return _decode_boolean(name, chunks, shared)
    spec = native.DECODE_PRIMITIVES.get(str(t))
    if spec is None:
        return None
    return _decode_primitive(name, chunks, arrow_table, shared, str(t), spec)


def _validity_addr(arr) -> Optional[int]:
    """Address of the chunk's validity bitmap, or None when null-free.
    A chunk with nulls always has buffer 0 in arrow's layout."""
    bufs = arr.buffers()
    if arr.null_count == 0 or bufs[0] is None:
        return None
    return bufs[0].address


def _decode_primitive(name, chunks, arrow_table, shared, kind, spec):
    fn_name, itemsize = spec
    is_float = kind in ("double", "float")
    n = sum(len(c) for c in chunks)
    # outputs come from the arrow pool: recycled warm pages instead of a
    # fresh mmap the kernel then page-faults through (see pool_empty)
    out_vals = pool_empty(n, np.float64 if is_float else np.int64)
    out_valid = pool_empty(n, np.bool_)
    invalid = 0
    pos = 0
    for ch in chunks:
        bufs = ch.buffers()
        if len(bufs) != 2 or bufs[1] is None:
            return None
        rc = native.decode_primitive(
            kind,
            bufs[1].address + ch.offset * itemsize,
            _validity_addr(ch),
            ch.offset,
            len(ch),
            out_vals[pos:],
            out_valid[pos:],
        )
        if rc is None:
            return None
        invalid += rc
        pos += len(ch)
    # invalid == 0 covers the fallback's two mask elisions at once:
    # null-free chunks AND (for floats) no NaN folds
    valid = shared_all_true(shared, n) if invalid == 0 else out_valid
    if is_float:
        ctype = (
            ColumnType.DECIMAL
            if _arrow_logical_decimal(arrow_table, name)
            else ColumnType.DOUBLE
        )
    else:
        ctype = ColumnType.LONG
    return Column(name, ctype, out_vals, valid)


def _decode_boolean(name, chunks, shared):
    n = sum(len(c) for c in chunks)
    out_vals = pool_empty(n, np.bool_)
    out_valid = pool_empty(n, np.bool_)
    invalid = 0
    pos = 0
    for ch in chunks:
        bufs = ch.buffers()
        if len(bufs) != 2 or bufs[1] is None:
            return None
        # the values buffer is itself a bitmap sharing the chunk's offset
        rc = native.decode_bool_bitmap(
            bufs[1].address,
            ch.offset,
            _validity_addr(ch),
            ch.offset,
            len(ch),
            out_vals[pos:],
            out_valid[pos:],
        )
        if rc is None:
            return None
        invalid += rc
        pos += len(ch)
    valid = shared_all_true(shared, n) if invalid == 0 else out_valid
    return Column(name, ColumnType.BOOLEAN, out_vals, valid)


def _decode_dictionary(name, chunks, shared):
    """dictionary<string, int32> via the index-buffer kernel. Multi-chunk
    dictionary columns need dictionary unification, which only the
    combine_chunks fallback performs — route those back."""
    import pyarrow as pa

    if len(chunks) != 1:
        return None
    arr = chunks[0]
    t = arr.type
    if not (
        pa.types.is_string(t.value_type) or pa.types.is_large_string(t.value_type)
    ):
        return None
    if t.index_type != pa.int32():
        return None
    idx = arr.indices
    bufs = idx.buffers()
    if len(bufs) != 2 or bufs[1] is None:
        return None
    n = len(idx)
    codes = pool_empty(n, np.int32)
    out_valid = pool_empty(n, np.bool_)
    rc = native.decode_dict_codes(
        bufs[1].address + idx.offset * 4,
        _validity_addr(idx),
        idx.offset,
        n,
        codes,
        out_valid,
    )
    if rc is None:
        return None
    valid = shared_all_true(shared, n) if rc == 0 else out_valid
    uniques = dictionary_uniques_fallback(arr.dictionary)
    col = Column(
        name,
        ColumnType.STRING,
        lambda codes=codes, uniques=uniques: gather_with_null(uniques, codes, ""),
        valid,
    )
    col._cache["dict_encode"] = (codes, uniques)
    col._dict_content_key = _arrow_dictionary_digest(arr.dictionary)
    return col

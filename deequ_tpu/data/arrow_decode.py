"""Buffer-level Arrow decode: the fast path behind Table.from_arrow.

For a planner-approved column (ops/fused.py:plan_decode_fastpath) this
module walks the column's chunks and hands each chunk's raw buffers —
values, validity BITMAP, dictionary index buffer — to the C kernels in
ops/native/decode.c, which write the engine Column backing in one pass
(neutral fill in null slots, uint8 mask, NaN fold for floats). No
intermediate numpy arrays, no bitmap byte-expansion, no fill_null copy.

Every function returns None whenever the native route cannot take the
input (library unavailable, unexpected buffer layout, multi-chunk
dictionary); Table.from_arrow then re-decodes the column through the
host fallback chain. Both paths produce bit-identical Columns, so
eligibility is purely a performance decision.

tools/lint.py's DECODE rule bans `.to_numpy(`/`np.frombuffer` copy
idioms in this module — host materialization belongs to the designated
fallbacks in data/table.py.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from deequ_tpu.data.table import (
    Column,
    ColumnType,
    _arrow_dictionary_digest,
    _arrow_logical_decimal,
    _column_from_arrow_fallback,
    dictionary_uniques_fallback,
    gather_with_null,
    pool_empty,
    shared_all_true,
)
from deequ_tpu.ops import native, runtime


def decode_fast_column(
    name: str, chunks: List, arrow_table, shared: Dict[str, np.ndarray]
) -> Optional[Column]:
    """Decode one column's chunks through the native kernels.

    `chunks` are the raw (possibly sliced) arrow chunks — never
    combined; each chunk decodes at its row offset into one
    preallocated output, so multi-chunk columns cost no concat copy.
    Returns None to route the column to the host fallback."""
    import pyarrow as pa

    if not chunks or not native.available():
        return None
    t = chunks[0].type
    if pa.types.is_dictionary(t):
        return _decode_dictionary(name, chunks, shared)
    if pa.types.is_boolean(t):
        return _decode_boolean(name, chunks, shared)
    spec = native.DECODE_PRIMITIVES.get(str(t))
    if spec is None:
        return None
    return _decode_primitive(name, chunks, arrow_table, shared, str(t), spec)


def _validity_addr(arr) -> Optional[int]:
    """Address of the chunk's validity bitmap, or None when null-free.
    A chunk with nulls always has buffer 0 in arrow's layout."""
    bufs = arr.buffers()
    if arr.null_count == 0 or bufs[0] is None:
        return None
    return bufs[0].address


def _decode_primitive(name, chunks, arrow_table, shared, kind, spec):
    fn_name, itemsize = spec
    is_float = kind in ("double", "float")
    n = sum(len(c) for c in chunks)
    # outputs come from the arrow pool: recycled warm pages instead of a
    # fresh mmap the kernel then page-faults through (see pool_empty)
    out_vals = pool_empty(n, np.float64 if is_float else np.int64)
    out_valid = pool_empty(n, np.bool_)
    invalid = 0
    pos = 0
    for ch in chunks:
        bufs = ch.buffers()
        if len(bufs) != 2 or bufs[1] is None:
            return None
        rc = native.decode_primitive(
            kind,
            bufs[1].address + ch.offset * itemsize,
            _validity_addr(ch),
            ch.offset,
            len(ch),
            out_vals[pos:],
            out_valid[pos:],
        )
        if rc is None:
            return None
        invalid += rc
        pos += len(ch)
    # invalid == 0 covers the fallback's two mask elisions at once:
    # null-free chunks AND (for floats) no NaN folds
    valid = shared_all_true(shared, n) if invalid == 0 else out_valid
    if is_float:
        ctype = (
            ColumnType.DECIMAL
            if _arrow_logical_decimal(arrow_table, name)
            else ColumnType.DOUBLE
        )
    else:
        ctype = ColumnType.LONG
    return Column(name, ctype, out_vals, valid)


def _decode_boolean(name, chunks, shared):
    n = sum(len(c) for c in chunks)
    out_vals = pool_empty(n, np.bool_)
    out_valid = pool_empty(n, np.bool_)
    invalid = 0
    pos = 0
    for ch in chunks:
        bufs = ch.buffers()
        if len(bufs) != 2 or bufs[1] is None:
            return None
        # the values buffer is itself a bitmap sharing the chunk's offset
        rc = native.decode_bool_bitmap(
            bufs[1].address,
            ch.offset,
            _validity_addr(ch),
            ch.offset,
            len(ch),
            out_vals[pos:],
            out_valid[pos:],
        )
        if rc is None:
            return None
        invalid += rc
        pos += len(ch)
    valid = shared_all_true(shared, n) if invalid == 0 else out_valid
    return Column(name, ColumnType.BOOLEAN, out_vals, valid)


def _wire_stub_valid_fallback(bits: np.ndarray, n: int) -> np.ndarray:
    """Designated fallback: expand a wire bitmask (MSB-first packed, one
    bit per row) back into the Column uint8-bool mask. Only runs when a
    consumer outside the planned packed set touches a fused column's
    `.valid` — never in the steady-state wire path."""
    return np.unpackbits(bits[: (n + 7) // 8], count=n).astype(np.bool_)


def _wire_stub_column_fallback(name, chunks, arrow_table):
    """Designated fallback: rebuild the full engine Column for a
    wire-fused column from its retained arrow chunks. Exact same decode
    the column would have taken without fusion (native fast path first,
    host chain second), so values/valid are bit-identical."""
    import pyarrow as pa

    shared: Dict[str, np.ndarray] = {}
    col = decode_fast_column(name, chunks, arrow_table, shared)
    if col is not None:
        return col
    if len(chunks) == 1:
        arr = chunks[0]
    elif not chunks:
        arr = pa.array([], type=pa.float64())
    else:
        arr = pa.chunked_array(chunks).combine_chunks()
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.chunk(0)
    return _column_from_arrow_fallback(name, arr, arrow_table, shared)


class WireStubColumn(Column):
    """Stand-in Column for a decode-to-wire fused column.

    The wire buffers already hold everything the planned consumers need,
    so in the steady state nothing ever reads this column's host
    backing. Both accessors stay lazy and exact anyway: `.valid`
    expands the wire bitmask, `.values` re-decodes the retained arrow
    chunks through the ordinary path — so an unplanned consumer (debug
    hook, REPL poke) sees bit-identical data, just slower."""

    def __init__(self, name, ctype, n, chunks, arrow_table, wire_bits):
        self._wire_n = int(n)
        self._wire_bits = wire_bits  # None for value-only fusion
        self._wire_chunks = chunks
        self._wire_arrow = arrow_table
        super().__init__(name, ctype, self._wire_rebuild_values, None)

    def __len__(self) -> int:
        # Column.__len__ reads len(self.valid); that would materialize
        # the mask on every batch just to size-check the table
        return self._wire_n

    def _wire_rebuild_values(self):
        col = _wire_stub_column_fallback(
            self.name, self._wire_chunks, self._wire_arrow
        )
        if self._valid_arr is None:
            self._valid_arr = np.asarray(col.valid)
        return col.values

    @property
    def valid(self):
        if self._valid_arr is None:
            if self._wire_bits is not None:
                self._valid_arr = _wire_stub_valid_fallback(
                    self._wire_bits, self._wire_n
                )
            else:
                col = _wire_stub_column_fallback(
                    self.name, self._wire_chunks, self._wire_arrow
                )
                self._valid_arr = np.asarray(col.valid)
        return self._valid_arr

    @valid.setter
    def valid(self, value):
        self._valid_arr = value


def decode_wire_column(name, chunks, arrow_table, spec, wire):
    """Decode one column's chunks straight to wire buffers.

    Returns ``(column_stub, {wire_key: WireRow})`` on success or None to
    route the column back through the ordinary decode (this batch only —
    the planner's verdict stands and the next batch retries). The wire
    kernels write each chunk at its running row offset, so row groups
    that end off a multiple of 8 continue mid-byte in the shared
    bitmask (OR-only writes keep boundary bytes safe across workers).

    Failure modes that fall back per-batch: unexpected chunk layout,
    narrowed-int overflow against the pinned width (kernel returns -1),
    and an f32 shift not yet published by the pack thread."""
    import pyarrow as pa

    if not chunks or not native.available():
        return None
    token = str(chunks[0].type)
    if token != spec.token or any(str(c.type) != token for c in chunks):
        return None
    n = sum(len(c) for c in chunks)
    if n == 0:
        return None
    shift = 0.0
    if spec.needs_shift:
        resolved = wire.shift_for(f"num:{name}")
        if resolved is None:
            return None
        shift = resolved
    padded = runtime.wire_pad_size(n, wire.batch_size)
    # np.zeros, not pool_empty: the pad tail must be zero to match the
    # zeroed group buffer pack_batch_inputs would have built, and the
    # bitmask is OR-only so every byte must start cleared
    bits = np.zeros(padded // 8, dtype=np.uint8) if spec.want_valid else None
    vals = (
        np.zeros(padded, dtype=np.dtype(spec.value_dtype))
        if spec.want_value
        else None
    )
    is_float = token in ("double", "float")
    invalid = 0
    pos = 0
    for ch in chunks:
        m = len(ch)
        if m == 0:
            continue
        if spec.want_value or is_float:
            bufs = ch.buffers()
            if len(bufs) != 2 or bufs[1] is None:
                return None
            itemsize = native.DECODE_PRIMITIVES[token][1]
            rc = native.wire_primitive(
                token,
                bufs[1].address + ch.offset * itemsize,
                _validity_addr(ch),
                ch.offset,
                m,
                shift,
                vals[pos:] if vals is not None else None,
                bits,
                pos,
            )
        else:
            # int/bool valid-only fusion: no value row, bitmask direct
            # from the validity bitmap (no NaN fold for these types)
            rc = native.wire_valid_bits(_validity_addr(ch), ch.offset, m, bits, pos)
        if rc is None:
            return None
        invalid += rc
        pos += m
    rows: Dict[str, runtime.WireRow] = {}
    if spec.want_value:
        rows[f"num:{name}"] = runtime.WireRow(
            kind=spec.value_kind, arr=vals, shift=shift
        )
    if spec.want_valid:
        rows[f"valid:{name}"] = runtime.WireRow(
            kind="bits", arr=bits, all_valid=(invalid == 0)
        )
    if token == "bool":
        ctype = ColumnType.BOOLEAN
    elif is_float:
        ctype = (
            ColumnType.DECIMAL
            if _arrow_logical_decimal(arrow_table, name)
            else ColumnType.DOUBLE
        )
    else:
        ctype = ColumnType.LONG
    stub = WireStubColumn(name, ctype, n, list(chunks), arrow_table, bits)
    return stub, rows


def _decode_dictionary(name, chunks, shared):
    """dictionary<string, int32> via the index-buffer kernel. Multi-chunk
    dictionary columns need dictionary unification, which only the
    combine_chunks fallback performs — route those back."""
    import pyarrow as pa

    if len(chunks) != 1:
        return None
    arr = chunks[0]
    t = arr.type
    if not (
        pa.types.is_string(t.value_type) or pa.types.is_large_string(t.value_type)
    ):
        return None
    if t.index_type != pa.int32():
        return None
    idx = arr.indices
    bufs = idx.buffers()
    if len(bufs) != 2 or bufs[1] is None:
        return None
    n = len(idx)
    codes = pool_empty(n, np.int32)
    out_valid = pool_empty(n, np.bool_)
    rc = native.decode_dict_codes(
        bufs[1].address + idx.offset * 4,
        _validity_addr(idx),
        idx.offset,
        n,
        codes,
        out_valid,
    )
    if rc is None:
        return None
    valid = shared_all_true(shared, n) if rc == 0 else out_valid
    uniques = dictionary_uniques_fallback(arr.dictionary)
    col = Column(
        name,
        ColumnType.STRING,
        lambda codes=codes, uniques=uniques: gather_with_null(uniques, codes, ""),
        valid,
    )
    col._cache["dict_encode"] = (codes, uniques)
    col._dict_content_key = _arrow_dictionary_digest(arr.dictionary)
    return col

"""Encoded-fold batch layer: analyzer families folded over run streams.

data/native_reader.py's decode_chunk_runs turns a planner-approved
dictionary-coded column chunk into RunChunk streams — coalesced
(run_length, dict_code) value runs plus definition-level runs — without
ever expanding to row width. This module is the bridge from those
streams to the scan's per-batch memo keys:

- `build_payload` slices a batch's row range out of the run streams
  (cumulative-sum rank lookups pick the boundary runs; the
  encfold_code_counts C kernel folds the interior) and rolls dictionary
  codes up to engine values ONCE per batch, yielding the batch's exact
  value multiset plus its definition-run null count.
- `publish_memos` derives the family memos (fused moments, decimated
  quantile sample, HLL++ registers) from that multiset through
  ops/counts_family.family_from_value_counts — the SAME derivation the
  row path's counts fast path uses, which is what makes encoded-fold
  results bit-identical to the row path by construction rather than by
  testing alone.
- `EncFoldStub` stands in for the row-width Column; an unplanned
  consumer (forensics capture, a declined publication) triggers lazy
  expansion through the row path's own read_chunk/assemble_column
  machinery, so fallback is bit-identical too.

Publication is always optional: declining (too many distinct values, a
corrupt run slice, an unprovable exact sum) just leaves the memos unset
and the stub expands — fail closed to the row-width path, never to
wrong values.

tools/lint.py's READER rule covers this module: the encoded-fold path
owns the bytes end to end and must never lean on pyarrow.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from deequ_tpu.data import native_reader as nr
from deequ_tpu.data.table import Column
from deequ_tpu.ops import native

__all__ = [
    "DISTINCT_PUBLISH_CAP",
    "EncFoldColSpec",
    "EncFoldPayload",
    "EncFoldStub",
    "build_payload",
    "publish_memos",
]

#: distinct-value ceiling for publishing SKETCH-family memos from a
#: batch payload: below it the row path's counts fast path provably
#: fires on the same batch (its 4096-row sample pre-check can never see
#: more distincts than the whole batch holds), so both paths derive the
#: family from the same multiset through the same counts_family code —
#: bit-identical. Above it the row path might run the select kernel
#: instead, so publication declines and the stub expands.
DISTINCT_PUBLISH_CAP = 4000

_WHERE_ALL = "where:<all>"


@dataclass(frozen=True)
class EncFoldColSpec:
    """The planner's per-column encoded-fold verdict
    (ops/fused.py:classify_encfold_columns), shipped to the source so
    decode and publication stay inside the statically proven scope."""

    column: str
    token: str
    #: "i64" | "f64": counts-family kind of the engine representation
    kind: str
    #: True when the planner proved the moments memo may publish WITHOUT
    #: a sketch job on the column: integer engine values, footer bounds
    #: inside +-2^31 (the sequential kernel's long-double sum is then
    #: exact, equal to the counts path's exact integer sum), and no
    #: StandardDeviation consumer (its m2 needs the kernel's stream
    #: order). Re-checked at runtime against the actual dictionary.
    publish_moments: bool


@dataclass
class EncFoldPayload:
    """One (column, batch) value multiset folded from run streams:
    distinct engine values with occurrence counts (NaN dictionary
    entries folded into the null count, exactly like decode.c folds NaN
    rows into the validity mask), plus the batch row/null totals."""

    spec: EncFoldColSpec
    values: np.ndarray  # distinct engine values (int64 or float64)
    counts: np.ndarray  # int64 occurrence counts, same length
    n_rows: int
    null_count: int
    runs: int  # sliced runs folded (telemetry: run_ratio)
    codes_folded: int  # distinct dictionary codes rolled up


def _cums(rc: nr.RunChunk):
    """Cached cumulative sums for rank lookups into one RunChunk:
    (def_cum rows, null_cum nulls, run_cum non-null values)."""
    cached = getattr(rc, "_encfold_cums", None)
    if cached is None:
        def_cum = np.cumsum(rc.def_len)
        null_cum = np.cumsum(rc.def_len * (rc.def_val == 0))
        run_cum = np.cumsum(rc.run_len)
        cached = (def_cum, null_cum, run_cum)
        rc._encfold_cums = cached
    return cached


def _nulls_before(rc: nr.RunChunk, row: int) -> int:
    """Nulls among the chunk's first `row` rows, from definition-level
    runs alone — no materialized validity mask."""
    if row <= 0:
        return 0
    def_cum, null_cum, _ = _cums(rc)
    i = int(np.searchsorted(def_cum, row, side="left"))
    prev_rows = int(def_cum[i - 1]) if i > 0 else 0
    prev_nulls = int(null_cum[i - 1]) if i > 0 else 0
    extra = (row - prev_rows) if rc.def_val[i] == 0 else 0
    return prev_nulls + extra


def _slice_code_counts(
    rc: nr.RunChunk, lo: int, hi: int
) -> Optional[Tuple[np.ndarray, int, int]]:
    """Fold chunk rows [lo, hi) into per-code occurrence counts:
    (counts[dict_count], nulls_in_range, runs_folded). The boundary runs
    are clipped by rank lookup; the interior folds through the C kernel.
    None when a run is corrupt — the caller fails closed."""
    nulls_lo = _nulls_before(rc, lo)
    nulls_hi = _nulls_before(rc, hi)
    nn_lo = lo - nulls_lo
    nn_hi = hi - nulls_hi
    nulls_in_range = (hi - lo) - (nn_hi - nn_lo)
    if nn_hi <= nn_lo:
        return np.zeros(rc.dict_count, dtype=np.int64), nulls_in_range, 0
    _, _, run_cum = _cums(rc)
    i0 = int(np.searchsorted(run_cum, nn_lo, side="right"))
    i1 = int(np.searchsorted(run_cum, nn_hi - 1, side="right"))
    run_len = rc.run_len[i0 : i1 + 1].astype(np.int64, copy=True)
    run_code = rc.run_code[i0 : i1 + 1]
    prev = int(run_cum[i0 - 1]) if i0 > 0 else 0
    run_len[0] -= nn_lo - prev
    run_len[-1] -= int(run_cum[i1]) - nn_hi
    counts = native.encfold_code_counts(run_len, run_code, rc.dict_count)
    if counts is None:
        return None
    return counts, nulls_in_range, len(run_len)


def build_payload(
    spec: EncFoldColSpec,
    segments: List[nr.RunChunk],
    start: int,
    stop: int,
) -> Optional[EncFoldPayload]:
    """Fold rows [start, stop) of the run segments into the batch's
    value multiset. One code->value rollup per chunk at the end — the
    dictionary is the only per-value work; everything else is per-run.
    Returns None when any slice fails validation or the multiset
    disagrees with the definition-run null count (fail closed: the memo
    publication is skipped and the stub expands to the row path)."""
    parts_v: List[np.ndarray] = []
    parts_c: List[np.ndarray] = []
    null_count = 0
    runs = 0
    for rc, lo, hi in nr._segment_overlaps(segments, start, stop):
        sliced = _slice_code_counts(rc, lo, hi)
        if sliced is None:
            return None
        counts, seg_nulls, seg_runs = sliced
        null_count += seg_nulls
        runs += seg_runs
        nz = np.flatnonzero(counts)
        if len(nz):
            parts_v.append(rc.dict_values[nz])
            parts_c.append(counts[nz])
    n_rows = stop - start
    if parts_v:
        allv = np.concatenate(parts_v)
        allc = np.concatenate(parts_c)
        # merge by bit pattern: chunks have independent dictionaries, and
        # a wrap-narrowed dictionary can map two codes to one engine
        # value even within a single chunk
        keys, inverse = np.unique(allv.view(np.uint64), return_inverse=True)
        counts = np.zeros(len(keys), dtype=np.int64)
        np.add.at(counts, inverse, allc)
        values = keys.view(allv.dtype)
        if spec.kind == "f64":
            nan = np.isnan(values)
            if nan.any():
                # NaN rows are nulls in the engine representation
                # (decode.c folds them into the mask); the multiset must
                # match what the row path's valid mask admits
                null_count += int(counts[nan].sum())
                values = values[~nan]
                counts = counts[~nan]
    else:
        values = np.zeros(
            0, dtype=np.float64 if spec.kind == "f64" else np.int64
        )
        counts = np.zeros(0, dtype=np.int64)
    if int(counts.sum()) != n_rows - null_count:
        return None
    return EncFoldPayload(
        spec=spec,
        values=values,
        counts=counts,
        n_rows=n_rows,
        null_count=null_count,
        runs=runs,
        codes_folded=len(values),
    )


def _moments_memo(mom, n_rows: int) -> Dict[str, float]:
    return {
        "count": float(mom[0]),
        "sum": float(mom[1]),
        "min": float(mom[2]),
        "max": float(mom[3]),
        "m2": float(mom[4]),
        "n_where": float(mom[5]),
        "n_rows": float(n_rows),
    }


def publish_memos(
    built: Dict,
    payloads: Dict[str, EncFoldPayload],
    planned,
) -> int:
    """Publish family memos derived from batch payloads, BEFORE the
    family-kernel loop runs: a published qkey makes
    _precompute_family_kernels skip the column's select job, and the
    assisted/merge members answer from the memos without ever
    materializing the column. Derivations go through
    counts_family.family_from_value_counts — shared with the row path's
    counts fast path — and publication declines whenever bit-identity
    with the row path is not PROVEN for this batch (too many distincts
    for the row-side shortcut to be guaranteed, unprovable exact sum).
    Returns the number of columns whose memos were published."""
    from deequ_tpu.ops import counts_family

    published = set()
    covered = set()
    for pj in planned:
        payload = payloads.get(pj.column)
        if payload is None or pj.where is not None:
            continue
        covered.add(pj.column)
        if pj.qkey in built:
            continue
        if len(payload.values) > DISTINCT_PUBLISH_CAP:
            continue
        mom, sample, n_valid, level, regs = (
            counts_family.family_from_value_counts(
                payload.values,
                payload.counts,
                payload.spec.kind,
                pj.cap,
                payload.n_rows,
                pj.want_regs,
            )
        )
        built[pj.qkey] = {
            "sample": sample,
            "n": np.asarray([n_valid], dtype=np.float64),
            "level": np.asarray([level], dtype=np.int32),
        }
        if regs is not None:
            built[pj.rkey] = regs
        if pj.mkey not in built:
            built[pj.mkey] = _moments_memo(mom, payload.n_rows)
        published.add(pj.column)
    for column, payload in payloads.items():
        # moments-only publication for columns without a sketch job: the
        # row path would run the sequential moments kernel, so the
        # planner's exact-sum proof is re-checked against the actual
        # values (|v| < 2^31 keeps the kernel's long-double stream sum
        # exact and equal to the counts path's exact integer sum)
        if column in covered or not payload.spec.publish_moments:
            continue
        if payload.spec.kind != "i64":
            continue
        if len(payload.values) and (
            int(payload.values.min()) <= -(1 << 31)
            or int(payload.values.max()) >= (1 << 31)
        ):
            continue
        # the row path's int64 moments fallback sums in PAIRWISE float64
        # (np.sum): every partial sum is a subset sum of the values, so
        # Σ|v| < 2^53 makes every partial an exact integer and the
        # pairwise total equal to this path's exact integer sum. The
        # int64 dot cannot wrap: |v| < 2^31 and n_rows < 2^32 bound it
        # under 2^63.
        if payload.n_rows >= (1 << 32):
            continue
        if len(payload.values) and int(
            np.dot(payload.counts, np.abs(payload.values))
        ) >= (1 << 53):
            continue
        mkey = f"__moments:{column}:{_WHERE_ALL}"
        if mkey in built:
            continue
        mom, _sample, _n_valid, _level, _regs = (
            counts_family.family_from_value_counts(
                payload.values,
                payload.counts,
                payload.spec.kind,
                4096,
                payload.n_rows,
                False,
            )
        )
        built[mkey] = _moments_memo(mom, payload.n_rows)
        published.add(column)
    return len(published)


class EncFoldStub(Column):
    """Stand-in Column for an encoded-fold column: consumers that the
    planner proved memo-served never touch it; an unplanned consumer (a
    declined publication, forensics capture) triggers lazy expansion of
    the retained RunChunks through the row path's own
    read_chunk/assemble_column machinery — bit-identical by
    construction, same contract as NativeWireStub."""

    def __init__(self, name, ctype, token, run_segments, start, stop):
        self._enc_n = int(stop - start)
        self._enc_token = token
        self._enc_segments = run_segments
        self._enc_start = int(start)
        self._enc_stop = int(stop)
        super().__init__(name, ctype, self._enc_rebuild_values, None)

    def __len__(self) -> int:
        return self._enc_n

    def _enc_rebuild(self) -> Column:
        segs = []
        for rc in self._enc_segments:
            dc = getattr(rc, "_encfold_expanded", None)
            if dc is None:
                dc = nr.expand_runs(rc)
                if dc is None:
                    raise RuntimeError(
                        "native library became unavailable during "
                        f"encoded-fold expansion of column {self.name!r}"
                    )
                rc._encfold_expanded = dc
            segs.append(dc)
        return nr.assemble_column(
            self.name,
            self._enc_token,
            segs,
            self._enc_start,
            self._enc_stop,
            {},
        )

    def _enc_rebuild_values(self):
        col = self._enc_rebuild()
        if self._valid_arr is None:
            self._valid_arr = np.asarray(col.valid)
        return col.values

    def _enc_defs_valid(self) -> Optional[np.ndarray]:
        """Validity straight from the definition-level runs, with no
        value expansion — exact for integer columns; float columns with
        a NaN dictionary entry must expand instead (the row path folds
        NaN rows into the mask, which def levels alone cannot see)."""
        for rc in self._enc_segments:
            if rc.kind == "f64" and np.isnan(rc.dict_values).any():
                return None
        out = np.empty(self._enc_n, dtype=np.bool_)
        pos = 0
        for rc, lo, hi in nr._segment_overlaps(
            self._enc_segments, self._enc_start, self._enc_stop
        ):
            mask = np.repeat(rc.def_val.astype(np.bool_), rc.def_len)
            out[pos : pos + (hi - lo)] = mask[lo:hi]
            pos += hi - lo
        return out

    @property
    def valid(self):
        if self._valid_arr is None:
            mask = self._enc_defs_valid()
            if mask is None:
                mask = np.asarray(self._enc_rebuild().valid)
            self._valid_arr = mask
        return self._valid_arr

    @valid.setter
    def valid(self, value):
        self._valid_arr = value

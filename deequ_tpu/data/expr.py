"""Vectorized SQL predicate/expression engine with 3-valued NULL logic.

The reference leans on Spark SQL strings for row-level predicates: `where`
filters (analyzers/Analyzer.scala:385-402 conditionalSelection),
`Compliance(instance, predicate)` (analyzers/Compliance.scala:37),
`isContainedIn`'s generated IN-lists (checks/Check.scala:836-841) and
`isNonNegative`'s `COALESCE(col, 0.0) >= 0` (checks/Check.scala:676).
This module parses the same predicate surface and evaluates it vectorized
over a Table into (values, null-mask) pairs, reproducing SQL/Kleene NULL
semantics exactly (the NullHandlingTests contract — SURVEY.md §7 hard parts).

Evaluation is host-side numpy (strings must stay on host); the resulting
boolean masks are what ships to device for the fused reductions.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

from deequ_tpu.data.table import Column, ColumnType, Table


class ExpressionParseError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Lexer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<num>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+(?:[eE][+-]?\d+)?)
  | (?P<str>'(?:[^']|'')*')
  | (?P<bq>`[^`]+`)
  | (?P<op><=|>=|!=|<>|==|=|<|>|\(|\)|,|\+|-|\*|/|%)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_.]*)
    """,
    re.VERBOSE,
)

_KEYWORDS = {
    "AND", "OR", "NOT", "IS", "NULL", "IN", "BETWEEN", "LIKE", "RLIKE",
    "TRUE", "FALSE", "CASE", "WHEN", "THEN", "ELSE", "END",
}


@dataclass
class Token:
    kind: str  # num | str | op | ident | kw
    text: str
    # source span [pos, end) into the original expression string; -1 on
    # synthesized tokens. Excluded from equality so token comparisons
    # stay purely textual.
    pos: int = field(default=-1, compare=False)
    end: int = field(default=-1, compare=False)


def _tokenize(s: str) -> List[Token]:
    tokens: List[Token] = []
    pos = 0
    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if not m:
            raise ExpressionParseError(f"cannot tokenize at {s[pos:pos+20]!r}")
        start, pos = m.start(), m.end()
        if m.lastgroup == "ws":
            continue
        kind = m.lastgroup
        text = m.group()
        if kind == "ident" and text.upper() in _KEYWORDS:
            tokens.append(Token("kw", text.upper(), start, pos))
        elif kind == "bq":
            tokens.append(Token("ident", text[1:-1], start, pos))
        else:
            tokens.append(Token(kind, text, start, pos))
    return tokens


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------


@dataclass
class Node:
    # source span (start, end) into the expression string this node was
    # parsed from; deliberately unannotated so it stays a plain class
    # attribute (NOT a dataclass field) and subclass constructors and
    # equality are unchanged. The lint layer reads it to anchor
    # diagnostics.
    span = None


@dataclass
class Lit(Node):
    value: object  # float | str | bool | None


@dataclass
class Col(Node):
    name: str


@dataclass
class Un(Node):
    op: str  # 'neg' | 'not'
    x: Node


@dataclass
class Bin(Node):
    op: str
    l: Node
    r: Node


@dataclass
class IsNull(Node):
    x: Node
    negated: bool


@dataclass
class InList(Node):
    x: Node
    items: List[Node]
    negated: bool


@dataclass
class Between(Node):
    x: Node
    lo: Node
    hi: Node
    negated: bool


@dataclass
class Like(Node):
    x: Node
    pattern: Node
    regex: bool
    negated: bool


@dataclass
class Func(Node):
    name: str
    args: List[Node]


@dataclass
class Case(Node):
    branches: List[Tuple[Node, Node]]
    otherwise: Optional[Node]


class _Parser:
    def __init__(self, tokens: List[Token]):
        self.tokens = tokens
        self.i = 0

    def peek(self) -> Optional[Token]:
        return self.tokens[self.i] if self.i < len(self.tokens) else None

    def next(self) -> Token:
        t = self.peek()
        if t is None:
            raise ExpressionParseError("unexpected end of expression")
        self.i += 1
        return t

    def expect(self, kind: str, text: Optional[str] = None) -> Token:
        t = self.next()
        if t.kind != kind or (text is not None and t.text != text):
            raise ExpressionParseError(f"expected {text or kind}, got {t.text!r}")
        return t

    def accept_kw(self, kw: str) -> bool:
        t = self.peek()
        if t is not None and t.kind == "kw" and t.text == kw:
            self.i += 1
            return True
        return False

    def _span(self, node: Node, start_i: int) -> Node:
        # Anchor the node to the [start_i, self.i) token range. Inner nodes
        # keep the tighter span they were given when first constructed.
        if node.span is None and 0 <= start_i < self.i <= len(self.tokens):
            a = self.tokens[start_i].pos
            b = self.tokens[self.i - 1].end
            if a >= 0 and b >= 0:
                node.span = (a, b)
        return node

    # grammar: or_expr
    def parse(self) -> Node:
        node = self.or_expr()
        if self.peek() is not None:
            raise ExpressionParseError(f"trailing input at {self.peek().text!r}")
        return node

    def or_expr(self) -> Node:
        start = self.i
        node = self.and_expr()
        while self.accept_kw("OR"):
            node = self._span(Bin("or", node, self.and_expr()), start)
        return node

    def and_expr(self) -> Node:
        start = self.i
        node = self.not_expr()
        while self.accept_kw("AND"):
            node = self._span(Bin("and", node, self.not_expr()), start)
        return node

    def not_expr(self) -> Node:
        start = self.i
        if self.accept_kw("NOT"):
            return self._span(Un("not", self.not_expr()), start)
        return self.predicate()

    def predicate(self) -> Node:
        start = self.i
        node = self.add_expr()
        t = self.peek()
        if t is None:
            return node
        if t.kind == "op" and t.text in ("=", "==", "!=", "<>", "<", "<=", ">", ">="):
            self.next()
            op = {"=": "eq", "==": "eq", "!=": "ne", "<>": "ne", "<": "lt",
                  "<=": "le", ">": "gt", ">=": "ge"}[t.text]
            return self._span(Bin(op, node, self.add_expr()), start)
        if t.kind == "kw":
            negated = False
            if t.text == "IS":
                self.next()
                negated = self.accept_kw("NOT")
                self.expect("kw", "NULL")
                return self._span(IsNull(node, negated), start)
            if t.text == "NOT":
                self.next()
                negated = True
                t = self.peek()
                if t is None or t.kind != "kw":
                    raise ExpressionParseError("expected IN/BETWEEN/LIKE after NOT")
            if self.accept_kw("IN"):
                self.expect("op", "(")
                items = [self.add_expr()]
                while self.peek() and self.peek().kind == "op" and self.peek().text == ",":
                    self.next()
                    items.append(self.add_expr())
                self.expect("op", ")")
                return self._span(InList(node, items, negated), start)
            if self.accept_kw("BETWEEN"):
                lo = self.add_expr()
                self.expect("kw", "AND")
                hi = self.add_expr()
                return self._span(Between(node, lo, hi, negated), start)
            if self.accept_kw("LIKE"):
                return self._span(
                    Like(node, self.add_expr(), regex=False, negated=negated), start
                )
            if self.accept_kw("RLIKE"):
                return self._span(
                    Like(node, self.add_expr(), regex=True, negated=negated), start
                )
            if negated:
                raise ExpressionParseError("dangling NOT")
        return node

    def add_expr(self) -> Node:
        start = self.i
        node = self.mul_expr()
        while True:
            t = self.peek()
            if t is not None and t.kind == "op" and t.text in ("+", "-"):
                self.next()
                node = self._span(
                    Bin("add" if t.text == "+" else "sub", node, self.mul_expr()), start
                )
            else:
                return node

    def mul_expr(self) -> Node:
        start = self.i
        node = self.unary()
        while True:
            t = self.peek()
            if t is not None and t.kind == "op" and t.text in ("*", "/", "%"):
                self.next()
                op = {"*": "mul", "/": "div", "%": "mod"}[t.text]
                node = self._span(Bin(op, node, self.unary()), start)
            else:
                return node

    def unary(self) -> Node:
        start = self.i
        t = self.peek()
        if t is not None and t.kind == "op" and t.text == "-":
            self.next()
            return self._span(Un("neg", self.unary()), start)
        if t is not None and t.kind == "op" and t.text == "+":
            self.next()
            return self.unary()
        return self.atom()

    def atom(self) -> Node:
        start = self.i
        t = self.next()
        if t.kind == "num":
            return self._span(Lit(float(t.text)), start)
        if t.kind == "str":
            return self._span(Lit(t.text[1:-1].replace("''", "'")), start)
        if t.kind == "kw":
            if t.text == "TRUE":
                return self._span(Lit(True), start)
            if t.text == "FALSE":
                return self._span(Lit(False), start)
            if t.text == "NULL":
                return self._span(Lit(None), start)
            if t.text == "CASE":
                branches = []
                otherwise = None
                while self.accept_kw("WHEN"):
                    cond = self.or_expr()
                    self.expect("kw", "THEN")
                    branches.append((cond, self.or_expr()))
                if self.accept_kw("ELSE"):
                    otherwise = self.or_expr()
                self.expect("kw", "END")
                return self._span(Case(branches, otherwise), start)
            raise ExpressionParseError(f"unexpected keyword {t.text}")
        if t.kind == "op" and t.text == "(":
            node = self.or_expr()
            self.expect("op", ")")
            return node
        if t.kind == "ident":
            nxt = self.peek()
            if nxt is not None and nxt.kind == "op" and nxt.text == "(":
                self.next()
                args: List[Node] = []
                if not (self.peek() and self.peek().kind == "op" and self.peek().text == ")"):
                    args.append(self.or_expr())
                    while self.peek() and self.peek().kind == "op" and self.peek().text == ",":
                        self.next()
                        args.append(self.or_expr())
                self.expect("op", ")")
                return self._span(Func(t.text.upper(), args), start)
            return self._span(Col(t.text), start)
        raise ExpressionParseError(f"unexpected token {t.text!r}")


def parse(expression: str) -> Node:
    return _Parser(_tokenize(expression)).parse()


# ---------------------------------------------------------------------------
# Evaluator: (values ndarray, null bool ndarray, kind)
# ---------------------------------------------------------------------------

# kind: 'num' | 'str' | 'bool'
Series = Tuple[np.ndarray, np.ndarray, str]


def _const(n: int, value, kind: str) -> Series:
    if value is None:
        return np.zeros(n), np.ones(n, dtype=bool), kind
    if kind == "str":
        arr = np.empty(n, dtype=object)
        arr[:] = value
        return arr, np.zeros(n, dtype=bool), "str"
    if kind == "bool":
        return np.full(n, bool(value)), np.zeros(n, dtype=bool), "bool"
    return np.full(n, float(value)), np.zeros(n, dtype=bool), "num"


def _col_series(col: Column) -> Series:
    null = ~col.valid
    if col.ctype == ColumnType.STRING:
        return col.values, null, "str"
    if col.ctype == ColumnType.BOOLEAN:
        return col.values.astype(bool), null, "bool"
    return col.as_float(), null, "num"


def _to_num(s: Series) -> Series:
    vals, null, kind = s
    if kind == "num":
        return s
    if kind == "bool":
        return vals.astype(np.float64), null, "num"
    # same parse as Column.numeric_values (ops/strings.parse_floats), so
    # a Compliance predicate and a Mean/Sum analyzer agree on which rows
    # of a string column are numeric — vectorized over unique values
    from deequ_tpu.ops.strings import parse_floats

    present = ~null
    if not present.any():
        return np.zeros(len(vals)), null.copy(), "num"
    uniques, inv = np.unique(
        np.asarray(vals[present], dtype=object).astype(str), return_inverse=True
    )
    u_vals, u_ok = parse_floats(uniques)
    out = np.zeros(len(vals))
    extra_null = np.zeros(len(vals), dtype=bool)
    out[present] = u_vals[inv]
    extra_null[present] = ~u_ok[inv]
    return out, null | extra_null, "num"


def _to_str(s: Series) -> Series:
    vals, null, kind = s
    if kind == "str":
        return s
    out = np.empty(len(vals), dtype=object)
    for i, v in enumerate(vals):
        if kind == "num":
            f = float(v)
            out[i] = str(int(f)) if f == int(f) else str(f)
        elif kind == "bool":
            out[i] = "true" if v else "false"
    return out, null, "str"


def _coerce_pair(l: Series, r: Series) -> Tuple[Series, Series]:
    lk, rk = l[2], r[2]
    if lk == rk:
        return l, r
    # numeric wins (Spark-style implicit cast of strings/bools to double)
    if "num" in (lk, rk):
        return _to_num(l), _to_num(r)
    # bool vs str -> compare as strings 'true'/'false'
    return _to_str(l), _to_str(r)


def _cmp(op: str, l: Series, r: Series) -> Series:
    l, r = _coerce_pair(l, r)
    lv, ln, kind = l
    rv, rn, _ = r
    null = ln | rn
    if kind == "str":
        lv = lv.astype(str)
        rv = rv.astype(str)
    fn = {
        "eq": lambda a, b: a == b,
        "ne": lambda a, b: a != b,
        "lt": lambda a, b: a < b,
        "le": lambda a, b: a <= b,
        "gt": lambda a, b: a > b,
        "ge": lambda a, b: a >= b,
    }[op]
    with np.errstate(invalid="ignore"):
        out = fn(lv, rv)
    return np.asarray(out, dtype=bool) & ~null, null, "bool"


def _arith(op: str, l: Series, r: Series) -> Series:
    lv, ln, _ = _to_num(l)
    rv, rn, _ = _to_num(r)
    null = ln | rn
    with np.errstate(divide="ignore", invalid="ignore"):
        if op == "add":
            out = lv + rv
        elif op == "sub":
            out = lv - rv
        elif op == "mul":
            out = lv * rv
        elif op == "div":
            out = np.where(rv != 0, lv / np.where(rv != 0, rv, 1.0), np.nan)
            null = null | (rv == 0)  # SQL: x/0 -> NULL
        elif op == "mod":
            out = np.where(rv != 0, np.fmod(lv, np.where(rv != 0, rv, 1.0)), np.nan)
            null = null | (rv == 0)
        else:
            raise ExpressionParseError(op)
    return np.where(null, 0.0, out), null, "num"


def _kleene_and(l: Series, r: Series) -> Series:
    lv, ln, _ = l
    rv, rn, _ = r
    lv = lv.astype(bool) & ~ln
    rv = rv.astype(bool) & ~rn
    false_l = ~lv & ~ln
    false_r = ~rv & ~rn
    out = lv & rv
    null = (ln | rn) & ~false_l & ~false_r
    return out, null, "bool"


def _kleene_or(l: Series, r: Series) -> Series:
    lv, ln, _ = l
    rv, rn, _ = r
    lv = lv.astype(bool) & ~ln
    rv = rv.astype(bool) & ~rn
    out = lv | rv
    null = (ln | rn) & ~lv & ~rv
    return out, null, "bool"


def _like_to_regex(pattern: str) -> str:
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "^" + "".join(out) + "$"


def _eval(node: Node, table: Table, n: int) -> Series:
    if isinstance(node, Lit):
        if node.value is None:
            return _const(n, None, "num")
        if isinstance(node.value, bool):
            return _const(n, node.value, "bool")
        if isinstance(node.value, (int, float)):
            return _const(n, node.value, "num")
        return _const(n, node.value, "str")
    if isinstance(node, Col):
        return _col_series(table.column(node.name))
    if isinstance(node, Un):
        x = _eval(node.x, table, n)
        if node.op == "neg":
            v, nl, _ = _to_num(x)
            return -v, nl, "num"
        v, nl, _ = x
        return ~(v.astype(bool) & ~nl) & ~nl, nl, "bool"
    if isinstance(node, Bin):
        if node.op == "and":
            return _kleene_and(_eval(node.l, table, n), _eval(node.r, table, n))
        if node.op == "or":
            return _kleene_or(_eval(node.l, table, n), _eval(node.r, table, n))
        if node.op in ("eq", "ne", "lt", "le", "gt", "ge"):
            return _cmp(node.op, _eval(node.l, table, n), _eval(node.r, table, n))
        return _arith(node.op, _eval(node.l, table, n), _eval(node.r, table, n))
    if isinstance(node, IsNull):
        _, nl, _ = _eval(node.x, table, n)
        out = ~nl if node.negated else nl
        return out, np.zeros(n, dtype=bool), "bool"
    if isinstance(node, InList):
        x = _eval(node.x, table, n)
        acc: Optional[Series] = None
        for item in node.items:
            c = _cmp("eq", x, _eval(item, table, n))
            acc = c if acc is None else _kleene_or(acc, c)
        if acc is None:
            acc = _const(n, False, "bool")
        if node.negated:
            v, nl, _ = acc
            return ~v & ~nl, nl, "bool"
        return acc
    if isinstance(node, Between):
        x = _eval(node.x, table, n)
        lo = _cmp("ge", x, _eval(node.lo, table, n))
        hi = _cmp("le", x, _eval(node.hi, table, n))
        out = _kleene_and(lo, hi)
        if node.negated:
            v, nl, _ = out
            return ~v & ~nl, nl, "bool"
        return out
    if isinstance(node, Like):
        xv, xn, _ = _to_str(_eval(node.x, table, n))
        pat = node.pattern
        if not isinstance(pat, Lit) or not isinstance(pat.value, str):
            raise ExpressionParseError("LIKE/RLIKE pattern must be a string literal")
        rx = re.compile(pat.value if node.regex else _like_to_regex(pat.value))
        out = np.zeros(n, dtype=bool)
        for i in range(n):
            if not xn[i]:
                s = str(xv[i])
                out[i] = bool(rx.search(s)) if node.regex else bool(rx.match(s))
        if node.negated:
            out = ~out & ~xn
        return out, xn, "bool"
    if isinstance(node, Func):
        return _eval_func(node, table, n)
    if isinstance(node, Case):
        conds = [_eval(cond, table, n) for cond, _ in node.branches]
        thens = [_eval(then, table, n) for _, then in node.branches]
        otherwise = (
            _eval(node.otherwise, table, n) if node.otherwise is not None else None
        )
        results = thens + ([otherwise] if otherwise is not None else [])
        kind = _common_kind([s[2] for s in results]) if results else "num"
        results = [_coerce_kind(s, kind) for s in results]
        result_v = np.empty(n, dtype=object) if kind == "str" else np.zeros(
            n, dtype=bool if kind == "bool" else np.float64
        )
        if kind == "str":
            result_v[:] = ""
        result_null = np.ones(n, dtype=bool)
        assigned = np.zeros(n, dtype=bool)
        for (cv, cn, _), (tv, tn, _) in zip(conds, results[: len(thens)]):
            hit = cv.astype(bool) & ~cn & ~assigned
            result_v[hit] = tv[hit]
            result_null[hit] = tn[hit]
            assigned |= hit
        if otherwise is not None:
            ov, on, _ = results[-1]
            rest = ~assigned
            result_v[rest] = ov[rest]
            result_null[rest] = on[rest]
        return result_v, result_null, kind
    raise ExpressionParseError(f"cannot evaluate {node}")


def _common_kind(kinds: Sequence[str]) -> str:
    if "str" in kinds:
        return "str"
    if "num" in kinds:
        return "num"
    return "bool"


def _coerce_kind(s: Series, kind: str) -> Series:
    if s[2] == kind:
        return s
    if kind == "str":
        return _to_str(s)
    if kind == "num":
        return _to_num(s)
    v, nl, _ = s
    return v.astype(bool), nl, "bool"


def _eval_func(node: Func, table: Table, n: int) -> Series:
    name = node.name
    if name == "COALESCE":
        args = [_eval(arg, table, n) for arg in node.args]
        if not args:
            return np.zeros(n), np.ones(n, dtype=bool), "num"
        kind = _common_kind([s[2] for s in args])
        args = [_coerce_kind(s, kind) for s in args]
        out_v = np.empty(n, dtype=object) if kind == "str" else np.zeros(
            n, dtype=bool if kind == "bool" else np.float64
        )
        if kind == "str":
            out_v[:] = ""
        out_null = np.ones(n, dtype=bool)
        for v, nl, _ in args:
            fill = out_null & ~nl
            out_v[fill] = v[fill]
            out_null &= nl
        return out_v, out_null, kind
    if name == "ABS":
        v, nl, _ = _to_num(_eval(node.args[0], table, n))
        return np.abs(v), nl, "num"
    if name in ("LENGTH", "LEN", "CHAR_LENGTH"):
        v, nl, _ = _to_str(_eval(node.args[0], table, n))
        out = np.array([len(str(x)) if not nl[i] else 0 for i, x in enumerate(v)], dtype=np.float64)
        return out, nl, "num"
    if name in ("LOWER", "UPPER", "TRIM"):
        v, nl, _ = _to_str(_eval(node.args[0], table, n))
        fn = {"LOWER": str.lower, "UPPER": str.upper, "TRIM": str.strip}[name]
        out = np.empty(n, dtype=object)
        for i, x in enumerate(v):
            out[i] = fn(str(x)) if not nl[i] else ""
        return out, nl, "str"
    if name == "ISNULL":
        _, nl, _ = _eval(node.args[0], table, n)
        return nl.copy(), np.zeros(n, dtype=bool), "bool"
    if name == "ISNOTNULL":
        _, nl, _ = _eval(node.args[0], table, n)
        return ~nl, np.zeros(n, dtype=bool), "bool"
    raise ExpressionParseError(f"unknown function {name}")


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


class Predicate:
    """A parsed SQL-ish expression evaluable over a Table."""

    def __init__(self, expression: str):
        self.expression = expression
        self.ast = parse(expression)

    def eval_mask(self, table: Table) -> np.ndarray:
        """Boolean row mask; NULL -> False (SQL WHERE semantics)."""
        v, null, kind = _eval(self.ast, table, table.num_rows)
        return np.asarray(v, dtype=bool) & ~null

    def eval(self, table: Table) -> Series:
        return _eval(self.ast, table, table.num_rows)

    def referenced_columns(self) -> List[str]:
        out: List[str] = []

        def walk(node: Node):
            if isinstance(node, Col):
                out.append(node.name)
            for f in getattr(node, "__dataclass_fields__", {}):
                v = getattr(node, f)
                if isinstance(v, Node):
                    walk(v)
                elif isinstance(v, list):
                    for item in v:
                        if isinstance(item, Node):
                            walk(item)
                        elif isinstance(item, tuple):
                            for x in item:
                                if isinstance(x, Node):
                                    walk(x)

        walk(self.ast)
        return out


def eval_predicate(expression: str, table: Table) -> np.ndarray:
    return Predicate(expression).eval_mask(table)


def validate_expression(expression: str) -> None:
    """Raise ExpressionParseError if the expression does not parse."""
    parse(expression)


def normalize_expression(expression: str) -> str:
    """Canonical text for an expression: token-normalized, single-spaced.

    Two where-clauses that normalize identically are semantically the same
    predicate even if they differ in whitespace, backticks, `==` vs `=`,
    keyword case, or numeric literal spelling (`1` vs `1.0`). The fused-scan
    batcher groups jobs by where-clause *text*, so the lint layer uses this
    to flag formatting-only differences that would silently break fusion.

    Raises ExpressionParseError if the expression does not tokenize.
    """
    canon_ops = {"==": "=", "<>": "!="}
    parts: List[str] = []
    for tok in _tokenize(expression):
        text = tok.text
        if tok.kind == "op":
            text = canon_ops.get(text, text)
        elif tok.kind == "num":
            text = repr(float(text))
        elif tok.kind == "ident":
            # backticks were stripped by the tokenizer; re-quote uniformly
            text = f"`{text}`"
        parts.append(text)
    return " ".join(parts)

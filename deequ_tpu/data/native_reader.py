"""Native parquet column-chunk reader: the page->wire dispatch path.

For a planner-approved column chunk (ops/fused.py:
classify_reader_columns) this module preads the chunk's exact byte
range, hands it to ops/native/parquet_read.c — Thrift page headers,
snappy/zstd page bodies, PLAIN and RLE-dictionary value decode — and
gets back Arrow-layout buffers (contiguous engine-dtype values with
zeros at null slots, LSB validity bitmap). Assembly into the engine
Column backing or the packed wire buffers then reuses the EXACT kernels
the Arrow-buffer fast path uses (decode.c / wire rows), so the result
is bit-identical to the pyarrow chain by construction.

Every function returns None whenever the native route cannot take the
input (library unavailable, page decode error, unpublished f32 shift);
data/source.py then re-reads that column through pyarrow, bit-identical.

tools/lint.py's READER rule bans pyarrow imports in this module outside
the designated ``*_fallback`` functions — the dispatch path owns the
bytes end to end and must never lean on pyarrow to stay honest about
what the native reader actually covers.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from deequ_tpu.data.table import Column, ColumnType, pool_empty, shared_all_true
from deequ_tpu.ops import native, runtime
from deequ_tpu.testing import faults

__all__ = [
    "ChunkMeta",
    "DecodedChunk",
    "NativeWireStub",
    "RunChunk",
    "assemble_column",
    "assemble_wire_column",
    "decode_chunk",
    "decode_chunk_runs",
    "expand_runs",
    "fadvise_chunk",
    "fetch_chunk",
]


@dataclass(frozen=True)
class ChunkMeta:
    """One column chunk's native-decode recipe, proved statically from
    the parquet footer by the planner (everything here comes from
    RowGroupStats — no page bytes were read to build it)."""

    column: str
    token: str  # engine decode token ("double", "int32", "bool", ...)
    dtype: str  # numpy dtype name for the backing, or "bits" for bool
    phys: int  # parquet physical type enum (native.READER_PHYS_ENUM)
    codec: int  # parquet codec enum (native.READER_CODEC_ENUM)
    offset: int  # chunk's first page byte (dict page when present)
    nbytes: int  # total_compressed_size: the pread/fadvise span
    num_values: int
    max_def: int  # 0 = required column (no validity bitmap in pages)


@dataclass(frozen=True)
class DecodedChunk:
    """One natively decoded column chunk in Arrow buffer layout:
    `values` holds engine-dtype values (LSB bitmap for bool) with zeros
    at null slots; `validity` is the LSB bitmap or None when null-free —
    the same shape _validity_addr() sees on a real arrow chunk."""

    token: str
    values: np.ndarray
    validity: Optional[np.ndarray]
    null_count: int
    num_values: int
    pages: int
    uncompressed_bytes: int


def fadvise_chunk(fd: int, meta: ChunkMeta) -> None:
    """Hint the kernel that `meta`'s byte range is about to be pread
    (readahead for the NEXT row group while this one decodes).
    Best-effort: platforms without posix_fadvise just skip it."""
    try:
        os.posix_fadvise(fd, meta.offset, meta.nbytes, os.POSIX_FADV_WILLNEED)
    except (AttributeError, OSError):  # fault-ok: best-effort readahead hint
        pass


def fetch_chunk(fd: int, meta: ChunkMeta) -> Optional[np.ndarray]:
    """pread the chunk's exact byte range. Returns the raw bytes as a
    uint8 array, or None on a short read (file changed under us — the
    column falls back to pyarrow, which will raise its own error)."""
    raw = os.pread(fd, meta.nbytes, meta.offset)
    if len(raw) != meta.nbytes:
        return None
    return np.frombuffer(raw, dtype=np.uint8)


def decode_chunk(raw: np.ndarray, meta: ChunkMeta) -> Optional[DecodedChunk]:
    """Decode one raw chunk byte range through parquet_read.c into
    Arrow-layout buffers. Returns None on any decode error (truncated
    page, unexpected encoding, corrupt Thrift) — never raises for bad
    bytes; the caller falls back to pyarrow for this column."""
    if faults.fault_point("decode.chunk") == "fail":
        return None
    nv = meta.num_values
    if meta.token == "bool":
        out_values = np.zeros((nv + 7) // 8, dtype=np.uint8)
        itemsize = 0
    else:
        out_values = np.zeros(nv, dtype=np.dtype(meta.dtype))
        itemsize = out_values.dtype.itemsize
    out_validity = (
        np.zeros((nv + 7) // 8, dtype=np.uint8) if meta.max_def else None
    )
    res = native.read_chunk(
        raw, meta.phys, meta.codec, itemsize, meta.max_def, nv, out_values, out_validity
    )
    if res is None:
        return None
    null_count, pages, uncompressed = res
    return DecodedChunk(
        token=meta.token,
        values=out_values,
        validity=out_validity if null_count else None,
        null_count=null_count,
        num_values=nv,
        pages=pages,
        uncompressed_bytes=uncompressed,
    )


#: tokens the encoded-run mode handles: numeric columns whose dictionary
#: rolls up to the engine's int64/float64 representation. bool pages are
#: not dictionary-coded and uint64 has no exact engine widening.
ENCFOLD_TOKENS = frozenset(
    {
        "double",
        "float",
        "int8",
        "int16",
        "int32",
        "int64",
        "uint8",
        "uint16",
        "uint32",
    }
)


@dataclass
class RunChunk:
    """One column chunk decoded to encoded-run streams instead of row
    width: coalesced (run_length, dict_code) value runs, coalesced
    (run_length, present) definition-level runs, and the dictionary
    rolled up to engine representation. `raw` retains the compressed
    chunk bytes so an unplanned consumer can still expand to the
    row-width path lazily (expand_runs) — the expansion goes through the
    SAME read_chunk/assemble_column machinery the row path uses, which
    is what keeps the fallback bit-identical by construction."""

    meta: ChunkMeta
    raw: np.ndarray  # compressed chunk bytes (kept for lazy expansion)
    kind: str  # "i64" | "f64": engine representation of dict_values
    dict_values: np.ndarray  # dictionary in engine repr (int64/float64)
    run_len: np.ndarray  # int64 coalesced non-null value runs
    run_code: np.ndarray  # uint32 dict codes, validated < dict_count
    def_len: np.ndarray  # int64 coalesced definition-level runs
    def_val: np.ndarray  # uint8, 0 = null rows / 1 = present rows
    null_count: int
    num_values: int
    pages: int
    uncompressed_bytes: int

    @property
    def dict_count(self) -> int:
        return len(self.dict_values)


def _dict_to_engine(draw: np.ndarray, phys: int, token: str):
    """Dictionary page values (physical layout) -> engine representation
    (int64/float64) plus the counts-family kind, replicating the exact
    widening chain the row path applies per value (store_cast's
    truncating narrow to the backing dtype, then decode.c's widen): both
    are numpy astype C-casts, so a wrap-narrowed dictionary entry rolls
    up to the same engine value its row-expanded copies would."""
    phys_np = {1: "<i4", 2: "<i8", 4: "<f4", 5: "<f8"}[int(phys)]
    entries = draw.view(np.dtype(phys_np))
    if token in ("double", "float"):
        return entries.astype(np.float64), "f64"
    backing = native.READER_TOKENS[token][1]
    return entries.astype(np.dtype(backing)).astype(np.int64), "i64"


def decode_chunk_runs(raw: np.ndarray, meta: ChunkMeta) -> Optional[RunChunk]:
    """Decode one raw chunk byte range into encoded-run streams through
    pq_decode_chunk_runs. Returns None on any decode error — a PLAIN
    data page (dictionary fallback mid-chunk), oversized dictionary,
    corrupt run structure — and on the decode.runs chaos directive; the
    caller decodes the chunk at row width instead, so a corrupt run can
    fail closed but never fold into wrong values."""
    if faults.fault_point("decode.runs") == "fail":
        return None
    if meta.token not in ENCFOLD_TOKENS:
        return None
    res = native.read_chunk_runs(
        raw, meta.phys, meta.codec, meta.max_def, meta.num_values
    )
    if res is None:
        return None
    draw, run_len, run_code, def_len, def_val, nulls, pages, unc, dcount = res
    # cross-check the def-run fold against the page-loop null count and
    # the value-run total against the non-null count: any disagreement
    # means a corrupt stream slipped the C validation — fail closed
    def_nulls = native.encfold_def_nulls(def_len, def_val, meta.num_values)
    if def_nulls is None or def_nulls != nulls:
        return None
    if int(run_len.sum()) != meta.num_values - nulls:
        return None
    dict_values, kind = _dict_to_engine(draw, meta.phys, meta.token)
    return RunChunk(
        meta=meta,
        raw=raw,
        kind=kind,
        dict_values=dict_values,
        run_len=run_len,
        run_code=run_code,
        def_len=def_len,
        def_val=def_val,
        null_count=nulls,
        num_values=meta.num_values,
        pages=pages,
        uncompressed_bytes=unc,
    )


def expand_runs(rc: RunChunk) -> Optional[DecodedChunk]:
    """Row-width expansion of a RunChunk from its retained raw bytes,
    for unplanned consumers (decode_chunk minus the decode.chunk chaos
    gate: the bytes already run-decoded cleanly this session, so the
    expansion seam is internal, not an injection point). Returns None
    only if the native library became unavailable mid-session."""
    meta = rc.meta
    nv = meta.num_values
    out_values = np.zeros(nv, dtype=np.dtype(meta.dtype))
    out_validity = (
        np.zeros((nv + 7) // 8, dtype=np.uint8) if meta.max_def else None
    )
    res = native.read_chunk(
        rc.raw,
        meta.phys,
        meta.codec,
        out_values.dtype.itemsize,
        meta.max_def,
        nv,
        out_values,
        out_validity,
    )
    if res is None:
        return None
    null_count, pages, uncompressed = res
    return DecodedChunk(
        token=meta.token,
        values=out_values,
        validity=out_validity if null_count else None,
        null_count=null_count,
        num_values=nv,
        pages=pages,
        uncompressed_bytes=uncompressed,
    )


def _segment_overlaps(
    segments: List[DecodedChunk], start: int, stop: int
) -> List[Tuple[DecodedChunk, int, int]]:
    """(segment, local_start, local_stop) triples covering [start, stop)
    of the segments' concatenation — the batch-slice walk both assembly
    paths share."""
    out = []
    base = 0
    for seg in segments:
        lo = max(start, base)
        hi = min(stop, base + seg.num_values)
        if lo < hi:
            out.append((seg, lo - base, hi - base))
        base += seg.num_values
        if base >= stop:
            break
    return out


def _validity_addr(seg: DecodedChunk) -> Optional[int]:
    """Address of the segment's validity bitmap, or None when null-free
    — mirrors arrow_decode._validity_addr on a real chunk."""
    if seg.validity is None:
        return None
    return seg.validity.ctypes.data


def assemble_column(
    name: str,
    token: str,
    segments: List[DecodedChunk],
    start: int,
    stop: int,
    shared: Dict[str, np.ndarray],
) -> Optional[Column]:
    """Rows [start, stop) of the decoded segments -> engine Column, via
    the same decode.c kernels arrow_decode._decode_primitive feeds, at
    the same (address, bit_offset) contract — so widening, neutral
    fill, NaN fold, and the shared all-true mask elision are all
    bit-identical to the Arrow-buffer fast path."""
    if not native.available():
        return _assemble_column_numpy_fallback(name, token, segments, start, stop)
    n = stop - start
    is_float = token in ("double", "float")
    is_bool = token == "bool"
    if is_bool:
        out_vals = pool_empty(n, np.bool_)
    else:
        out_vals = pool_empty(n, np.float64 if is_float else np.int64)
    out_valid = pool_empty(n, np.bool_)
    invalid = 0
    pos = 0
    itemsize = 0 if is_bool else native.DECODE_PRIMITIVES[token][1]
    for seg, lo, hi in _segment_overlaps(segments, start, stop):
        m = hi - lo
        if is_bool:
            rc = native.decode_bool_bitmap(
                seg.values.ctypes.data,
                lo,
                _validity_addr(seg),
                lo,
                m,
                out_vals[pos:],
                out_valid[pos:],
            )
        else:
            rc = native.decode_primitive(
                token,
                seg.values.ctypes.data + lo * itemsize,
                _validity_addr(seg),
                lo,
                m,
                out_vals[pos:],
                out_valid[pos:],
            )
        if rc is None:
            return _assemble_column_numpy_fallback(name, token, segments, start, stop)
        invalid += rc
        pos += m
    valid = shared_all_true(shared, n) if invalid == 0 else out_valid
    if is_bool:
        ctype = ColumnType.BOOLEAN
    elif is_float:
        # decimal logical types never reach the reader: their decode
        # token is "decimal128(...)", not in READER_TOKENS
        ctype = ColumnType.DOUBLE
    else:
        ctype = ColumnType.LONG
    return Column(name, ctype, out_vals, valid)


def _assemble_column_numpy_fallback(
    name: str, token: str, segments: List[DecodedChunk], start: int, stop: int
) -> Column:
    """Designated fallback mirroring decode.c's semantics in numpy
    (neutral fill 0, float NaN folds into the mask, int C-cast
    widening). Only runs if the native library becomes unavailable
    between chunk decode and assembly — effectively never."""
    n = stop - start
    is_float = token in ("double", "float")
    is_bool = token == "bool"
    if is_bool:
        out_vals = np.zeros(n, dtype=np.bool_)
    else:
        out_vals = np.zeros(n, dtype=np.float64 if is_float else np.int64)
    out_valid = np.zeros(n, dtype=np.bool_)
    pos = 0
    for seg, lo, hi in _segment_overlaps(segments, start, stop):
        m = hi - lo
        if seg.validity is None:
            vmask = np.ones(m, dtype=np.bool_)
        else:
            vmask = np.unpackbits(seg.validity, bitorder="little")[lo:hi].astype(
                np.bool_
            )
        if is_bool:
            bits = np.unpackbits(seg.values, bitorder="little")[lo:hi]
            out_vals[pos : pos + m] = bits.astype(np.bool_) & vmask
        else:
            vals = seg.values[lo:hi].astype(out_vals.dtype)
            if is_float:
                nan = np.isnan(vals)
                vals = np.where(nan, 0.0, vals)
                vmask = vmask & ~nan
            out_vals[pos : pos + m] = np.where(vmask, vals, 0)
        out_valid[pos : pos + m] = vmask
        pos += m
    ctype = (
        ColumnType.BOOLEAN
        if is_bool
        else (ColumnType.DOUBLE if is_float else ColumnType.LONG)
    )
    return Column(name, ctype, out_vals, out_valid)


def _wire_stub_valid_fallback(bits: np.ndarray, n: int) -> np.ndarray:
    """Designated fallback: wire bitmask (MSB-packed) -> Column mask.
    Same expansion arrow_decode's wire stub uses."""
    return np.unpackbits(bits[: (n + 7) // 8], count=n).astype(np.bool_)


class NativeWireStub(Column):
    """Stand-in Column for a column the native reader decoded straight
    to wire buffers. Mirrors arrow_decode.WireStubColumn, except the
    lazy rebuild source is the retained DecodedChunk segments rather
    than arrow chunks — an unplanned consumer still sees bit-identical
    values/valid through assemble_column."""

    def __init__(self, name, ctype, token, segments, start, stop, wire_bits):
        self._wire_n = int(stop - start)
        self._wire_bits = wire_bits  # None for value-only fusion
        self._wire_token = token
        self._wire_segments = segments
        self._wire_start = int(start)
        self._wire_stop = int(stop)
        super().__init__(name, ctype, self._wire_rebuild_values, None)

    def __len__(self) -> int:
        return self._wire_n

    def _wire_rebuild(self) -> Column:
        return assemble_column(
            self.name,
            self._wire_token,
            self._wire_segments,
            self._wire_start,
            self._wire_stop,
            {},
        )

    def _wire_rebuild_values(self):
        col = self._wire_rebuild()
        if self._valid_arr is None:
            self._valid_arr = np.asarray(col.valid)
        return col.values

    @property
    def valid(self):
        if self._valid_arr is None:
            if self._wire_bits is not None:
                self._valid_arr = _wire_stub_valid_fallback(
                    self._wire_bits, self._wire_n
                )
            else:
                self._valid_arr = np.asarray(self._wire_rebuild().valid)
        return self._valid_arr

    @valid.setter
    def valid(self, value):
        self._valid_arr = value


def assemble_wire_column(
    name: str,
    token: str,
    segments: List[DecodedChunk],
    start: int,
    stop: int,
    spec,
    wire,
) -> Optional[Tuple[Column, Dict[str, "runtime.WireRow"]]]:
    """Rows [start, stop) of the decoded segments -> packed wire
    buffers, via the same wire_* kernels decode_wire_column feeds at the
    same running-row-offset contract. Returns (stub, {wire_key:
    WireRow}) or None to route the column through assemble_column this
    batch (unpublished f32 shift, narrowed-int overflow)."""
    if not native.available():
        return None
    n = stop - start
    if n == 0:
        return None
    shift = 0.0
    if spec.needs_shift:
        resolved = wire.shift_for(f"num:{name}")
        if resolved is None:
            return None
        shift = resolved
    padded = runtime.wire_pad_size(n, wire.batch_size)
    # np.zeros, not pool_empty: pad tail must be zero and the bitmask
    # is OR-only (same invariants as decode_wire_column)
    bits = np.zeros(padded // 8, dtype=np.uint8) if spec.want_valid else None
    vals = (
        np.zeros(padded, dtype=np.dtype(spec.value_dtype))
        if spec.want_value
        else None
    )
    is_float = token in ("double", "float")
    invalid = 0
    pos = 0
    for seg, lo, hi in _segment_overlaps(segments, start, stop):
        m = hi - lo
        if spec.want_value or is_float:
            itemsize = native.DECODE_PRIMITIVES[token][1]
            rc = native.wire_primitive(
                token,
                seg.values.ctypes.data + lo * itemsize,
                _validity_addr(seg),
                lo,
                m,
                shift,
                vals[pos:] if vals is not None else None,
                bits,
                pos,
            )
        else:
            # int/bool valid-only fusion: bitmask direct from validity
            rc = native.wire_valid_bits(_validity_addr(seg), lo, m, bits, pos)
        if rc is None:
            return None
        invalid += rc
        pos += m
    rows: Dict[str, runtime.WireRow] = {}
    if spec.want_value:
        rows[f"num:{name}"] = runtime.WireRow(
            kind=spec.value_kind, arr=vals, shift=shift
        )
    if spec.want_valid:
        rows[f"valid:{name}"] = runtime.WireRow(
            kind="bits", arr=bits, all_valid=(invalid == 0)
        )
    if token == "bool":
        ctype = ColumnType.BOOLEAN
    elif is_float:
        ctype = ColumnType.DOUBLE
    else:
        ctype = ColumnType.LONG
    stub = NativeWireStub(name, ctype, token, segments, start, stop, bits)
    return stub, rows

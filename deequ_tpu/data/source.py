"""Streaming data sources: out-of-core input for every pass.

The reference scans "billions of rows" through Spark's partitioned
readers (reference: README.md:43); the TPU-native equivalent streams
Arrow record batches from Parquet through the fused/distributed passes
with a prefetch thread overlapping host decode with device compute —
host memory stays bounded at O(batch + #groups), never O(rows).

A source duck-types the slice of the Table interface the engine reads:
``num_rows``, ``column_names``, ``schema``, ``has_column``,
``column(name)`` (schema-only: a zero-row column for precondition
checks), ``batches(n)`` (the row stream), and ``is_streaming = True``
which switches group-by/histogram folds to batch-merge mode.
"""

from __future__ import annotations

import hashlib
import os
import queue
import struct
import threading
import time
from collections import OrderedDict
from typing import Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from deequ_tpu.data.table import Column, ColumnType, NUMPY_BACKING, Table
from deequ_tpu.observe import spans as _spans

_SENTINEL = object()

#: how long `batches()` waits for its decode thread at shutdown before
#: abandoning it (the thread is a daemon; it can only still be alive if
#: a single row-group decode takes longer than this)
JOIN_TIMEOUT_S = 10.0

#: ranged GETs a native-reader fetch slot keeps in flight against the
#: DEEQU_TPU_SOURCE_STALL_MS latency model — the conventional range
#: -request concurrency of object-store clients. Only the stall model
#: consults this; local preads are issued back to back either way.
READER_INFLIGHT_GETS = 8


def _resolve_quietly_fallback(fut) -> None:
    """Resolve a readahead future to None, tolerating the race where a
    fetch slot resolves it concurrently. Fallback-designated (the FAULTS
    lint in tools/lint.py permits the swallowed exception here): losing
    the race IS the success case."""
    try:
        fut.set_result(None)
    except Exception:  # noqa: BLE001 - racing fetch slot already resolved it
        pass


def _close_all_fallback(handles) -> None:
    """Best-effort teardown of reader handles. Fallback-designated: a
    close failure during unwind must never mask the primary error, and
    the fd itself is bounded by the open_files registry."""
    for handle in handles:
        try:
            handle.close()
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass


def _arrow_ctype(t) -> ColumnType:
    import pyarrow as pa

    if pa.types.is_dictionary(t):
        t = t.value_type
    if pa.types.is_boolean(t):
        return ColumnType.BOOLEAN
    if pa.types.is_integer(t):
        return ColumnType.LONG
    if pa.types.is_floating(t):
        return ColumnType.DOUBLE
    if pa.types.is_decimal(t):
        return ColumnType.DECIMAL
    if pa.types.is_timestamp(t):
        return ColumnType.TIMESTAMP
    return ColumnType.STRING


def _decode_table(arrow_table, fastpath, wire=None) -> Table:
    """Arrow batch -> engine Table under an `arrow_decode` span.

    The span isolates the buffer->wire conversion self-time from the
    parquet read/decompression that surrounds it in the decode stage,
    so traces (and BENCH_DECODE.json) report the exact seconds the
    decode fast path targets. `wire_fuse` counts the columns this batch
    decoded straight to wire buffers (decode-to-wire fusion)."""
    sp = _spans.span("arrow_decode", cat="decode")
    with sp:
        table = Table.from_arrow(arrow_table, fastpath, wire=wire)
        if sp:
            wire_rows = getattr(table, "wire_rows", None) or {}
            fused_cols = {k.split(":", 1)[1] for k in wire_rows}
            sp.set(
                rows=int(table.num_rows),
                fast=bool(fastpath),
                wire_fuse=len(fused_cols),
            )
    return table


def _empty_column(name: str, ctype: ColumnType) -> Column:
    backing = NUMPY_BACKING[ctype]
    return Column(
        name,
        ctype,
        np.empty(0, dtype=backing),
        np.empty(0, dtype=np.bool_),
    )


class DataSource:
    """Base for streaming sources. Subclasses implement `_schema()` and
    `_iter_tables(batch_size)`."""

    is_streaming = True
    batch_rows = 1 << 22

    # -- schema ------------------------------------------------------------

    def _schema(self) -> List[Tuple[str, ColumnType]]:
        raise NotImplementedError

    @property
    def schema(self) -> List[Tuple[str, ColumnType]]:
        return self._schema()

    @property
    def column_names(self) -> List[str]:
        return [name for name, _ in self._schema()]

    def has_column(self, name: str) -> bool:
        return any(n == name for n, _ in self._schema())

    def column(self, name: str) -> Column:
        """Zero-row column carrying the schema type — enough for the
        precondition system (has_column / is_numeric / is_string)."""
        for n, ctype in self._schema():
            if n == name:
                return _empty_column(n, ctype)
        from deequ_tpu.core.exceptions import NoSuchColumnException

        raise NoSuchColumnException(f"Input data does not include column {name}!")

    def __getitem__(self, name: str) -> Column:
        return self.column(name)

    # -- rows --------------------------------------------------------------

    @property
    def num_rows(self) -> int:
        raise NotImplementedError

    def __len__(self) -> int:
        return self.num_rows

    def _iter_tables(self, batch_size: int) -> Iterator[Table]:
        raise NotImplementedError

    def batches(self, batch_size: int) -> Iterator[Table]:
        """Stream decoded Tables with a bounded prefetch thread: the next
        batch's host decode overlaps the consumer's device compute. The
        producer is the DECODE STAGE of the stream pipeline
        (ops/pipeline.py): it adopts the consumer's trace context and
        reports per-batch `pipe_item` spans under a `pipe_stage` span,
        which the run report's pipeline-occupancy section aggregates.

        Abandonment-safe (pinned by tests/test_pipeline_shutdown.py): if
        the consumer drops the generator early (an error mid-pass, a
        downstream stage shutting down), the finally block signals the
        producer, drains the queue so its blocked put() wakes, and joins
        the thread within JOIN_TIMEOUT_S. The producer closes its
        `_iter_tables` iterator ON the producer thread before exiting,
        so file handles (e.g. the open ParquetFile) release
        deterministically rather than at garbage collection.

        `DEEQU_TPU_PIPELINE=0` (runtime.pipeline_enabled) decodes
        synchronously on the caller's thread instead — no prefetch
        thread, no queue: the fully SERIAL fallback the stream
        pipeline's differential tests compare against. Batch content
        and order are identical either way."""
        from deequ_tpu.ops import runtime

        if not runtime.pipeline_enabled():
            yield from self._batches_serial(batch_size)
            return
        q: "queue.Queue" = queue.Queue(maxsize=2)
        stop = threading.Event()
        error: List[BaseException] = []
        tracer = _spans.current_tracer()
        parent = _spans.current_span()

        def _put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer() -> None:
            it = self._iter_tables(batch_size)

            def _next():
                try:
                    return next(it)
                except StopIteration:
                    return _SENTINEL

            try:
                with _spans.attached(tracer, parent):
                    with _spans.span(
                        "pipe_stage", cat="pipeline", stage="decode"
                    ) as stage_sp:
                        items = 0
                        while not stop.is_set():
                            sp = _spans.span(
                                "pipe_item", cat="pipeline", stage="decode"
                            )
                            with sp:
                                table = _next()
                                if sp:
                                    # the exhausted fetch still runs the
                                    # iterator's tail (flush + close) —
                                    # real decode time, but not an item
                                    if table is _SENTINEL:
                                        sp.set(eos=True)
                                    else:
                                        sp.set(rows=int(table.num_rows))
                            if table is _SENTINEL:
                                break
                            if not _put(table):
                                return
                            items += 1
                        if stage_sp:
                            stage_sp.set(items=items)
            except BaseException as e:  # noqa: BLE001
                error.append(e)
            finally:
                close = getattr(it, "close", None)
                if close is not None:
                    try:
                        close()
                    except BaseException as e:  # noqa: BLE001
                        if not error:
                            error.append(e)
                _put(_SENTINEL)

        thread = threading.Thread(
            target=producer, daemon=True, name="deequ-decode"
        )
        thread.start()
        produced_any = False
        try:
            while True:
                item = q.get()
                if item is _SENTINEL:
                    break
                produced_any = True
                yield item
        finally:
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:  # fault-ok: drain-until-empty teardown
                pass
            thread.join(timeout=JOIN_TIMEOUT_S)
        if error:
            raise error[0]
        if not produced_any:
            # zero-row source: one empty batch so aggregations see the
            # schema and produce their empty-state verdicts, matching the
            # in-memory Table contract
            yield Table([_empty_column(n, t) for n, t in self._schema()])

    def _batches_serial(self, batch_size: int) -> Iterator[Table]:
        """The DEEQU_TPU_PIPELINE=0 decode: same iterator, same batch
        sequence, same empty-batch fallback — on the calling thread."""
        produced_any = False
        it = self._iter_tables(batch_size)
        try:
            for table in it:
                produced_any = True
                yield table
        finally:
            close = getattr(it, "close", None)
            if close is not None:
                close()
        if not produced_any:
            yield Table([_empty_column(n, t) for n, t in self._schema()])


class ParquetSource(DataSource):
    """Out-of-core Parquet scan (reference scale claim: README.md:43;
    SURVEY §7 step 10 — streamed Arrow batches through the fused pass)."""

    def __init__(
        self,
        path: str,
        columns: Optional[List[str]] = None,
        batch_rows: int = 1 << 22,
        prune_groups: Optional[Sequence[int]] = None,
        decode_fastpath: Optional[Sequence[str]] = None,
        wire_fusion=None,
        native_reader: Optional[Sequence[str]] = None,
        encoded_fold=None,
    ):
        import pyarrow.parquet as pq

        self.path = path
        self.columns = columns
        self.batch_rows = batch_rows
        # row groups statically proven skippable (lint/pushdown.py): the
        # scan never reads them, so num_rows reports decoded rows only
        self.prune_groups = (
            frozenset(int(g) for g in prune_groups) if prune_groups else None
        )
        # columns the planner approved for the buffer-level native decode
        # (ops/fused.py:plan_decode_fastpath → with_decode_fastpath);
        # None/empty = every column takes the host from_arrow chain
        self.decode_fastpath = (
            frozenset(decode_fastpath) if decode_fastpath else None
        )
        # decode-to-wire plan (runtime.WireFusionPlan) for the subset of
        # fast-decode columns whose every consumer is packed-only: those
        # skip the Column intermediate entirely. Shared by reference —
        # the plan carries the pass's sticky-shift handshake.
        self.wire_fusion = wire_fusion
        # columns the planner proved native-reader-eligible from footer
        # metadata (ops/fused.py:classify_reader_columns): their chunks
        # pread + page-decode through ops/native/parquet_read.c instead
        # of pyarrow. None/empty = the pyarrow read path everywhere.
        self.native_reader = (
            frozenset(native_reader) if native_reader else None
        )
        # per-column encoded-fold specs (data/encfold.EncFoldColSpec)
        # the planner proved run-foldable (classify_encfold_columns):
        # those chunks decode to (run, code) streams and fold family
        # state over runs instead of rows. None/empty = row-width path.
        self.encoded_fold = dict(encoded_fold) if encoded_fold else None
        pf = pq.ParquetFile(path)
        meta = pf.metadata
        if self.prune_groups:
            self._num_rows = sum(
                meta.row_group(g).num_rows
                for g in range(meta.num_row_groups)
                if g not in self.prune_groups
            )
        else:
            self._num_rows = meta.num_rows
        arrow_schema = pf.schema_arrow
        names = columns if columns is not None else arrow_schema.names
        self._schema_cache = [
            (name, _arrow_ctype(arrow_schema.field(name).type)) for name in names
        ]
        pf.close()

    def _schema(self) -> List[Tuple[str, ColumnType]]:
        return self._schema_cache

    @property
    def num_rows(self) -> int:
        return self._num_rows

    def with_columns(self, names) -> "ParquetSource":
        """Column-pruned view: the fused pass calls this with the union
        of its input specs' columns so only consumed columns are decoded
        (Spark's column pruning, the dominant stream-mode cost). A prune
        set survives projection — the two compose in either order."""
        keep = [n for n, _ in self._schema_cache if n in set(names)]
        if keep == [n for n, _ in self._schema_cache] or not keep:
            return self
        return ParquetSource(
            self.path,
            columns=keep,
            batch_rows=self.batch_rows,
            prune_groups=self.prune_groups,
            decode_fastpath=self.decode_fastpath,
            wire_fusion=self.wire_fusion,
            native_reader=self.native_reader,
            encoded_fold=self.encoded_fold,
        )

    def with_prune(self, skip) -> "ParquetSource":
        """Row-group-pruned view: `skip` holds indices the pushdown
        interpreter proved all-false for every fused member's where.
        Composes with an existing prune set (union) and with
        with_columns (the projection carries the set forward)."""
        skip = frozenset(int(g) for g in skip)
        if not skip:
            return self
        if self.prune_groups:
            skip = skip | self.prune_groups
        return ParquetSource(
            self.path,
            columns=self.columns,
            batch_rows=self.batch_rows,
            prune_groups=skip,
            decode_fastpath=self.decode_fastpath,
            wire_fusion=self.wire_fusion,
            native_reader=self.native_reader,
            encoded_fold=self.encoded_fold,
        )

    def with_decode_fastpath(self, names) -> "ParquetSource":
        """Fast-decode view: `names` are the columns the planner proved
        eligible for the buffer-level native decode. Pure routing — the
        fast and fallback decode emit bit-identical Columns — so this
        composes freely with with_columns/with_prune."""
        names = frozenset(names)
        if not names or names == (self.decode_fastpath or frozenset()):
            return self
        return ParquetSource(
            self.path,
            columns=self.columns,
            batch_rows=self.batch_rows,
            prune_groups=self.prune_groups,
            decode_fastpath=names,
            wire_fusion=self.wire_fusion,
            native_reader=self.native_reader,
            encoded_fold=self.encoded_fold,
        )

    def with_wire_fusion(self, plan) -> "ParquetSource":
        """Decode-to-wire view: `plan` is the runtime.WireFusionPlan the
        planner built for this pass's packed-only columns. Carried by
        reference (it holds the sticky-shift handshake); composes freely
        with the other with_* views."""
        if plan is None or not plan.columns:
            return self
        return ParquetSource(
            self.path,
            columns=self.columns,
            batch_rows=self.batch_rows,
            prune_groups=self.prune_groups,
            decode_fastpath=self.decode_fastpath,
            wire_fusion=plan,
            native_reader=self.native_reader,
            encoded_fold=self.encoded_fold,
        )

    def with_native_reader(self, names) -> "ParquetSource":
        """Native-reader view: `names` are the columns the planner proved
        eligible for the page-level native decode (every chunk's codec,
        encodings and nesting checked against the footer). Pure routing
        — the native and pyarrow reads emit bit-identical buffers — so
        this composes freely with the other with_* views."""
        names = frozenset(names)
        if not names or names == (self.native_reader or frozenset()):
            return self
        return ParquetSource(
            self.path,
            columns=self.columns,
            batch_rows=self.batch_rows,
            prune_groups=self.prune_groups,
            decode_fastpath=self.decode_fastpath,
            wire_fusion=self.wire_fusion,
            native_reader=names,
            encoded_fold=self.encoded_fold,
        )

    def with_encoded_fold(self, specs) -> "ParquetSource":
        """Encoded-fold view: `specs` maps columns the planner proved
        run-foldable (ops/fused.py:classify_encfold_columns) to their
        EncFoldColSpec. Encoded fold rides on the native reader
        (enc ⊆ reader by planner contract) and fails closed per chunk to
        the row-width decode, so this composes freely with the other
        with_* views."""
        specs = dict(specs) if specs else None
        if not specs or specs == self.encoded_fold:
            return self
        return ParquetSource(
            self.path,
            columns=self.columns,
            batch_rows=self.batch_rows,
            prune_groups=self.prune_groups,
            decode_fastpath=self.decode_fastpath,
            wire_fusion=self.wire_fusion,
            native_reader=self.native_reader,
            encoded_fold=specs,
        )

    @property
    def wire_plan(self):
        """The attached WireFusionPlan (None when not planned) — the
        handle the fused pass uses for the shift publish handshake."""
        return self.wire_fusion

    def decode_column_types(self):
        """Arrow type tokens per scanned column AS THE SCAN DECODES THEM
        (string columns arrive dictionary-encoded via read_dictionary,
        with int32 indices) — the pure vocabulary the decode planner
        (ops/fused.py:classify_decode_columns) and the cost model key
        against ops/native.DECODE_PRIMITIVES, keeping both pyarrow-free.
        This is the only reader of the arrow schema for decode planning,
        like row_group_stats is for pushdown."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        out = {}
        pf = pq.ParquetFile(self.path)
        try:
            arrow_schema = pf.schema_arrow
            for name, _ in self._schema_cache:
                t = arrow_schema.field(name).type
                if pa.types.is_string(t) or pa.types.is_large_string(t):
                    # read_dictionary rewrites these on the way in
                    out[name] = "dictionary<string,int32>"
                elif pa.types.is_dictionary(t) and (
                    pa.types.is_string(t.value_type)
                    or pa.types.is_large_string(t.value_type)
                ) and t.index_type == pa.int32():
                    out[name] = "dictionary<string,int32>"
                else:
                    out[name] = str(t)
        finally:
            pf.close()
        return out

    def row_group_stats(self):
        """Per-row-group parquet statistics as pure records for the
        pushdown interpreter — the ONLY statistics reader, so
        lint/pushdown.py itself never touches pyarrow (tools/lint.py
        PUSHDOWN rule). Unusable stats become None fields; verdicts then
        degrade to unknown, never to wrong."""
        import pyarrow.parquet as pq

        from deequ_tpu.lint.pushdown import ColumnStats, RowGroupStats

        names = {name for name, _ in self._schema_cache}
        out: List[RowGroupStats] = []
        pf = pq.ParquetFile(self.path)
        try:
            meta = pf.metadata
            schema = meta.schema
            for g in range(meta.num_row_groups):
                rg = meta.row_group(g)
                cols = {}
                for j in range(rg.num_columns):
                    chunk = rg.column(j)
                    name = chunk.path_in_schema
                    if name not in names:
                        continue
                    # chunk-layout fields for the native-reader planner
                    # (classify_reader_columns): physical type, codec,
                    # page encodings, byte range, nesting levels. Any
                    # read failure leaves them None — the column then
                    # falls off the native reader, never mis-qualifies.
                    try:
                        se = schema.column(j)
                        dpo = int(chunk.data_page_offset)
                        dictpo = (
                            int(chunk.dictionary_page_offset)
                            if chunk.has_dictionary_page
                            and chunk.dictionary_page_offset is not None
                            else None
                        )
                        offset = (
                            dpo if dictpo is None else min(dpo, dictpo)
                        )
                        layout = dict(
                            physical_type=str(chunk.physical_type),
                            codec=str(chunk.compression),
                            encodings=tuple(
                                str(e) for e in chunk.encodings
                            ),
                            chunk_offset=offset,
                            chunk_bytes=int(chunk.total_compressed_size),
                            num_values=int(chunk.num_values),
                            max_def_level=int(se.max_definition_level),
                            max_rep_level=int(se.max_repetition_level),
                            data_page_offset=dpo,
                            dictionary_page_offset=dictpo,
                        )
                    except Exception:  # noqa: BLE001 - degrade to unknown
                        layout = {}
                    st = chunk.statistics
                    if st is None:
                        cols[name] = ColumnStats(**layout)
                        continue
                    has_mm = bool(getattr(st, "has_min_max", False))
                    nc = (
                        st.null_count
                        if bool(getattr(st, "has_null_count", True))
                        else None
                    )
                    cols[name] = ColumnStats(
                        min_value=st.min if has_mm else None,
                        max_value=st.max if has_mm else None,
                        null_count=int(nc) if nc is not None else None,
                        **layout,
                    )
                out.append(
                    RowGroupStats(
                        index=g, num_rows=int(rg.num_rows), columns=cols
                    )
                )
        finally:
            pf.close()
        return out

    def _decode_fastpath_set(self) -> Optional[frozenset]:
        """The planner-approved fast-decode set, or None when the knob
        forces the host chain (the decode differential's baseline)."""
        from deequ_tpu.ops import runtime

        if self.decode_fastpath and runtime.decode_fastpath_enabled():
            return self.decode_fastpath
        return None

    def _wire_fusion_active(self):
        """The attached WireFusionPlan when the kill switch allows it.
        Wire fusion rides on the native fast path, so both knobs gate
        it — DEEQU_TPU_WIRE_FUSED=0 (or fastpath off) restores the
        exact pre-fusion decode for the differential baseline."""
        from deequ_tpu.ops import runtime

        if (
            self.wire_fusion is not None
            and self.wire_fusion.columns
            and runtime.wire_fused_enabled()
            and runtime.decode_fastpath_enabled()
        ):
            return self.wire_fusion
        return None

    def _native_reader_active(self) -> Optional[frozenset]:
        """The planner-approved native-reader column set when every gate
        allows it: the DEEQU_TPU_NATIVE_READER kill switch, the decode
        fast path it assembles through (reader ⊆ fastpath by planner
        contract), and the native library itself."""
        from deequ_tpu.ops import native, runtime

        if (
            self.native_reader
            and runtime.native_reader_enabled()
            and runtime.decode_fastpath_enabled()
            and native.available()
        ):
            return self.native_reader
        return None

    def _encoded_fold_active(self, native_cols):
        """The planner-approved encoded-fold spec map restricted to the
        active native-reader columns, or None when the
        DEEQU_TPU_ENCODED_FOLD kill switch (or any native-reader gate)
        turns the run-fold path off — the differential's baseline."""
        from deequ_tpu.ops import runtime

        if (
            self.encoded_fold
            and native_cols
            and runtime.encoded_fold_enabled()
        ):
            specs = {
                n: s
                for n, s in self.encoded_fold.items()
                if n in native_cols
            }
            return specs or None
        return None

    def _reader_chunk_meta(self, native_cols):
        """Per-(row-group, column) native decode recipes from the footer,
        re-proving each chunk's eligibility against what is actually on
        disk (physical type, codec loadability, page encodings, nesting,
        value counts). A chunk the planner approved but the footer now
        disqualifies simply gets no recipe — it reads through pyarrow,
        bit-identical. Never returns a recipe it cannot honor."""
        import pyarrow.parquet as pq

        from deequ_tpu.data import native_reader as nr
        from deequ_tpu.ops import native

        codec_mask = native.reader_codecs()
        metas = {}
        pf = pq.ParquetFile(self.path)
        try:
            meta = pf.metadata
            schema = meta.schema
            arrow_schema = pf.schema_arrow
            tokens = {}
            for name in native_cols:
                try:
                    tok = str(arrow_schema.field(name).type)
                except KeyError:
                    continue
                if tok in native.READER_TOKENS:
                    tokens[name] = tok
            for g in range(meta.num_row_groups):
                if self.prune_groups is not None and g in self.prune_groups:
                    continue
                rg = meta.row_group(g)
                for j in range(rg.num_columns):
                    chunk = rg.column(j)
                    name = chunk.path_in_schema
                    tok = tokens.get(name)
                    if tok is None:
                        continue
                    se = schema.column(j)
                    allowed_phys, dtype = native.READER_TOKENS[tok]
                    phys = str(chunk.physical_type)
                    codec = str(chunk.compression)
                    encodings = {str(e) for e in chunk.encodings}
                    if (
                        phys not in allowed_phys
                        or codec not in native.READER_CODEC_ENUM
                        or not (
                            codec_mask & native.READER_CODEC_MASK[codec]
                        )
                        or not encodings <= native.READER_ENCODINGS
                        or se.max_repetition_level != 0
                        or se.max_definition_level > 1
                        or int(chunk.num_values) != int(rg.num_rows)
                    ):
                        continue
                    offset = int(chunk.data_page_offset)
                    if (
                        chunk.has_dictionary_page
                        and chunk.dictionary_page_offset is not None
                    ):
                        offset = min(
                            offset, int(chunk.dictionary_page_offset)
                        )
                    metas[(g, name)] = nr.ChunkMeta(
                        column=name,
                        token=tok,
                        dtype=dtype,
                        phys=native.READER_PHYS_ENUM[phys],
                        codec=native.READER_CODEC_ENUM[codec],
                        offset=offset,
                        nbytes=int(chunk.total_compressed_size),
                        num_values=int(chunk.num_values),
                        max_def=int(se.max_definition_level),
                    )
        finally:
            pf.close()
        return metas

    def _iter_tables(self, batch_size: int) -> Iterator[Table]:
        from deequ_tpu.ops import runtime

        workers = runtime.decode_workers()
        if self._native_reader_active():
            yield from self._iter_tables_native(batch_size, workers)
        elif workers > 1:
            yield from self._iter_tables_parallel(batch_size, workers)
        else:
            yield from self._iter_tables_serial(batch_size)

    def _iter_tables_native(
        self, batch_size: int, workers: int
    ) -> Iterator[Table]:
        """The native parquet read path: a dedicated read-ahead thread
        preads each unit's planner-approved column-chunk byte ranges
        (posix_fadvise(WILLNEED) hints the NEXT unit before this one's
        preads, so the object-store stall model overlaps IO with
        decompression), and the decode pool page-decodes them through
        ops/native/parquet_read.c + data/native_reader.py — pyarrow
        reads only the columns without a native recipe. Units, batch
        slicing and the ordered merge are IDENTICAL to
        _iter_tables_parallel, so the batch sequence is bit-identical
        to the pyarrow path at any worker count."""
        import collections
        from concurrent.futures import Future, ThreadPoolExecutor

        import pyarrow as pa
        import pyarrow.parquet as pq

        from deequ_tpu.core.controller import retry_call
        from deequ_tpu.data import encfold as _encfold
        from deequ_tpu.data import native_reader as nr
        from deequ_tpu.observe import heartbeat
        from deequ_tpu.ops import runtime
        from deequ_tpu.testing import faults

        fastpath = self._decode_fastpath_set()
        wire = self._wire_fusion_active()
        size = min(batch_size, self.batch_rows)
        units = self._plan_decode_units(size)
        if not units:
            return
        native_cols = self._native_reader_active()
        enc_specs = self._encoded_fold_active(native_cols)
        ctypes = dict(self._schema_cache)
        metas = self._reader_chunk_meta(native_cols)
        if not metas:
            # nothing on disk qualified (footer changed since planning):
            # take the ordinary path wholesale rather than paying the
            # fetch-thread machinery for zero native chunks
            if workers > 1:
                yield from self._iter_tables_parallel(batch_size, workers)
            else:
                yield from self._iter_tables_serial(batch_size)
            return
        tokens = {m.column: m.token for m in metas.values()}
        scanned = [n for n, _ in self._schema_cache]
        stall_s = runtime.source_stall_s()
        retry_attempts = runtime.retry_budget()
        retry_base = runtime.retry_base_s()
        str_cols = [
            n for n, t in self._schema_cache if t == ColumnType.STRING
        ]
        tracer = _spans.current_tracer()
        parent = _spans.current_span()
        # per-unit fetch plan: the (group, recipe) pairs the read-ahead
        # thread preads, in deterministic (group, schema) order
        unit_chunks = [
            [
                (g, metas[(g, n)])
                for g in unit
                for n in scanned
                if (g, n) in metas
            ]
            for unit in units
        ]
        futures: List[Future] = [Future() for _ in units]
        stop = threading.Event()
        # Read-ahead window: fetch slot i may start once fewer than
        # workers + 2 units separate it from the decode cursor. This is
        # admission by UNIT INDEX, not a counting semaphore, because a
        # semaphore can be barged: a slot starting unit i+3 can steal
        # the permit a sleeping slot i was woken for, and once the
        # window fills with units AHEAD of the decode cursor the scan
        # deadlocks (decode waits for unit i, unit i waits for decode).
        # The index test cannot starve: decode waiting on unit i means
        # every unit before i is consumed, so i always clears the gate.
        window = threading.Condition()
        consumed = [0]

        def window_wait(i: int) -> None:
            with window:
                while (
                    i >= consumed[0] + workers + 2
                    and not stop.is_set()
                ):
                    window.wait(1.0)

        def window_advance() -> None:
            with window:
                consumed[0] += 1
                window.notify_all()
        # Readahead depth: real object stores serve overlapping range
        # requests, so the latency model is paid per in-flight GET, not
        # summed serially across the scan. Depth stays small — enough
        # to hide one unit's GET behind another's decode without
        # flooding the page cache; the window gate still bounds
        # fetched-but-undecoded units at workers + 2.
        fetch_depth = min(len(units), max(2, workers))
        try:
            read_fd = os.open(self.path, os.O_RDONLY)
        except OSError:
            if workers > 1:
                yield from self._iter_tables_parallel(batch_size, workers)
            else:
                yield from self._iter_tables_serial(batch_size)
            return

        def fetch_unit(i: int) -> None:
            try:
                if stop.is_set():
                    return
                window_wait(i)
                if stop.is_set():
                    return
                chunks = unit_chunks[i]
                with _spans.attached(tracer, parent):
                    # hint this unit's ranges up front: the kernel
                    # fills the page cache during the very stall the
                    # latency model charges below
                    for _, m in chunks:
                        nr.fadvise_chunk(read_fd, m)
                    raw = {}
                    bytes_read = 0
                    retries = recovered = exhausted = observed = 0
                    sp = _spans.span("page_read", cat="read")
                    with sp, heartbeat.current().timed("read"):
                        faults.fault_point("read.latency")
                        # the object-store latency model: one ranged
                        # GET per row group. Owning the byte schedule
                        # means the GETs fly concurrently (capped like
                        # any real range-request client), so the slot
                        # pays one round of latency per in-flight
                        # window — the serial per-group stall is
                        # exactly what the blocking pyarrow read pays
                        if stall_s > 0.0:
                            rounds = -(-len(units[i]) // READER_INFLIGHT_GETS)
                            time.sleep(stall_s * rounds)
                        for g, m in chunks:
                            # bounded retry + exponential backoff around
                            # the pread/ranged GET: transient errors and
                            # short reads re-issue up to the budget; an
                            # exhausted chunk degrades to the pyarrow
                            # fallback on the decode side — never a
                            # failed scan, never a wrong answer
                            def _fetch(m=m):
                                faults.fault_point("read.pread")
                                data = nr.fetch_chunk(read_fd, m)
                                if (
                                    data is not None
                                    and faults.fault_point("read.short")
                                    == "short"
                                ):
                                    return None  # truncated: retryable
                                return data

                            data, r, rec_ok = retry_call(
                                _fetch,
                                attempts=retry_attempts,
                                base_s=retry_base,
                                key=f"{self.path}:{i}:{m.column}",
                            )
                            retries += r
                            observed += r
                            if rec_ok:
                                recovered += 1
                            elif data is None:
                                exhausted += 1
                                observed += 1
                            if data is not None:
                                if (
                                    faults.fault_point("read.corrupt")
                                    == "corrupt"
                                ):
                                    # truncation, not a bit flip: the
                                    # decoder detects short buffers and
                                    # returns None (column falls back
                                    # whole); a flipped payload byte
                                    # could decode to wrong VALUES
                                    observed += 1
                                    data = data[: max(1, len(data) // 2)]
                                bytes_read += len(data)
                            raw[(g, m.column)] = data
                        if sp:
                            sp.set(
                                groups=len(units[i]),
                                chunks=len(chunks),
                                bytes_read=bytes_read,
                            )
                    if retries or exhausted:
                        runtime.record_retry(retries, recovered, exhausted)
                    if observed:
                        runtime.record_fault(injected=observed)
                futures[i].set_result(raw)
            except BaseException:  # noqa: BLE001 - degrade to pyarrow
                # a failed fetch slot is contained, never silent: the
                # unit decodes through the pyarrow fallback and the
                # degrade is counted in the fault telemetry
                with _spans.attached(tracer, parent):
                    runtime.record_fault(injected=1, fallback_units=1)
            finally:
                if not futures[i].done():
                    _resolve_quietly_fallback(futures[i])

        local = threading.local()
        open_files: List = []
        files_lock = threading.Lock()

        def _pf():
            pf = getattr(local, "pf", None)
            if pf is None:
                pf = pq.ParquetFile(
                    self.path, read_dictionary=str_cols or None
                )
                local.pf = pf
                with files_lock:
                    open_files.append(pf)
            return pf

        wire_cols = set(wire.columns) if wire is not None else set()

        def decode_unit(i: int) -> List[Table]:
            faults.fault_point("decode.worker")
            unit = units[i]
            readahead_hit = futures[i].done()
            heartbeat.current().note_readahead(bool(readahead_hit))
            raw = futures[i].result()
            window_advance()
            with _spans.attached(tracer, parent):
                with _spans.span(
                    "page_decode", cat="decode", groups=len(unit)
                ) as sp:
                    segments: dict = {}
                    failed: set = set()
                    enc_off: set = set()
                    enc_fallback = 0
                    if raw is not None:
                        for g, m in unit_chunks[i]:
                            data = raw.get((g, m.column))
                            dec = None
                            if data is not None:
                                if (
                                    enc_specs
                                    and m.column in enc_specs
                                    and m.column not in enc_off
                                ):
                                    dec = nr.decode_chunk_runs(data, m)
                                    if dec is None:
                                        # fail closed: a chunk the run
                                        # decoder refuses (corrupt run,
                                        # plain data page, fault) takes
                                        # the row-width path — never
                                        # wrong values
                                        enc_off.add(m.column)
                                        enc_fallback += 1
                                if dec is None:
                                    dec = nr.decode_chunk(data, m)
                            if dec is None:
                                failed.add(m.column)
                            else:
                                segments.setdefault(m.column, []).append(
                                    dec
                                )
                    # a column is native for this unit only when EVERY
                    # group chunk decoded: partial columns cannot
                    # assemble, so they fall back whole
                    covered = {
                        n
                        for n, segs in segments.items()
                        if n not in failed and len(segs) == len(unit)
                    }
                    # a column folds over runs only when EVERY chunk
                    # run-decoded; a mixed column expands its run chunks
                    # back to row width so the ordinary assemble path
                    # applies unchanged
                    run_cols: set = set()
                    for name in list(covered):
                        segs = segments[name]
                        is_run = [
                            isinstance(s, nr.RunChunk) for s in segs
                        ]
                        if all(is_run):
                            run_cols.add(name)
                        elif any(is_run):
                            expanded = []
                            for s in segs:
                                if isinstance(s, nr.RunChunk):
                                    s = nr.expand_runs(s)
                                if s is None:
                                    break
                                expanded.append(s)
                            if len(expanded) == len(segs):
                                segments[name] = expanded
                            else:
                                covered.discard(name)
                                failed.add(name)
                    enc_runs = enc_values = enc_saved = 0
                    for name in run_cols:
                        for rc in segments[name]:
                            enc_runs += len(rc.run_len)
                            enc_values += rc.num_values
                            # row-width materialization avoided: the
                            # row path builds an 8-byte value plus a
                            # 1-byte mask per row; the runs path keeps
                            # 12 bytes per run plus the dictionary
                            enc_saved += max(
                                0,
                                9 * rc.num_values
                                - 12 * len(rc.run_len)
                                - rc.dict_values.nbytes,
                            )
                    enc_codes = 0
                    fb_cols = [n for n in scanned if n not in covered]
                    fb_merged = None
                    if fb_cols:
                        pf = _pf()
                        parts = [
                            pf.read_row_group(g, columns=fb_cols)
                            for g in unit
                        ]
                        fb_merged = (
                            parts[0]
                            if len(parts) == 1
                            else pa.concat_tables(parts)
                        )
                        del parts
                        total = int(fb_merged.num_rows)
                    else:
                        first = next(iter(covered))
                        total = sum(
                            seg.num_values for seg in segments[first]
                        )
                    tables = []
                    for start in range(0, total, size):
                        stop_row = min(start + size, total)
                        fb_table = (
                            _decode_table(
                                fb_merged.slice(start, size),
                                fastpath,
                                wire,
                            )
                            if fb_merged is not None
                            else None
                        )
                        shared: dict = {}
                        wire_rows = dict(
                            getattr(fb_table, "wire_rows", None) or {}
                        )
                        enc_payloads: dict = {}
                        cols = []
                        for name in scanned:
                            if name not in covered:
                                cols.append(fb_table.column(name))
                                continue
                            if name in run_cols:
                                cols.append(
                                    _encfold.EncFoldStub(
                                        name,
                                        ctypes[name],
                                        tokens[name],
                                        segments[name],
                                        start,
                                        stop_row,
                                    )
                                )
                                payload = _encfold.build_payload(
                                    enc_specs[name],
                                    segments[name],
                                    start,
                                    stop_row,
                                )
                                if payload is not None:
                                    enc_payloads[name] = payload
                                    enc_codes += payload.codes_folded
                                continue
                            col = None
                            if name in wire_cols:
                                res = nr.assemble_wire_column(
                                    name,
                                    tokens[name],
                                    segments[name],
                                    start,
                                    stop_row,
                                    wire.columns[name],
                                    wire,
                                )
                                if res is not None:
                                    col, rows = res
                                    wire_rows.update(rows)
                            if col is None:
                                col = nr.assemble_column(
                                    name,
                                    tokens[name],
                                    segments[name],
                                    start,
                                    stop_row,
                                    shared,
                                )
                            cols.append(col)
                        table = Table(cols)
                        if wire_rows:
                            table.wire_rows = wire_rows
                        if enc_payloads:
                            table.encfold = enc_payloads
                        tables.append(table)
                    if sp:
                        chunks_native = len(unit) * len(covered)
                        sp.set(
                            rows=int(total),
                            chunks_native=chunks_native,
                            chunks_fallback=len(unit) * len(scanned)
                            - chunks_native,
                            readahead_hit=bool(readahead_hit),
                            runs_native=int(enc_runs),
                            chunks_runs=len(unit) * len(run_cols),
                        )
                    if enc_specs and (run_cols or enc_fallback):
                        runtime.record_encfold(
                            chunks=len(unit) * len(run_cols),
                            fallback=enc_fallback,
                            runs=enc_runs,
                            values=enc_values,
                            codes=enc_codes,
                            bytes_saved=enc_saved,
                        )
                    return tables

        fetch_pool = ThreadPoolExecutor(
            max_workers=fetch_depth, thread_name_prefix="deequ-read-ahead"
        )
        for i in range(len(units)):
            fetch_pool.submit(fetch_unit, i)
        pool = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="deequ-decode-worker"
        )
        pending = collections.deque()
        next_unit = 0
        try:
            while next_unit < len(units) or pending:
                while next_unit < len(units) and len(pending) < workers + 1:
                    pending.append(
                        (next_unit, pool.submit(decode_unit, next_unit))
                    )
                    next_unit += 1
                unit_i, fut = pending.popleft()
                try:
                    tables = fut.result()
                except Exception:  # noqa: BLE001 - contained: one inline redo
                    # a decode worker died mid-unit. The fetched bytes
                    # are still resolved in futures[unit_i], so the unit
                    # re-decodes inline on the consumer thread — bit
                    # -identical output, one unit of lost parallelism.
                    # A second failure is persistent and propagates.
                    runtime.record_fault(injected=1)
                    tables = decode_unit(unit_i)
                    runtime.record_retry(1, 1, 0)
                for table in tables:
                    yield table
        finally:
            stop.set()
            with window:
                window.notify_all()
            fetch_pool.shutdown(wait=False, cancel_futures=True)
            for fut in futures:
                if not fut.done():
                    _resolve_quietly_fallback(fut)
            for _, fut in pending:
                fut.cancel()
            pool.shutdown(wait=True)
            # no fetch slot may outlive the fd it preads from
            fetch_pool.shutdown(wait=True)
            try:
                os.close(read_fd)
            except OSError:  # fault-ok: teardown double-close guard
                pass
            with files_lock:
                _close_all_fallback(open_files)

    def _iter_tables_serial(self, batch_size: int) -> Iterator[Table]:
        import pyarrow.parquet as pq

        from deequ_tpu.ops import runtime

        fastpath = self._decode_fastpath_set()
        wire = self._wire_fusion_active()
        size = min(batch_size, self.batch_rows)
        # Read row group by row group: this pyarrow's iter_batches /
        # dataset scanner retain every decoded batch in the pool for the
        # reader's lifetime (measured: RSS grows linearly with batches
        # consumed), while read_row_group frees cleanly. Memory bound is
        # O(row group + batch), so files written with sane group sizes
        # stream at constant memory.
        # String columns decode as DictionaryArray (read_dictionary):
        # parquet pages are dictionary-encoded on disk, so this skips
        # materializing per-row strings AND hands dict_encode its codes
        # for free (Table.from_arrow stores them directly).
        str_cols = [
            n for n, t in self._schema_cache if t == ColumnType.STRING
        ]
        with pq.ParquetFile(
            self.path, read_dictionary=str_cols or None
        ) as pf:
            # NOTE: memory_map=True was tried and REVERTED: it saves a
            # buffer copy (~3%) but maps the whole file into RSS, turning
            # the bounded-memory contract's headline number (peak RSS)
            # into file size.
            # One batch per row group (sliced down when a group exceeds
            # the cap). TINY groups (< size/4 — incremental writers often
            # produce 10k-row groups) still coalesce, or per-batch fold
            # machinery would multiply 100x; near-batch-size groups pass
            # through directly because pa.concat_tables forces a
            # dictionary unification on string columns that costs more
            # (~0.9s/100M measured) than the machinery it saves.
            import pyarrow as pa

            tiny = max(1, size // 4)
            pending: list = []
            pending_rows = 0
            # benchmark-only latency injection (object-store model):
            # sleeps on the decoding thread before each row-group read,
            # i.e. exactly where a remote range-GET would block
            stall_s = runtime.source_stall_s()

            def flush():
                if not pending:
                    return None
                merged = (
                    pending[0]
                    if len(pending) == 1
                    else pa.concat_tables(pending)
                )
                pending.clear()
                return merged

            skip = self.prune_groups
            for g in range(pf.metadata.num_row_groups):
                if skip is not None and g in skip:
                    continue  # statically proven all-false: never decode
                if stall_s > 0.0:
                    time.sleep(stall_s)
                group = pf.read_row_group(g, columns=self.columns)
                if group.num_rows < tiny:
                    pending.append(group)
                    pending_rows += group.num_rows
                    if pending_rows < size:
                        continue
                    group = flush()
                    pending_rows = 0
                elif pending:
                    head = flush()
                    pending_rows = 0
                    for start in range(0, head.num_rows, size):
                        yield _decode_table(head.slice(start, size), fastpath, wire)
                for start in range(0, group.num_rows, size):
                    yield _decode_table(group.slice(start, size), fastpath, wire)
                del group
            tail = flush()
            if tail is not None:
                for start in range(0, tail.num_rows, size):
                    yield _decode_table(tail.slice(start, size), fastpath, wire)

    def _plan_decode_units(self, size: int) -> List[Tuple[int, ...]]:
        """Replay the serial loop's coalescing decisions from metadata
        alone: every branch there depends only on each group's row count,
        so the unit list — each unit a tuple of row-group indices whose
        concat is sliced into batches — reproduces the serial batch
        sequence EXACTLY. This is what keeps the parallel decode
        bit-identical at any worker count."""
        import pyarrow.parquet as pq

        pf = pq.ParquetFile(self.path)
        try:
            meta = pf.metadata
            rows = [
                meta.row_group(g).num_rows for g in range(meta.num_row_groups)
            ]
        finally:
            pf.close()
        tiny = max(1, size // 4)
        units: List[Tuple[int, ...]] = []
        pending: List[int] = []
        pending_rows = 0
        skip = self.prune_groups
        for g, num in enumerate(rows):
            if skip is not None and g in skip:
                continue
            if num < tiny:
                pending.append(g)
                pending_rows += num
                if pending_rows < size:
                    continue
                units.append(tuple(pending))
                pending = []
                pending_rows = 0
            else:
                if pending:
                    units.append(tuple(pending))
                    pending = []
                    pending_rows = 0
                units.append((g,))
        if pending:
            units.append(tuple(pending))
        return units

    def _iter_tables_parallel(
        self, batch_size: int, workers: int
    ) -> Iterator[Table]:
        """Row-group decode fanned across `workers` threads with an
        ordered merge: units (see _plan_decode_units) are submitted in
        serial order and results yielded in submission order, so the
        batch sequence is bit-identical to the serial loop. pyarrow's
        parquet decode and the native kernels release the GIL, so the
        units genuinely overlap. Each worker thread opens its OWN
        ParquetFile (the handle is not thread-safe); in-flight units are
        bounded at workers + 1, so host memory stays
        O(workers × row group)."""
        import collections
        from concurrent.futures import ThreadPoolExecutor

        import pyarrow as pa
        import pyarrow.parquet as pq

        from deequ_tpu.ops import runtime
        from deequ_tpu.testing import faults

        fastpath = self._decode_fastpath_set()
        wire = self._wire_fusion_active()
        size = min(batch_size, self.batch_rows)
        units = self._plan_decode_units(size)
        if not units:
            return
        stall_s = runtime.source_stall_s()
        str_cols = [
            n for n, t in self._schema_cache if t == ColumnType.STRING
        ]
        tracer = _spans.current_tracer()
        parent = _spans.current_span()
        local = threading.local()
        open_files: List = []
        files_lock = threading.Lock()

        def _pf():
            pf = getattr(local, "pf", None)
            if pf is None:
                pf = pq.ParquetFile(
                    self.path, read_dictionary=str_cols or None
                )
                local.pf = pf
                with files_lock:
                    open_files.append(pf)
            return pf

        def decode_unit(unit: Tuple[int, ...]) -> List[Table]:
            faults.fault_point("decode.worker")
            pf = _pf()
            with _spans.attached(tracer, parent):
                with _spans.span(
                    "decode_unit", cat="decode", groups=len(unit)
                ) as sp:
                    parts = []
                    for g in unit:
                        if stall_s > 0.0:
                            time.sleep(stall_s)
                        parts.append(
                            pf.read_row_group(g, columns=self.columns)
                        )
                    merged = (
                        parts[0] if len(parts) == 1 else pa.concat_tables(parts)
                    )
                    del parts
                    tables = [
                        _decode_table(merged.slice(start, size), fastpath, wire)
                        for start in range(0, merged.num_rows, size)
                    ]
                    if sp:
                        sp.set(rows=int(merged.num_rows))
                    return tables

        pool = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="deequ-decode-worker"
        )
        pending = collections.deque()
        next_unit = 0
        try:
            while next_unit < len(units) or pending:
                while next_unit < len(units) and len(pending) < workers + 1:
                    pending.append(
                        (
                            units[next_unit],
                            pool.submit(decode_unit, units[next_unit]),
                        )
                    )
                    next_unit += 1
                unit, fut = pending.popleft()
                try:
                    tables = fut.result()
                except Exception:  # noqa: BLE001 - contained: one inline redo
                    # a decode worker died mid-unit: re-decode inline on
                    # the consumer thread (bit-identical — units are
                    # pure functions of the file). A second failure is
                    # persistent and propagates.
                    runtime.record_fault(injected=1)
                    tables = decode_unit(unit)
                    runtime.record_retry(1, 1, 0)
                for table in tables:
                    yield table
        finally:
            for _, fut in pending:
                fut.cancel()
            pool.shutdown(wait=True)
            with files_lock:
                _close_all_fallback(open_files)

    def __repr__(self) -> str:
        return f"ParquetSource({self.path!r}, rows={self._num_rows})"


class MappedSource(DataSource):
    """Lazy per-batch transform over another source — e.g. the profiler's
    pass-2 cast of inferred-numeric string columns
    (reference: profiles/ColumnProfiler.scala:329-339,399-417)."""

    def __init__(
        self,
        base,
        fn: Callable[[Table], Table],
        schema_overrides: Optional[List[Tuple[str, ColumnType]]] = None,
        fn_columns: Optional[Sequence[str]] = None,
    ):
        self.base = base
        self.fn = fn
        # fn's read set. Column pruning can only be forwarded past fn when
        # the caller declares which columns fn consumes — an undeclared fn
        # may derive one column from another, and a pruned batch would
        # silently starve it (or raise mid-scan).
        self.fn_columns = None if fn_columns is None else tuple(fn_columns)
        self._overrides = list(schema_overrides or [])
        overrides = dict(self._overrides)
        self._schema_cache = [
            (name, overrides.get(name, ctype)) for name, ctype in base.schema
        ]
        self.batch_rows = getattr(base, "batch_rows", DataSource.batch_rows)

    def with_columns(self, names) -> "MappedSource":
        base_wc = getattr(self.base, "with_columns", None)
        if base_wc is None:
            return self
        if self.fn_columns is None:
            # fn's read set is unknown: pruning the base could starve it
            return self
        # the pruned source's schema is names ∪ fn_columns (fn's inputs
        # stay decoded and visible — a superset of the request, like an
        # unprunable source would be); overrides are kept for EVERY
        # surviving column so the schema matches what fn actually emits
        base_needs = sorted(set(names) | set(self.fn_columns))
        return MappedSource(
            base_wc(base_needs),
            self.fn,
            [(n, t) for n, t in self._overrides if n in set(base_needs)],
            fn_columns=self.fn_columns,
        )

    def _schema(self) -> List[Tuple[str, ColumnType]]:
        return self._schema_cache

    @property
    def num_rows(self) -> int:
        return self.base.num_rows

    def batches(self, batch_size: int) -> Iterator[Table]:
        # the base source already prefetches; apply fn inline
        produced_any = False
        for batch in self.base.batches(batch_size):
            produced_any = True
            yield self.fn(batch)
        if not produced_any:
            yield Table([_empty_column(n, t) for n, t in self._schema()])

# -- partitioned datasets (incremental scans) ---------------------------------

# footer fingerprints memoized by (device, inode, size, mtime_ns): any
# rewrite of the file changes size or mtime (and usually inode), so a
# stat hit can only ever return the digest of the bytes currently on
# disk. Bounded FIFO so a long-lived service scanning many datasets
# can't grow it without limit.
_FP_CACHE: "OrderedDict[str, Tuple[Tuple[int, int, int, int], str]]" = (
    OrderedDict()
)
_FP_CACHE_LOCK = threading.Lock()
_FP_CACHE_MAX = 8192


def partition_fingerprint(path: str) -> str:
    """Content fingerprint of one parquet partition file: sha256 over
    the file's NAME within the dataset, its byte size, and the parquet
    footer's row-group metadata (per-group row counts and byte sizes,
    per-chunk column paths, compressed sizes and min/max/null-count
    statistics). Any rewrite of the file — appended rows, mutated
    values, recompression — changes the footer and therefore the
    fingerprint, so a cached state for the old content can never be
    reused (the state-cache invalidation contract,
    repository/states.py). The directory part of the path is
    deliberately excluded: relocating a dataset wholesale keeps its
    cache warm, since entries are already namespaced by dataset.

    Fingerprints are memoized per stat signature: a preempted run that
    resumes over an N-partition dataset re-fingerprints nothing that
    hasn't changed on disk, so time-to-first-resume-boundary stays flat
    in N instead of costing one footer read per partition per attempt."""
    import pyarrow.parquet as pq

    fstat = os.stat(path)
    stat_sig = (fstat.st_dev, fstat.st_ino, fstat.st_size, fstat.st_mtime_ns)
    with _FP_CACHE_LOCK:
        hit = _FP_CACHE.get(path)
        if hit is not None and hit[0] == stat_sig:
            _FP_CACHE.move_to_end(path)
            return hit[1]

    h = hashlib.sha256()
    h.update(os.path.basename(path).encode("utf-8") + b"\x00")
    h.update(struct.pack(">q", fstat.st_size))
    pf = pq.ParquetFile(path)
    try:
        meta = pf.metadata
        h.update(struct.pack(">qq", meta.num_rows, meta.num_row_groups))
        for g in range(meta.num_row_groups):
            rg = meta.row_group(g)
            h.update(struct.pack(">qq", rg.num_rows, rg.total_byte_size))
            for j in range(rg.num_columns):
                chunk = rg.column(j)
                h.update(chunk.path_in_schema.encode("utf-8") + b"\x00")
                h.update(struct.pack(">q", chunk.total_compressed_size))
                st = chunk.statistics
                if st is not None and bool(getattr(st, "has_min_max", False)):
                    h.update(repr(st.min).encode("utf-8") + b"\x00")
                    h.update(repr(st.max).encode("utf-8") + b"\x00")
                if st is not None and bool(getattr(st, "has_null_count", False)):
                    h.update(struct.pack(">q", int(st.null_count)))
    finally:
        pf.close()
    digest = h.hexdigest()
    with _FP_CACHE_LOCK:
        _FP_CACHE[path] = (stat_sig, digest)
        _FP_CACHE.move_to_end(path)
        while len(_FP_CACHE) > _FP_CACHE_MAX:
            _FP_CACHE.popitem(last=False)
    return digest


class Partition:
    """One partition of a `PartitionedParquetSource`: a parquet file,
    its dataset-stable name, and its content fingerprint (computed
    lazily — a fingerprint reads footer metadata, never a row)."""

    def __init__(self, path: str, columns: Optional[List[str]], batch_rows: int):
        self.path = path
        self.name = os.path.basename(path)
        self._columns = columns
        self._batch_rows = batch_rows
        self._fingerprint: Optional[str] = None

    @property
    def fingerprint(self) -> str:
        if self._fingerprint is None:
            self._fingerprint = partition_fingerprint(self.path)
        return self._fingerprint

    def source(self) -> ParquetSource:
        """A fresh single-file source for scanning just this partition —
        it rides the full existing scan stack (pushdown, decode fast
        path, wire fusion) unchanged."""
        return ParquetSource(
            self.path, columns=self._columns, batch_rows=self._batch_rows
        )

    def __repr__(self) -> str:
        return f"Partition({self.name!r})"


class PartitionedParquetSource(DataSource):
    """A dataset of parquet files scanned one partition at a time, in
    deterministic name order. The fused pass folds EACH partition to
    analyzer states and merges them through the `State.merge` semigroup
    in that same order whether or not a state cache is attached — which
    is what makes cached and uncached runs trivially bit-identical
    (float addition is non-associative, so the merge ORDER is the
    contract, not an implementation detail). With a `StateRepository`
    attached, partitions whose fingerprint + plan signature already
    have a stored envelope load as states instead of scanning."""

    def __init__(
        self,
        paths,
        columns: Optional[List[str]] = None,
        batch_rows: int = 1 << 22,
    ):
        if isinstance(paths, str):
            if os.path.isdir(paths):
                resolved = [
                    os.path.join(paths, n)
                    for n in os.listdir(paths)
                    if n.endswith(".parquet") and not n.startswith(".")
                ]
            else:
                resolved = [paths]
        else:
            resolved = [str(p) for p in paths]
        if not resolved:
            raise ValueError(
                "PartitionedParquetSource needs at least one parquet file"
            )
        # name order, not listing order: the merge order (and therefore
        # the exact float result) must not depend on directory traversal
        self.paths = sorted(resolved, key=os.path.basename)
        self.columns = columns
        self.batch_rows = batch_rows
        first = ParquetSource(
            self.paths[0], columns=columns, batch_rows=batch_rows
        )
        self._schema_cache = first.schema
        import pyarrow.parquet as pq

        total = 0
        for p in self.paths:
            pf = pq.ParquetFile(p)
            try:
                total += pf.metadata.num_rows
            finally:
                pf.close()
        self._num_rows = total

    def _schema(self) -> List[Tuple[str, ColumnType]]:
        return self._schema_cache

    @property
    def num_rows(self) -> int:
        return self._num_rows

    def partitions(self) -> List[Partition]:
        """The per-file partitions in deterministic (name) order — the
        duck-typed hook `FusedScanPass.run` splits on."""
        return [
            Partition(p, self.columns, self.batch_rows) for p in self.paths
        ]

    def with_columns(self, names) -> "PartitionedParquetSource":
        keep = [n for n, _ in self._schema_cache if n in set(names)]
        if keep == [n for n, _ in self._schema_cache] or not keep:
            return self
        return PartitionedParquetSource(
            self.paths, columns=keep, batch_rows=self.batch_rows
        )

    def subset(self, paths) -> "PartitionedParquetSource":
        """Shard-filtered view of the dataset: the same source restricted
        to `paths` (a shard's slice from `parallel/shard.py`), preserving
        column projection, batch sizing and — critically — the global
        name order, so a per-shard fold merges its partitions in exactly
        the order the solo fold visits them. Unknown paths are a plan
        bug, not data: raise instead of silently scanning less."""
        keep = set(str(p) for p in paths)
        unknown = keep - set(self.paths)
        if unknown:
            raise ValueError(
                f"subset paths not in this dataset: {sorted(unknown)}"
            )
        picked = [p for p in self.paths if p in keep]
        if not picked:
            raise ValueError("subset would leave no partitions")
        return PartitionedParquetSource(
            picked, columns=self.columns, batch_rows=self.batch_rows
        )

    def decode_column_types(self):
        """Decode vocabulary of the dataset (all partitions share one
        schema): delegate to the first partition."""
        return ParquetSource(
            self.paths[0], columns=self.columns, batch_rows=self.batch_rows
        ).decode_column_types()

    def _iter_tables(self, batch_size: int) -> Iterator[Table]:
        # whole-dataset stream for consumers outside the partitioned
        # fold (grouping passes, profiler): partitions chain in the same
        # deterministic order the per-partition merge uses
        for part in self.partitions():
            yield from part.source()._iter_tables(batch_size)

    def __repr__(self) -> str:
        return (
            f"PartitionedParquetSource({len(self.paths)} files, "
            f"rows={self._num_rows})"
        )

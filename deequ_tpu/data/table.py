"""Columnar in-memory table: the engine's DataFrame equivalent.

Design (SURVEY.md §7): numeric/bool columns are dense numpy arrays plus a
validity bitmask; strings stay host-side (object arrays + dictionary
encoding) because TPUs can't regex; batches stream to device for fused
reductions. Replaces the role Spark's DataFrame plays for the reference
(reference: pom.xml:70-91, L0 in SURVEY layer map).
"""

from __future__ import annotations

import enum
import threading
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np


class ColumnType(enum.Enum):
    STRING = "StringType"
    LONG = "LongType"
    DOUBLE = "DoubleType"
    BOOLEAN = "BooleanType"
    TIMESTAMP = "TimestampType"
    DECIMAL = "DecimalType"

    @property
    def is_numeric(self) -> bool:
        return self in (ColumnType.LONG, ColumnType.DOUBLE, ColumnType.DECIMAL)


NUMPY_BACKING = {
    ColumnType.STRING: object,
    ColumnType.LONG: np.int64,
    ColumnType.DOUBLE: np.float64,
    ColumnType.BOOLEAN: np.bool_,
    ColumnType.TIMESTAMP: "datetime64[us]",
    ColumnType.DECIMAL: np.float64,
}


class Column:
    """One column: dense values + validity mask (True = present).

    CONTRACT: null slots in ``values`` hold the neutral fill (0 / "" /
    epoch) — never NaN — so masked reductions can consume the backing
    array directly (0 * mask == 0; NaN would poison every sum). All
    constructors enforce this; build Columns through them.

    `values` may be passed as a zero-arg callable for LAZY
    materialization: streamed string columns keep their Arrow backing
    (dictionary codes serve the analyzers) and only pay the
    object-array conversion if something truly needs per-row Python
    strings.
    """

    # content digest of the backing arrow dictionary (set by from_arrow
    # for parquet dictionary columns): lets dictionary-LEVEL derived
    # values (classify/parse/hash of the dict itself) be shared across
    # STREAM batches, whose equal dictionaries are rebuilt per row group
    _dict_content_key = None

    def __init__(self, name: str, ctype: ColumnType, values, valid: np.ndarray):
        self.name = name
        self.ctype = ctype
        self.valid = valid
        if callable(values):
            self._values = None
            self._values_fn = values
        else:
            assert len(values) == len(valid)
            self._values = values
            self._values_fn = None
        # per-instance memo for derived encodings (dict codes, parsed
        # numerics) shared by every analyzer reading this batch's column
        self._cache: Dict[str, object] = {}

    @property
    def values(self) -> np.ndarray:
        if self._values is None:
            materialized = self._values_fn()
            assert len(materialized) == len(self.valid)
            self._values = materialized
        return self._values

    def __repr__(self) -> str:
        return f"Column({self.name!r}, {self.ctype})"

    def __len__(self) -> int:
        return len(self.valid)

    @property
    def null_count(self) -> int:
        return int(len(self.valid) - self.valid.sum())

    def non_null_values(self) -> np.ndarray:
        return self.values[self.valid]

    def slice(self, start: int, stop: int) -> "Column":
        child = Column(
            self.name,
            self.ctype,
            # lazy through the slice: only materialize the parent if the
            # child's python-object values are actually consumed
            lambda: self.values[start:stop],
            self.valid[start:stop],
        )
        # derived encodings (dict codes, parsed numerics) are row-wise, so
        # a slice can reuse the parent's arrays — string columns are then
        # encoded ONCE per table, not once per batch per pass
        child._parent = (self, start, stop)
        return child

    def take(self, indices: np.ndarray) -> "Column":
        return Column(self.name, self.ctype, self.values[indices], self.valid[indices])

    def numeric_values(self) -> Tuple[np.ndarray, np.ndarray]:
        """(float64 values, valid) — strings that don't parse as numbers
        become invalid (null), matching the expr-engine coercion.

        Returned arrays are shared (cached / possibly the column's own
        backing store): callers must treat them as immutable."""
        if self.ctype == ColumnType.DOUBLE or self.ctype == ColumnType.DECIMAL:
            # constructors fill null slots with 0.0, so the backing array
            # is directly usable under mask algebra (0 * mask == 0, no NaN
            # poisoning) — no per-batch materialization
            return self.values, self.valid

        def compute(col: "Column"):
            if col.ctype == ColumnType.BOOLEAN:
                return col.values.astype(np.float64), col.valid
            if col.ctype == ColumnType.TIMESTAMP:
                return (
                    col.values.astype("datetime64[us]")
                    .astype(np.int64)
                    .astype(np.float64),
                    col.valid,
                )
            if col.ctype == ColumnType.STRING:
                codes, _uniques = col.dict_encode()
                u_vals, u_ok = parsed_dictionary(col)
                return (
                    gather_with_null(u_vals, codes, 0.0),
                    gather_with_null(u_ok, codes, False),
                )
            # LONG
            return (
                np.where(col.valid, col.values.astype(np.float64), 0.0),
                col.valid,
            )

        return cached_column_encode(
            self,
            "numeric_values",
            compute,
            slicer=lambda v, s, e: (v[0][s:e], v[1][s:e]),
        )

    def as_float(self) -> np.ndarray:
        """Values as float64; null/unparseable slots = 0.0 (mask separately
        via ``numeric_values`` when the parse-failure mask matters)."""
        return self.numeric_values()[0]

    def dict_encode(self) -> Tuple[np.ndarray, np.ndarray]:
        """Dictionary-encode: (codes, uniques). Null rows get code -1.
        Codes are an integer array — int64 from the numpy/arrow encode
        paths, int32 when a parquet dictionary column's indices map
        zero-copy; consumers must not assume an 8-byte stride.

        The group-by building block: arbitrary keys become dense integer
        codes the device can bincount/segment-reduce over. Memoized per
        Column instance — every string analyzer on a batch shares one
        encode.
        """
        return cached_column_encode(
            self,
            "dict_encode",
            _compute_dict_encode,
            # codes slice row-wise; the dictionary is shared whole
            slicer=lambda v, s, e: (v[0][s:e], v[1]),
        )


def _compute_dict_encode(col: "Column") -> Tuple[np.ndarray, np.ndarray]:
    if not col.valid.any():
        return (
            np.full(len(col.values), -1, dtype=np.int64),
            np.array([], dtype=object),
        )
    arrow_arr = col._cache.get("arrow")
    if arrow_arr is not None:
        # arrow-backed string column: hash-based C dictionary encode
        return _arrow_dict_encode(arrow_arr)
    if col.ctype == ColumnType.STRING:
        # arrow's hash-based dictionary encode is ~8x numpy's sort-based
        # unique on object arrays (measured: 0.6s vs 5.2s per 4M rows);
        # fall back to np.unique only without pyarrow
        try:
            import pyarrow as pa

            return _arrow_dict_encode(
                pa.array(
                    col.values,
                    type=pa.string(),
                    mask=None if col.valid.all() else ~col.valid,
                )
            )
        except ImportError:
            pass
        except pa.lib.ArrowException:
            # backing values that aren't str (mixed object arrays,
            # numeric values under a STRING ctype): the numpy path
            # below stringifies them
            pass
    vals = col.values[col.valid]
    if col.ctype == ColumnType.STRING:
        vals = vals.astype(str)
    uniques, inv = np.unique(vals, return_inverse=True)
    codes = np.full(len(col.values), -1, dtype=np.int64)
    codes[col.valid] = inv
    return codes, uniques


def _arrow_dict_encode(arrow_arr) -> Tuple[np.ndarray, np.ndarray]:
    encoded = arrow_arr.dictionary_encode()
    codes = (
        encoded.indices.fill_null(-1)
        .to_numpy(zero_copy_only=False)
        .astype(np.int64)
    )
    uniques = encoded.dictionary.to_numpy(zero_copy_only=False)
    if uniques.dtype != object:
        uniques = uniques.astype(object)
    return codes, uniques


def cached_column_encode(col: "Column", key: str, compute, slicer=None):
    """Column-deterministic derived encoding, memoized on the Column with
    parent-slice delegation: one materialization per TABLE, batches slice
    it. `compute(column)` builds the full-column value on the root
    column; `slicer(value, start, stop)` produces a batch view of it
    (default: plain array slicing — pass one when the cached value is a
    tuple with non-row-wise parts, e.g. dict_encode's uniques)."""
    cached = col._cache.get(key)
    if cached is None:
        parent = getattr(col, "_parent", None)
        if parent is not None:
            p, start, stop = parent
            whole = cached_column_encode(p, key, compute, slicer)
            cached = (
                slicer(whole, start, stop)
                if slicer is not None
                else whole[start:stop]
            )
        else:
            cached = compute(col)
        col._cache[key] = cached
    return cached


_DICT_DERIVED_CACHE: "OrderedDict" = None  # type: ignore[assignment]
_DICT_DERIVED_MAX = 256
# byte budget: a stream whose every row group carries a DISTINCT
# near-64k-entry dictionary must not pin hundreds of MB of derived
# arrays for the process lifetime (the bounded-RSS stream contract)
_DICT_DERIVED_MAX_BYTES = 32 << 20
_DICT_DERIVED_BYTES = 0
# family kernels run dictionary encodes from a thread pool (fused.py):
# the OrderedDict reorder/evict and the byte counter are not atomic
_DICT_DERIVED_LOCK = threading.Lock()


def _derived_nbytes(value) -> int:
    if isinstance(value, np.ndarray):
        return int(value.nbytes)
    if isinstance(value, (tuple, list)):
        return sum(_derived_nbytes(v) for v in value)
    return 64  # scalars / small objects: nominal


def cached_dictionary_encode(col: "Column", key: str, compute):
    """DICTIONARY-level derived value (classify / numeric parse / hash of
    the dictionary itself — NOT row data): memoized on the root Column
    like `cached_column_encode`, and additionally across BATCHES via the
    arrow dictionary's content digest when available. A streamed parquet
    source rebuilds an equal dictionary for every row group; without
    this memo every batch re-classifies/re-parses/re-hashes the same few
    thousand strings. The cross-batch tier is bounded by entry count AND
    bytes (LRU eviction)."""
    global _DICT_DERIVED_CACHE, _DICT_DERIVED_BYTES
    root = col
    while getattr(root, "_parent", None) is not None:
        root = root._parent[0]
    cached = root._cache.get(key)
    if cached is not None:
        return cached
    content_key = root._dict_content_key
    if content_key is not None:
        with _DICT_DERIVED_LOCK:
            if _DICT_DERIVED_CACHE is None:
                from collections import OrderedDict

                _DICT_DERIVED_CACHE = OrderedDict()
            hit = _DICT_DERIVED_CACHE.get((content_key, key))
            if hit is not None:
                _DICT_DERIVED_CACHE.move_to_end((content_key, key))
                root._cache[key] = hit[0]
                return hit[0]
    value = compute(root)
    root._cache[key] = value
    if content_key is not None:
        nbytes = _derived_nbytes(value)
        with _DICT_DERIVED_LOCK:
            _DICT_DERIVED_CACHE[(content_key, key)] = (value, nbytes)
            _DICT_DERIVED_BYTES += nbytes
            while _DICT_DERIVED_CACHE and (
                len(_DICT_DERIVED_CACHE) > _DICT_DERIVED_MAX
                or _DICT_DERIVED_BYTES > _DICT_DERIVED_MAX_BYTES
            ):
                _key, (_value, evicted_bytes) = _DICT_DERIVED_CACHE.popitem(
                    last=False
                )
                _DICT_DERIVED_BYTES -= evicted_bytes
    return value


def _arrow_dictionary_digest(dictionary):
    """Content digest of an arrow string dictionary (the cross-batch
    memo key): sha1 over its raw buffers, ~µs for the few-thousand-entry
    dictionaries parquet produces. None (no sharing) for offset/sliced
    or oversized dictionaries, where buffer bytes would not equal
    content."""
    try:
        if dictionary.offset != 0 or len(dictionary) > (1 << 16):
            return None
        import hashlib

        h = hashlib.sha1()
        for buf in dictionary.buffers():
            if buf is not None:
                h.update(buf)
        return (len(dictionary), h.digest())
    except Exception:  # noqa: BLE001 - memo is an optimization only
        return None


def parsed_dictionary(col: "Column"):
    """(parsed float64 values, parse-ok bool) per dictionary entry of a
    STRING column, through the cross-batch dictionary memo — shared by
    numeric_values' per-row gather and the profiler's counts-based
    numeric-stats path."""
    from deequ_tpu.ops.strings import parse_floats

    return cached_dictionary_encode(
        col,
        "dictparse",
        lambda c: parse_floats(np.asarray(c.dict_encode()[1], dtype=object)),
    )


def hashed_dictionary(col: "Column") -> np.ndarray:
    """uint64 xxhash per dictionary entry of a STRING column, through
    the cross-batch dictionary memo — shared by the packed-HLL input
    spec and the _LowCardCounts presence path of ApproxCountDistinct."""
    from deequ_tpu.ops.strings import hash_strings

    return cached_dictionary_encode(
        col,
        "dicthash",
        lambda c: hash_strings(np.asarray(c.dict_encode()[1], dtype=object)),
    )


def gather_with_null(lut: np.ndarray, codes: np.ndarray, null_value) -> np.ndarray:
    """Per-row gather of a per-unique LUT through dict_encode codes in ONE
    pass: dict_encode's null sentinel (-1) indexes a slot holding
    `null_value` appended at the end (numpy negative indexing), so no
    mask/scatter temporaries are needed. Relies on codes ∈ [-1, len(lut))."""
    lut = np.asarray(lut)
    ext = np.append(lut, np.asarray([null_value], dtype=lut.dtype))
    return ext[codes]


def _infer_type(values: Sequence) -> ColumnType:
    non_null = [v for v in values if v is not None]
    if not non_null:
        return ColumnType.STRING
    if all(isinstance(v, bool) for v in non_null):
        return ColumnType.BOOLEAN
    if all(isinstance(v, (int, np.integer)) and not isinstance(v, bool) for v in non_null):
        return ColumnType.LONG
    if all(
        isinstance(v, (int, float, np.integer, np.floating)) and not isinstance(v, bool)
        for v in non_null
    ):
        return ColumnType.DOUBLE
    return ColumnType.STRING


def _column_from_list(name: str, values: Sequence, ctype: Optional[ColumnType]) -> Column:
    if ctype is None:
        ctype = _infer_type(values)
    n = len(values)
    valid = np.array([v is not None and v == v for v in values], dtype=np.bool_) \
        if ctype in (ColumnType.DOUBLE, ColumnType.DECIMAL) \
        else np.array([v is not None for v in values], dtype=np.bool_)
    backing = NUMPY_BACKING[ctype]
    if ctype == ColumnType.STRING:
        arr = np.empty(n, dtype=object)
        for i, v in enumerate(values):
            arr[i] = str(v) if v is not None else ""
    else:
        fill = {
            ColumnType.LONG: 0,
            ColumnType.DOUBLE: 0.0,
            ColumnType.DECIMAL: 0.0,
            ColumnType.BOOLEAN: False,
            ColumnType.TIMESTAMP: np.datetime64(0, "us"),
        }[ctype]
        arr = np.array(
            [v if (v is not None and v == v) else fill for v in values], dtype=backing
        ) if ctype in (ColumnType.DOUBLE, ColumnType.DECIMAL) else np.array(
            [v if v is not None else fill for v in values], dtype=backing
        )
    return Column(name, ctype, arr, valid)


def shared_all_true(shared: Dict[str, np.ndarray], n: int) -> np.ndarray:
    """One read-only all-true mask shared by every null-free column of a
    batch (lets pack elide mask work via `valid.all()` without a scan
    per column). `shared` is the per-from_arrow scratch dict."""
    mask = shared.get("all_true")
    if mask is None or len(mask) != n:
        mask = np.ones(n, dtype=bool)
        mask.setflags(write=False)
        shared["all_true"] = mask
    return mask


def pool_empty(n: int, dtype) -> np.ndarray:
    """Uninitialized Column backing allocated from the arrow memory pool.

    Streaming decode allocates and keeps dozens of outputs per batch;
    fresh `np.empty` arrays at that size come from new mmaps, so the
    decode kernels pay a page fault per 4KB on first touch. The arrow
    pool recycles the previous batch's pages (it already backs the
    fallback's `fill_null` outputs), which measures ~1.8x faster per
    column on the wide-stream shape. Degrades to `np.empty` when
    pyarrow is unavailable."""
    try:
        import pyarrow as pa
    except ImportError:
        return np.empty(n, dtype=dtype)
    dt = np.dtype(dtype)
    buf = pa.allocate_buffer(int(n) * dt.itemsize)
    out = np.frombuffer(buf, dtype=dt)
    # np.frombuffer honors the source's mutability; assert rather than
    # silently hand the kernels a read-only backing
    assert out.flags.writeable
    return out


def _column_from_arrow_fallback(name, arr, arrow_table, shared) -> Column:
    """Host-side decode of one (already combined) arrow chunk.

    This is the designated fallback behind the native fast path
    (data/arrow_decode.py): columns whose values must exist host-side
    (plain strings, decimals) or whose layout the native kernels don't
    take land here. tools/lint.py's DECODE rule keeps `.to_numpy(` copy
    idioms confined to this chain."""
    import pyarrow as pa

    if pa.types.is_dictionary(arr.type) and not (
        pa.types.is_string(arr.type.value_type)
        or pa.types.is_large_string(arr.type.value_type)
    ):
        # only string dictionaries have a first-class code path;
        # others decode to their value type so the column's ctype
        # matches what _arrow_ctype reports for the schema
        arr = arr.dictionary_decode()
    # null-free columns skip the fill_null/where copies and get
    # zero-copy numpy views of the arrow buffers where possible
    # (views are read-only; Column treats values as immutable,
    # which also lets all null-free columns share one mask)
    no_nulls = arr.null_count == 0
    if no_nulls:
        valid = shared_all_true(shared, len(arr))
    else:
        valid = np.asarray(arr.is_valid())
    t = arr.type
    if pa.types.is_boolean(t):
        vals = np.asarray(arr if no_nulls else arr.fill_null(False))
        return Column(name, ColumnType.BOOLEAN, vals, valid)
    elif pa.types.is_integer(t):
        vals = np.asarray(arr if no_nulls else arr.fill_null(0))
        if vals.dtype != np.int64:
            vals = vals.astype(np.int64)
        return Column(name, ColumnType.LONG, vals, valid)
    elif pa.types.is_floating(t):
        vals = np.asarray(arr if no_nulls else arr.fill_null(0.0))
        if vals.dtype != np.float64:
            vals = vals.astype(np.float64)
        nan = np.isnan(vals)
        if nan.any():
            valid = valid & ~nan
            vals = np.where(valid, vals, 0.0)
        # a float64 field annotated by to_arrow keeps its
        # DECIMAL ctype across the arrow/parquet round trip
        # (values were float64 already; only the logical type
        # needs restoring)
        ctype = (
            ColumnType.DECIMAL
            if _arrow_logical_decimal(arrow_table, name)
            else ColumnType.DOUBLE
        )
        return Column(name, ctype, vals, valid)
    elif pa.types.is_decimal(t):
        vals = np.array(
            [float(v) if v is not None else 0.0 for v in arr.to_pylist()],
            dtype=np.float64,
        )
        return Column(name, ColumnType.DECIMAL, vals, valid)
    elif pa.types.is_timestamp(t):
        vals = np.asarray(arr.cast(pa.timestamp("us")).fill_null(0))
        return Column(
            name, ColumnType.TIMESTAMP, vals.astype("datetime64[us]"), valid
        )
    elif pa.types.is_dictionary(t) and (
        pa.types.is_string(t.value_type)
        or pa.types.is_large_string(t.value_type)
    ):
        # dictionary-decoded string column (ParquetSource reads
        # string columns this way): the codes ARE the dict_encode
        # result — no per-row string materialization, no re-encode.
        # `values` stays lazy; only consumers that truly need
        # per-row python strings pay the gather.
        # int32 stays int32: arrow dictionary indices feed
        # bincount/gathers directly (the int64 upcast cost a
        # copy plus double the bincount traffic); null-free
        # indices map zero-copy
        idx = arr.indices
        if idx.null_count == 0:
            codes = idx.to_numpy(zero_copy_only=True)
        else:
            codes = idx.fill_null(-1).to_numpy(zero_copy_only=False)
        uniques = dictionary_uniques_fallback(arr.dictionary)
        col = Column(
            name,
            ColumnType.STRING,
            lambda codes=codes, uniques=uniques: gather_with_null(
                uniques, codes, ""
            ),
            valid,
        )
        col._cache["dict_encode"] = (codes, uniques)
        col._dict_content_key = _arrow_dictionary_digest(
            arr.dictionary
        )
        return col
    elif pa.types.is_string(t) or pa.types.is_large_string(t):
        vals = arr.to_numpy(zero_copy_only=False)
        if vals.dtype != object:
            vals = vals.astype(object)
        if not valid.all():
            vals[~valid] = ""
        col = Column(name, ColumnType.STRING, vals, valid)
        # keep the arrow array: dict_encode uses its C hash-based
        # dictionary_encode instead of a sort-based np.unique
        col._cache["arrow"] = arr
        return col
    else:
        py = arr.to_pylist()
        vals = np.empty(len(py), dtype=object)
        for i, v in enumerate(py):
            vals[i] = str(v) if v is not None else ""
        return Column(name, ColumnType.STRING, vals, valid)


def _arrow_logical_decimal(arrow_table, name: str) -> bool:
    """True when the float64 field carries the deequ_tpu DECIMAL
    logical-type annotation written by Table.to_arrow."""
    try:
        md = arrow_table.schema.field(name).metadata or {}
    except Exception:  # noqa: BLE001 - schemaless inputs
        md = {}
    return md.get(b"deequ_tpu.logical_type") == ColumnType.DECIMAL.value.encode()


def dictionary_uniques_fallback(dictionary) -> np.ndarray:
    """Designated fallback: materialize a dictionary's uniques as a host
    object array. This is the only host-side string materialization the
    dictionary decode paths (native and fallback) perform — per-row
    strings stay lazy."""
    uniques = dictionary.to_numpy(zero_copy_only=False)
    if uniques.dtype != object:
        uniques = uniques.astype(object)
    return uniques


class Table:
    """Immutable columnar table."""

    def __init__(self, columns: Sequence[Column]):
        self._columns: Dict[str, Column] = {c.name: c for c in columns}
        lengths = {len(c) for c in columns}
        if len(lengths) > 1:
            raise ValueError(f"ragged columns: {lengths}")
        self._num_rows = lengths.pop() if lengths else 0

    # -- construction -------------------------------------------------------

    @staticmethod
    def from_pydict(
        data: Dict[str, Sequence], types: Optional[Dict[str, ColumnType]] = None
    ) -> "Table":
        types = types or {}
        return Table(
            [_column_from_list(k, v, types.get(k)) for k, v in data.items()]
        )

    @staticmethod
    def from_numpy(
        data: Dict[str, np.ndarray],
        valid: Optional[Dict[str, np.ndarray]] = None,
        types: Optional[Dict[str, ColumnType]] = None,
    ) -> "Table":
        valid = valid or {}
        types = types or {}
        cols = []
        for name, arr in data.items():
            arr = np.asarray(arr)
            if name in types:
                ctype = types[name]
            elif arr.dtype == np.bool_:
                ctype = ColumnType.BOOLEAN
            elif np.issubdtype(arr.dtype, np.integer):
                ctype = ColumnType.LONG
            elif np.issubdtype(arr.dtype, np.floating):
                ctype = ColumnType.DOUBLE
            elif np.issubdtype(arr.dtype, np.datetime64):
                ctype = ColumnType.TIMESTAMP
            else:
                # object arrays go through the same inference as
                # from_pydict: {bool, None} is a BOOLEAN column (its
                # histogram keys must be 'true'/'false', not Python's
                # str(True)), object ints are LONG, etc. A caller-supplied
                # mask ANDs with the non-null mask the values imply.
                inferred = _column_from_list(name, list(arr), None)
                extra_mask = valid.get(name)
                if extra_mask is not None:
                    inferred = Column(
                        name,
                        inferred.ctype,
                        inferred.values,
                        inferred.valid & np.asarray(extra_mask, dtype=np.bool_),
                    )
                cols.append(inferred)
                continue
            v = valid.get(name)
            if v is None:
                if ctype in (ColumnType.DOUBLE, ColumnType.DECIMAL):
                    v = ~np.isnan(np.asarray(arr, dtype=np.float64))
                    arr = np.where(v, arr, 0.0)
                elif ctype == ColumnType.STRING:
                    v = np.array([x is not None for x in arr], dtype=np.bool_)
                    if not v.all():
                        arr = arr.copy()
                        arr[~v] = ""
                else:
                    v = np.ones(len(arr), dtype=np.bool_)
            elif ctype in (ColumnType.DOUBLE, ColumnType.DECIMAL):
                # NaN == NULL under this engine; enforce the neutral-fill
                # contract even when the caller supplies the mask
                v = np.asarray(v, dtype=np.bool_) & ~np.isnan(
                    np.asarray(arr, dtype=np.float64)
                )
                arr = np.where(v, arr, 0.0)
            cols.append(Column(name, ctype, arr, np.asarray(v, dtype=np.bool_)))
        return Table(cols)

    @staticmethod
    def from_pandas(df) -> "Table":
        import pandas as pd  # noqa: F401

        cols = []
        for name in df.columns:
            s = df[name]
            valid = (~s.isna()).to_numpy(dtype=np.bool_)
            if s.dtype == object or str(s.dtype) in ("string", "str"):
                arr = np.empty(len(s), dtype=object)
                raw = s.tolist()
                all_bool = True
                for i, v in enumerate(raw):
                    arr[i] = "" if not valid[i] else str(v)
                    if valid[i] and not isinstance(v, bool):
                        all_bool = False
                if all_bool and valid.any():
                    barr = np.array(
                        [bool(v) if valid[i] else False for i, v in enumerate(raw)],
                        dtype=np.bool_,
                    )
                    cols.append(Column(str(name), ColumnType.BOOLEAN, barr, valid))
                    continue
                cols.append(Column(str(name), ColumnType.STRING, arr, valid))
            elif str(s.dtype).startswith("datetime"):
                arr = s.to_numpy(dtype="datetime64[us]")
                arr = np.where(valid, arr, np.datetime64(0, "us"))
                cols.append(Column(str(name), ColumnType.TIMESTAMP, arr, valid))
            elif s.dtype == np.bool_ or str(s.dtype) == "boolean":
                arr = s.fillna(False).to_numpy(dtype=np.bool_)
                cols.append(Column(str(name), ColumnType.BOOLEAN, arr, valid))
            elif str(s.dtype).startswith(("Int", "UInt")) or (
                isinstance(s.dtype, np.dtype) and np.issubdtype(s.dtype, np.integer)
            ):
                arr = s.fillna(0).to_numpy(dtype=np.int64)
                cols.append(Column(str(name), ColumnType.LONG, arr, valid))
            elif str(s.dtype).startswith("Float"):
                # pandas nullable Float32/Float64 extension dtypes
                arr = s.to_numpy(dtype=np.float64, na_value=np.nan)
                valid = valid & ~np.isnan(np.where(valid, arr, 0.0))
                arr = np.where(valid, arr, 0.0)
                cols.append(Column(str(name), ColumnType.DOUBLE, arr, valid))
            else:
                arr = s.to_numpy(dtype=np.float64)
                valid = valid & ~np.isnan(np.where(valid, arr, 0.0))
                arr = np.where(valid, arr, 0.0)
                cols.append(Column(str(name), ColumnType.DOUBLE, arr, valid))
        return Table(cols)

    @staticmethod
    def from_arrow(arrow_table, fastpath_columns=None, wire=None) -> "Table":
        """Decode an arrow table into engine Columns.

        `fastpath_columns` (a set of names, normally threaded through
        `ParquetSource.with_decode_fastpath` by the planner's
        `plan_decode_fastpath`) routes those columns through the
        buffer-level native decode (data/arrow_decode.py + ops/native/
        decode.c): one C pass from arrow buffers to the Column backing,
        no intermediate numpy materialization. Any column the native
        path cannot take (missing library, unexpected layout) falls back
        to the host chain automatically — the two produce bit-identical
        Columns, so the fast path is a pure perf decision.

        `wire` (a `runtime.WireFusionPlan`) goes one step further for
        its columns: decode straight to the packed device wire format,
        skipping the Column intermediate entirely. Fused columns get a
        lazy stub Column plus wire rows collected on the returned
        table's ``wire_rows`` attribute; any per-batch failure (layout
        surprise, narrow-int overflow, unresolved shift) falls back to
        the ordinary decode for that column, that batch."""
        import pyarrow as pa

        cols = []
        wire_rows: Dict[str, object] = {}
        shared: Dict[str, np.ndarray] = {}  # one mask for null-free columns
        fast = None
        wire_fast = None
        if fastpath_columns or (wire is not None and wire.columns):
            from deequ_tpu.data import arrow_decode

            fast = arrow_decode.decode_fast_column
            wire_fast = arrow_decode.decode_wire_column
        for name in arrow_table.column_names:
            chunked = arrow_table.column(name)
            if isinstance(chunked, pa.ChunkedArray):
                chunks = list(chunked.chunks)
            else:
                chunks = [chunked]
            if wire_fast is not None and wire is not None and name in wire.columns:
                fused = wire_fast(
                    name, chunks, arrow_table, wire.columns[name], wire
                )
                if fused is not None:
                    stub, rows = fused
                    cols.append(stub)
                    wire_rows.update(rows)
                    continue
            if fast is not None and fastpath_columns and name in fastpath_columns:
                col = fast(name, chunks, arrow_table, shared)
                if col is not None:
                    cols.append(col)
                    continue
            # single-chunk columns (every row-group/slice read) skip
            # the combine_chunks memcpy; the chunk may carry a slice
            # offset, which every consumer below handles
            if len(chunks) == 1:
                arr = chunks[0]
            elif not chunks:
                arr = pa.array([], chunked.type)
            else:
                arr = chunked.combine_chunks()
                if isinstance(arr, pa.ChunkedArray):
                    arr = arr.chunk(0)
            cols.append(
                _column_from_arrow_fallback(name, arr, arrow_table, shared)
            )
        table = Table(cols)
        if wire_rows:
            table.wire_rows = wire_rows
        return table

    def to_arrow(self, dictionary_encode_strings: bool = False):
        """Arrow table with faithful nulls: the Column neutral-fill
        contract is inverted (null slots become arrow nulls, not the
        0.0/""/False fillers). The single conversion used by every
        write-to-parquet path (tests, dryruns, bench).

        DECIMAL columns are float64-backed in memory (the precision was
        already capped at ingest — see `from_arrow`), so they emit as
        float64 with the logical type recorded in field metadata
        (``deequ_tpu.logical_type = DecimalType``). A round trip through
        arrow/parquet keeps the DecimalType ctype but NOT decimal
        precision beyond float64's 53 bits."""
        import pyarrow as pa

        arrays, fields = [], []
        for name, ctype in self.schema:
            col = self.column(name)
            values = col.values
            valid = np.asarray(col.valid)
            if values.dtype == object:
                # explicit string type: an ALL-NULL column would
                # otherwise infer arrow's null type, whose
                # dictionary_encode produces a DictionaryArray parquet
                # cannot write ("null encoded in dictionary")
                arr = pa.array(
                    [v if ok else None for v, ok in zip(values, valid)],
                    type=pa.string() if ctype == ColumnType.STRING else None,
                )
                if dictionary_encode_strings and pa.types.is_string(arr.type):
                    arr = arr.dictionary_encode()
            else:
                arr = pa.array(values, mask=~valid)
            metadata = (
                {b"deequ_tpu.logical_type": ctype.value.encode()}
                if ctype == ColumnType.DECIMAL
                else None
            )
            fields.append(pa.field(name, arr.type, metadata=metadata))
            arrays.append(arr)
        return pa.table(arrays, schema=pa.schema(fields))

    def to_parquet(self, path: str, row_group_size: Optional[int] = None,
                   dictionary_encode_strings: bool = False) -> None:
        import pyarrow.parquet as pq

        pq.write_table(
            self.to_arrow(dictionary_encode_strings),
            path,
            row_group_size=row_group_size,
        )

    @staticmethod
    def from_parquet(path: str, columns: Optional[List[str]] = None) -> "Table":
        import pyarrow.parquet as pq

        return Table.from_arrow(pq.read_table(path, columns=columns))

    @staticmethod
    def scan_parquet(
        path: str,
        columns: Optional[List[str]] = None,
        batch_rows: int = 1 << 22,
    ):
        """Out-of-core scan: a streaming source every pass can consume
        (bounded host memory; prefetch thread overlaps decode with device
        compute). Use instead of `from_parquet` when the table exceeds
        host RAM."""
        from deequ_tpu.data.source import ParquetSource

        return ParquetSource(path, columns=columns, batch_rows=batch_rows)

    @staticmethod
    def scan_parquet_dataset(
        paths,
        columns: Optional[List[str]] = None,
        batch_rows: int = 1 << 22,
    ):
        """Out-of-core scan over a directory (or explicit list) of
        parquet partition files, folded one partition at a time and
        merged through the analyzer state semigroup in deterministic
        name order. The shape incremental runs require: attach a
        `StateRepository` (`AnalysisRunBuilder.with_state_repository`)
        and re-runs scan only new or modified partitions."""
        from deequ_tpu.data.source import PartitionedParquetSource

        return PartitionedParquetSource(
            paths, columns=columns, batch_rows=batch_rows
        )

    # -- schema / access ----------------------------------------------------

    @property
    def num_rows(self) -> int:
        return self._num_rows

    def __len__(self) -> int:
        return self._num_rows

    @property
    def column_names(self) -> List[str]:
        return list(self._columns.keys())

    def has_column(self, name: str) -> bool:
        return name in self._columns

    def column(self, name: str) -> Column:
        if name not in self._columns:
            from deequ_tpu.core.exceptions import NoSuchColumnException

            raise NoSuchColumnException(f"Input data does not include column {name}!")
        return self._columns[name]

    def __getitem__(self, name: str) -> Column:
        return self.column(name)

    @property
    def schema(self) -> List[Tuple[str, ColumnType]]:
        return [(c.name, c.ctype) for c in self._columns.values()]

    # -- transforms ---------------------------------------------------------

    def slice(self, start: int, stop: int) -> "Table":
        return Table([c.slice(start, stop) for c in self._columns.values()])

    def filter(self, row_mask: np.ndarray) -> "Table":
        idx = np.nonzero(np.asarray(row_mask, dtype=bool))[0]
        return Table([c.take(idx) for c in self._columns.values()])

    def select(self, names: Sequence[str]) -> "Table":
        return Table([self.column(n) for n in names])

    def with_column(self, col: Column) -> "Table":
        cols = [c for c in self._columns.values() if c.name != col.name]
        return Table(cols + [col])

    def batches(self, batch_size: int) -> Iterator["Table"]:
        """Stream fixed-size row slices (the unit shipped to device)."""
        if self._num_rows <= batch_size:
            # single batch: yield self so per-Column caches (dict codes,
            # parsed numerics) are shared across every pass over this table
            yield self
            return
        for start in range(0, self._num_rows, batch_size):
            yield self.slice(start, min(start + batch_size, self._num_rows))

    def random_split(
        self, weights: Sequence[float], seed: Optional[int] = None
    ) -> List["Table"]:
        """reference: suggestions/ConstraintSuggestionRunner.scala:127-148
        (df.randomSplit for train/test)."""
        rng = np.random.default_rng(seed)
        total = float(sum(weights))
        u = rng.random(self._num_rows)
        bounds = np.cumsum([w / total for w in weights])
        out = []
        lo = 0.0
        for hi in bounds:
            out.append(self.filter((u >= lo) & (u < hi)))
            lo = hi
        return out

    def to_pydict(self) -> Dict[str, List]:
        out: Dict[str, List] = {}
        for c in self._columns.values():
            vals: List = []
            for i in range(len(c)):
                if not c.valid[i]:
                    vals.append(None)
                elif c.ctype == ColumnType.STRING:
                    vals.append(c.values[i])
                elif c.ctype == ColumnType.BOOLEAN:
                    vals.append(bool(c.values[i]))
                elif c.ctype == ColumnType.LONG:
                    vals.append(int(c.values[i]))
                elif c.ctype == ColumnType.TIMESTAMP:
                    vals.append(c.values[i])
                else:
                    vals.append(float(c.values[i]))
            out[c.name] = vals
        return out

    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame(self.to_pydict())

    def __repr__(self):
        cols = ", ".join(f"{n}:{t.value}" for n, t in self.schema)
        return f"Table({self._num_rows} rows; {cols})"

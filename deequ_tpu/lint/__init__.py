"""Plan-time semantic analyzer: typed expression checking, constraint-plan
linting, and fail-fast diagnostics — all with zero data scans.

The Catalyst-analysis analogue for deequ_tpu (see README "Plan
validation"): resolve columns, infer dtypes/nullability with Kleene
semantics, and reject impossible plans before any kernel dispatch.
"""

from deequ_tpu.lint.cost import (
    FamilyGroupCost,
    PassCost,
    PlanCost,
    analyze_plan,
)
from deequ_tpu.lint.diagnostics import (
    CODES,
    Diagnostic,
    LintReport,
    PlanValidationError,
    Severity,
)
from deequ_tpu.lint.effects import AnalyzerEffect, scan_effects
from deequ_tpu.lint.explain import (
    ExplainResult,
    cost_diagnostics,
    explain,
    explain_plan,
    render_explain,
)
from deequ_tpu.lint.fold import const_fold, fold_to_constant, satisfiability
from deequ_tpu.lint.interval import Interval
from deequ_tpu.lint.pushdown import (
    ColumnStats,
    PredicatePrune,
    PrunePlan,
    RowGroupStats,
    build_prune_plan,
)
from deequ_tpu.lint.planlint import (
    lint_analyzer,
    lint_expression_use,
    lint_plan,
    validate_plan,
)
from deequ_tpu.lint.schema import FieldInfo, SchemaInfo
from deequ_tpu.lint.subsume import (
    PlanEnv,
    SubsumptionProof,
    prove_subsumption,
    wheres_equivalent,
)
from deequ_tpu.lint.typecheck import TypedExpr, analyze_ast, analyze_expression

__all__ = [
    "CODES",
    "Diagnostic",
    "LintReport",
    "PlanValidationError",
    "Severity",
    "FieldInfo",
    "SchemaInfo",
    "TypedExpr",
    "analyze_ast",
    "analyze_expression",
    "const_fold",
    "fold_to_constant",
    "satisfiability",
    "lint_analyzer",
    "lint_expression_use",
    "lint_plan",
    "validate_plan",
    "AnalyzerEffect",
    "ColumnStats",
    "ExplainResult",
    "FamilyGroupCost",
    "Interval",
    "PassCost",
    "PlanCost",
    "PlanEnv",
    "PredicatePrune",
    "PrunePlan",
    "RowGroupStats",
    "SubsumptionProof",
    "analyze_plan",
    "build_prune_plan",
    "cost_diagnostics",
    "explain",
    "explain_plan",
    "prove_subsumption",
    "render_explain",
    "scan_effects",
    "wheres_equivalent",
]

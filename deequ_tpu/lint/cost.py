"""Static scan-cost analyzer: predict the execution shape of an analysis
plan — passes, fused family groups, batches, wire bytes, transfers —
WITHOUT touching a row of data.

The predictions are not estimates of a separate model: placement
partitioning, input-spec dedup, and family grouping come from the SAME
pure planner the runtime consumes (`ops/fused.plan_scan_members` /
`plan_family_jobs` / `group_family_jobs`), and the batching/wire math
replays `FusedScanPass._run_pass` / `pack_batch_inputs` arithmetic. The
trace-differential suite (tests/test_trace_differential.py) pins the
predicted dispatch signature against the observed `RunTrace` span tree,
so the model cannot silently drift from execution.

Stated model assumptions (where runtime behavior is data-dependent):

  * bool where/predicate masks are transferred (the runtime elides a
    mask that happens to be all-true on a given batch);
  * the counts-family shortcut is off (DEEQU_TPU_NO_COUNTS_FASTPATH=1
    in the differential suite);
  * the shared freq aggregation stays on host (group count below the
    device threshold) unless a cardinality hint says otherwise.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deequ_tpu.lint.effects import (
    AnalyzerEffect,
    _MASK_PREFIXES,
    analyzer_read_columns,
    pass_read_bytes_per_row,
    pass_wire_bytes_per_row,
    prednn_elided,
    scan_effects,
)
from deequ_tpu.lint.schema import SchemaInfo

#: every span name the execution layer can emit for one analysis run;
#: `span_counts` carries an entry for each (0 = predicted absent) so the
#: differential suite compares complete vocabularies, not subsets.
EXECUTION_SPANS = (
    "plan_fuse",
    "fused_scan",
    "dist_scan",
    "dispatch",
    "host_fold",
    "transfer",
    "merge",
    "family_kernel",
    "grouping",
    "group_pass",
    "freq_agg",
    "state_allgather",
)

#: counter names `runtime` records that the model predicts
COUNTERS = ("device_passes", "device_launches", "group_passes")


@dataclass(frozen=True)
class FamilyGroupCost:
    """One predicted family-kernel dispatch group: the (where, cap)
    batch of quantile-family columns a single native traversal serves
    per scan batch. Mirrors the `family_kernel` span attrs."""

    where: str  # where_key of the family ("where:<all>" for no filter)
    cap: int
    dtype: str  # compute dtype of the value arrays
    columns: Tuple[str, ...]
    batched: bool
    want_regs: bool = False


@dataclass
class PassCost:
    """Predicted cost of ONE pass over the data (a fused scan, one
    grouping-column-set frequency pass, or a solo analyzer's own scan)."""

    kind: str  # 'scan' | 'grouping' | 'aux'
    label: str
    analyzers: Tuple[str, ...] = ()
    columns: Tuple[str, ...] = ()
    device_members: int = 0
    host_members: int = 0
    input_keys: Tuple[str, ...] = ()
    read_bytes_per_row: float = 0.0
    wire_bytes_per_row: float = 0.0
    n_batches: int = 1
    #: exact packed wire bytes of the FIRST batch (replays the
    #: `pack_batch_inputs` layout math); None when the key set contains
    #: a data-dependent format (e.g. range-narrowed int codes)
    wire_bytes_per_batch: Optional[int] = None
    #: row-group pushdown prediction (scan passes over parquet sources
    #: with statistics only): groups in the file / groups the runtime
    #: will skip / decode bytes those skipped groups would have cost.
    #: None = no statistics were available to the planner.
    rg_total: Optional[int] = None
    rg_skipped: Optional[int] = None
    saved_read_bytes: Optional[float] = None
    #: decode fast-path prediction (scan passes over parquet sources
    #: whose decode vocabulary was provided): columns the native
    #: buffer-level decode will take / columns scanned / per-column
    #: fallback reasons / bytes of intermediate host materialization the
    #: fast columns avoid over the decoded rows. None = no decode
    #: vocabulary (in-memory table) or the fast path is unavailable.
    decode_cols_total: Optional[int] = None
    decode_cols_fast: Optional[int] = None
    decode_fallbacks: Tuple[Tuple[str, str], ...] = ()
    saved_decode_bytes: Optional[float] = None
    decode_workers: Optional[int] = None
    #: decode-to-wire prediction (layered on the fast-path verdict,
    #: single-engine scans only): columns decoding straight to packed
    #: wire slices / per-column fall-off reasons with the offending
    #: consumer key / bytes of host pack re-reads the fused columns skip
    #: over the decoded rows. None = wire planning will not run (knob
    #: off, distributed pass, no member plan).
    wire_fused_cols: Optional[int] = None
    wire_falloffs: Tuple[Tuple[str, str, str], ...] = ()
    saved_pack_bytes: Optional[float] = None
    #: native-parquet-reader prediction (layered on the fast-path
    #: verdict, needs footer chunk metadata in `row_groups`): column
    #: chunks the page-level native reader will decode / chunks the scan
    #: touches (scanned columns × non-pruned groups) / per-column
    #: fall-off reasons naming the disqualifying encoding or codec /
    #: bytes of arrow materialization the native chunks avoid over the
    #: decoded rows. None = reader planning will not run (knob off, no
    #: chunk metadata, no loadable codec).
    reader_chunks_total: Optional[int] = None
    reader_chunks_native: Optional[int] = None
    reader_fallbacks: Tuple[Tuple[str, str], ...] = ()
    saved_alloc_bytes: Optional[float] = None
    #: encoded-fold prediction (layered on the native-reader verdict,
    #: single-engine scans only — the consumer proofs need the live
    #: analyzer set): columns whose chunks will fold over (run, code)
    #: streams without row-width materialization / columns scanned /
    #: per-column fall-off reasons naming the disqualifying codec,
    #: analyzer family, dtype, or dict-size condition. None =
    #: encoded-fold planning will not run (knob off, distributed pass,
    #: no reader verdict).
    encfold_cols: Optional[int] = None
    encfold_cols_total: Optional[int] = None
    #: of encfold_cols: columns whose moments fold as Σ(run_len × value)
    #: directly over RLE runs (the rest roll dictionary codes up into
    #: their sketch families)
    encfold_moment_cols: Optional[int] = None
    encfold_falloffs: Tuple[Tuple[str, str], ...] = ()
    #: partition-state-cache prediction (partitioned parquet sources
    #: only): partitions in the dataset / partitions whose states will
    #: load from the attached StateRepository instead of scanning / file
    #: bytes those cached partitions would have read+decoded. None = the
    #: source is not partitioned.
    partitions_total: Optional[int] = None
    partitions_cached: Optional[int] = None
    saved_partition_bytes: Optional[float] = None
    family_groups: Tuple[FamilyGroupCost, ...] = ()
    #: grouping passes: estimated distinct-group count (product of
    #: `approx_distinct` hints); None when any hint is missing
    estimated_groups: Optional[int] = None
    spill_risk: bool = False
    notes: Tuple[str, ...] = ()


#: stated host-side throughput for the decode+prep stages of the stream
#: pipeline (Arrow decode + wire pack are memcpy-shaped): used to turn
#: read bytes/batch into a host seconds/batch for the overlap model.
PIPELINE_HOST_BYTES_PER_S = 2e9


@dataclass
class PipelineCost:
    """Predicted shape of the backpressured stream pipeline
    (ops/pipeline.py) for the scan pass: per-batch stage costs under the
    stated overlap model, and whether the configured queue depth can
    hide the measured H2D transfer latency.

    Model: decode+prep host work per batch is `read_bytes / batch` at
    `PIPELINE_HOST_BYTES_PER_S` (stated constant); the H2D wire time is
    the exact packed first-batch bytes over the measured link bandwidth
    (the same disk-cached probe the placement policy uses, or an
    injected `link_bandwidth`). Serially those costs add; pipelined, the
    critical path is the slowest stage — the overlap-adjusted cost. With
    queue depth d the prep stage can run at most d batches ahead, so a
    single transfer outlasting d batches of host work starves the fold
    stage no matter how the stages interleave (the DQ305 condition)."""

    enabled: bool
    queue_depth: int
    stages: Tuple[str, ...] = ("decode", "prep", "fold")
    n_batches: int = 1
    wire_bytes_per_batch: Optional[int] = None
    link_bandwidth: Optional[float] = None  # bytes/s; None = unmeasured
    host_s_per_batch: Optional[float] = None
    wire_s_per_batch: Optional[float] = None
    serial_s_per_batch: Optional[float] = None
    overlapped_s_per_batch: Optional[float] = None
    bottleneck: Optional[str] = None  # 'host' | 'transfer'

    @property
    def depth_hides_transfer(self) -> Optional[bool]:
        """False when one batch's H2D transfer outlasts `queue_depth`
        batches of host work — the queue drains and the fold stage
        starves. None when either side is unmeasured."""
        if self.wire_s_per_batch is None or self.host_s_per_batch is None:
            return None
        return self.wire_s_per_batch <= self.queue_depth * self.host_s_per_batch


@dataclass
class PlanCost:
    """Machine-readable prediction of a plan's execution shape."""

    placement: str
    compute_dtype: str
    engine: str
    num_rows: Optional[int]
    batch_size: Optional[int]
    analyzers: Tuple[str, ...] = ()  # post-dedupe, pre-precondition
    precondition_failures: Tuple[Tuple[str, str], ...] = ()
    effects: Tuple[AnalyzerEffect, ...] = ()
    passes: List[PassCost] = field(default_factory=list)
    counters: Dict[str, int] = field(default_factory=dict)
    span_counts: Dict[str, int] = field(default_factory=dict)
    num_hosts: int = 1
    allgather_rounds: int = 0
    #: sharded streaming scan (parallel/multihost.run_sharded_analysis):
    #: processes in the mesh and each one's partition-slice size in
    #: shard order (from parallel/shard.plan_shards) — rendered in
    #: EXPLAIN's `shards:` line and pinned against the observed
    #: `shard.count` / `shard.partitions_max` trace counters
    num_shards: int = 1
    shard_partitions: Tuple[int, ...] = ()
    #: stream-pipeline prediction for the scan pass; None for
    #: non-streaming plans (in-memory tables never engage the pipeline)
    pipeline: Optional[PipelineCost] = None
    #: the full lint/pushdown.PrunePlan behind the scan pass's rg_*
    #: fields (per-predicate verdicts + eligibility for DQ310/DQ311);
    #: None when no row-group statistics reached the planner
    prune: Optional[Any] = None
    #: resilience knobs the run will execute under: the transient-IO
    #: retry budget (DEEQU_TPU_RETRIES) and the caller's deadline in
    #: seconds (None = unbounded) — rendered in EXPLAIN's resilience
    #: line and checked by DQ318 (a deadline over an unpartitioned
    #: source leaves nothing committed for a resume)
    retry_budget: Optional[int] = None
    deadline_s: Optional[float] = None
    #: admission classification (DQService admission control): the cost
    #: tier this plan lands in — 'interactive' | 'batch' | 'heavy' —
    #: from the predicted post-prune, post-cache scan bytes against the
    #: ADMISSION_*_BYTES thresholds. Unknown row counts classify as
    #: 'batch' (admit, but never preempt others). Set by analyze_plan.
    admission_tier: Optional[str] = None
    #: scan-bytes headroom left in the tenant's quota window after this
    #: plan runs once — set by explain_plan when the caller supplies
    #: `quota_scan_bytes`; negative means the plan overdraws the window
    #: and DQ319 fires when it can NEVER fit
    quota_headroom_bytes: Optional[float] = None
    #: windowed query (windows/query.py): the window spec text, how many
    #: segment envelopes the merge tree touches, how many member
    #: partitions must rescan (no usable cached state), and the member
    #: bytes the segment algebra avoids reading — rendered in EXPLAIN's
    #: `windows:` line and pinned against the observed `window.*` trace
    #: counters. window_spec None = not a window query.
    window_spec: Optional[str] = None
    window_segments_merged: int = 0
    window_partitions_rescanned: int = 0
    saved_window_bytes: float = 0.0

    @property
    def shard_partitions_max(self) -> int:
        """The largest shard's partition count (the straggler bound)."""
        return max(self.shard_partitions) if self.shard_partitions else 0

    @property
    def shard_skew(self) -> float:
        """Largest shard over the even split; 1.0 = perfectly balanced."""
        total = sum(self.shard_partitions)
        if not total or self.num_shards < 1:
            return 1.0
        return self.shard_partitions_max / (total / self.num_shards)

    @property
    def total_read_bytes_per_row(self) -> float:
        return sum(p.read_bytes_per_row for p in self.passes)

    @property
    def total_wire_bytes_per_row(self) -> float:
        return sum(p.wire_bytes_per_row for p in self.passes)

    @property
    def scan_pass(self) -> Optional[PassCost]:
        for p in self.passes:
            if p.kind == "scan":
                return p
        return None

    @property
    def predicted_scan_bytes(self) -> Optional[float]:
        """Predicted bytes this plan reads end to end: per-pass read
        bytes/row × rows, minus what pushdown skips and what cached
        partition states avoid. None when the row count is unknown —
        admission then classifies conservatively ('batch')."""
        if self.num_rows is None:
            return None
        total = 0.0
        for p in self.passes:
            total += p.read_bytes_per_row * float(self.num_rows)
        scan = self.scan_pass
        if scan is not None:
            total -= float(scan.saved_read_bytes or 0.0)
            total -= float(scan.saved_partition_bytes or 0.0)
        return max(0.0, total)

    def dispatch_signature(self) -> Dict[str, Any]:
        """The comparable execution shape: counters, span histogram, and
        the deduplicated family-group set — exactly what
        `observe.compare.dispatch_signature(trace)` extracts from a real
        run's trace."""
        families = sorted(
            (g.where, g.cap, g.dtype, g.columns, g.batched)
            for p in self.passes
            for g in p.family_groups
        )
        return {
            "counters": dict(self.counters),
            "spans": {k: v for k, v in self.span_counts.items() if v},
            "family_groups": families,
        }


# -- admission tiers (DQService admission control) ---------------------------

#: plans predicted to read fewer bytes than this are 'interactive':
#: they may preempt a running heavy profile (~64 MiB ≈ well under a
#: second of scan on any placement)
ADMISSION_INTERACTIVE_BYTES = 64 << 20
#: plans predicted to read at least this many bytes are 'heavy': they
#: are preemptible at partition boundaries and never preempt others
ADMISSION_HEAVY_BYTES = 1 << 30

ADMISSION_TIERS = ("interactive", "batch", "heavy")


def _tier_threshold(env: str, default: float) -> float:
    """Operator override for a tier boundary (fleet tuning: a deploy
    whose 'interactive' latency budget maps to a different scan size
    than the defaults)."""
    import os

    raw = os.environ.get(env, "")
    if not raw:
        return float(default)
    try:
        return float(raw)
    except ValueError:
        return float(default)


def cost_tier(cost: "PlanCost") -> str:
    """Classify a PlanCost into an admission tier from its predicted
    scan bytes. Unknown row counts land in 'batch': admitted, queued
    behind interactive work, but never trusted to preempt. Boundaries
    are overridable via DEEQU_TPU_TIER_INTERACTIVE_BYTES and
    DEEQU_TPU_TIER_HEAVY_BYTES."""
    scan_bytes = cost.predicted_scan_bytes
    if scan_bytes is None:
        return "batch"
    if scan_bytes < _tier_threshold(
        "DEEQU_TPU_TIER_INTERACTIVE_BYTES", ADMISSION_INTERACTIVE_BYTES
    ):
        return "interactive"
    if scan_bytes >= _tier_threshold(
        "DEEQU_TPU_TIER_HEAVY_BYTES", ADMISSION_HEAVY_BYTES
    ):
        return "heavy"
    return "batch"


def cost_drift(cost: "PlanCost", trace: Any) -> Dict[str, float]:
    """Predicted-vs-observed drift per PlanCost field, from a RunTrace.

    Positive values mean the run did *more* than the planner predicted
    (extra passes/launches, more batches, wider wire rows). Keys:
    `drift.counter.<name>`, `drift.span.<name>`, `drift.family_groups`,
    and — when both sides are known — `drift.batches` and
    `drift.wire_bytes_first_batch`. Feeds `engine.drift.*` in the
    telemetry record so the sentinel can watch prediction quality as a
    time series alongside throughput.
    """
    from deequ_tpu.observe import compare  # lazy: keep lint importable without observe

    predicted = cost.dispatch_signature()
    observed = compare.dispatch_signature(trace)
    out: Dict[str, float] = {}
    for key in set(predicted["counters"]) | set(observed["counters"]):
        out[f"drift.counter.{key}"] = float(
            observed["counters"].get(key, 0) - predicted["counters"].get(key, 0)
        )
    for key in set(predicted["spans"]) | set(observed["spans"]):
        out[f"drift.span.{key}"] = float(
            observed["spans"].get(key, 0) - predicted["spans"].get(key, 0)
        )
    out["drift.family_groups"] = float(
        len(observed["family_groups"]) - len(predicted["family_groups"])
    )

    scan = cost.scan_pass
    if scan is not None:
        observed_batches = 0
        saw_batches = False
        first_wire: Optional[int] = None
        for sp in trace.spans():
            if sp.name in ("fused_scan", "dist_scan") and "batches" in sp.attrs:
                observed_batches += int(sp.attrs["batches"])
                saw_batches = True
            elif (
                first_wire is None
                and sp.name == "dispatch"
                and "wire_bytes" in sp.attrs
            ):
                first_wire = int(sp.attrs["wire_bytes"])
        if saw_batches:
            out["drift.batches"] = float(observed_batches - scan.n_batches)
        if first_wire is not None and scan.wire_bytes_per_batch is not None:
            out["drift.wire_bytes_first_batch"] = float(
                first_wire - scan.wire_bytes_per_batch
            )
        if scan.rg_skipped is not None and "rg_total" in trace.counters:
            out["drift.rg_skipped"] = float(
                int(trace.counters.get("rg_skipped", 0)) - scan.rg_skipped
            )
        if (
            scan.decode_cols_fast is not None
            and "decode_cols_total" in trace.counters
        ):
            out["drift.decode_cols_fast"] = float(
                int(trace.counters.get("decode_cols_fast", 0))
                - scan.decode_cols_fast
            )
        if (
            scan.wire_fused_cols is not None
            and "wire_cols_total" in trace.counters
        ):
            out["drift.wire_fused_cols"] = float(
                int(trace.counters.get("wire_fused_cols", 0))
                - scan.wire_fused_cols
            )
        if (
            scan.reader_chunks_native is not None
            and "reader_chunks_total" in trace.counters
        ):
            out["drift.reader_chunks_native"] = float(
                int(trace.counters.get("reader_chunks_native", 0))
                - scan.reader_chunks_native
            )
        if (
            scan.encfold_cols is not None
            and "encfold_cols" in trace.counters
        ):
            out["drift.encfold_columns"] = float(
                int(trace.counters.get("encfold_cols", 0))
                - scan.encfold_cols
            )
        if (
            scan.partitions_cached is not None
            and scan.partitions_total is not None
            and "partitions_total" in trace.counters
        ):
            out["drift.partitions_cached"] = float(
                int(trace.counters.get("partitions_cached", 0))
                - scan.partitions_cached
            )
            out["drift.partitions_scanned"] = float(
                int(trace.counters.get("partitions_scanned", 0))
                - (scan.partitions_total - scan.partitions_cached)
            )

    # sharded-scan pins: the shard planner is deterministic, so the
    # observed shard split must equal the predicted one exactly
    if cost.num_shards > 1 and "shard.count" in trace.counters:
        out["drift.shard_count"] = float(
            int(trace.counters.get("shard.count", 0)) - cost.num_shards
        )
        if cost.shard_partitions:
            out["drift.shard_partitions_max"] = float(
                int(trace.counters.get("shard.partitions_max", 0))
                - cost.shard_partitions_max
            )

    # window pins: the cover decomposition is deterministic, so a warm
    # window query must merge exactly the predicted number of segment
    # envelopes and rescan exactly the predicted partitions
    if cost.window_spec is not None and "window.segments_merged" in trace.counters:
        out["drift.window_segments_merged"] = float(
            int(trace.counters.get("window.segments_merged", 0))
            - cost.window_segments_merged
        )
        out["drift.window_partitions_rescanned"] = float(
            int(trace.counters.get("window.partitions_rescanned", 0))
            - cost.window_partitions_rescanned
        )
    return out


# -- wire-format replay -------------------------------------------------------


def _predict_packed_bytes(
    device_keys: Sequence[str],
    schema: SchemaInfo,
    rows: int,
    batch_size: int,
    compute_itemsize: int,
    elided: frozenset = frozenset(),
) -> Optional[int]:
    """Replay `pack_batch_inputs` byte accounting for one batch of
    `rows` rows. Returns None when a key's wire format is data-dependent
    (runtime range-narrowing) and therefore not statically exact.
    `elided` holds where-keys the pushdown analyzer proved all-true on
    every decoded group: the runtime swaps them for constant masks, so
    they cost scalar bookkeeping, not mask bytes."""
    from deequ_tpu.ops.fused import _pad_size

    padded = _pad_size(rows, batch_size)
    total = 0
    any_const = False
    for key in device_keys:
        if key == "where:<all>" or key in elided:
            any_const = True
        elif key.startswith("valid:"):
            fld = schema.field(key[len("valid:") :])
            if fld is not None and not fld.nullable:
                any_const = True  # all-true mask: synthesized on device
            else:
                total += padded // 8
        elif key.startswith("prednn:") and prednn_elided(
            key[len("prednn:") :], schema
        ):
            any_const = True
        elif key.startswith(_MASK_PREFIXES):
            total += padded // 8
        elif key.startswith("num:"):
            total += padded * compute_itemsize
        elif key.startswith("dtclass:"):
            total += padded  # int8 codes; narrow_int_wire keeps int8
        else:
            return None  # e.g. hll: hash codes — narrowing is data-dependent
    if any_const:
        total += 4  # the int32[1] `__nrows` scalar
    return total


def _n_batches(num_rows: Optional[int], batch_size: int) -> int:
    if num_rows is None:
        return 1
    return max(1, math.ceil(num_rows / batch_size))


def _quantile_cap(analyzer: Any) -> Optional[int]:
    sample_size = getattr(analyzer, "_sample_size", None)
    if callable(sample_size):
        try:
            return int(sample_size())
        except Exception:  # noqa: BLE001
            return None
    return None


# -- the analyzer -------------------------------------------------------------


def analyze_plan(
    analyzers: Sequence[Any],
    schema: SchemaInfo,
    *,
    num_rows: Optional[int] = None,
    batch_size: Optional[int] = None,
    placement: Optional[str] = None,
    engine: str = "single",
    num_hosts: int = 1,
    num_shards: int = 1,
    shard_partitions: Optional[Sequence[int]] = None,
    num_devices: int = 1,
    streaming: bool = False,
    stream_batch_rows: Optional[int] = None,
    link_bandwidth: Optional[float] = None,
    pipeline_depth: Optional[int] = None,
    row_groups: Optional[Sequence[Any]] = None,
    decode_types: Optional[Dict[str, str]] = None,
    partitions: Optional[Sequence[Any]] = None,
    deadline_s: Optional[float] = None,
) -> PlanCost:
    """Abstract interpretation of `AnalysisRunner._do_analysis_run`:
    dedupe -> static precondition filtering (zero-row table) ->
    grouping/scanning split -> the pure scan planner -> batching and
    wire math. Pure: no kernel is compiled, no row is read.

    `streaming=True` additionally predicts the stream pipeline's shape
    (`PlanCost.pipeline`): per-batch host vs wire seconds under the
    stated overlap model, with the link bandwidth taken from
    `link_bandwidth` or the disk-cached placement probe.
    `stream_batch_rows` is the source's own per-batch row cap
    (`ParquetSource.batch_rows`): a streamed source yields batches of
    `min(batch_size, batch_rows)` rows, so the batch count and per-batch
    wire bytes must honor it to stay trace-exact.

    `row_groups` (a `lint/pushdown.RowGroupStats` sequence, from
    `ParquetSource.row_group_stats()`) switches the scan pass onto the
    pushdown model: batch count and first-batch rows come from an exact
    replay of the source's row-group iteration over the groups the
    runtime will actually decode, and the pass reports predicted
    skipped/decoded groups + saved read bytes.

    `decode_types` (`ParquetSource.decode_column_types()`) switches on
    the decode fast-path prediction: the scan pass reports which columns
    the buffer-level native decode will take, the per-column fallback
    reasons, and the intermediate materialization bytes avoided — via
    the SAME classifier the runtime planner runs, so
    `drift.decode_cols_fast` pins to zero.

    `num_shards` / `shard_partitions` (per-shard partition counts in
    shard order, from `parallel/shard.plan_shards`) describe a sharded
    streaming scan: rendered in EXPLAIN's `shards:` line and pinned
    against the observed `shard.*` trace counters.

    `partitions` (per-partition `{"cached": bool, "bytes": int}` records
    from the runner's state-repository probe, partition order) switches
    on the partition-state-cache prediction: the scan pass reports how
    many partitions will load as cached states vs scan, and the file
    bytes the cached ones avoid reading — pinned against the observed
    `partitions_cached` / `partitions_scanned` trace counters."""
    from deequ_tpu.analyzers.base import Preconditions, ScanShareableAnalyzer
    from deequ_tpu.analyzers.frequency import (
        FrequencyBasedAnalyzer,
        ScanShareableFrequencyBasedAnalyzer,
    )
    from deequ_tpu.analyzers.freq_spill import default_max_groups_in_memory
    from deequ_tpu.analyzers.grouping import GroupingAnalyzer
    from deequ_tpu.ops import runtime
    from deequ_tpu.ops.fused import (
        DEFAULT_BATCH_SIZE,
        group_family_jobs,
        plan_family_jobs,
    )
    from deequ_tpu.ops.freq_agg import _DEVICE_THRESHOLD

    compute_dtype = np.dtype(runtime.compute_dtype())
    itemsize = int(compute_dtype.itemsize)

    # dedupe preserving order — same identity the runner uses
    seen: set = set()
    unique: List[Any] = []
    for a in analyzers:
        if a not in seen:
            seen.add(a)
            unique.append(a)

    # static precondition replay on the zero-row schema table
    empty = schema.empty_table()
    passed: List[Any] = []
    failures: List[Tuple[str, str]] = []
    for a in unique:
        try:
            err = Preconditions.find_first_failing(empty, a.preconditions())
        except Exception as e:  # noqa: BLE001
            err = e
        if err is None:
            passed.append(a)
        else:
            failures.append((repr(a), f"{type(err).__name__}: {err}"))

    grouping = [a for a in passed if isinstance(a, GroupingAnalyzer)]
    scanning = [a for a in passed if not isinstance(a, GroupingAnalyzer)]
    shareable = [a for a in scanning if isinstance(a, ScanShareableAnalyzer)]
    solo = [a for a in scanning if not isinstance(a, ScanShareableAnalyzer)]

    cost = PlanCost(
        placement=placement or runtime.placement_mode(),
        compute_dtype=compute_dtype.name,
        engine=engine,
        num_rows=num_rows,
        batch_size=batch_size,
        analyzers=tuple(repr(a) for a in unique),
        precondition_failures=tuple(failures),
        num_hosts=max(1, int(num_hosts)),
        num_shards=max(1, int(num_shards)),
        shard_partitions=tuple(int(c) for c in (shard_partitions or ())),
        counters={k: 0 for k in COUNTERS},
        span_counts={k: 0 for k in EXECUTION_SPANS},
        retry_budget=runtime.retry_budget(),
        deadline_s=float(deadline_s) if deadline_s is not None else None,
    )
    spans = cost.span_counts
    counters = cost.counters
    distributed = engine == "distributed"

    # ---- the fused scan pass ------------------------------------------------
    if shareable:
        plan, effects = scan_effects(shareable, mode=cost.placement)
        cost.effects = tuple(effects)
        use_device = bool(plan.merge_idx or plan.assisted_idx)

        if distributed:
            eff_batch = (batch_size or (1 << 21)) * max(1, int(num_devices))
        else:
            eff_batch = batch_size or DEFAULT_BATCH_SIZE
            if (
                not use_device
                and not streaming
                and batch_size is None
                and num_rows is not None
            ):
                # pure host fold over an in-memory table widens to one
                # batch (FusedScanPass._run_pass host-widening rule;
                # streamed sources never widen)
                eff_batch = max(eff_batch, min(num_rows, 1 << 24))
        # a streaming source caps each batch at its own batch_rows
        # (data/source.py: min(batch_size, batch_rows)); padding still
        # follows the engine batch size, so `eff_batch` keeps feeding
        # the _pad_size replay while `per_batch` drives the batch count
        per_batch = eff_batch
        if streaming and stream_batch_rows:
            per_batch = min(per_batch, int(stream_batch_rows))
        batches = _n_batches(num_rows, per_batch)

        # ---- row-group pushdown (parquet statistics available) ----------
        # Mirrors the runtime decision point exactly: FusedScanPass.run
        # prunes with the wheres of the LIVE members (spec errors are
        # already out), gated on the same knob this prediction reads.
        prune_plan = None
        pushdown_on = runtime.pushdown_enabled()
        batch_rows_list: Optional[Tuple[int, ...]] = None
        if row_groups and streaming and plan.any_members:
            from deequ_tpu.lint.pushdown import build_prune_plan, types_from_schema

            live_idx = (
                plan.merge_idx + plan.assisted_idx
                + plan.host_idx + plan.host_assisted_idx
            )
            try:
                prune_plan = build_prune_plan(
                    [getattr(shareable[i], "where", None) for i in live_idx],
                    row_groups,
                    types_from_schema(schema),
                )
            except Exception:  # noqa: BLE001 — prediction only, never fatal
                prune_plan = None
        if prune_plan is not None:
            cost.prune = prune_plan
            batch_rows_list = prune_plan.predicted_batch_rows(
                per_batch, pruned=pushdown_on
            )
            # the decode replay is exact even without any skip: it
            # models the source's tiny-group coalescing, which plain
            # ceil(num_rows / per_batch) cannot
            batches = max(1, len(batch_rows_list))

        device_keys = sorted(plan.device_keys)
        scan_columns: List[str] = []
        for eff in effects:
            for c in eff.columns:
                if c not in scan_columns:
                    scan_columns.append(c)

        host_assisted_members = [shareable[i] for i in plan.host_assisted_idx]
        host_only_members = [shareable[i] for i in plan.host_idx]
        jobs = plan_family_jobs(host_assisted_members, host_only_members)
        groups = group_family_jobs(jobs)
        family_groups = tuple(
            FamilyGroupCost(
                where=key[0],
                cap=key[1],
                # family kernels consume `numeric_values()` host arrays,
                # which are float64 regardless of the device dtype
                dtype="float64",
                columns=tuple(j.column for j in grp),
                batched=len(grp) > 1,
                want_regs=any(j.want_regs for j in grp),
            )
            for key, grp in groups
        )

        first_rows = (
            min(num_rows, per_batch) if num_rows is not None else per_batch
        )
        elided_keys: frozenset = frozenset()
        if batch_rows_list is not None:
            first_rows = batch_rows_list[0] if batch_rows_list else 0
        if prune_plan is not None and pushdown_on:
            elided_keys = frozenset(
                f"where:{text}" for text in prune_plan.elided_wheres()
            )
        wire_exact = (
            _predict_packed_bytes(
                device_keys, schema, first_rows, eff_batch, itemsize,
                elided=elided_keys,
            )
            if use_device
            else 0
        )

        notes: List[str] = []
        if plan.spec_errors:
            notes.append(f"{len(plan.spec_errors)} member(s) fail at spec build")
        scan_pass = PassCost(
            kind="scan",
            label="fused scan",
            analyzers=tuple(repr(a) for a in shareable),
            columns=tuple(scan_columns),
            device_members=plan.device_member_count,
            host_members=plan.host_member_count,
            input_keys=tuple(device_keys),
            read_bytes_per_row=pass_read_bytes_per_row(scan_columns, schema),
            wire_bytes_per_row=(
                pass_wire_bytes_per_row(device_keys, schema, itemsize)
                if use_device
                else 0.0
            ),
            n_batches=batches,
            wire_bytes_per_batch=wire_exact,
            family_groups=family_groups,
            notes=tuple(notes),
        )
        if prune_plan is not None:
            scan_pass.rg_total = prune_plan.total_groups
            scan_pass.rg_skipped = (
                prune_plan.skipped_groups if pushdown_on else 0
            )
            scan_pass.saved_read_bytes = (
                scan_pass.read_bytes_per_row * prune_plan.skipped_rows
                if pushdown_on
                else 0.0
            )

        # ---- decode fast-path (parquet decode vocabulary available) -----
        # Mirrors FusedScanPass.run's plan_decode_fastpath exactly: same
        # knob, same native-library gate, same classifier over the same
        # post-pruning, post-elision column set — so the prediction pins
        # to the observed decode_cols_fast counter with zero drift.
        if decode_types and plan.any_members:
            from deequ_tpu.ops import native
            from deequ_tpu.ops.fused import (
                DecodePlan,
                classify_decode_columns,
                classify_wire_columns,
                decode_saved_bytes_per_row,
                wire_int_bounds_from_groups,
                wire_saved_pack_bytes_per_row,
            )

            if runtime.decode_fastpath_enabled() and native.available():
                specs_eff = {
                    k: s for k, s in plan.specs.items() if k not in elided_keys
                }
                needed: set = set()
                prunable = True
                for spec in specs_eff.values():
                    if spec.columns is None:
                        prunable = False
                        break
                    needed.update(spec.columns)
                if not prunable:
                    kept = list(decode_types)
                elif needed:
                    kept = [n for n in decode_types if n in needed]
                else:
                    # Size()-only pass: the source keeps its first column
                    kept = list(decode_types)[:1]
                col_types = {n: decode_types[n] for n in kept}
                if col_types:
                    fast, fallbacks = classify_decode_columns(
                        col_types, specs_eff
                    )
                    dplan = DecodePlan(
                        fast=tuple(fast),
                        fallbacks=tuple(fallbacks),
                        workers=runtime.decode_workers(),
                    )
                    scan_pass.decode_cols_total = dplan.total
                    scan_pass.decode_cols_fast = len(dplan.fast)
                    scan_pass.decode_fallbacks = dplan.fallbacks
                    scan_pass.decode_workers = dplan.workers
                    decoded_rows = num_rows
                    if (
                        decoded_rows is not None
                        and prune_plan is not None
                        and pushdown_on
                    ):
                        decoded_rows = max(
                            0, decoded_rows - prune_plan.skipped_rows
                        )
                    scan_pass.saved_decode_bytes = (
                        float(
                            decode_saved_bytes_per_row(dplan, col_types)
                            * decoded_rows
                        )
                        if decoded_rows is not None
                        else None
                    )
                    # ---- decode-to-wire verdict (layered on the fast
                    # set, single-engine scans only — the distributed
                    # pass plans without a member plan). Mirrors
                    # plan_decode_fastpath's wire branch: same knob,
                    # same classifier, same packed-only key set, same
                    # statically pinned int bounds — so the prediction
                    # pins to the observed wire_fused_cols counter with
                    # zero drift.
                    if not distributed and runtime.wire_fused_enabled():
                        fast_types = {c: col_types[c] for c in fast}
                        wire_specs, wire_falloffs = classify_wire_columns(
                            fast_types,
                            specs_eff,
                            plan.packed_only_keys,
                            compute_dtype.name,
                            int_bounds=wire_int_bounds_from_groups(
                                row_groups or (), sorted(fast_types)
                            ),
                        )
                        scan_pass.wire_fused_cols = len(wire_specs)
                        scan_pass.wire_falloffs = tuple(wire_falloffs)
                        scan_pass.saved_pack_bytes = (
                            float(
                                wire_saved_pack_bytes_per_row(wire_specs)
                                * decoded_rows
                            )
                            if decoded_rows is not None
                            else None
                        )
                    # ---- native-reader verdict (layered on the fast
                    # set; needs the footer chunk metadata carried by
                    # row_groups). Mirrors plan_decode_fastpath's
                    # reader branch: same knob, same classifier, same
                    # codec mask, same prune replay — so the prediction
                    # pins to the observed reader_chunks_native counter
                    # with zero drift.
                    if runtime.native_reader_enabled() and row_groups:
                        from deequ_tpu.ops.fused import (
                            classify_reader_columns,
                            reader_saved_alloc_bytes_per_row,
                        )

                        codec_mask = native.reader_codecs()
                        if codec_mask:
                            skip = (
                                prune_plan.skip
                                if prune_plan is not None and pushdown_on
                                else frozenset()
                            )
                            r_cols, r_falloffs, r_groups = (
                                classify_reader_columns(
                                    {c: col_types[c] for c in fast},
                                    row_groups,
                                    codec_mask,
                                    skip,
                                )
                            )
                            scan_pass.reader_chunks_native = (
                                len(r_cols) * r_groups
                            )
                            scan_pass.reader_chunks_total = (
                                dplan.total * r_groups
                            )
                            scan_pass.reader_fallbacks = tuple(r_falloffs)
                            scan_pass.saved_alloc_bytes = (
                                float(
                                    reader_saved_alloc_bytes_per_row(
                                        r_cols, col_types
                                    )
                                    * decoded_rows
                                )
                                if decoded_rows is not None
                                else None
                            )
                            # ---- encoded-fold verdict (layered on the
                            # reader set, single-engine scans only — the
                            # consumer proofs need the live analyzers).
                            # Mirrors plan_decode_fastpath's
                            # encoded-fold branch: same knob, same
                            # classifier over the same reader columns,
                            # same footer replay — so the prediction
                            # pins to the observed encfold_cols counter
                            # with zero drift.
                            if (
                                not distributed
                                and r_cols
                                and runtime.encoded_fold_enabled()
                            ):
                                from deequ_tpu.ops.fused import (
                                    classify_encfold_columns,
                                )

                                e_specs, e_falloffs = (
                                    classify_encfold_columns(
                                        {c: col_types[c] for c in r_cols},
                                        shareable,
                                        specs_eff,
                                        device_keys,
                                        row_groups,
                                        skip,
                                        int_bounds=(
                                            wire_int_bounds_from_groups(
                                                row_groups, sorted(r_cols)
                                            )
                                        ),
                                    )
                                )
                                scan_pass.encfold_cols = len(e_specs)
                                scan_pass.encfold_cols_total = dplan.total
                                scan_pass.encfold_moment_cols = sum(
                                    1
                                    for s in e_specs.values()
                                    if s.publish_moments
                                )
                                scan_pass.encfold_falloffs = tuple(
                                    e_falloffs
                                )
        cost.passes.append(scan_pass)

        if streaming:
            depth = (
                pipeline_depth
                if pipeline_depth is not None
                else runtime.pipeline_depth()
            )
            bw = link_bandwidth
            if bw is None and use_device:
                bw = runtime._load_bandwidth_from_disk()
            read_per_batch = scan_pass.read_bytes_per_row * first_rows
            host_s = (
                read_per_batch / PIPELINE_HOST_BYTES_PER_S
                if read_per_batch > 0
                else None
            )
            if not use_device:
                wire_s: Optional[float] = 0.0
            elif wire_exact is not None and bw:
                wire_s = wire_exact / float(bw)
            else:
                wire_s = None  # data-dependent wire or unmeasured link
            serial = overlapped = bottleneck = None
            if host_s is not None and wire_s is not None:
                serial = host_s + wire_s
                overlapped = max(host_s, wire_s)
                bottleneck = "transfer" if wire_s > host_s else "host"
            cost.pipeline = PipelineCost(
                enabled=runtime.pipeline_enabled(),
                queue_depth=depth,
                n_batches=batches,
                wire_bytes_per_batch=wire_exact if use_device else 0,
                link_bandwidth=bw,
                host_s_per_batch=host_s,
                wire_s_per_batch=wire_s,
                serial_s_per_batch=serial,
                overlapped_s_per_batch=overlapped,
                bottleneck=bottleneck,
            )

        if plan.any_members:
            counters["device_passes"] += 1
            spans["host_fold"] += batches
            if distributed:
                spans["dist_scan"] += 1
            else:
                spans["fused_scan"] += 1
            if use_device:
                counters["device_launches"] += batches
                spans["dispatch"] += batches
                spans["transfer"] += batches
                spans["merge"] += batches
            spans["family_kernel"] += len(groups) * batches
        if not distributed:
            spans["plan_fuse"] += 1
        if cost.num_hosts > 1 and plan.any_members:
            cost.allgather_rounds = 1
            spans["state_allgather"] += 1

    # ---- solo scanning analyzers (their own pass each) ----------------------
    for a in solo:
        cols = analyzer_read_columns(a)
        cost.passes.append(
            PassCost(
                kind="aux",
                label=f"solo scan: {getattr(a, 'name', type(a).__name__)}",
                analyzers=(repr(a),),
                columns=cols,
                read_bytes_per_row=pass_read_bytes_per_row(cols, schema),
                n_batches=1,
                notes=("runs outside the shared pass",),
            )
        )
        # Histogram's vectorized group pass records a group_pass counter
        if getattr(a, "name", "") == "Histogram":
            counters["group_passes"] += 1

    # ---- grouping passes (one frequency pass per column set) ----------------
    freq_based = [a for a in grouping if isinstance(a, FrequencyBasedAnalyzer)]
    other_grouping = [
        a for a in grouping if not isinstance(a, FrequencyBasedAnalyzer)
    ]
    sets: Dict[Tuple[str, ...], List[Any]] = {}
    for a in freq_based:
        sets.setdefault(tuple(sorted(a.grouping_columns())), []).append(a)

    max_groups = default_max_groups_in_memory()
    for cols, group in sets.items():
        est: Optional[int] = 1
        for c in cols:
            fld = schema.field(c)
            if fld is None or fld.approx_distinct is None:
                est = None
                break
            est *= max(1, int(fld.approx_distinct))
        freq_shareable = [
            a for a in group if isinstance(a, ScanShareableFrequencyBasedAnalyzer)
        ]
        freq_solo = [
            a
            for a in group
            if not isinstance(a, ScanShareableFrequencyBasedAnalyzer)
        ]
        notes = []
        spill = est is not None and est > max_groups
        if spill:
            notes.append(
                f"~{est} groups exceeds the in-memory budget ({max_groups}): "
                "the frequency state will spill to disk"
            )
        cost.passes.append(
            PassCost(
                kind="grouping",
                label=f"grouping pass over ({', '.join(cols)})",
                analyzers=tuple(repr(a) for a in group),
                columns=cols,
                read_bytes_per_row=pass_read_bytes_per_row(cols, schema),
                n_batches=1,
                estimated_groups=est,
                spill_risk=spill,
                notes=tuple(notes),
            )
        )
        spans["grouping"] += 1
        spans["group_pass"] += 1
        counters["group_passes"] += 1
        if freq_shareable:
            spans["freq_agg"] += 1
            counters["device_passes"] += 1
            # spilled states stream on host; only an in-memory counts
            # array at/above the device threshold launches a kernel
            if est is not None and est >= _DEVICE_THRESHOLD and not spill:
                counters["device_launches"] += 1
        # non-shareable frequency analyzers (e.g. MutualInformation)
        # each take an extra aggregation pass over the counts
        counters["device_passes"] += len(freq_solo)

    for a in other_grouping:
        cols = analyzer_read_columns(a)
        cost.passes.append(
            PassCost(
                kind="aux",
                label=f"grouping (own pass): {getattr(a, 'name', type(a).__name__)}",
                analyzers=(repr(a),),
                columns=cols,
                read_bytes_per_row=pass_read_bytes_per_row(cols, schema),
            )
        )

    # ---- partition-state cache (partitioned parquet sources) ---------------
    # `partitions` records ({"cached": bool, "bytes": int}, partition
    # order) come from the runner's pre-scan repository probe with the
    # exact fingerprint + plan signature the fused pass will use, so
    # `drift.partitions_cached` / `drift.partitions_scanned` pin to zero
    if partitions is not None:
        scan = cost.scan_pass
        if scan is not None:
            cached = [p for p in partitions if p.get("cached")]
            scan.partitions_total = len(partitions)
            scan.partitions_cached = len(cached)
            scan.saved_partition_bytes = float(
                sum(int(p.get("bytes", 0)) for p in cached)
            )

    cost.admission_tier = cost_tier(cost)
    return cost


__all__ = [
    "ADMISSION_HEAVY_BYTES",
    "ADMISSION_INTERACTIVE_BYTES",
    "ADMISSION_TIERS",
    "COUNTERS",
    "EXECUTION_SPANS",
    "PIPELINE_HOST_BYTES_PER_S",
    "FamilyGroupCost",
    "PassCost",
    "PipelineCost",
    "PlanCost",
    "analyze_plan",
    "cost_tier",
]

"""Structured diagnostics for the plan-time semantic analyzer.

The reference's Catalyst layer resolves columns and checks types in an
analysis phase before any execution; deequ_tpu's analogue is this lint
package, and every problem it finds is reported as a `Diagnostic` with a
stable `DQxxx` code, a severity, an optional source span, and an optional
did-you-mean suggestion. Strict-mode runs aggregate all error-severity
diagnostics into one `PlanValidationError` raised before any kernel
dispatch.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple


class Severity(enum.Enum):
    WARNING = "warning"
    ERROR = "error"


# Stable code registry. Codes are part of the public contract: tests and
# downstream tooling match on them, so never renumber — only append.
CODES = {
    # expression-level (typed expression analysis)
    "DQ100": "expression does not parse",
    "DQ101": "unresolved column",
    "DQ102": "type mismatch",
    "DQ103": "invalid literal",
    "DQ104": "unknown function",
    "DQ105": "wrong function arity",
    # analyzer / constraint spec level
    "DQ110": "invalid analyzer specification",
    # plan level
    "DQ202": "duplicate analyzer in plan",
    "DQ203": "contradictory constraints",
    "DQ204": "unsatisfiable predicate",
    "DQ205": "constant-foldable predicate",
    "DQ206": "fusion-breaking where-clause formatting",
    # performance diagnostics (static cost analyzer, lint/cost.py)
    "DQ300": "redundant analyzer scan covered by the shared pass",
    "DQ301": "fusion-splitting equivalent where-clauses",
    "DQ302": "cap/cardinality blowup",
    "DQ303": "per-pass working set exceeds the cache-tile budget",
    "DQ304": "transfer-per-row anti-pattern",
    "DQ305": "pipeline queue depth cannot hide the measured transfer latency",
    "DQ310": "where predicate not pushdown-eligible",
    "DQ311": "statistics prove every row group skippable",
    "DQ312": "column falls off the decode fast path",
    "DQ313": "column falls off decode-to-wire fusion",
    "DQ314": "state-cache entry unusable; partition falls back to rescan",
    "DQ315": "column-chunk falls off the native parquet reader",
    "DQ316": "constraint falls off row-level failure forensics",
    "DQ317": "forensics audit-trail entry unusable; forensics unavailable",
    "DQ318": "deadline set but the source has no partition boundaries",
    "DQ319": "plan can never be admitted under the tenant's quota window",
    # fleet-level scan sharing (plan-subsumption prover, lint/subsume.py)
    "DQ321": "suite provably contained in a shared scan",
    "DQ322": "scan sharing declined; obligation not provably contained",
    # windowed metrics / drift (windows/, checks/drift.py)
    "DQ323": "window not resolvable from precomputed segments",
    "DQ324": "drift baseline missing or plan-signature mismatched",
    "DQ325": "column falls off the encoded (run/dictionary) fold",
}


@dataclass
class Diagnostic:
    code: str
    severity: Severity
    message: str
    # the expression text the span indexes into, when the diagnostic is
    # anchored to an expression; None for plan-level diagnostics
    source: Optional[str] = None
    span: Optional[Tuple[int, int]] = None
    # what the diagnostic is about in plan terms (analyzer/constraint repr)
    subject: Optional[str] = None
    suggestion: Optional[str] = None

    def __post_init__(self):
        assert self.code in CODES, f"unregistered diagnostic code {self.code}"

    def render(self) -> str:
        head = f"{self.code} [{self.severity.value}] {self.message}"
        if self.suggestion:
            head += f" (did you mean {self.suggestion!r}?)"
        if self.subject:
            head += f" [in {self.subject}]"
        if self.source is not None and self.span is not None:
            a, b = self.span
            a = max(0, min(a, len(self.source)))
            b = max(a, min(b, len(self.source)))
            caret = " " * a + "^" * max(1, b - a)
            head += f"\n    {self.source}\n    {caret}"
        elif self.source is not None:
            head += f"\n    {self.source}"
        return head

    def __str__(self) -> str:
        return self.render()


@dataclass
class LintReport:
    """All diagnostics from one plan validation pass."""

    diagnostics: List[Diagnostic] = field(default_factory=list)
    # machine-readable cost prediction (lint/cost.PlanCost) when the
    # validation pass ran the static cost analyzer; None otherwise
    plan_cost: Optional[object] = None

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == Severity.ERROR]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == Severity.WARNING]

    def extend(self, diags: Sequence[Diagnostic]) -> None:
        self.diagnostics.extend(diags)

    def render(self) -> str:
        return "\n".join(d.render() for d in self.diagnostics)


class PlanValidationError(ValueError):
    """Aggregated plan-time failure: every error-severity diagnostic from
    the static pass, raised once, before any data is scanned."""

    def __init__(self, diagnostics: Sequence[Diagnostic]):
        self.diagnostics = list(diagnostics)
        errors = [d for d in self.diagnostics if d.severity == Severity.ERROR]
        summary = "; ".join(f"{d.code}: {d.message}" for d in errors[:5])
        if len(errors) > 5:
            summary += f"; ... ({len(errors) - 5} more)"
        super().__init__(
            f"Plan validation failed with {len(errors)} error(s): {summary}\n"
            + "\n".join(d.render() for d in self.diagnostics)
        )

"""Static effect model: what a planned scan READS and SHIPS, per input
key — the abstract-interpretation layer under the cost analyzer
(lint/cost.py).

The fused engine's wire format (ops/fused.pack_batch_inputs) is fully
determined by the input-spec key and the schema:

  * `num:{col}`      -> float values, cast to the compute dtype
  * `valid:{col}`    -> bool mask; all-true masks (non-nullable column)
                        are NOT transferred (synthesized from the row
                        count), otherwise bitpacked to 1 bit/row
  * `where:<all>`    -> all-true, never transferred
  * `where:`/`pred:`/`prednn:`/`match:` -> bool masks, 1 bit/row
  * `dtclass:{col}`  -> int8 class codes, 1 byte/row
  * `hll:{col}`      -> packed hash codes (int32), 4 bytes/row

Placement, member partitioning, and family grouping come from the pure
planner in ops/fused.py (`plan_scan_members`/`plan_family_jobs`); this
module adds the byte model and the per-analyzer effect summary. Nothing
here ever touches data — schema only.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

from deequ_tpu.data.table import ColumnType
from deequ_tpu.lint.schema import SchemaInfo

# Host-memory bytes per row a scan reads per column, by type. STRING and
# TIMESTAMP are nominal (object pointers / us ticks) — good enough for
# relative pass costs, which is all the report claims for them.
COLUMN_READ_BYTES: Dict[ColumnType, int] = {
    ColumnType.STRING: 16,
    ColumnType.LONG: 8,
    ColumnType.DOUBLE: 8,
    ColumnType.BOOLEAN: 1,
    ColumnType.TIMESTAMP: 8,
    ColumnType.DECIMAL: 8,
}

#: input-key prefixes whose wire payload is a bitpacked bool mask
_MASK_PREFIXES = ("where:", "pred:", "prednn:", "match:")


def prednn_elided(expression: str, schema: SchemaInfo) -> bool:
    """True when a `prednn:` (predicate-not-null) mask is provably
    all-true — the typechecker proves the predicate never yields NULL,
    so the runtime's all-true elision is a static fact, not a data
    accident. The typechecker's contract (never report non-nullable for
    an expression that can be NULL) makes this the safe direction."""
    try:
        from deequ_tpu.lint.typecheck import analyze_expression

        typed, _diags = analyze_expression(expression, schema)
        return typed is not None and not typed.nullable
    except Exception:  # noqa: BLE001 — fall back to "transferred"
        return False


def column_read_bytes(schema: SchemaInfo, column: str) -> float:
    field = schema.field(column)
    if field is None:
        return 8.0
    return float(COLUMN_READ_BYTES.get(field.ctype, 8))


def key_wire_bytes_per_row(
    key: str, schema: SchemaInfo, compute_itemsize: int = 8
) -> float:
    """Device-wire bytes per row one input key costs under the fused
    engine's packed format; 0.0 for keys that are never transferred."""
    if key == "where:<all>":
        return 0.0
    if key.startswith("num:"):
        return float(compute_itemsize)
    if key.startswith("valid:"):
        field = schema.field(key[len("valid:"):])
        if field is not None and not field.nullable:
            return 0.0  # all-true mask: synthesized on device
        return 1.0 / 8.0
    if key.startswith("prednn:") and prednn_elided(key[len("prednn:"):], schema):
        return 0.0  # provably never-NULL predicate: all-true, elided
    if key.startswith(_MASK_PREFIXES):
        return 1.0 / 8.0
    if key.startswith("dtclass:"):
        return 1.0
    if key.startswith("hll:"):
        return 4.0
    return 8.0  # unknown key: assume a full-width value column


def key_read_columns(key: str, spec: Optional[Any] = None) -> Tuple[str, ...]:
    """Columns a key's build reads, from its InputSpec when declared."""
    columns = getattr(spec, "columns", None)
    if columns:
        return tuple(columns)
    return ()


@dataclass(frozen=True)
class AnalyzerEffect:
    """One analyzer's static effect inside a scan pass."""

    analyzer: str  # repr, stable across plan/runtime
    name: str
    #: 'merge' | 'assisted' | 'host' | 'host-assisted' | 'error'
    role: str
    input_keys: Tuple[str, ...]
    columns: Tuple[str, ...]  # deduplicated columns the inputs read

    @property
    def on_device(self) -> bool:
        return self.role in ("merge", "assisted")


def scan_effects(
    analyzers: Sequence[Any],
    mode: Optional[str] = None,
) -> Tuple[Any, List[AnalyzerEffect]]:
    """Run the pure planner and summarize each member's effect.

    Returns (ScanMemberPlan, [AnalyzerEffect]) — the plan object is the
    same one the runtime consumes, so downstream cost predictions cannot
    drift from execution."""
    from deequ_tpu.ops.fused import plan_scan_members

    plan = plan_scan_members(analyzers, mode=mode)
    role_of: Dict[int, str] = {}
    for i in plan.merge_idx:
        role_of[i] = "merge"
    for i in plan.assisted_idx:
        role_of[i] = "assisted"
    for i in plan.host_idx:
        role_of[i] = "host"
    for i in plan.host_assisted_idx:
        role_of[i] = "host-assisted"
    for i in plan.spec_errors:
        role_of[i] = "error"

    key_columns = {
        key: key_read_columns(key, spec) for key, spec in plan.specs.items()
    }
    effects: List[AnalyzerEffect] = []
    for i, analyzer in enumerate(analyzers):
        role = role_of.get(i, "error")
        if role == "error":
            keys: Tuple[str, ...] = ()
        elif i in plan.host_keys:
            keys = tuple(plan.host_keys[i])
        else:
            try:
                keys = tuple(s.key for s in analyzer.input_specs())
            except Exception:  # noqa: BLE001
                keys = ()
        columns: List[str] = []
        for key in keys:
            for col in key_columns.get(key, ()):
                if col not in columns:
                    columns.append(col)
        effects.append(
            AnalyzerEffect(
                analyzer=repr(analyzer),
                name=str(getattr(analyzer, "name", type(analyzer).__name__)),
                role=role,
                input_keys=keys,
                columns=tuple(columns),
            )
        )
    return plan, effects


def pass_read_bytes_per_row(
    columns: Sequence[str], schema: SchemaInfo
) -> float:
    return float(sum(column_read_bytes(schema, c) for c in columns))


def pass_wire_bytes_per_row(
    device_keys: Sequence[str], schema: SchemaInfo, compute_itemsize: int = 8
) -> float:
    return float(
        sum(
            key_wire_bytes_per_row(k, schema, compute_itemsize)
            for k in device_keys
        )
    )


def analyzer_read_columns(analyzer: Any) -> Tuple[str, ...]:
    """Columns an analyzer reads, from its input specs (spec-declared
    read sets) with a fallback to the common column attributes."""
    columns: List[str] = []
    try:
        for spec in analyzer.input_specs():
            for col in getattr(spec, "columns", None) or ():
                if col not in columns:
                    columns.append(col)
        return tuple(columns)
    except Exception:  # noqa: BLE001
        pass
    for attr in ("column", "first_column", "second_column"):
        value = getattr(analyzer, attr, None)
        if isinstance(value, str) and value not in columns:
            columns.append(value)
    multi = getattr(analyzer, "columns", None)
    if isinstance(multi, (list, tuple)):
        for value in multi:
            if isinstance(value, str) and value not in columns:
                columns.append(value)
    return tuple(columns)

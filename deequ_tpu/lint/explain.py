"""EXPLAIN for analysis plans: the human-readable report over the
static cost model (lint/cost.py) plus the DQ300-DQ304 performance
diagnostics.

`explain_plan(data_or_schema, analyzers=..., checks=...)` is the public
entrypoint: it predicts the execution shape (passes, batches, wire
bytes, family groups) without scanning a row, lints the plan for
performance anti-patterns, and renders both as a report. The same
diagnostics feed `validate_plan` when a row-count is known, so strict
runs aggregate DQ3xx warnings next to DQ1xx/DQ2xx errors.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from deequ_tpu.data.expr import (
    Bin,
    ExpressionParseError,
    Un,
    normalize_expression,
    parse,
)
from deequ_tpu.lint.cost import PassCost, PlanCost, analyze_plan, _quantile_cap
from deequ_tpu.lint.diagnostics import Diagnostic, Severity
from deequ_tpu.lint.fold import satisfiability
from deequ_tpu.lint.schema import SchemaInfo

#: DQ302: a quantile sketch cap at/above this many sample slots per
#: (column, where) family dominates the scan's host working set
DQ302_CAP_LIMIT = 1 << 20

#: DQ303: native family kernels tile the scan in SD_MC_BLOCK=4096-row
#: blocks; one tile's working set (values + valid + mask bytes per
#: column) above this budget thrashes L2 and serializes the multi-column
#: batch. ~1 MiB: half a typical per-core L2.
DQ303_TILE_ROWS = 4096
DQ303_TILE_BUDGET_BYTES = 1 << 20

#: DQ304: an explicit batch size below this floor with more than this
#: many batches pays per-dispatch latency per handful of rows
DQ304_MIN_BATCH = 1 << 16
DQ304_MAX_BATCHES = 8

_MAX_PAIRWISE_WHERES = 32


def _implied(a: Any, b: Any, schema: Optional[SchemaInfo]) -> bool:
    """True when predicate `a` admits no TRUE row that `b` excludes —
    i.e. the filter masks agree on every row (Kleene: NULL rows are
    excluded by both sides already)."""
    verdict = satisfiability(Bin("and", a, Un("not", b)), schema)
    return verdict in ("unsat", "null-only")


def cost_diagnostics(
    cost: PlanCost,
    analyzers: Sequence[Any] = (),
    schema: Optional[SchemaInfo] = None,
    *,
    quota_scan_bytes: Optional[float] = None,
) -> List[Diagnostic]:
    """The DQ300-DQ304 performance lints over a computed `PlanCost`.

    `quota_scan_bytes` — the tenant's scan-bytes-per-window budget,
    when known (the DQService admission path supplies it) — arms the
    DQ319 never-admittable lint."""
    diags: List[Diagnostic] = []
    scan = cost.scan_pass
    scan_columns = set(scan.columns) if scan is not None else set()

    # DQ300 — a solo-pass analyzer re-reads columns the shared scan
    # already covers: its work could ride the fused pass
    if scan is not None and scan_columns:
        for p in cost.passes:
            if p.kind != "aux" or not p.columns:
                continue
            if set(p.columns) <= scan_columns:
                diags.append(
                    Diagnostic(
                        "DQ300",
                        Severity.WARNING,
                        f"{p.label} re-reads column(s) "
                        f"{', '.join(sorted(p.columns))} that the shared "
                        "scan pass already reads — an extra full pass "
                        "over data the plan touches anyway",
                        subject=p.analyzers[0] if p.analyzers else None,
                    )
                )

    # DQ301 — where-clauses that are provably equivalent but normalize
    # differently: they split the fused (where, cap) family groups and
    # duplicate mask inputs, where one spelling would share both
    by_norm: Dict[str, Tuple[str, Any]] = {}
    for analyzer in analyzers:
        where = getattr(analyzer, "where", None)
        if not isinstance(where, str):
            continue
        try:
            key = normalize_expression(where)
            ast = parse(where)
        except ExpressionParseError:
            continue
        by_norm.setdefault(key, (where, ast))
    norms = list(by_norm.items())
    if 1 < len(norms) <= _MAX_PAIRWISE_WHERES:
        for i in range(len(norms)):
            for j in range(i + 1, len(norms)):
                (_, (ti, ai)), (_, (tj, aj)) = norms[i], norms[j]
                if _implied(ai, aj, schema) and _implied(aj, ai, schema):
                    diags.append(
                        Diagnostic(
                            "DQ301",
                            Severity.WARNING,
                            f"where-clauses {ti!r} and {tj!r} are "
                            "semantically equivalent but spelled "
                            "differently: they transfer two masks and "
                            "split one fused family group into two "
                            "kernel dispatches",
                            suggestion=ti,
                        )
                    )

    # DQ302 — blowup: an extreme quantile cap, or a grouping pass whose
    # estimated cardinality exceeds the in-memory group budget
    for analyzer in analyzers:
        cap = _quantile_cap(analyzer)
        if cap is not None and cap >= DQ302_CAP_LIMIT:
            diags.append(
                Diagnostic(
                    "DQ302",
                    Severity.WARNING,
                    f"quantile sketch cap {cap} (from relative_error="
                    f"{getattr(analyzer, 'relative_error', '?')}) holds "
                    f"{cap} sample slots per (column, where) family — "
                    "the sketch stops being a sketch; relax "
                    "relative_error",
                    subject=repr(analyzer),
                )
            )
    for p in cost.passes:
        if p.kind == "grouping" and p.spill_risk:
            diags.append(
                Diagnostic(
                    "DQ302",
                    Severity.WARNING,
                    f"grouping over ({', '.join(p.columns)}) is estimated "
                    f"at ~{p.estimated_groups} groups — beyond the "
                    "in-memory budget; the frequency state will spill to "
                    "disk partition by partition",
                )
            )

    # DQ303 — one family-kernel group's cache tile outgrows the budget:
    # too many columns batched into one (where, cap) traversal
    if scan is not None:
        itemsize = 8 if cost.compute_dtype == "float64" else 4
        for g in scan.family_groups:
            tile = DQ303_TILE_ROWS * (len(g.columns) * (itemsize + 1) + 1)
            if tile > DQ303_TILE_BUDGET_BYTES:
                diags.append(
                    Diagnostic(
                        "DQ303",
                        Severity.WARNING,
                        f"family group (where={g.where!r}, cap={g.cap}) "
                        f"batches {len(g.columns)} columns: one "
                        f"{DQ303_TILE_ROWS}-row tile needs ~{tile} bytes, "
                        f"over the {DQ303_TILE_BUDGET_BYTES}-byte cache "
                        "budget — split the plan or the where groups",
                    )
                )

    # DQ304 — transfer-per-row anti-pattern: a tiny explicit batch size
    # turns one streaming scan into many per-dispatch round-trips
    if (
        scan is not None
        and scan.device_members > 0
        and cost.batch_size is not None
        and cost.batch_size < DQ304_MIN_BATCH
        and scan.n_batches > DQ304_MAX_BATCHES
    ):
        diags.append(
            Diagnostic(
                "DQ304",
                Severity.WARNING,
                f"batch_size={cost.batch_size} dispatches "
                f"{scan.n_batches} device round-trips for this row "
                "count; below ~65536 rows/batch the per-dispatch "
                "latency dominates the wire time — raise batch_size",
            )
        )

    # DQ305 — the stream pipeline's queue depth cannot hide the measured
    # H2D transfer latency: one batch's wire time exceeds `depth` batches
    # of host (decode+prep) work, so however the stages interleave the
    # fold stage starves on transfer (cost.PipelineCost overlap model)
    pipe = cost.pipeline
    if (
        pipe is not None
        and pipe.enabled
        and scan is not None
        and scan.device_members > 0
        and scan.n_batches > 1
        and pipe.depth_hides_transfer is False
    ):
        diags.append(
            Diagnostic(
                "DQ305",
                Severity.WARNING,
                f"stream-pipeline queue depth {pipe.queue_depth} cannot "
                f"hide the measured H2D transfer: one batch's wire time "
                f"(~{pipe.wire_s_per_batch:.3g}s at the measured "
                f"{pipe.link_bandwidth:.3g} B/s link) exceeds "
                f"{pipe.queue_depth}x the per-batch host work "
                f"(~{pipe.host_s_per_batch:.3g}s) — raise "
                "DEEQU_TPU_PIPELINE_DEPTH or batch_size, or shed wire "
                "bytes (host placement folds discrete members without "
                "a transfer)",
            )
        )

    # DQ310/DQ311 — row-group pushdown (lint/pushdown.py). DQ310: a
    # where filter the interpreter cannot reason about, anchored on the
    # offending subexpression; DQ311: the statistics prove every group
    # skippable — a scan that decodes nothing almost always means a
    # misconfigured suite (wrong column, impossible range, stale file)
    prune = cost.prune
    if prune is not None:
        for p in prune.predicates:
            if not p.eligible:
                diags.append(
                    Diagnostic(
                        "DQ310",
                        Severity.WARNING,
                        f"where filter {p.where!r} is not pushdown-"
                        f"eligible ({p.reason}): every row group decodes "
                        "and filters at runtime even when statistics "
                        "could have excluded it",
                        source=p.where,
                        span=p.span,
                    )
                )
        if prune.proven_empty:
            diags.append(
                Diagnostic(
                    "DQ311",
                    Severity.WARNING,
                    "row-group statistics prove every where filter FALSE "
                    f"on all {prune.total_groups} row group(s): every "
                    "filtered metric is empty (one sentinel group still "
                    "decodes to keep results identical to an unpruned "
                    "scan) — check the predicates against the data's "
                    "actual ranges (wrong column, impossible range, or a "
                    "stale file)",
                )
            )

    # DQ312 — decode fast path: columns that fall off the buffer-level
    # native decode keep the multi-pass host from_arrow chain. Each is
    # named with the planner's reason (the same classifier the runtime
    # routes with), so the fix — recast a decimal/timestamp upstream, or
    # stop consuming host string values — is actionable per column.
    if scan is not None and scan.decode_fallbacks:
        for col, reason in scan.decode_fallbacks:
            diags.append(
                Diagnostic(
                    "DQ312",
                    Severity.WARNING,
                    f"column {col!r} falls off the decode fast path "
                    f"({reason}): it decodes through the multi-pass host "
                    "chain while fast-path columns decode in one native "
                    "pass",
                    source=col,
                )
            )

    # DQ313 — decode-to-wire fusion: fast-path columns that still build
    # the Column intermediate because a consumer needs it. The planner's
    # reason names the offending consumer key when there is one, and the
    # caret lands on it — so the fix (drop the host re-read, move the
    # member onto the compiled reduce) is actionable per column.
    if scan is not None and scan.wire_falloffs:
        for col, reason, key in scan.wire_falloffs:
            diags.append(
                Diagnostic(
                    "DQ313",
                    Severity.WARNING,
                    f"column {col!r} decodes to a host Column instead of "
                    f"fusing straight to the wire ({reason}): its pack "
                    "re-reads the decoded arrays every batch",
                    source=key or col,
                    span=(0, len(key)) if key else None,
                )
            )

    # DQ315 — native parquet reader: fast-path columns whose column-
    # chunks still decode through arrow because a page encoding, codec,
    # or physical layout has no native decoder. The reason names the
    # disqualifying property, so the fix — re-encode the file with
    # PLAIN/RLE-dictionary pages and snappy/zstd, or flatten the nested
    # column — is actionable per column.
    if scan is not None and scan.reader_fallbacks:
        for col, reason in scan.reader_fallbacks:
            diags.append(
                Diagnostic(
                    "DQ315",
                    Severity.WARNING,
                    f"column {col!r} falls off the native parquet reader "
                    f"({reason}): its pages decompress and decode through "
                    "arrow instead of the page-to-wire path",
                    source=col,
                )
            )

    # DQ325 — encoded fold: reader columns whose chunks still expand to
    # row width because a codec property, consumer analyzer, dtype, or
    # dictionary-size condition keeps the run-fold kernels off. The
    # reason names the disqualifying property with its class prefix
    # (codec:/analyzer:/dtype:/dict-size:), so the fix — rewrite the
    # file with dictionary pages, drop the row-width consumer, or move
    # the member off the device — is actionable per column.
    if scan is not None and scan.encfold_falloffs:
        for col, reason in scan.encfold_falloffs:
            diags.append(
                Diagnostic(
                    "DQ325",
                    Severity.WARNING,
                    f"column {col!r} falls off the encoded fold "
                    f"({reason}): its chunks expand to row width instead "
                    "of folding over (run, code) streams",
                    source=col,
                )
            )

    # DQ318 — a deadline over a source with no partition boundaries:
    # nothing commits to the state repository mid-run, so a deadline
    # trip loses ALL scanned work — the rerun starts from zero instead
    # of resuming at the partitions already folded
    if cost.deadline_s is not None and (
        scan is None or scan.partitions_total is None
    ):
        diags.append(
            Diagnostic(
                "DQ318",
                Severity.WARNING,
                f"deadline {cost.deadline_s:g}s set but the source has no "
                "partition boundaries: a deadline trip discards all "
                "progress (a partitioned source + StateRepository resumes "
                "at the partitions already committed)",
            )
        )

    # DQ319 — the plan can NEVER be admitted under the tenant's quota:
    # its predicted scan bytes exceed the whole bytes-per-window budget,
    # so admission control rejects it every time (DQ410) no matter how
    # empty the window is — the plan must shrink (filters that push
    # down, cached partitions, fewer columns) or the quota must grow
    if quota_scan_bytes is not None:
        predicted = cost.predicted_scan_bytes
        if predicted is not None and predicted > float(quota_scan_bytes):
            diags.append(
                Diagnostic(
                    "DQ319",
                    Severity.WARNING,
                    f"plan predicts ~{predicted:.0f} scan bytes but the "
                    f"tenant's quota window admits at most "
                    f"{float(quota_scan_bytes):.0f}: this plan can never "
                    "be admitted (rejected DQ410 at every submission) — "
                    "shed read bytes (pushdown-eligible filters, fewer "
                    "columns, a partitioned source with cached states) "
                    "or raise the tenant's scan-bytes quota",
                )
            )
    return diags


# -- rendering ----------------------------------------------------------------


def _fmt_bytes(n: Optional[float]) -> str:
    if n is None:
        return "?"
    value = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if value < 1024 or unit == "GiB":
            return f"{value:.1f} {unit}" if unit != "B" else f"{int(value)} B"
        value /= 1024
    return f"{value:.1f} GiB"


def _render_pass(p: PassCost, idx: int) -> List[str]:
    lines = [f"Pass {idx}: {p.label}  [{p.kind}]"]
    if p.analyzers:
        lines.append(f"  members: {len(p.analyzers)} "
                     f"(device {p.device_members}, host {p.host_members})"
                     if p.kind == "scan" else f"  members: {len(p.analyzers)}")
    if p.columns:
        lines.append(f"  reads: {', '.join(p.columns)} "
                     f"(~{p.read_bytes_per_row:g} B/row)")
    if p.input_keys:
        lines.append(f"  device inputs: {len(p.input_keys)} key(s), "
                     f"~{p.wire_bytes_per_row:g} wire B/row")
    if p.kind == "scan":
        lines.append(f"  batches: {p.n_batches}"
                     + (f", first-batch wire {_fmt_bytes(p.wire_bytes_per_batch)}"
                        if p.wire_bytes_per_batch is not None else ""))
        if p.partitions_total is not None and p.partitions_cached is not None:
            lines.append(
                f"  partitions: {p.partitions_cached} cached, "
                f"{p.partitions_total - p.partitions_cached} scanned"
                + (f" (saves ~{_fmt_bytes(p.saved_partition_bytes)} read)"
                   if p.saved_partition_bytes else "")
            )
        if p.rg_total is not None and p.rg_skipped is not None:
            lines.append(
                f"  row groups: {p.rg_total - p.rg_skipped} decoded, "
                f"{p.rg_skipped} skipped statically"
                + (f" (saves ~{_fmt_bytes(p.saved_read_bytes)} decode)"
                   if p.saved_read_bytes else "")
            )
        if p.decode_cols_total is not None and p.decode_cols_fast is not None:
            line = (
                f"  decode: {p.decode_cols_fast}/{p.decode_cols_total} "
                "column(s) on the native fast path"
            )
            if p.decode_workers is not None:
                line += f", {p.decode_workers} worker(s)"
            if p.saved_decode_bytes:
                line += (
                    f" (avoids ~{_fmt_bytes(p.saved_decode_bytes)} "
                    "intermediate)"
                )
            lines.append(line)
        if p.wire_fused_cols is not None and p.decode_cols_total is not None:
            line = (
                f"  wire: {p.wire_fused_cols}/{p.decode_cols_total} "
                "column(s) fused at decode"
            )
            if p.saved_pack_bytes:
                line += f" (skips ~{_fmt_bytes(p.saved_pack_bytes)} pack)"
            lines.append(line)
        if p.reader_chunks_total is not None and p.reader_chunks_native is not None:
            line = (
                f"  reader: {p.reader_chunks_native}/{p.reader_chunks_total} "
                "column-chunks native"
            )
            if p.decode_workers is not None:
                line += f", {p.decode_workers} worker(s)"
            if p.saved_alloc_bytes:
                line += (
                    f" (avoids ~{_fmt_bytes(p.saved_alloc_bytes)} "
                    "arrow materialization)"
                )
            lines.append(line)
        if p.encfold_cols is not None and p.encfold_cols_total is not None:
            moments = p.encfold_moment_cols or 0
            lines.append(
                f"  encoded-fold: {p.encfold_cols}/{p.encfold_cols_total} "
                f"column(s) (runs={moments}, "
                f"dict={p.encfold_cols - moments})"
            )
        for g in p.family_groups:
            tag = "batched" if g.batched else "solo"
            lines.append(
                f"  family group (where={g.where!r}, cap={g.cap}): "
                f"{len(g.columns)} column(s) [{tag}]"
                + (" +hll" if g.want_regs else "")
            )
    if p.estimated_groups is not None:
        lines.append(f"  estimated groups: ~{p.estimated_groups}"
                     + ("  !! spill" if p.spill_risk else ""))
    for note in p.notes:
        lines.append(f"  note: {note}")
    return lines


def sharing_diagnostics(
    proof: Any, analyzers: Sequence[Any] = ()
) -> List[Diagnostic]:
    """DQ321/DQ322 over a `lint.subsume.SubsumptionProof` — one DQ321
    when the suite provably rides a shared scan, else one DQ322 per
    undischarged obligation with the caret on the offending where."""
    diags: List[Diagnostic] = []
    if proof is None:
        return diags
    if proof.contained:
        diags.append(
            Diagnostic(
                "DQ321",
                Severity.WARNING,
                "suite is provably contained in the candidate shared "
                f"scan — {proof.summary()}; one superset scan computes "
                "these metrics bit-identically over the state semigroup",
            )
        )
        return diags
    for mismatch in proof.env_mismatches:
        diags.append(
            Diagnostic(
                "DQ322",
                Severity.WARNING,
                "scan sharing declined: plan environments are "
                f"incomparable ({mismatch}) — states folded under "
                "different arithmetic are never merged",
            )
        )
    for obligation in proof.obligations:
        if obligation.satisfied:
            continue
        where = obligation.where
        diags.append(
            Diagnostic(
                "DQ322",
                Severity.WARNING,
                "scan sharing declined: "
                + (obligation.detail or "obligation not provably contained"),
                source=where,
                span=(0, len(where)) if where else None,
                subject=obligation.analyzer,
            )
        )
    return diags


def render_explain(
    cost: PlanCost,
    diagnostics: Sequence[Diagnostic] = (),
    sharing: Optional[str] = None,
) -> str:
    """The EXPLAIN report: predicted execution shape, then diagnostics.

    `sharing` — the one-line subsumption-proof summary
    (`SubsumptionProof.summary()`) when the plan was checked against a
    candidate shared scan; rendered as the `sharing:` line."""
    head = [
        "== Plan explain (static — no data scanned) ==",
        f"analyzers: {len(cost.analyzers)}   placement: {cost.placement}   "
        f"engine: {cost.engine}   compute dtype: {cost.compute_dtype}",
        f"rows: {cost.num_rows if cost.num_rows is not None else '?'}   "
        f"batch_size: {cost.batch_size if cost.batch_size is not None else 'default'}",
    ]
    if cost.num_hosts > 1:
        head.append(
            f"hosts: {cost.num_hosts}   allgather rounds: {cost.allgather_rounds}"
        )
    if cost.num_shards > 1:
        total = sum(cost.shard_partitions)
        per = -(-total // cost.num_shards) if total else 0  # ceil
        head.append(
            f"shards: {cost.num_shards} processes × {per} partitions each "
            f"(max skew {cost.shard_skew:.2f})"
        )
    if cost.precondition_failures:
        head.append(
            f"precondition failures: {len(cost.precondition_failures)} "
            "analyzer(s) will fail without scanning"
        )
        for rep, err in cost.precondition_failures:
            head.append(f"  - {rep}: {err}")
    body: List[str] = []
    for i, p in enumerate(cost.passes, 1):
        body.extend(_render_pass(p, i))
    if not cost.passes:
        body.append("(no passes: nothing to compute)")
    pipe = cost.pipeline
    if pipe is not None:
        state = "on" if pipe.enabled else "off (DEEQU_TPU_PIPELINE=0)"
        body.append(
            f"stream pipeline: {state}   depth: {pipe.queue_depth}   "
            f"stages: {' > '.join(pipe.stages)}"
        )
        if pipe.serial_s_per_batch is not None:
            body.append(
                f"  per-batch: host ~{pipe.host_s_per_batch:.3g}s "
                f"+ wire ~{pipe.wire_s_per_batch:.3g}s  ->  "
                f"overlapped ~{pipe.overlapped_s_per_batch:.3g}s "
                f"(serial ~{pipe.serial_s_per_batch:.3g}s, "
                f"bottleneck: {pipe.bottleneck})"
            )
        elif pipe.wire_s_per_batch is None and pipe.wire_bytes_per_batch:
            body.append(
                "  per-batch wire time unmeasured "
                "(no cached link-bandwidth probe)"
            )
    if cost.window_spec is not None:
        body.append(
            f"windows: {cost.window_spec} -> "
            f"{cost.window_segments_merged} segment merges, "
            f"{cost.window_partitions_rescanned} partitions rescanned "
            f"(saves ~{_fmt_bytes(cost.saved_window_bytes)} read)"
        )
    if cost.admission_tier is not None:
        scan_bytes = cost.predicted_scan_bytes
        line = (
            f"admission: tier={cost.admission_tier}, "
            f"predicted scan {_fmt_bytes(scan_bytes)}"
        )
        if cost.quota_headroom_bytes is not None:
            headroom = cost.quota_headroom_bytes
            line += (
                f", quota headroom ~{_fmt_bytes(headroom)}"
                if headroom >= 0
                else f", quota overdrawn by ~{_fmt_bytes(-headroom)}"
            )
        body.append(line)
    if sharing is not None:
        body.append(f"sharing: {sharing}")
    if cost.retry_budget is not None or cost.deadline_s is not None:
        scan = cost.scan_pass
        resume = (
            f"{scan.partitions_cached} cached partitions"
            if scan is not None and scan.partitions_cached is not None
            else "none (unpartitioned source)"
        )
        line = f"resilience: retries={cost.retry_budget}, resume={resume}"
        if cost.deadline_s is not None:
            line += f", deadline={cost.deadline_s:g}s"
        body.append(line)
    sig = cost.dispatch_signature()
    body.append(
        "predicted counters: "
        + ", ".join(f"{k}={v}" for k, v in sig["counters"].items())
    )
    spans = sig["spans"]
    if spans:
        body.append(
            "predicted spans: "
            + ", ".join(f"{k}×{v}" for k, v in spans.items())
        )
    tail: List[str] = []
    if diagnostics:
        tail.append(f"-- {len(diagnostics)} diagnostic(s) --")
        tail.extend(d.render() for d in diagnostics)
    else:
        tail.append("-- no performance diagnostics --")
    return "\n".join(head + body + tail)


# -- entrypoint ---------------------------------------------------------------


@dataclass
class ExplainResult:
    cost: PlanCost
    diagnostics: List[Diagnostic] = field(default_factory=list)
    # failure-forensics capability prediction (observe/forensics
    # classification, computed statically from the checks): constraint
    # repr -> row-level family for capable constraints, and
    # (constraint repr, reason) for the DQ316 fall-offs
    forensics_capable: List[Tuple[str, str]] = field(default_factory=list)
    forensics_falloffs: List[Tuple[str, str]] = field(default_factory=list)
    # the plan-subsumption proof (lint/subsume.SubsumptionProof) when
    # the plan was checked against a candidate shared scan; its summary
    # renders as the `sharing:` line
    sharing: Optional[Any] = None

    def render(self) -> str:
        text = render_explain(
            self.cost,
            self.diagnostics,
            sharing=self.sharing.summary() if self.sharing is not None else None,
        )
        if self.forensics_capable or self.forensics_falloffs:
            lines = [
                "failure forensics (with_forensics() / "
                "DEEQU_TPU_FORENSICS=1): "
                f"{len(self.forensics_capable)} of "
                f"{len(self.forensics_capable) + len(self.forensics_falloffs)}"
                " constraint(s) capture violating rows"
            ]
            for rep, kind in self.forensics_capable:
                lines.append(f"  + {rep}: {kind}")
            text = "\n".join([text] + lines)
        return text

    def __str__(self) -> str:
        return self.render()


def _plan_analyzers(analyzers: Sequence[Any], checks: Sequence[Any]) -> List[Any]:
    from deequ_tpu.lint.planlint import _constraint_analyzers

    occurrences: List[Any] = list(analyzers)
    occurrences.extend(
        inner.analyzer for _, inner in _constraint_analyzers(checks)
    )
    seen: set = set()
    unique: List[Any] = []
    for a in occurrences:
        if a not in seen:
            seen.add(a)
            unique.append(a)
    return unique


def explain_plan(
    data_or_schema: Any,
    analyzers: Sequence[Any] = (),
    checks: Sequence[Any] = (),
    *,
    num_rows: Optional[int] = None,
    batch_size: Optional[int] = None,
    placement: Optional[str] = None,
    engine: str = "single",
    num_hosts: int = 1,
    num_shards: int = 1,
    shard_partitions: Optional[Sequence[int]] = None,
    num_devices: int = 1,
    streaming: Optional[bool] = None,
    stream_batch_rows: Optional[int] = None,
    link_bandwidth: Optional[float] = None,
    pipeline_depth: Optional[int] = None,
    row_groups: Optional[Sequence] = None,
    decode_types: Optional[Dict[str, str]] = None,
    partitions: Optional[Sequence] = None,
    deadline_s: Optional[float] = None,
    quota_scan_bytes: Optional[float] = None,
    sharing_with: Optional[Sequence[Any]] = None,
) -> ExplainResult:
    """EXPLAIN an analysis plan against a `Table` (schema and row count
    are taken from it — still zero data scanned) or a `SchemaInfo`.

    `streaming` defaults to the table's own `is_streaming` (False for a
    bare `SchemaInfo`), and `stream_batch_rows` to the table's own
    per-batch row cap; streaming plans additionally predict the stream
    pipeline's overlap shape and the DQ305 queue-depth lint, with the
    link bandwidth from `link_bandwidth` or the cached placement probe.

    `row_groups` defaults to the source's own parquet statistics
    (`row_group_stats()`) when it exposes them — reading file metadata,
    never a row — which turns on the pushdown prediction: skipped vs
    decoded row groups, the exact decode batch replay, and the
    DQ310/DQ311 lints.

    `decode_types` likewise defaults to the source's own decode
    vocabulary (`decode_column_types()`), which turns on the decode
    fast-path prediction and the per-column DQ312 fallback lints.

    `num_shards` / `shard_partitions` (per-shard partition counts from
    `parallel.shard.plan_shards`) describe a sharded streaming scan and
    add the `shards: N processes × K partitions each (max skew S)` line.

    `quota_scan_bytes` — a tenant's scan-bytes-per-window budget (the
    DQService admission path supplies it) — adds the quota headroom to
    the `admission:` line and arms the DQ319 never-admittable lint.

    `sharing_with` — the analyzer list of a candidate superset scan
    (another tenant's admitted plan over the same table): runs the
    plan-subsumption prover (lint/subsume.py) against it, attaches the
    proof as `result.sharing` (rendered on the `sharing:` line), and
    arms the DQ321/DQ322 diagnostics."""
    if isinstance(data_or_schema, SchemaInfo):
        schema = data_or_schema
    else:
        schema = SchemaInfo.from_table(data_or_schema)
        if num_rows is None:
            num_rows = int(data_or_schema.num_rows)
        if streaming is None:
            streaming = bool(getattr(data_or_schema, "is_streaming", False))
        if stream_batch_rows is None and streaming:
            cap = getattr(data_or_schema, "batch_rows", None)
            stream_batch_rows = int(cap) if cap else None
        if row_groups is None:
            stats_fn = getattr(data_or_schema, "row_group_stats", None)
            if stats_fn is not None:
                try:
                    row_groups = stats_fn()
                except Exception:  # noqa: BLE001 — stats are advisory
                    row_groups = None
        if decode_types is None:
            types_fn = getattr(data_or_schema, "decode_column_types", None)
            if types_fn is not None:
                try:
                    decode_types = types_fn()
                except Exception:  # noqa: BLE001 — advisory, like stats
                    decode_types = None
    plan = _plan_analyzers(analyzers, checks)
    cost = analyze_plan(
        plan,
        schema,
        num_rows=num_rows,
        batch_size=batch_size,
        placement=placement,
        engine=engine,
        num_hosts=num_hosts,
        num_shards=num_shards,
        shard_partitions=shard_partitions,
        num_devices=num_devices,
        streaming=bool(streaming),
        stream_batch_rows=stream_batch_rows,
        link_bandwidth=link_bandwidth,
        pipeline_depth=pipeline_depth,
        row_groups=row_groups,
        decode_types=decode_types,
        partitions=partitions,
        deadline_s=deadline_s,
    )
    if quota_scan_bytes is not None:
        predicted = cost.predicted_scan_bytes
        if predicted is not None:
            cost.quota_headroom_bytes = float(quota_scan_bytes) - predicted
    diagnostics = cost_diagnostics(
        cost, plan, schema, quota_scan_bytes=quota_scan_bytes
    )
    sharing_proof = None
    if sharing_with is not None:
        try:
            from deequ_tpu.lint.subsume import prove_subsumption

            sharing_proof = prove_subsumption(plan, list(sharing_with), schema)
            diagnostics.extend(sharing_diagnostics(sharing_proof, plan))
        except Exception:  # noqa: BLE001 — the prover is advisory here
            sharing_proof = None
    # DQ316 — failure-forensics capability, predicted from the SAME
    # static classification the capture itself uses: constraints whose
    # violating rows cannot be identified per batch fall off with the
    # classifier's reason, so an operator knows before running which
    # failures will come back with row evidence and which won't
    capable: List[Tuple[str, str]] = []
    falloffs: List[Tuple[str, str]] = []
    if checks:
        try:
            from deequ_tpu.observe.forensics import classify_constraints

            for constraint, _inner, kind, reason in classify_constraints(
                checks
            ):
                if kind is not None:
                    capable.append((repr(constraint), kind))
                else:
                    falloffs.append((repr(constraint), reason))
                    diagnostics.append(
                        Diagnostic(
                            "DQ316",
                            Severity.WARNING,
                            f"constraint {constraint!r} falls off row-level "
                            f"failure forensics ({reason}): a FAILURE "
                            "reports the metric value only, with no "
                            "sampled violating rows",
                        )
                    )
        except Exception:  # noqa: BLE001 — prediction is advisory
            capable, falloffs = [], []
    return ExplainResult(
        cost=cost,
        diagnostics=diagnostics,
        forensics_capable=capable,
        forensics_falloffs=falloffs,
        sharing=sharing_proof,
    )


def explain(
    analyzers: Sequence[Any],
    schema: SchemaInfo,
    **kwargs: Any,
) -> str:
    """Render the EXPLAIN report for a plan as a string."""
    return explain_plan(schema, analyzers=analyzers, **kwargs).render()


__all__ = [
    "DQ302_CAP_LIMIT",
    "DQ303_TILE_BUDGET_BYTES",
    "DQ303_TILE_ROWS",
    "DQ304_MAX_BATCHES",
    "DQ304_MIN_BATCH",
    "ExplainResult",
    "cost_diagnostics",
    "explain",
    "explain_plan",
    "render_explain",
    "sharing_diagnostics",
]

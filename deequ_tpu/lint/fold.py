"""Constant folding and predicate satisfiability for plan linting.

Two static facts about a predicate matter at plan time:

* it folds to a constant (`1 = 1`, `TRUE OR x > 0`) — the filter is a
  no-op or drops every row (DQ205 / DQ204), and
* it is unsatisfiable for non-NULL rows (`x < 1 AND x > 2`, or an
  `isContainedIn(lower=5, upper=1)` whose generated range is empty and
  only the `IS NULL` escape branch can ever hold) — DQ204.

Satisfiability works on a bounded DNF expansion over simple atoms
(column-vs-literal comparisons, IS [NOT] NULL, constants); anything else
is opaque and makes the verdict 'unknown' rather than wrong. Kleene
semantics are respected when pushing NOT through comparisons:
NOT (a < b) == a >= b holds in 3-valued logic (both are NULL on NULL).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from deequ_tpu.data.expr import (
    Between,
    Bin,
    Col,
    Func,
    InList,
    IsNull,
    Like,
    Lit,
    Node,
    Un,
)
from deequ_tpu.lint.interval import Interval
from deequ_tpu.lint.schema import SchemaInfo

_DNF_BRANCH_CAP = 64

# -- constant folding --------------------------------------------------------

_UNSET = object()


def const_fold(node: Node):
    """Fold a literal-only subtree to its value (float | str | bool | None
    with SQL NULL semantics). Returns _UNSET sentinel-free API: a tuple
    (True, value) when the node is a compile-time constant, else
    (False, None)."""
    ok, v = _fold(node)
    return ok, v


def _fold(node: Node) -> Tuple[bool, object]:
    if isinstance(node, Lit):
        return True, node.value
    if isinstance(node, Un):
        ok, v = _fold(node.x)
        if not ok:
            return False, None
        if node.op == "neg":
            if v is None:
                return True, None
            try:
                return True, -float(v)
            except (TypeError, ValueError):
                return False, None
        # not: Kleene
        if v is None:
            return True, None
        return True, not bool(v)
    if isinstance(node, Bin):
        lok, lv = _fold(node.l)
        rok, rv = _fold(node.r)
        if not (lok and rok):
            # Kleene shortcuts: FALSE AND x == FALSE, TRUE OR x == TRUE
            if node.op == "and":
                for ok, v in ((lok, lv), (rok, rv)):
                    if ok and v is not None and not bool(v):
                        return True, False
            if node.op == "or":
                for ok, v in ((lok, lv), (rok, rv)):
                    if ok and v is not None and bool(v):
                        return True, True
            return False, None
        if node.op == "and":
            l3 = None if lv is None else bool(lv)
            r3 = None if rv is None else bool(rv)
            if l3 is False or r3 is False:
                return True, False
            if l3 is None or r3 is None:
                return True, None
            return True, True
        if node.op == "or":
            l3 = None if lv is None else bool(lv)
            r3 = None if rv is None else bool(rv)
            if l3 is True or r3 is True:
                return True, True
            if l3 is None or r3 is None:
                return True, None
            return True, False
        if lv is None or rv is None:
            return True, None
        if node.op in ("eq", "ne", "lt", "le", "gt", "ge"):
            try:
                if isinstance(lv, str) or isinstance(rv, str):
                    a, b = str(lv), str(rv)
                else:
                    a, b = float(lv), float(rv)
            except (TypeError, ValueError):
                return False, None
            out = {
                "eq": a == b, "ne": a != b, "lt": a < b,
                "le": a <= b, "gt": a > b, "ge": a >= b,
            }[node.op]
            return True, out
        try:
            a, b = float(lv), float(rv)
        except (TypeError, ValueError):
            return False, None
        if node.op == "add":
            return True, a + b
        if node.op == "sub":
            return True, a - b
        if node.op == "mul":
            return True, a * b
        if node.op == "div":
            return True, (None if b == 0 else a / b)
        if node.op == "mod":
            return True, (None if b == 0 else math.fmod(a, b))
        return False, None
    if isinstance(node, IsNull):
        ok, v = _fold(node.x)
        if not ok:
            return False, None
        is_null = v is None
        return True, (not is_null) if node.negated else is_null
    return False, None


# -- DNF satisfiability ------------------------------------------------------

# atom forms:
#   ('cmp', col, op, value)      op in eq/ne/lt/le/gt/ge; value float or str
#   ('null', col, must_be_null)  bool
#   ('const', bool)
#   ('opaque',)
Atom = Tuple
Branch = List[Atom]

_NEG_CMP = {"eq": "ne", "ne": "eq", "lt": "ge", "ge": "lt", "le": "gt", "gt": "le"}
_FLIP_CMP = {"lt": "gt", "gt": "lt", "le": "ge", "ge": "le", "eq": "eq", "ne": "ne"}


def _lit_value(node: Node):
    """Literal usable in an atom: (True, value) for numeric/str literals."""
    ok, v = _fold(node)
    if not ok or v is None:
        return False, None
    if isinstance(v, bool):
        return False, None
    if isinstance(v, (int, float)):
        return True, float(v)
    if isinstance(v, str):
        return True, v
    return False, None


def _cmp_atom(node: Bin) -> Optional[Atom]:
    if isinstance(node.l, Col):
        ok, v = _lit_value(node.r)
        if ok:
            return ("cmp", node.l.name, node.op, v)
    if isinstance(node.r, Col):
        ok, v = _lit_value(node.l)
        if ok:
            return ("cmp", node.r.name, _FLIP_CMP[node.op], v)
    return None


def _cross(a: List[Branch], b: List[Branch]) -> Optional[List[Branch]]:
    if len(a) * len(b) > _DNF_BRANCH_CAP:
        return None
    return [x + y for x in a for y in b]


def _dnf(node: Node, neg: bool) -> Optional[List[Branch]]:
    """DNF branches of `node` (negated when neg). None = too complex."""
    ok, v = _fold(node)
    if ok:
        if v is None:
            # NULL predicate is never TRUE (and its negation is NULL too)
            return [[("const", False)]]
        truth = bool(v) ^ neg
        return [[("const", truth)]]

    if isinstance(node, Un) and node.op == "not":
        return _dnf(node.x, not neg)

    if isinstance(node, Bin) and node.op in ("and", "or"):
        is_and = (node.op == "and") ^ neg
        l = _dnf(node.l, neg)
        r = _dnf(node.r, neg)
        if l is None or r is None:
            return None
        if is_and:
            return _cross(l, r)
        out = l + r
        return out if len(out) <= _DNF_BRANCH_CAP else None

    if isinstance(node, Bin) and node.op in _NEG_CMP:
        op = _NEG_CMP[node.op] if neg else node.op
        atom = _cmp_atom(Bin(op, node.l, node.r))
        return [[atom]] if atom is not None else [[("opaque",)]]

    if isinstance(node, IsNull):
        if isinstance(node.x, Col):
            must_be_null = (not node.negated) ^ neg
            return [[("null", node.x.name, must_be_null)]]
        return [[("opaque",)]]

    if isinstance(node, Between):
        if isinstance(node.x, Col):
            lo_ok, lo = _lit_value(node.lo)
            hi_ok, hi = _lit_value(node.hi)
            if lo_ok and hi_ok:
                effective_neg = node.negated ^ neg
                if not effective_neg:
                    return [[("cmp", node.x.name, "ge", lo),
                             ("cmp", node.x.name, "le", hi)]]
                return [[("cmp", node.x.name, "lt", lo)],
                        [("cmp", node.x.name, "gt", hi)]]
        return [[("opaque",)]]

    if isinstance(node, InList):
        if isinstance(node.x, Col):
            values = []
            for item in node.items:
                ok, v = _lit_value(item)
                if not ok:
                    return [[("opaque",)]]
                values.append(v)
            effective_neg = node.negated ^ neg
            if not effective_neg:
                branches = [[("cmp", node.x.name, "eq", v)] for v in values]
                return branches if len(branches) <= _DNF_BRANCH_CAP else None
            return [[("cmp", node.x.name, "ne", v) for v in values]]
        return [[("opaque",)]]

    if isinstance(node, (Like, Func, Col, Bin, Un)):
        return [[("opaque",)]]

    return [[("opaque",)]]


class _ColFacts:
    """Per-column conjunction state: one Interval element (the shared
    lattice in lint/interval.py, also the pushdown interpreter's domain)
    plus eq/ne point facts the interval form can't express."""

    __slots__ = ("iv", "eq", "ne", "domain")

    def __init__(self):
        self.iv = Interval.top()
        self.eq: object = _UNSET
        self.ne: set = set()
        self.domain: Optional[str] = None  # 'num' | 'str' once constrained


def _branch_verdict(
    branch: Branch, schema: Optional[SchemaInfo]
) -> Tuple[str, bool]:
    """-> (verdict 'sat'|'unsat'|'unknown', has_null_escape)."""
    facts: Dict[str, _ColFacts] = {}
    must_null: Dict[str, bool] = {}
    unknown = False
    has_escape = False

    for atom in branch:
        tag = atom[0]
        if tag == "const":
            if not atom[1]:
                return "unsat", False
        elif tag == "opaque":
            unknown = True
        elif tag == "null":
            _, col, is_null = atom
            if col in must_null and must_null[col] != is_null:
                return "unsat", False
            must_null[col] = is_null
            if is_null:
                has_escape = True
                if schema is not None:
                    fld = schema.field(col)
                    if fld is not None and not fld.nullable:
                        return "unsat", False
        elif tag == "cmp":
            _, col, op, v = atom
            # a TRUE comparison requires the column to be non-NULL
            if must_null.get(col) is True:
                return "unsat", False
            must_null[col] = False
            f = facts.setdefault(col, _ColFacts())
            dom = "str" if isinstance(v, str) else "num"
            if f.domain is None:
                f.domain = dom
            elif f.domain != dom:
                # mixed string/number constraints involve eval-side
                # coercion; don't try to reason about them
                unknown = True
                continue
            if dom == "str":
                if op == "eq":
                    if f.eq is not _UNSET and f.eq != v:
                        return "unsat", False
                    if v in f.ne:
                        return "unsat", False
                    f.eq = v
                elif op == "ne":
                    if f.eq is not _UNSET and f.eq == v:
                        return "unsat", False
                    f.ne.add(v)
                else:
                    unknown = True  # string ordering: out of scope
                continue
            if op == "eq":
                if f.eq is not _UNSET and f.eq != v:
                    return "unsat", False
                if v in f.ne:
                    return "unsat", False
                f.eq = v
            elif op == "ne":
                if f.eq is not _UNSET and f.eq == v:
                    return "unsat", False
                f.ne.add(v)
            elif op in ("ge", "gt", "le", "lt"):
                f.iv = f.iv.narrow(op, v)

    for col, f in facts.items():
        if f.domain != "num":
            continue
        if f.iv.is_empty:
            return "unsat", False
        if f.eq is not _UNSET:
            if not f.iv.contains_point(f.eq):
                return "unsat", False
        elif f.iv.is_point and f.iv.lo in f.ne:
            return "unsat", False

    # check for a must-null column that schema forbids was handled inline
    return ("unknown" if unknown else "sat"), has_escape


def satisfiability(node: Node, schema: Optional[SchemaInfo] = None) -> str:
    """-> 'sat' | 'unsat' | 'null-only' | 'unknown'.

    'null-only': some rows can satisfy the predicate, but ONLY via an
    IS NULL escape branch while every non-escape branch is impossible —
    e.g. `c IS NULL OR (c >= 5 AND c <= 1)`. A plain `c IS NULL`
    predicate has no impossible non-escape branch and stays 'sat'.
    """
    branches = _dnf(node, neg=False)
    if branches is None or not branches:
        return "unknown"

    sat_escape = unsat_n = unknown_n = sat_plain = 0
    for branch in branches:
        verdict, has_escape = _branch_verdict(branch, schema)
        if verdict == "unsat":
            unsat_n += 1
        elif verdict == "unknown":
            unknown_n += 1
        elif has_escape:
            sat_escape += 1
        else:
            sat_plain += 1

    if unsat_n == len(branches):
        return "unsat"
    if sat_plain == 0 and unknown_n == 0 and sat_escape > 0 and unsat_n > 0:
        return "null-only"
    if sat_plain == 0 and sat_escape == 0:
        return "unknown"
    return "sat"


def dnf_branches(node: Node) -> Optional[List[Branch]]:
    """Public DNF entry shared with the row-group pruning interpreter
    (lint/pushdown.py): branches of `node` un-negated; None when the
    expansion exceeds _DNF_BRANCH_CAP."""
    return _dnf(node, neg=False)


def cmp_atom(node: Bin) -> Optional[Atom]:
    """Public alias of the column-vs-literal atom extractor, used by the
    pushdown eligibility walk to classify comparison nodes."""
    return _cmp_atom(node)


def fold_to_constant(node: Node) -> Optional[Tuple[bool, object]]:
    """(True, value) when the whole predicate folds to a compile-time
    constant, else None. Kept as a thin alias over const_fold for the
    plan linter."""
    ok, v = _fold(node)
    return (True, v) if ok else None

"""The interval lattice shared by predicate satisfiability (DQ204,
lint/fold.py) and the row-group pruning interpreter (lint/pushdown.py).

One element is a possibly-open numeric interval with independent
strictness per bound. `narrow()` reproduces the exact tie-breaking the
DQ204 branch verdict always used (a strict bound replaces a non-strict
bound at the same endpoint, never the reverse), so the fold.py refactor
onto this type is verdict-preserving by construction. All operations
are total over +-inf endpoints; NaN endpoints are the caller's bug —
both consumers filter NaN before constructing intervals.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

_CMP_OPS = ("eq", "lt", "le", "gt", "ge")


@dataclass(frozen=True)
class Interval:
    """{x : lo (<|<=) x (<|<=) hi} — strict flags select the strict form."""

    lo: float = -math.inf
    lo_strict: bool = False
    hi: float = math.inf
    hi_strict: bool = False

    # -- constructors --------------------------------------------------------

    @staticmethod
    def top() -> "Interval":
        return Interval()

    @staticmethod
    def point(v: float) -> "Interval":
        return Interval(v, False, v, False)

    @staticmethod
    def closed(lo: float, hi: float) -> "Interval":
        return Interval(lo, False, hi, False)

    @staticmethod
    def from_cmp(op: str, v: float) -> "Interval":
        """The solution set of `x <op> v` for op in eq/lt/le/gt/ge
        (`ne` has no interval form — callers handle it as a point
        complement)."""
        if op == "eq":
            return Interval.point(v)
        if op == "lt":
            return Interval(hi=v, hi_strict=True)
        if op == "le":
            return Interval(hi=v)
        if op == "gt":
            return Interval(lo=v, lo_strict=True)
        if op == "ge":
            return Interval(lo=v)
        raise ValueError(f"no interval form for comparison op {op!r}")

    # -- lattice ops ---------------------------------------------------------

    def narrow(self, op: str, v: float) -> "Interval":
        """Conjoin one ge/gt/le/lt bound. A bound only replaces the
        current one when it is tighter: larger (lo) / smaller (hi), or
        equal-but-strict over equal-but-non-strict."""
        lo, lo_strict, hi, hi_strict = self.lo, self.lo_strict, self.hi, self.hi_strict
        if op in ("ge", "gt"):
            strict = op == "gt"
            if v > lo or (v == lo and strict and not lo_strict):
                lo, lo_strict = v, strict
        elif op in ("le", "lt"):
            strict = op == "lt"
            if v < hi or (v == hi and strict and not hi_strict):
                hi, hi_strict = v, strict
        else:
            raise ValueError(f"cannot narrow with comparison op {op!r}")
        return Interval(lo, lo_strict, hi, hi_strict)

    def intersect(self, other: "Interval") -> "Interval":
        out = self
        out = out.narrow("gt" if other.lo_strict else "ge", other.lo)
        out = out.narrow("lt" if other.hi_strict else "le", other.hi)
        return out

    # -- predicates ----------------------------------------------------------

    @property
    def is_empty(self) -> bool:
        if self.lo > self.hi:
            return True
        return self.lo == self.hi and (self.lo_strict or self.hi_strict)

    @property
    def is_point(self) -> bool:
        return self.lo == self.hi and not (self.lo_strict or self.hi_strict)

    def contains_point(self, v: float) -> bool:
        if v < self.lo or (v == self.lo and self.lo_strict):
            return False
        if v > self.hi or (v == self.hi and self.hi_strict):
            return False
        return True

    def contains(self, other: "Interval") -> bool:
        """self is a superset of other (empty `other` is contained in
        anything)."""
        if other.is_empty:
            return True
        lower_ok = self.lo < other.lo or (
            self.lo == other.lo and (not self.lo_strict or other.lo_strict)
        )
        upper_ok = self.hi > other.hi or (
            self.hi == other.hi and (not self.hi_strict or other.hi_strict)
        )
        return lower_ok and upper_ok

    def disjoint(self, other: "Interval") -> bool:
        return self.intersect(other).is_empty


__all__ = ["Interval"]

"""Plan linting: static analysis over Check/Analysis plans.

Runs before any scan, against a `SchemaInfo` only:

* per-analyzer: unresolved columns (DQ101, with did-you-mean), static
  precondition failures — wrong column types, bad parameters — via the
  analyzers' own `preconditions()` run on a ZERO-ROW schema table
  (DQ102/DQ110), expression problems in `where`/Compliance predicates
  (DQ100..DQ105), invalid PatternMatch regexes (DQ103);
* per-predicate: constant-foldable filters (DQ205), unsatisfiable or
  NULL-escape-only predicates (DQ204);
* cross-plan: duplicate analyzers (DQ202), contradictory must-hold
  constraints like isComplete(c) + satisfies("c IS NULL") (DQ203), and
  where-clauses that are semantically identical but textually different,
  which silently split the fused-scan batching groups (DQ206).
"""

from __future__ import annotations

import re as _re
from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple

from deequ_tpu.core.exceptions import (
    NoSuchColumnException,
    WrongColumnTypeException,
)
from deequ_tpu.data.expr import (
    Bin,
    Col,
    ExpressionParseError,
    IsNull,
    Node,
    normalize_expression,
    parse,
)
from deequ_tpu.lint.diagnostics import Diagnostic, LintReport, Severity
from deequ_tpu.lint.fold import fold_to_constant, satisfiability
from deequ_tpu.lint.schema import SchemaInfo
from deequ_tpu.lint.typecheck import analyze_expression

_MAX_PAIRWISE_PREDICATES = 32


def _analyzer_columns(analyzer) -> List[str]:
    cols: List[str] = []
    col = getattr(analyzer, "column", None)
    if isinstance(col, str):
        cols.append(col)
    for attr in ("first_column", "second_column"):
        v = getattr(analyzer, attr, None)
        if isinstance(v, str):
            cols.append(v)
    multi = getattr(analyzer, "columns", None)
    if isinstance(multi, (list, tuple)):
        cols.extend(c for c in multi if isinstance(c, str))
    return cols


def lint_expression_use(
    expression: str,
    schema: SchemaInfo,
    subject: Optional[str] = None,
    role: str = "predicate",
) -> List[Diagnostic]:
    """Full static pass over one expression string: parse + typecheck +
    constant-fold + satisfiability."""
    typed, diags = analyze_expression(expression, schema)
    for d in diags:
        d.subject = subject
    if typed is None:
        return diags

    if typed.kind == "str":
        diags.append(
            Diagnostic(
                "DQ102",
                Severity.WARNING,
                f"{role} evaluates to a string, not a boolean",
                source=expression,
                subject=subject,
            )
        )

    # skip fold/sat when the expression has unresolved columns — verdicts
    # against a half-resolved tree would be noise on top of the DQ101s
    if any(d.code == "DQ101" for d in diags):
        return diags

    try:
        ast = parse(expression)
    except ExpressionParseError:
        return diags

    folded = fold_to_constant(ast)
    if folded is not None:
        _, value = folded
        truth = value is not None and bool(value)
        if truth:
            diags.append(
                Diagnostic(
                    "DQ205",
                    Severity.WARNING,
                    f"{role} is constant TRUE — it never filters or fails "
                    "anything",
                    source=expression,
                    subject=subject,
                )
            )
        else:
            diags.append(
                Diagnostic(
                    "DQ204",
                    Severity.ERROR,
                    f"{role} is constant "
                    f"{'NULL' if value is None else 'FALSE'} — no row can "
                    "ever satisfy it",
                    source=expression,
                    subject=subject,
                )
            )
        return diags

    verdict = satisfiability(ast, schema)
    if verdict == "unsat":
        diags.append(
            Diagnostic(
                "DQ204",
                Severity.ERROR,
                f"{role} is unsatisfiable — no row can ever satisfy it",
                source=expression,
                subject=subject,
            )
        )
    elif verdict == "null-only":
        diags.append(
            Diagnostic(
                "DQ204",
                Severity.ERROR,
                f"{role} is satisfiable only by NULL rows — its non-NULL "
                "range is empty (check the bounds)",
                source=expression,
                subject=subject,
            )
        )
    return diags


def lint_analyzer(analyzer, schema: SchemaInfo) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    subject = repr(analyzer)

    missing: List[str] = []
    for col in _analyzer_columns(analyzer):
        if not schema.has(col):
            missing.append(col)
            diags.append(
                Diagnostic(
                    "DQ101",
                    Severity.ERROR,
                    f"unresolved column {col!r}",
                    subject=subject,
                    suggestion=schema.suggest(col),
                )
            )

    # run the analyzer's own preconditions against a zero-row table with
    # this schema: wrong-type and bad-parameter failures surface with the
    # exact same exception text a real scan would produce, but statically
    try:
        empty = schema.empty_table()
        for check in analyzer.preconditions():
            try:
                check(empty)
            except NoSuchColumnException:
                continue  # already reported as DQ101 above
            except WrongColumnTypeException as e:
                diags.append(
                    Diagnostic(
                        "DQ102", Severity.ERROR, str(e), subject=subject
                    )
                )
            except Exception as e:  # noqa: BLE001 — any precondition failure
                diags.append(
                    Diagnostic(
                        "DQ110", Severity.ERROR, str(e), subject=subject
                    )
                )
    except Exception:  # noqa: BLE001 — lint must never crash the run
        pass

    pattern = getattr(analyzer, "pattern", None)
    if isinstance(pattern, str):
        try:
            _re.compile(pattern)
        except _re.error as e:
            diags.append(
                Diagnostic(
                    "DQ103",
                    Severity.ERROR,
                    f"invalid pattern regex {pattern!r}: {e}",
                    subject=subject,
                )
            )

    predicate = getattr(analyzer, "predicate", None)
    if isinstance(predicate, str):
        diags.extend(
            lint_expression_use(
                predicate, schema, subject=subject, role="compliance predicate"
            )
        )

    where = getattr(analyzer, "where", None)
    if isinstance(where, str):
        diags.extend(
            lint_expression_use(where, schema, subject=subject, role="where filter")
        )

    return diags


# -- cross-plan checks -------------------------------------------------------


def _constraint_analyzers(checks: Sequence) -> List[Tuple[object, object]]:
    """(constraint, analyzer) pairs in plan order, decorators unwrapped."""
    from deequ_tpu.constraints.constraint import (
        AnalysisBasedConstraint,
        ConstraintDecorator,
    )

    out = []
    for check in checks:
        for constraint in getattr(check, "constraints", []):
            inner = (
                constraint.inner
                if isinstance(constraint, ConstraintDecorator)
                else constraint
            )
            if isinstance(inner, AnalysisBasedConstraint):
                out.append((constraint, inner))
    return out


def _must_hold_predicates(
    checks: Sequence,
) -> List[Tuple[object, Optional[str], Node]]:
    """(constraint, where, predicate-AST) for constraints that assert the
    predicate holds on EVERY row: Compliance/Completeness with the
    default is-one assertion. Completeness(c) is `c IS NOT NULL`."""
    from deequ_tpu.checks.check import is_one

    out = []
    for constraint, inner in _constraint_analyzers(checks):
        if inner.assertion is not is_one:
            continue
        analyzer = inner.analyzer
        predicate = getattr(analyzer, "predicate", None)
        where = getattr(analyzer, "where", None)
        if isinstance(predicate, str):
            try:
                out.append((constraint, where, parse(predicate)))
            except ExpressionParseError:
                continue
        elif type(analyzer).__name__ == "Completeness":
            column = getattr(analyzer, "column", None)
            if isinstance(column, str):
                out.append((constraint, where, IsNull(Col(column), negated=True)))
    return out


def lint_plan(
    schema: SchemaInfo,
    checks: Sequence = (),
    required_analyzers: Sequence = (),
) -> LintReport:
    report = LintReport()

    # gather analyzers in plan order: explicit ones, then per-constraint
    occurrences: List[object] = list(required_analyzers)
    occurrences.extend(a for _, a in
                       ((c, inner.analyzer) for c, inner in
                        _constraint_analyzers(checks)))

    seen = set()
    unique = []
    for a in occurrences:
        if a not in seen:
            seen.add(a)
            unique.append(a)

    for analyzer in unique:
        report.extend(lint_analyzer(analyzer, schema))

    # DQ202 — the runner dedupes these, but a duplicate usually means two
    # constraints were meant to differ and don't
    counts = Counter(occurrences)
    for analyzer, n in counts.items():
        if n > 1:
            report.extend(
                [
                    Diagnostic(
                        "DQ202",
                        Severity.WARNING,
                        f"analyzer requested {n} times; the duplicates share "
                        "one computation",
                        subject=repr(analyzer),
                    )
                ]
            )

    # DQ203 — pairwise conjunction of must-hold predicates per where-group
    must_hold = _must_hold_predicates(checks)
    if len(must_hold) <= _MAX_PAIRWISE_PREDICATES:
        by_where: Dict[Optional[str], List] = {}
        for item in must_hold:
            by_where.setdefault(item[1], []).append(item)
        for group in by_where.values():
            for i in range(len(group)):
                for j in range(i + 1, len(group)):
                    ci, _, pi = group[i]
                    cj, _, pj = group[j]
                    verdict = satisfiability(Bin("and", pi, pj), schema)
                    if verdict in ("unsat", "null-only"):
                        report.extend(
                            [
                                Diagnostic(
                                    "DQ203",
                                    Severity.ERROR,
                                    "contradictory constraints: "
                                    f"{ci!r} and {cj!r} cannot both hold "
                                    "on any row",
                                )
                            ]
                        )

    # DQ206 — semantically identical wheres with different spelling split
    # the fused-scan (where, cap, dtype) batching groups
    where_texts: Dict[str, set] = {}
    for analyzer in unique:
        where = getattr(analyzer, "where", None)
        if not isinstance(where, str):
            continue
        try:
            key = normalize_expression(where)
        except ExpressionParseError:
            continue
        where_texts.setdefault(key, set()).add(where)
    for key, texts in where_texts.items():
        if len(texts) > 1:
            rendered = ", ".join(repr(t) for t in sorted(texts))
            report.extend(
                [
                    Diagnostic(
                        "DQ206",
                        Severity.WARNING,
                        "where-clauses differ only by formatting and will "
                        f"not share one fused scan group: {rendered}",
                    )
                ]
            )

    return report


def resolve_validation_mode(mode: Optional[str]) -> str:
    """Explicit argument wins, then env DEEQU_TPU_VALIDATE, then lenient.
    Unknown values degrade to lenient — validation must never break a
    run because of a typo'd knob."""
    import os

    resolved = mode or os.environ.get("DEEQU_TPU_VALIDATE") or "lenient"
    resolved = resolved.strip().lower()
    if resolved not in ("strict", "lenient", "off"):
        return "lenient"
    return resolved


def validate_plan(
    schema: SchemaInfo,
    checks: Sequence = (),
    required_analyzers: Sequence = (),
    mode: str = "lenient",
    num_rows: Optional[int] = None,
    batch_size: Optional[int] = None,
    streaming: bool = False,
    stream_batch_rows: Optional[int] = None,
    row_groups: Optional[Sequence] = None,
    partitions: Optional[Sequence] = None,
    deadline_s: Optional[float] = None,
    sharing_with: Optional[Sequence] = None,
) -> LintReport:
    """Run the full static pass: semantic lints (DQ1xx/DQ2xx) plus the
    cost analyzer's performance lints (DQ3xx, lint/explain.py). The
    computed `PlanCost` is attached as `report.plan_cost`. mode:
    'strict' raises one aggregated PlanValidationError when any
    error-severity diagnostic exists (warnings ride along in it);
    'lenient' returns the report for the caller to attach; 'off' skips.

    `sharing_with` — the analyzer list of a candidate superset scan:
    runs the plan-subsumption prover (lint/subsume.py) and attaches the
    DQ321/DQ322 sharing diagnostics, exactly like the DQ31x lints."""
    from deequ_tpu.lint.diagnostics import PlanValidationError

    if mode == "off":
        return LintReport()
    report = lint_plan(schema, checks, required_analyzers)
    try:
        from deequ_tpu.lint.cost import analyze_plan
        from deequ_tpu.lint.explain import _plan_analyzers, cost_diagnostics

        plan = _plan_analyzers(required_analyzers, checks)
        report.plan_cost = analyze_plan(
            plan,
            schema,
            num_rows=num_rows,
            batch_size=batch_size,
            streaming=streaming,
            stream_batch_rows=stream_batch_rows,
            row_groups=row_groups,
            partitions=partitions,
            deadline_s=deadline_s,
        )
        report.extend(cost_diagnostics(report.plan_cost, plan, schema))
        if sharing_with is not None:
            from deequ_tpu.lint.explain import sharing_diagnostics
            from deequ_tpu.lint.subsume import prove_subsumption

            proof = prove_subsumption(plan, list(sharing_with), schema)
            report.extend(sharing_diagnostics(proof, plan))
    except Exception:  # noqa: BLE001 — cost lint must never break a run
        report.plan_cost = None
    if mode == "strict" and report.errors:
        raise PlanValidationError(report.diagnostics)
    return report

"""Row-group pruning: a three-valued abstract interpreter over parquet
row-group statistics.

Per (where-predicate, row group) the interpreter proves one of

* ``all-false`` — no row in the group can satisfy the predicate: if
  EVERY member of the fused pass filters with an all-false where, the
  group is skipped before decode (it never touches Arrow),
* ``all-true``  — every row satisfies the predicate: the runtime swaps
  the filter's input spec for a constant mask, so the filter columns
  need not be decoded and the mask elides on the wire,
* ``unknown``   — decode and filter at runtime, exactly as without
  pruning.

The domain is the interval lattice shared with DQ204 (lint/interval.py)
applied to the DNF expansion from lint/fold.py: a clause (AND of atoms)
is all-false when any atom is, all-true when all atoms are; a predicate
(OR of clauses) is all-true when any clause is, all-false when all are.

Soundness is anchored to ENGINE semantics, not SQL's:

* Comparisons evaluate FALSE on NULL rows (the evaluator masks
  ``& ~null``), so an all-null group falsifies every comparison.
* ``Table.from_arrow`` folds NaN float values into the null mask at
  decode. Parquet statistics ignore NaN, so for DOUBLE/DECIMAL columns
  the file's null_count is only a LOWER bound on runtime nulls: no
  all-true verdict may rest on "null_count == 0" for those types, and
  no comparison over them ever proves all-true (a hidden NaN row would
  evaluate false). All-false verdicts stay sound: hidden NaN rows are
  runtime-null and evaluate false anyway.
* String min/max are never consulted (writers may truncate them); only
  null_count reasoning applies to STRING columns.
* min/max that fail float conversion or are themselves NaN count as
  absent.

Purity contract (enforced by the PUSHDOWN rule in tools/lint.py): this
module never imports pyarrow or opens files. Statistics arrive as plain
``RowGroupStats`` records; ``ParquetSource.row_group_stats()`` is the
single reader.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Mapping, Optional, Sequence, Set, Tuple

from deequ_tpu.data.expr import (
    Between,
    Bin,
    Col,
    InList,
    IsNull,
    Node,
    Un,
    parse,
)
from deequ_tpu.data.table import ColumnType
from deequ_tpu.lint.fold import Atom, Branch, cmp_atom, const_fold, dnf_branches
from deequ_tpu.lint.interval import Interval
from deequ_tpu.lint.schema import SchemaInfo

ALL_TRUE = "all-true"
ALL_FALSE = "all-false"
UNKNOWN = "unknown"

#: parquet null_count equals the engine's runtime null count only for
#: these types — DOUBLE/DECIMAL fold NaN into the null mask at decode
#: (see module docstring), TIMESTAMP rides the conservative side.
_EXACT_NULLS = frozenset(
    (ColumnType.LONG, ColumnType.STRING, ColumnType.BOOLEAN)
)

#: min/max statistics are consulted for these types only.
_RANGE_TYPES = frozenset((ColumnType.LONG, ColumnType.DOUBLE))

_CMP_OPS = ("eq", "ne", "lt", "le", "gt", "ge")


# -- statistics records ------------------------------------------------------


@dataclass(frozen=True)
class ColumnStats:
    """Raw per-column-chunk statistics. None = the writer did not record
    the stat (or recorded it unusably); absence degrades verdicts to
    unknown, never to wrong.

    The reader-eligibility fields (physical_type onward) carry the
    footer metadata the native parquet reader's planner verdict keys
    off (ops/fused.py:classify_reader_columns); they default to None so
    pruning-only callers construct stats exactly as before, and absence
    disqualifies a chunk from the native path, never mis-qualifies it."""

    min_value: Optional[object] = None
    max_value: Optional[object] = None
    null_count: Optional[int] = None
    physical_type: Optional[str] = None
    codec: Optional[str] = None
    encodings: Optional[Tuple[str, ...]] = None
    chunk_offset: Optional[int] = None
    chunk_bytes: Optional[int] = None
    num_values: Optional[int] = None
    max_def_level: Optional[int] = None
    max_rep_level: Optional[int] = None
    #: page-placement fields for the encoded-fold planner verdict
    #: (ops/fused.py:classify_encfold_columns): a chunk without a
    #: recorded dictionary page cannot be all-dictionary-coded, so its
    #: column falls off the run-fold path statically.
    data_page_offset: Optional[int] = None
    dictionary_page_offset: Optional[int] = None


@dataclass(frozen=True)
class RowGroupStats:
    index: int
    num_rows: int
    columns: Mapping[str, ColumnStats]


def types_from_schema(schema: SchemaInfo) -> Dict[str, ColumnType]:
    return {f.name: f.ctype for f in schema.fields}


def _bounds(stats: ColumnStats) -> Optional[Tuple[float, float]]:
    """Usable numeric [min, max] of a chunk, or None. NaN bounds (legacy
    writers stored them for NaN-polluted columns) count as absent."""
    if stats.min_value is None or stats.max_value is None:
        return None
    try:
        lo = float(stats.min_value)  # type: ignore[arg-type]
        hi = float(stats.max_value)  # type: ignore[arg-type]
    except (TypeError, ValueError):
        return None
    if math.isnan(lo) or math.isnan(hi):
        return None
    return lo, hi


# -- atom/clause/predicate verdicts ------------------------------------------


def _atom_verdict(
    atom: Atom,
    group: RowGroupStats,
    types: Mapping[str, ColumnType],
) -> str:
    tag = atom[0]
    if tag == "const":
        return ALL_TRUE if atom[1] else ALL_FALSE
    if tag == "opaque":
        return UNKNOWN

    if tag == "null":
        _, col, must_null = atom
        stats = group.columns.get(col)
        ctype = types.get(col)
        if stats is None or stats.null_count is None or ctype is None:
            return UNKNOWN
        nulls = int(stats.null_count)
        rows = group.num_rows
        exact = ctype in _EXACT_NULLS
        if must_null:
            if nulls >= rows:
                return ALL_TRUE  # runtime nulls ⊇ parquet nulls
            if nulls == 0 and exact:
                return ALL_FALSE
            return UNKNOWN
        if nulls >= rows:
            return ALL_FALSE
        if nulls == 0 and exact:
            return ALL_TRUE
        return UNKNOWN

    if tag == "cmp":
        _, col, op, v = atom
        stats = group.columns.get(col)
        ctype = types.get(col)
        rows = group.num_rows
        if rows == 0:
            # the scan materializes no row from an empty group; treat as
            # all-false so it prunes
            return ALL_FALSE
        if stats is None or ctype is None:
            return UNKNOWN
        if stats.null_count is not None and int(stats.null_count) >= rows:
            # comparisons are FALSE on null rows — any type
            return ALL_FALSE
        if isinstance(v, str) or ctype not in _RANGE_TYPES:
            return UNKNOWN
        bounds = _bounds(stats)
        if bounds is None:
            return UNKNOWN
        value = float(v)
        domain = Interval.closed(bounds[0], bounds[1])
        no_nulls = (
            ctype is ColumnType.LONG and stats.null_count == 0
        )  # DOUBLE never qualifies: hidden NaN ⇒ runtime null ⇒ false
        if op == "ne":
            if domain.is_point and domain.lo == value:
                return ALL_FALSE
            if no_nulls and not domain.contains_point(value):
                return ALL_TRUE
            return UNKNOWN
        pred = Interval.from_cmp(op, value)
        if domain.disjoint(pred):
            return ALL_FALSE
        if no_nulls and pred.contains(domain):
            return ALL_TRUE
        return UNKNOWN

    return UNKNOWN


def _clause_verdict(
    branch: Branch,
    group: RowGroupStats,
    types: Mapping[str, ColumnType],
) -> str:
    saw_unknown = False
    for atom in branch:
        verdict = _atom_verdict(atom, group, types)
        if verdict == ALL_FALSE:
            return ALL_FALSE
        if verdict == UNKNOWN:
            saw_unknown = True
    return UNKNOWN if saw_unknown else ALL_TRUE


def predicate_verdict(
    branches: Sequence[Branch],
    group: RowGroupStats,
    types: Mapping[str, ColumnType],
) -> str:
    saw_unknown = False
    for branch in branches:
        verdict = _clause_verdict(branch, group, types)
        if verdict == ALL_TRUE:
            return ALL_TRUE
        if verdict == UNKNOWN:
            saw_unknown = True
    return UNKNOWN if saw_unknown else ALL_FALSE


# -- pushdown eligibility (DQ310) --------------------------------------------


def _first_blocker(
    node: Node, types: Mapping[str, ColumnType]
) -> Optional[Tuple[Node, str]]:
    """First subexpression with no statistics form, with a reason — the
    DQ310 caret anchors on its source span. None = every leaf of the
    predicate maps to a stats-decidable atom."""
    ok, _ = const_fold(node)
    if ok:
        return None
    if isinstance(node, Un) and node.op == "not":
        return _first_blocker(node.x, types)
    if isinstance(node, Bin) and node.op in ("and", "or"):
        return _first_blocker(node.l, types) or _first_blocker(node.r, types)
    if isinstance(node, Bin) and node.op in _CMP_OPS:
        atom = cmp_atom(node)
        if atom is None:
            return node, "not a column-vs-literal comparison"
        return _col_cmp_blocker(node, atom[1], types)
    if isinstance(node, IsNull):
        if isinstance(node.x, Col):
            return None
        return node, "IS NULL over a computed expression"
    if isinstance(node, Between):
        if not isinstance(node.x, Col):
            return node, "BETWEEN over a computed expression"
        for bound in (node.lo, node.hi):
            ok, v = const_fold(bound)
            if not ok or v is None or isinstance(v, bool):
                return node, "non-literal BETWEEN bound"
        return _col_cmp_blocker(node, node.x.name, types)
    if isinstance(node, InList):
        if not isinstance(node.x, Col):
            return node, "IN over a computed expression"
        for item in node.items:
            ok, v = const_fold(item)
            if not ok or v is None or isinstance(v, bool):
                return node, "non-literal IN item"
            if isinstance(v, str):
                return (
                    node,
                    "string min/max statistics are untrustworthy "
                    "(writers may truncate them)",
                )
        return _col_cmp_blocker(node, node.x.name, types)
    return node, "expression has no statistics form"


def _col_cmp_blocker(
    node: Node, col: str, types: Mapping[str, ColumnType]
) -> Optional[Tuple[Node, str]]:
    ctype = types.get(col)
    if ctype is None:
        return node, f"column '{col}' not in the scanned schema"
    if ctype is ColumnType.STRING:
        return (
            node,
            "string min/max statistics are untrustworthy "
            "(writers may truncate them)",
        )
    if ctype not in _RANGE_TYPES:
        return node, f"{ctype.name} columns carry no usable min/max statistics"
    return None


def _atom_columns(branches: Sequence[Branch]) -> Set[str]:
    cols: Set[str] = set()
    for branch in branches:
        for atom in branch:
            if atom[0] in ("cmp", "null"):
                cols.add(atom[1])
    return cols


# -- prune plan --------------------------------------------------------------


@dataclass(frozen=True)
class PredicatePrune:
    """One distinct where text's static outcome across all row groups."""

    where: str
    eligible: bool
    reason: Optional[str]
    span: Optional[Tuple[int, int]]
    verdicts: Tuple[str, ...]  # aligned with the file's row-group order


def _slices(rows: int, size: int) -> List[int]:
    return [min(size, rows - start) for start in range(0, rows, size)]


@dataclass(frozen=True)
class PrunePlan:
    """Static decision for one fused scan over one parquet file."""

    group_rows: Tuple[int, ...]
    predicates: Tuple[PredicatePrune, ...]
    #: every fused member filters (no bare where=None member) — only then
    #: may any group be skipped
    prunable: bool
    skip: FrozenSet[int]
    #: the statistics proved every group all-false for every predicate.
    #: One sentinel group still decodes (see build_prune_plan) so the
    #: filtered-empty result stays bit-identical to the unpruned scan;
    #: DQ311 reports the proof itself.
    proven_empty: bool = False

    # -- aggregates ----------------------------------------------------------

    @property
    def total_groups(self) -> int:
        return len(self.group_rows)

    @property
    def skipped_groups(self) -> int:
        return len(self.skip)

    @property
    def decoded_groups(self) -> int:
        return self.total_groups - self.skipped_groups

    @property
    def skipped_rows(self) -> int:
        return sum(self.group_rows[g] for g in self.skip)

    @property
    def decoded_rows(self) -> int:
        return sum(self.group_rows) - self.skipped_rows

    def elided_wheres(self) -> Tuple[str, ...]:
        """Where texts proven all-true on every SURVIVING group: their
        mask spec can be swapped for a constant (filter columns never
        decode, the mask elides on the wire)."""
        surviving = [
            g for g in range(self.total_groups) if g not in self.skip
        ]
        if not surviving:
            return ()
        return tuple(
            p.where
            for p in self.predicates
            if p.eligible
            and all(p.verdicts[g] == ALL_TRUE for g in surviving)
        )

    # -- decode replay -------------------------------------------------------

    def predicted_batch_rows(
        self, batch_size: int, *, pruned: bool = True
    ) -> Tuple[int, ...]:
        """Per-batch row counts of ParquetSource._iter_tables over the
        (optionally pruned) groups — an exact replay of its tiny-group
        coalescing, so EXPLAIN's batch count and first-batch bytes match
        observed traces. Empty result = the zero-batch case; the stream
        then yields its single empty fallback batch."""
        size = max(1, int(batch_size))
        tiny = max(1, size // 4)
        out: List[int] = []
        pending = 0
        for g, rows in enumerate(self.group_rows):
            if pruned and g in self.skip:
                continue
            if rows < tiny:
                pending += rows
                if pending < size:
                    continue
                merged, pending = pending, 0
                out.extend(_slices(merged, size))
            else:
                if pending:
                    out.extend(_slices(pending, size))
                    pending = 0
                out.extend(_slices(rows, size))
        if pending:
            out.extend(_slices(pending, size))
        return tuple(out)


def build_prune_plan(
    wheres: Sequence[Optional[str]],
    groups: Sequence[RowGroupStats],
    types: Mapping[str, ColumnType],
) -> PrunePlan:
    """Evaluate every distinct where text over every row group.

    `wheres` is one entry PER FUSED MEMBER (None = the member scans
    unfiltered). A group is skipped only when every member filters and
    every distinct predicate is proven all-false on it — an unfiltered
    member reads every group, so nothing may be skipped then.
    """
    prunable = len(wheres) > 0 and all(w is not None for w in wheres)
    texts: List[str] = []
    seen: Set[str] = set()
    for w in wheres:
        if w is not None and w not in seen:
            seen.add(w)
            texts.append(w)

    n = len(groups)
    predicates: List[PredicatePrune] = []
    for text in texts:
        predicates.append(_analyze_predicate(text, groups, types))

    skip: FrozenSet[int] = frozenset(
        g
        for g in range(n)
        if prunable
        and predicates
        and all(p.verdicts[g] == ALL_FALSE for p in predicates)
    )
    proven_empty = n > 0 and len(skip) == n
    if proven_empty:
        # never skip EVERYTHING: a scan that yields no batch falls back
        # to one empty batch, and analyzer states from a 0-row input are
        # not the same as states from real rows that all fail the filter
        # (empty-state vs 0-count). Decoding one sentinel group — the
        # cheapest — keeps the result bit-identical to the unpruned scan
        # while still skipping n-1 groups; DQ311 surfaces the proof.
        keep = min(range(n), key=lambda g: (groups[g].num_rows, g))
        skip = frozenset(g for g in skip if g != keep)
    return PrunePlan(
        group_rows=tuple(int(g.num_rows) for g in groups),
        predicates=tuple(predicates),
        prunable=prunable,
        skip=skip,
        proven_empty=proven_empty,
    )


def _analyze_predicate(
    text: str,
    groups: Sequence[RowGroupStats],
    types: Mapping[str, ColumnType],
) -> PredicatePrune:
    unknown_everywhere = (UNKNOWN,) * len(groups)
    try:
        ast = parse(text)
    except Exception:  # noqa: BLE001 — the runtime surfaces parse errors
        return PredicatePrune(
            where=text,
            eligible=False,
            reason="predicate does not parse",
            span=None,
            verdicts=unknown_everywhere,
        )

    branches = dnf_branches(ast)
    if branches is None or not branches:
        return PredicatePrune(
            where=text,
            eligible=False,
            reason="predicate too complex (DNF branch cap)",
            span=None,
            verdicts=unknown_everywhere,
        )

    eligible = True
    reason: Optional[str] = None
    span: Optional[Tuple[int, int]] = None
    blocker = _first_blocker(ast, types)
    if blocker is not None:
        eligible = False
        reason = blocker[1]
        span = blocker[0].span

    verdicts = tuple(
        predicate_verdict(branches, group, types) for group in groups
    )

    if eligible and groups and all(v == UNKNOWN for v in verdicts):
        # structurally fine but undecidable everywhere — when that is
        # because the file carries no statistics at all for a referenced
        # column, say so (the other cause, genuinely overlapping ranges,
        # is not a defect and stays silent)
        for col in sorted(_atom_columns(branches)):
            if all(
                group.columns.get(col) is None
                or (
                    _bounds(group.columns[col]) is None
                    and group.columns[col].null_count is None
                )
                for group in groups
            ):
                eligible = False
                reason = f"no statistics recorded for column '{col}'"
                break

    return PredicatePrune(
        where=text,
        eligible=eligible,
        reason=reason,
        span=span,
        verdicts=verdicts,
    )


__all__ = [
    "ALL_TRUE",
    "ALL_FALSE",
    "UNKNOWN",
    "ColumnStats",
    "RowGroupStats",
    "PredicatePrune",
    "PrunePlan",
    "build_prune_plan",
    "predicate_verdict",
    "types_from_schema",
]

"""Schema model the static analyzer resolves names and types against.

Built from a live `Table`, from `applicability.SchemaField`s, or from
explicit (name, ctype, nullable) triples. Also manufactures a ZERO-ROW
Table with the right dtypes so existing `Preconditions` closures can run
statically — same exception texts as a real scan, no data touched.
"""

from __future__ import annotations

import difflib
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np

from deequ_tpu.data.table import Column, ColumnType, NUMPY_BACKING, Table


@dataclass(frozen=True)
class FieldInfo:
    name: str
    ctype: ColumnType
    # True = the column MAY contain nulls. The analyzer is conservative:
    # over-reporting nullability is safe, under-reporting is not.
    nullable: bool = True
    # Optional cardinality hint (e.g. from profiling) the cost analyzer
    # uses to estimate grouping-pass group counts / spill risk (DQ302).
    # None = unknown: no cardinality-based diagnostics fire.
    approx_distinct: Optional[int] = None


class SchemaInfo:
    def __init__(self, fields: Sequence[FieldInfo]):
        self.fields: List[FieldInfo] = list(fields)
        self._by_name: Dict[str, FieldInfo] = {f.name: f for f in self.fields}
        self._empty_table: Optional[Table] = None

    # -- constructors --------------------------------------------------------

    @classmethod
    def from_table(cls, table: Table) -> "SchemaInfo":
        fields = []
        for name, ctype in table.schema:
            col = table.column(name)
            fields.append(FieldInfo(name, ctype, bool((~col.valid).any())))
        return cls(fields)

    @classmethod
    def from_schema_fields(cls, schema_fields: Sequence) -> "SchemaInfo":
        """From applicability.SchemaField (name/ctype/nullable attrs)."""
        return cls(
            [FieldInfo(f.name, f.ctype, bool(f.nullable)) for f in schema_fields]
        )

    # -- lookup --------------------------------------------------------------

    def has(self, name: str) -> bool:
        return name in self._by_name

    def field(self, name: str) -> Optional[FieldInfo]:
        return self._by_name.get(name)

    def names(self) -> List[str]:
        return [f.name for f in self.fields]

    def suggest(self, name: str) -> Optional[str]:
        matches = difflib.get_close_matches(name, self.names(), n=1, cutoff=0.6)
        return matches[0] if matches else None

    # -- static precondition support ----------------------------------------

    def empty_table(self) -> Table:
        """Zero-row Table with this schema's dtypes: lets analyzer
        `preconditions()` (has_column / is_numeric / is_string / param
        checks) run unchanged with zero data scanned. Cached — lint runs
        it once per analyzer."""
        if self._empty_table is not None:
            return self._empty_table
        columns = []
        for f in self.fields:
            backing = NUMPY_BACKING[f.ctype]
            values = np.empty(0, dtype=backing)
            columns.append(
                Column(f.name, f.ctype, values, np.zeros(0, dtype=bool))
            )
        self._empty_table = Table(columns)
        return self._empty_table

"""Plan-subsumption prover: statically prove "suite A ⊆ scan S".

Given two validated plans over the same dataset fingerprint, decide
whether every metric suite A needs can be read off the folded states of
a (superset) fused scan S — without scanning a row. The verdict is one
of

* ``CONTAINED`` — every analyzer in A appears in S verbatim (analyzer
  identity is (type, repr), the engine's own equality), and the plan
  environments agree component-wise. S's folded per-family states fan
  back out to A bit-identically over the state semigroup.
* ``CONTAINED_WITH_RESIDUAL`` — as above, but at least one obligation
  matched up to the family-kernel equivalence: the same analyzer modulo
  its ``where`` spelling, with the two predicates proven EQUIVALENT by
  mutual three-valued implication over the schema (the same
  NaN/NULL-sound Kleene semantics as lint/pushdown.py — comparisons
  evaluate FALSE on NULL rows, and NaN folds into the null mask at
  decode). The states are still exact; only the (where, cap) family
  bucket spelling differs, so the proof carries the residual.
* ``INCOMPARABLE`` — any unmatched analyzer, any unprovable predicate
  implication, or ANY plan-environment component mismatch
  (placement / compute dtype / batch size / batch rows / fold
  variant). Signature components are never silently merged: a
  fold-variant or dtype mismatch changes the fold arithmetic, so the
  scan's states are not A's states even when the analyzer sets agree.

One-way implication (A's predicate implied by S's but not conversely)
is NEVER containment: a state folded under a strictly weaker predicate
covers a superset of rows and cannot be narrowed after the fact. The
prover records the one-way fact only as a fall-off detail for the
DQ322 diagnostic.

The proof object is machine-checkable: ``SubsumptionProof.pin`` takes
the reprs of the analyzers that actually executed (from the traced run
or the resulting metric map) and returns drift counters that must all
be zero for the proof to be pinned against execution.

Purity contract (enforced by the SUBSUME rule in tools/lint.py): this
module imports only the expression AST and the lint lattice — never
jax, pyarrow, numpy, pandas, nor the service/ops/runner layers — and
opens no files. Callers construct ``PlanEnv`` from live runtime knobs;
the prover itself only compares the components it is handed.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

from deequ_tpu.data.expr import (
    Bin,
    ExpressionParseError,
    Node,
    Un,
    parse,
)
from deequ_tpu.lint.fold import satisfiability
from deequ_tpu.lint.schema import SchemaInfo

CONTAINED = "CONTAINED"
CONTAINED_WITH_RESIDUAL = "CONTAINED_WITH_RESIDUAL"
INCOMPARABLE = "INCOMPARABLE"

#: obligation kinds
EXACT = "exact"
EQUIVALENT_WHERE = "equivalent-where"
UNMATCHED = "unmatched"


@dataclass(frozen=True)
class PlanEnv:
    """The plan-signature components that change fold arithmetic (the
    same ones ``repository.states.plan_signature`` hashes). Two plans
    are only comparable when every component agrees — the prover treats
    any mismatch as INCOMPARABLE, never as mergeable."""

    placement: str = ""
    compute_dtype: str = ""
    batch_size: Optional[int] = None
    batch_rows: Optional[int] = None
    fold_variant: str = ""

    def components(self) -> Dict[str, Any]:
        return {
            "placement": self.placement,
            "compute_dtype": self.compute_dtype,
            "batch_size": self.batch_size,
            "batch_rows": self.batch_rows,
            "fold_variant": self.fold_variant,
        }

    def mismatches(self, other: "PlanEnv") -> List[str]:
        """Component-wise differences, rendered for the proof object."""
        out: List[str] = []
        mine, theirs = self.components(), other.components()
        for name in mine:
            if mine[name] != theirs[name]:
                out.append(f"{name}: {mine[name]!r} != {theirs[name]!r}")
        return out


@dataclass(frozen=True)
class Obligation:
    """One analyzer A needs, and how (whether) the scan discharges it."""

    analyzer: str  # repr of A's analyzer (the engine's identity)
    kind: str  # exact | equivalent-where | unmatched
    target: Optional[str] = None  # repr of the covering scan analyzer
    detail: str = ""
    # A's where text, for the DQ322 caret on the offending predicate
    where: Optional[str] = None

    @property
    def satisfied(self) -> bool:
        return self.kind in (EXACT, EQUIVALENT_WHERE)


@dataclass(frozen=True)
class SubsumptionProof:
    """The machine-checkable containment proof for one (A, S) pair."""

    verdict: str
    obligations: Tuple[Obligation, ...] = ()
    env_mismatches: Tuple[str, ...] = ()

    @property
    def contained(self) -> bool:
        return self.verdict in (CONTAINED, CONTAINED_WITH_RESIDUAL)

    def summary(self) -> str:
        """One line for EXPLAIN's ``sharing:`` rendering."""
        n = len(self.obligations)
        exact = sum(1 for o in self.obligations if o.kind == EXACT)
        equiv = sum(1 for o in self.obligations if o.kind == EQUIVALENT_WHERE)
        if self.env_mismatches:
            return (
                f"{self.verdict}: plan environments differ "
                f"({'; '.join(self.env_mismatches)})"
            )
        line = f"{self.verdict}: {exact}/{n} obligation(s) exact"
        if equiv:
            line += f", {equiv} equivalent-where"
        missing = [o for o in self.obligations if not o.satisfied]
        if missing:
            first = missing[0]
            why = first.detail or "no covering analyzer in the scan"
            line += f"; first fall-off: {first.analyzer} ({why})"
        return line

    def to_dict(self) -> Dict[str, Any]:
        return {
            "verdict": self.verdict,
            "env_mismatches": list(self.env_mismatches),
            "obligations": [
                {
                    "analyzer": o.analyzer,
                    "kind": o.kind,
                    "target": o.target,
                    "detail": o.detail,
                }
                for o in self.obligations
            ],
        }

    def pin(self, executed: Sequence[str]) -> Dict[str, int]:
        """Pin the proof against traced execution. ``executed`` is the
        reprs of the analyzers that actually ran in the scan (from the
        run's metric map or trace). All drift fields zero <=> every
        proven obligation's covering analyzer really executed and the
        proof claimed nothing it did not prove."""
        ran = set(executed)
        missing = sum(
            1
            for o in self.obligations
            if o.satisfied and o.target is not None and o.target not in ran
        )
        unproven = sum(1 for o in self.obligations if not o.satisfied)
        return {
            "obligations_unexecuted": missing,
            "obligations_unproven": unproven if self.contained else 0,
            "env_mismatches": len(self.env_mismatches) if self.contained else 0,
        }


# -- where-clause implication over the Kleene lattice -------------------------


def _parse_where(where: Optional[str]) -> Optional[Node]:
    """None (no filter) parses to None — handled as the constant-true
    predicate by the implication tests below."""
    if where is None:
        return None
    return parse(where)


def where_implies(
    a: Optional[str], b: Optional[str], schema: Optional[SchemaInfo] = None
) -> bool:
    """True when predicate ``a``'s filter mask is a subset of ``b``'s:
    no row evaluates TRUE under ``a`` and not under ``b``. Three-valued
    and NaN/NULL-sound exactly like lint/pushdown.py — NULL (and NaN,
    folded to null at decode) rows evaluate FALSE under every
    comparison, so they are excluded by both sides already. Parse
    failures prove nothing (returns False, never a wrong True)."""
    try:
        na, nb = _parse_where(a), _parse_where(b)
    except ExpressionParseError:
        return False
    if nb is None:
        return True  # everything is a subset of "no filter"
    if na is None:
        # constant-true implies b only when b is itself a tautology
        # over non-null rows: NOT b must admit no true row
        verdict = satisfiability(Un("not", nb), schema)
        return verdict in ("unsat", "null-only")
    verdict = satisfiability(Bin("and", na, Un("not", nb)), schema)
    return verdict in ("unsat", "null-only")


def wheres_equivalent(
    a: Optional[str], b: Optional[str], schema: Optional[SchemaInfo] = None
) -> bool:
    """Mutual implication: the two filter masks agree on every row.
    This — not one-way implication — is the bar for reusing a folded
    state across spellings: a state folded under a strictly weaker
    predicate covers extra rows and cannot be narrowed post hoc."""
    if a == b:
        return True
    return where_implies(a, b, schema) and where_implies(b, a, schema)


# -- analyzer matching --------------------------------------------------------


def _params_excluding_where(analyzer: Any) -> Optional[Dict[str, Any]]:
    """The analyzer's constructor surface minus the where spelling —
    the family-kernel identity ((column, cap, ...) bucket). None when
    the analyzer exposes no attribute dict (then only exact matches
    apply)."""
    try:
        params = dict(vars(analyzer))
    except TypeError:
        return None
    params.pop("where", None)
    return params


def _family_equivalent(a: Any, s: Any, schema: Optional[SchemaInfo]) -> bool:
    """Same analyzer modulo where, wheres provably equivalent."""
    if type(a) is not type(s):
        return False
    pa, ps = _params_excluding_where(a), _params_excluding_where(s)
    if pa is None or ps is None:
        return False
    try:
        if pa != ps:
            return False
    except Exception:  # noqa: BLE001 — incomparable params prove nothing
        return False
    return wheres_equivalent(
        getattr(a, "where", None), getattr(s, "where", None), schema
    )


def _near_miss_detail(a: Any, scan: Sequence[Any], schema: Optional[SchemaInfo]) -> str:
    """Why the nearest scan analyzer does NOT discharge the obligation —
    the DQ322 fall-off reason."""
    aw = getattr(a, "where", None)
    for s in scan:
        if type(s) is not type(a):
            continue
        pa, ps = _params_excluding_where(a), _params_excluding_where(s)
        if pa is None or ps is None or pa != ps:
            continue
        sw = getattr(s, "where", None)
        if where_implies(aw, sw, schema):
            return (
                f"where {aw!r} is implied by the scan's {sw!r} but not "
                "equivalent — the scan's folded state covers a superset "
                "of rows and cannot be narrowed"
            )
        return (
            f"where {aw!r} not provably equivalent to the scan's {sw!r} "
            "under three-valued NaN/NULL semantics"
        )
    for s in scan:
        if type(s) is type(a):
            return (
                f"nearest scan analyzer {s!r} differs in parameters, "
                "not only in where"
            )
    return "no scan analyzer of this type"


def prove_subsumption(
    suite: Sequence[Any],
    scan: Sequence[Any],
    schema: Optional[SchemaInfo] = None,
    *,
    suite_env: Optional[PlanEnv] = None,
    scan_env: Optional[PlanEnv] = None,
) -> SubsumptionProof:
    """Prove (or refuse to prove) "suite ⊆ scan".

    ``suite`` / ``scan`` are the two plans' analyzer lists (duplicates
    in the suite dedupe by engine identity first — the runner does the
    same). ``schema`` feeds the predicate-implication lattice; without
    it only structurally identical wheres prove equivalent.
    ``suite_env`` / ``scan_env`` carry the plan-signature components;
    any component mismatch is INCOMPARABLE before a single analyzer is
    compared."""
    env_mismatches: Tuple[str, ...] = ()
    if suite_env is not None and scan_env is not None:
        env_mismatches = tuple(suite_env.mismatches(scan_env))

    seen: set = set()
    unique: List[Any] = []
    for a in suite:
        if a not in seen:
            seen.add(a)
            unique.append(a)

    scan_list = list(scan)
    scan_set = set(scan_list)
    obligations: List[Obligation] = []
    for a in unique:
        if a in scan_set:
            obligations.append(
                Obligation(analyzer=repr(a), kind=EXACT, target=repr(a))
            )
            continue
        matched = None
        for s in scan_list:
            if _family_equivalent(a, s, schema):
                matched = s
                break
        if matched is not None:
            obligations.append(
                Obligation(
                    analyzer=repr(a),
                    kind=EQUIVALENT_WHERE,
                    target=repr(matched),
                    detail=(
                        f"where {getattr(a, 'where', None)!r} proven "
                        f"equivalent to {getattr(matched, 'where', None)!r}"
                    ),
                    where=getattr(a, "where", None),
                )
            )
            continue
        obligations.append(
            Obligation(
                analyzer=repr(a),
                kind=UNMATCHED,
                detail=_near_miss_detail(a, scan_list, schema),
                where=getattr(a, "where", None),
            )
        )

    if env_mismatches:
        verdict = INCOMPARABLE
    elif any(not o.satisfied for o in obligations):
        verdict = INCOMPARABLE
    elif any(o.kind == EQUIVALENT_WHERE for o in obligations):
        verdict = CONTAINED_WITH_RESIDUAL
    else:
        verdict = CONTAINED
    return SubsumptionProof(
        verdict=verdict,
        obligations=tuple(obligations),
        env_mismatches=env_mismatches,
    )


__all__ = [
    "CONTAINED",
    "CONTAINED_WITH_RESIDUAL",
    "EQUIVALENT_WHERE",
    "EXACT",
    "INCOMPARABLE",
    "Obligation",
    "PlanEnv",
    "SubsumptionProof",
    "UNMATCHED",
    "prove_subsumption",
    "where_implies",
    "wheres_equivalent",
]

"""Typed expression analysis: static dtype + nullability inference.

Walks the `data/expr.py` AST against a `SchemaInfo`, mirroring the
evaluator's coercion rules (`_coerce_pair` / `_to_num` / Kleene logic)
WITHOUT touching data. Inference is conservative on nullability: it may
report nullable for an expression that never yields NULL, but must never
report non-nullable for one that can — the differential suite
(tests/test_lint_static_vs_eval.py) enforces exactly that contract
against real evaluation.

Kinds are the evaluator's: 'num' | 'str' | 'bool'.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import List, Optional, Tuple

from deequ_tpu.data.expr import (
    Between,
    Bin,
    Case,
    Col,
    ExpressionParseError,
    Func,
    InList,
    IsNull,
    Like,
    Lit,
    Node,
    Un,
    parse,
)
from deequ_tpu.data.table import ColumnType
from deequ_tpu.lint.diagnostics import Diagnostic, Severity
from deequ_tpu.lint.schema import SchemaInfo

_CMP_OPS = ("eq", "ne", "lt", "le", "gt", "ge")
_ARITH_OPS = ("add", "sub", "mul", "div", "mod")

_KIND_OF_CTYPE = {
    ColumnType.STRING: "str",
    ColumnType.BOOLEAN: "bool",
    # LONG / DOUBLE / DECIMAL / TIMESTAMP all evaluate through as_float()
}


@dataclass
class TypedExpr:
    kind: str  # 'num' | 'str' | 'bool'
    nullable: bool


def _parses_as_float(text: str) -> bool:
    try:
        float(text)
        return True
    except (TypeError, ValueError):
        return False


class _Analyzer:
    def __init__(self, schema: SchemaInfo, source: Optional[str]):
        self.schema = schema
        self.source = source
        self.diags: List[Diagnostic] = []

    def _diag(
        self,
        code: str,
        severity: Severity,
        message: str,
        node: Optional[Node] = None,
        suggestion: Optional[str] = None,
    ) -> None:
        self.diags.append(
            Diagnostic(
                code,
                severity,
                message,
                source=self.source,
                span=getattr(node, "span", None),
                suggestion=suggestion,
            )
        )

    # -- coercions (mirror _to_num / _to_str) -------------------------------

    def _as_num(self, t: TypedExpr, node: Node, context: str) -> TypedExpr:
        if t.kind == "num":
            return t
        if t.kind == "bool":
            return TypedExpr("num", t.nullable)
        # str -> num: parse failures become NULLs at eval time
        if isinstance(node, Lit) and isinstance(node.value, str):
            if not _parses_as_float(node.value):
                self._diag(
                    "DQ103",
                    Severity.ERROR,
                    f"string literal {node.value!r} is not numeric; "
                    f"{context} always yields NULL",
                    node,
                )
                return TypedExpr("num", True)
            return TypedExpr("num", t.nullable)
        self._diag(
            "DQ102",
            Severity.WARNING,
            f"string expression coerced to a number in {context}; "
            "non-numeric rows become NULL",
            node,
        )
        return TypedExpr("num", True)

    def _coerce_pair(
        self, lt: TypedExpr, rt: TypedExpr, lnode: Node, rnode: Node, context: str
    ) -> Tuple[TypedExpr, TypedExpr, str]:
        if lt.kind == rt.kind:
            return lt, rt, lt.kind
        if "num" in (lt.kind, rt.kind):
            if "bool" in (lt.kind, rt.kind):
                self._diag(
                    "DQ102",
                    Severity.WARNING,
                    f"comparing a boolean with a number in {context}",
                    lnode if lt.kind == "bool" else rnode,
                )
            lt2 = self._as_num(lt, lnode, context) if lt.kind != "num" else lt
            rt2 = self._as_num(rt, rnode, context) if rt.kind != "num" else rt
            return lt2, rt2, "num"
        # bool vs str -> both compared as strings 'true'/'false'
        self._diag(
            "DQ102",
            Severity.WARNING,
            f"comparing a boolean with a string in {context}; the boolean "
            "is rendered as 'true'/'false'",
            lnode if lt.kind == "bool" else rnode,
        )
        return TypedExpr("str", lt.nullable), TypedExpr("str", rt.nullable), "str"

    def _expect_bool(self, t: TypedExpr, node: Node, context: str) -> None:
        if t.kind == "str":
            self._diag(
                "DQ102",
                Severity.WARNING,
                f"string expression used as a boolean in {context}",
                node,
            )

    # -- walk ----------------------------------------------------------------

    def visit(self, node: Node) -> TypedExpr:
        if isinstance(node, Lit):
            if node.value is None:
                return TypedExpr("num", True)
            if isinstance(node.value, bool):
                return TypedExpr("bool", False)
            if isinstance(node.value, (int, float)):
                return TypedExpr("num", False)
            return TypedExpr("str", False)

        if isinstance(node, Col):
            fld = self.schema.field(node.name)
            if fld is None:
                self._diag(
                    "DQ101",
                    Severity.ERROR,
                    f"unresolved column {node.name!r}",
                    node,
                    suggestion=self.schema.suggest(node.name),
                )
                return TypedExpr("num", True)
            return TypedExpr(
                _KIND_OF_CTYPE.get(fld.ctype, "num"), bool(fld.nullable)
            )

        if isinstance(node, (Bin,)) and node.op in ("and", "or"):
            lt = self.visit(node.l)
            rt = self.visit(node.r)
            self._expect_bool(lt, node.l, f"{node.op.upper()}")
            self._expect_bool(rt, node.r, f"{node.op.upper()}")
            return TypedExpr("bool", lt.nullable or rt.nullable)

        if isinstance(node, Bin) and node.op in _CMP_OPS:
            lt = self.visit(node.l)
            rt = self.visit(node.r)
            lt2, rt2, _ = self._coerce_pair(lt, rt, node.l, node.r, "a comparison")
            return TypedExpr("bool", lt2.nullable or rt2.nullable)

        if isinstance(node, Bin) and node.op in _ARITH_OPS:
            lt = self._as_num(self.visit(node.l), node.l, "arithmetic")
            rt = self._as_num(self.visit(node.r), node.r, "arithmetic")
            nullable = lt.nullable or rt.nullable
            if node.op in ("div", "mod"):
                # x/0 -> NULL; only a provably non-zero literal divisor is safe
                safe = isinstance(node.r, Lit) and isinstance(
                    node.r.value, (int, float)
                ) and not isinstance(node.r.value, bool) and float(node.r.value) != 0.0
                nullable = nullable or not safe
            return TypedExpr("num", nullable)

        if isinstance(node, Bin):
            return TypedExpr("num", True)

        if isinstance(node, Un):
            if node.op == "neg":
                t = self._as_num(self.visit(node.x), node.x, "negation")
                return TypedExpr("num", t.nullable)
            t = self.visit(node.x)
            self._expect_bool(t, node.x, "NOT")
            return TypedExpr("bool", t.nullable)

        if isinstance(node, IsNull):
            self.visit(node.x)
            return TypedExpr("bool", False)

        if isinstance(node, InList):
            xt = self.visit(node.x)
            nullable = xt.nullable
            for item in node.items:
                it = self.visit(item)
                it2_l, it2_r, _ = self._coerce_pair(
                    xt, it, node.x, item, "an IN list"
                )
                nullable = nullable or it2_l.nullable or it2_r.nullable
            if not node.items:
                nullable = False
            return TypedExpr("bool", nullable)

        if isinstance(node, Between):
            xt = self.visit(node.x)
            lo = self.visit(node.lo)
            hi = self.visit(node.hi)
            l1, l2, _ = self._coerce_pair(xt, lo, node.x, node.lo, "BETWEEN")
            h1, h2, _ = self._coerce_pair(xt, hi, node.x, node.hi, "BETWEEN")
            return TypedExpr(
                "bool", l1.nullable or l2.nullable or h1.nullable or h2.nullable
            )

        if isinstance(node, Like):
            xt = self.visit(node.x)
            kw = "RLIKE" if node.regex else "LIKE"
            if xt.kind == "num":
                self._diag(
                    "DQ102",
                    Severity.WARNING,
                    f"{kw} applied to a numeric expression; it is matched "
                    "against its decimal rendering",
                    node.x,
                )
            pat = node.pattern
            if not isinstance(pat, Lit) or not isinstance(pat.value, str):
                self._diag(
                    "DQ103",
                    Severity.ERROR,
                    f"{kw} pattern must be a string literal",
                    pat,
                )
            elif node.regex:
                try:
                    re.compile(pat.value)
                except re.error as e:
                    self._diag(
                        "DQ103",
                        Severity.ERROR,
                        f"invalid regular expression {pat.value!r}: {e}",
                        pat,
                    )
            return TypedExpr("bool", xt.nullable)

        if isinstance(node, Func):
            return self._visit_func(node)

        if isinstance(node, Case):
            results: List[TypedExpr] = []
            for cond, then in node.branches:
                ct = self.visit(cond)
                self._expect_bool(ct, cond, "CASE WHEN")
                results.append(self.visit(then))
            otherwise = (
                self.visit(node.otherwise) if node.otherwise is not None else None
            )
            all_results = results + ([otherwise] if otherwise is not None else [])
            kinds = [t.kind for t in all_results]
            if "str" in kinds:
                kind = "str"
            elif "num" in kinds:
                kind = "num"
            elif kinds:
                kind = "bool"
            else:
                kind = "num"
            nullable = (
                node.otherwise is None
                or any(t.nullable for t in all_results)
                # str results coerced to num can gain NULLs
                or (kind == "num" and any(t.kind == "str" for t in all_results))
            )
            return TypedExpr(kind, nullable)

        return TypedExpr("num", True)

    def _visit_func(self, node: Func) -> TypedExpr:
        name = node.name
        args = [self.visit(a) for a in node.args]

        def need(n: int) -> bool:
            if len(node.args) < n:
                self._diag(
                    "DQ105",
                    Severity.ERROR,
                    f"{name} expects at least {n} argument(s), got {len(node.args)}",
                    node,
                )
                return False
            return True

        if name == "COALESCE":
            if not args:
                return TypedExpr("num", True)
            kinds = [t.kind for t in args]
            if "str" in kinds:
                kind = "str"
            elif "num" in kinds:
                kind = "num"
            else:
                kind = "bool"
            nullable = all(
                t.nullable or (kind == "num" and t.kind == "str") for t in args
            )
            return TypedExpr(kind, nullable)
        if name == "ABS":
            if not need(1):
                return TypedExpr("num", True)
            t = self._as_num(args[0], node.args[0], "ABS")
            return TypedExpr("num", t.nullable)
        if name in ("LENGTH", "LEN", "CHAR_LENGTH"):
            if not need(1):
                return TypedExpr("num", True)
            return TypedExpr("num", args[0].nullable)
        if name in ("LOWER", "UPPER", "TRIM"):
            if not need(1):
                return TypedExpr("str", True)
            return TypedExpr("str", args[0].nullable)
        if name in ("ISNULL", "ISNOTNULL"):
            if not need(1):
                return TypedExpr("bool", False)
            return TypedExpr("bool", False)
        self._diag(
            "DQ104",
            Severity.ERROR,
            f"unknown function {name}; the scan would fail at evaluation time",
            node,
        )
        return TypedExpr("num", True)


def analyze_ast(
    ast: Node, schema: SchemaInfo, source: Optional[str] = None
) -> Tuple[TypedExpr, List[Diagnostic]]:
    analyzer = _Analyzer(schema, source)
    typed = analyzer.visit(ast)
    return typed, analyzer.diags


def analyze_expression(
    expression: str, schema: SchemaInfo
) -> Tuple[Optional[TypedExpr], List[Diagnostic]]:
    """Parse + typecheck an expression against a schema. On parse failure
    returns (None, [DQ100 diagnostic]); never raises."""
    try:
        ast = parse(expression)
    except ExpressionParseError as e:
        return None, [
            Diagnostic(
                "DQ100",
                Severity.ERROR,
                f"expression does not parse: {e}",
                source=expression,
            )
        ]
    typed, diags = analyze_ast(ast, schema, source=expression)
    return typed, diags

"""Runtime observability: hierarchical spans, counters, trace export.

The paper's core claim — the mergeable-state algebra lets many metrics
share a minimal number of scan passes — becomes *measurable* here:
every run can record a span tree (suite → analysis run → plan/fuse →
per-family kernel dispatch → native call → state merge → constraint
eval) with wall/CPU time, rows/bytes scanned, device-transfer bytes and
pass/launch counters, exportable as Chrome-trace JSON (load it in
Perfetto / chrome://tracing) or rendered as a human-readable report.

Design constraints:
  * near-zero overhead when disabled: `span()` is one thread-local
    attribute probe returning a singleton no-op context manager;
  * no deequ_tpu dependencies outside `core.fileio` (imported lazily),
    so the engine layers (`ops/`, `runners/`, `parallel/`) can all
    import this package without cycles;
  * thread-correct: the context stack is thread-local, and worker-pool
    threads adopt the dispatching thread's context via `attached()`.

Enable per run with `.with_tracing(...)` on the builders, per block
with `tracing()`, or process-wide with `DEEQU_TPU_TRACE=1`
(`DEEQU_TPU_TRACE_OUT` overrides the output path).
"""

from deequ_tpu.observe.spans import (
    Span,
    Tracer,
    annotate,
    attached,
    current_span,
    current_tracer,
    span,
    timed_call,
    tracing,
)
from deequ_tpu.observe import counters
from deequ_tpu.observe.export import (
    chrome_trace,
    merge_chrome_traces,
    write_chrome_trace,
)
from deequ_tpu.observe.compare import (
    dispatch_signature,
    observed_family_groups,
    span_name_counts,
)
from deequ_tpu.observe.report import (
    PHASES,
    PIPE_ITEM_SPAN,
    PIPE_STAGE_SPAN,
    phase_seconds,
    pipeline_occupancy,
    render_report,
)
from deequ_tpu.observe.runtrace import (
    ENV_KNOB,
    ENV_OUT,
    RunTrace,
    default_trace_path,
    env_enabled,
    traced_run,
)
from deequ_tpu.observe import heartbeat
from deequ_tpu.observe.forensics import (
    ConstraintForensics,
    ForensicsCapture,
    ForensicsReport,
    ViolationSample,
    classify_constraints,
    render_forensics,
)
from deequ_tpu.observe.heartbeat import scan_heartbeat
from deequ_tpu.observe.telemetry import (
    engine_metric_record,
    latest_results,
    openmetrics_text,
    proc_resources,
)

__all__ = [
    "Span",
    "Tracer",
    "annotate",
    "attached",
    "current_span",
    "current_tracer",
    "span",
    "timed_call",
    "tracing",
    "counters",
    "chrome_trace",
    "merge_chrome_traces",
    "write_chrome_trace",
    "PHASES",
    "PIPE_ITEM_SPAN",
    "PIPE_STAGE_SPAN",
    "phase_seconds",
    "pipeline_occupancy",
    "render_report",
    "ENV_KNOB",
    "ENV_OUT",
    "ConstraintForensics",
    "ForensicsCapture",
    "ForensicsReport",
    "RunTrace",
    "ViolationSample",
    "classify_constraints",
    "default_trace_path",
    "dispatch_signature",
    "engine_metric_record",
    "env_enabled",
    "heartbeat",
    "render_forensics",
    "latest_results",
    "observed_family_groups",
    "openmetrics_text",
    "proc_resources",
    "scan_heartbeat",
    "span_name_counts",
    "traced_run",
]

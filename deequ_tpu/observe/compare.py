"""Trace-side extraction of the execution shape the static cost
analyzer predicts (lint/cost.PlanCost.dispatch_signature).

`dispatch_signature(trace)` reduces an observed `RunTrace` to the same
{counters, spans, family_groups} structure, so the trace-differential
suite is one dict equality: `cost.dispatch_signature() ==
compare.dispatch_signature(ctx.run_trace)`. Nothing here interprets
plans — it only folds the span tree.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from deequ_tpu.observe.runtrace import RunTrace

#: the execution-layer span vocabulary (mirror of lint/cost.EXECUTION_SPANS)
EXECUTION_SPANS = (
    "plan_fuse",
    "fused_scan",
    "dist_scan",
    "dispatch",
    "host_fold",
    "transfer",
    "merge",
    "family_kernel",
    "grouping",
    "group_pass",
    "freq_agg",
    "state_allgather",
)

COUNTERS = ("device_passes", "device_launches", "group_passes")


def span_name_counts(
    trace: RunTrace, names: Optional[Sequence[str]] = None
) -> Dict[str, int]:
    """Histogram of span names over the whole tree, restricted to the
    execution vocabulary (or an explicit name set)."""
    wanted = set(EXECUTION_SPANS if names is None else names)
    counts: Dict[str, int] = {}
    for sp in trace.spans():
        if sp.name in wanted:
            counts[sp.name] = counts.get(sp.name, 0) + 1
    return counts


def observed_family_groups(trace: RunTrace) -> List[Tuple[Any, ...]]:
    """Distinct family-kernel dispatch groups seen in the trace, as
    (where, cap, dtype, columns, batched) — deduplicated across batches
    (a multi-batch scan dispatches every group once per batch)."""
    groups: set = set()
    for sp in trace.spans():
        if sp.name != "family_kernel":
            continue
        cols = sp.attrs.get("cols", "")
        groups.add(
            (
                str(sp.attrs.get("where")),
                int(sp.attrs.get("cap", 0)),
                str(sp.attrs.get("dtype")),
                tuple(cols.split(",")) if cols else (),
                bool(sp.attrs.get("batched", False)),
            )
        )
    return sorted(groups)


def dispatch_signature(trace: RunTrace) -> Dict[str, Any]:
    """The observed execution shape, directly comparable to
    `PlanCost.dispatch_signature()`."""
    counters = {k: int(trace.counters.get(k, 0)) for k in COUNTERS}
    return {
        "counters": counters,
        "spans": span_name_counts(trace),
        "family_groups": observed_family_groups(trace),
    }


__all__ = [
    "COUNTERS",
    "EXECUTION_SPANS",
    "dispatch_signature",
    "observed_family_groups",
    "span_name_counts",
]

"""The counter API: pass/launch accounting shared by `ExecutionStats`
sinks and active tracers.

`ops/runtime.py`'s `monitored()` / `record_pass()` / `record_launch()`
delegate here (source-compatible migration, ISSUE 3 tentpole). A sink
is any object with `device_passes` / `device_launches` / `group_passes`
ints and a `pass_labels` list — `runtime.ExecutionStats` in practice,
duck-typed so this module never imports the ops layer.

The sink stack is thread-local (concurrent monitored scans on separate
threads never cross-contaminate), and every record also feeds the
thread's active tracer, whose counters therefore stay bit-identical to
what a `monitored()` block around the same run would report.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Iterator, List

from deequ_tpu.observe import spans

_local = threading.local()

_EMPTY: List = []


def _sinks() -> List:
    return getattr(_local, "sinks", _EMPTY)


@contextlib.contextmanager
def collect(sink) -> Iterator:
    """Register `sink` for every record_* on this thread in the block."""
    try:
        stack = _local.sinks
    except AttributeError:
        stack = _local.sinks = []
    stack.append(sink)
    try:
        yield sink
    finally:
        stack.pop()


def record_pass(label: str) -> None:
    """One fused scan over a dataset (≈ one Spark job)."""
    for sink in _sinks():
        sink.device_passes += 1
        sink.pass_labels.append(label)
    tracer = spans.current_tracer()
    if tracer is not None:
        tracer.count("device_passes", 1, label)


def record_launch() -> None:
    """One compiled-program invocation (per batch)."""
    for sink in _sinks():
        sink.device_launches += 1
    tracer = spans.current_tracer()
    if tracer is not None:
        tracer.count("device_launches", 1)


def record_group_pass(label: str) -> None:
    """One group-by frequency computation."""
    for sink in _sinks():
        sink.group_passes += 1
        sink.pass_labels.append(f"group:{label}")
    tracer = spans.current_tracer()
    if tracer is not None:
        tracer.count("group_passes", 1, f"group:{label}")


def record_pruned_groups(skipped: int, total: int) -> None:
    """Row-group pushdown outcome of one fused scan: groups statically
    skipped vs groups in the file. Tracer-only (no ExecutionStats
    field — pruning is an IO property, not an execution count); the
    counters feed cost_drift's predicted-vs-observed check and the
    `engine.rg_skipped_ratio` telemetry series."""
    tracer = spans.current_tracer()
    if tracer is not None:
        tracer.count("rg_skipped", int(skipped))
        tracer.count("rg_total", int(total))


def record_decode_fastpath(fast: int, total: int, workers: int) -> None:
    """Decode-plan outcome of one fused scan: columns routed through the
    buffer-level native decode vs columns scanned, plus the worker count
    the scan decodes with. Tracer-only, like record_pruned_groups; the
    counters feed cost_drift's decode pin and the
    `engine.decode_fastpath_ratio` / `engine.decode_workers` telemetry
    series (decode_passes normalizes workers to a per-scan average)."""
    tracer = spans.current_tracer()
    if tracer is not None:
        tracer.count("decode_cols_fast", int(fast))
        tracer.count("decode_cols_total", int(total))
        tracer.count("decode_workers", int(workers))
        tracer.count("decode_passes", 1)


def record_wire_fused(fused: int, total: int) -> None:
    """Decode-to-wire outcome of one fused scan: columns whose wire
    buffers the decode workers emit directly vs columns scanned.
    Tracer-only, like record_decode_fastpath; the counters feed
    cost_drift's wire pin and the `engine.wire_fused_ratio` telemetry
    series."""
    tracer = spans.current_tracer()
    if tracer is not None:
        tracer.count("wire_fused_cols", int(fused))
        tracer.count("wire_cols_total", int(total))


def record_reader_chunks(native: int, fallback: int, total: int) -> None:
    """Native-reader plan outcome of one fused scan: column chunks the
    native parquet reader decodes vs chunks that fall back to pyarrow,
    out of the chunks the scan touches (scanned columns × non-pruned row
    groups). Tracer-only, like record_decode_fastpath; the counters feed
    cost_drift's `drift.reader_chunks_native` pin and the
    `engine.reader_native_ratio` telemetry series."""
    tracer = spans.current_tracer()
    if tracer is not None:
        tracer.count("reader_chunks_native", int(native))
        tracer.count("reader_chunks_fallback", int(fallback))
        tracer.count("reader_chunks_total", int(total))


def record_encfold_plan(cols: int, total: int) -> None:
    """Encoded-fold plan outcome of one fused scan: columns the planner
    proved run-foldable (classify_encfold_columns) vs columns scanned.
    STATIC, recorded once per scan like record_reader_chunks — the trace
    side of cost_drift's `drift.encfold_columns` pin."""
    tracer = spans.current_tracer()
    if tracer is not None:
        tracer.count("encfold_cols", int(cols))
        tracer.count("encfold_cols_total", int(total))


def record_encfold(
    chunks: int,
    fallback: int,
    runs: int,
    values: int,
    codes: int,
    bytes_saved: int,
) -> None:
    """Encoded-fold outcome of one decode unit (the DYNAMIC half —
    record_encfold_plan carries the static column verdict): chunks that
    folded over (run, code) streams, chunks that failed closed to the
    row-width path, runs vs logical values folded (run_ratio — the
    compression the fold exploited), distinct dictionary codes rolled up
    to engine values, and row-width bytes never materialized.
    Tracer-only; the counters feed the `engine.encfold.*` telemetry
    series the sentinel watches."""
    tracer = spans.current_tracer()
    if tracer is not None:
        tracer.count("encfold_chunks", int(chunks))
        if fallback:
            tracer.count("encfold_chunks_fallback", int(fallback))
        if runs:
            tracer.count("encfold_runs", int(runs))
        if values:
            tracer.count("encfold_values", int(values))
        if codes:
            tracer.count("encfold_codes_folded", int(codes))
        if bytes_saved:
            tracer.count("encfold_bytes_saved", int(bytes_saved))


def record_retry(attempts: int, recovered: int, exhausted: int) -> None:
    """Transient-IO retry outcome of one readahead fetch operation:
    backoff sleeps taken, whether the operation recovered after >=1
    retry, and whether the budget ran dry (the unit then degrades to
    the pyarrow fallback — never a wrong answer). Tracer-only, like
    record_pruned_groups; the counters feed the
    `engine.retry.recovery_ratio` telemetry series the sentinel
    watches."""
    tracer = spans.current_tracer()
    if tracer is not None:
        if attempts:
            tracer.count("retry.attempts", int(attempts))
        if recovered:
            tracer.count("retry.recovered", int(recovered))
        if exhausted:
            tracer.count("retry.exhausted", int(exhausted))


def record_fault(injected: int = 0, fallback_units: int = 0) -> None:
    """Fault-containment accounting: faults observed at engine fault
    points (injected by the chaos harness or real transient IO errors),
    and decode units that degraded to the pyarrow fallback because of
    one. Tracer-only; feeds the `engine.fault.fallback_ratio` telemetry
    series the sentinel watches."""
    tracer = spans.current_tracer()
    if tracer is not None:
        if injected:
            tracer.count("fault.observed", int(injected))
        if fallback_units:
            tracer.count("fault.fallback_units", int(fallback_units))


def record_shard_scan(
    shard: int,
    num_shards: int,
    partitions_local: int,
    partitions_max: int,
    partitions_total: int,
    merge_bytes: int,
    rows_local: int,
) -> None:
    """Shard-split outcome of one sharded streaming scan (one record per
    participating process): which shard this is out of how many, its
    partition slice vs the largest shard's and the dataset total, the
    gathered state-envelope bytes that crossed the process boundary,
    and the rows this shard folded. Tracer-only, like
    record_state_cache; the counters feed cost_drift's shard pins and
    the `engine.shard.*` telemetry series the sentinel watches."""
    tracer = spans.current_tracer()
    if tracer is not None:
        tracer.count("shard.index", int(shard))
        tracer.count("shard.count", int(num_shards))
        tracer.count("shard.partitions_local", int(partitions_local))
        tracer.count("shard.partitions_max", int(partitions_max))
        tracer.count("shard.partitions_total", int(partitions_total))
        tracer.count("shard.merge_bytes", int(merge_bytes))
        tracer.count("shard.rows_local", int(rows_local))


def record_plan_cache(hit: bool) -> None:
    """Compiled-plan cache outcome of one fused-fn lookup: whether the
    jit/fuse cost for this plan *shape* (the analyzer-repr component of
    `repository.states.plan_signature`, plus wire layout and x64 flag)
    was already paid by an earlier plan anywhere in the process —
    fleet-wide under the DQService, where co-tenant suites share plan
    shapes. Tracer-only, like record_pruned_groups; the counters feed
    the `engine.plan_cache_hit_ratio` telemetry series the sentinel
    watches."""
    tracer = spans.current_tracer()
    if tracer is not None:
        tracer.count("plan_cache.lookups", 1)
        if hit:
            tracer.count("plan_cache.hits", 1)


def record_state_cache(cached: int, scanned: int, total: int) -> None:
    """Partition-split outcome of one partitioned fused scan: partitions
    whose states loaded from the state cache vs partitions that decoded
    and folded, out of the dataset's partition count. Tracer-only, like
    record_pruned_groups; the counters feed cost_drift's
    `drift.partitions_cached` pin and the `engine.state_cache_hit_ratio`
    telemetry series."""
    tracer = spans.current_tracer()
    if tracer is not None:
        tracer.count("partitions_cached", int(cached))
        tracer.count("partitions_scanned", int(scanned))
        tracer.count("partitions_total", int(total))


def record_window(
    segments: int, hits: int, built: int, rescanned: int, partitions: int
) -> None:
    """Segment-merge outcome of one window query (windows/query.py):
    cover spans merged, of which segment-envelope hits vs lazily built,
    plus partitions that had to rescan out of the window's member
    count. Tracer-only, like record_state_cache; the counters feed
    cost_drift's `drift.window_*` pins and the
    `engine.window.segment_hit_ratio` telemetry series the sentinel
    watches."""
    tracer = spans.current_tracer()
    if tracer is not None:
        tracer.count("window.spans", int(segments))
        tracer.count("window.segments_merged", int(segments))
        tracer.count("window.segment_hits", int(hits))
        tracer.count("window.segments_built", int(built))
        tracer.count("window.partitions_rescanned", int(rescanned))
        tracer.count("window.partitions", int(partitions))

"""Chrome-trace-format (Perfetto-viewable) JSON export.

Emits the Trace Event Format's duration events: a `B`/`E` pair per
span with microsecond `ts` relative to the tracer epoch, `pid` = the
jax process index (0 when uninitialized), `tid` = a small stable index
per OS thread. Load the file at https://ui.perfetto.dev or
chrome://tracing.

Events are emitted depth-first (B, children, E), so B/E pairs nest
properly by construction regardless of clock granularity. Multihost
runs write one file per process; `merge_chrome_traces` concatenates
them keyed by each file's recorded process index so one Perfetto view
shows every host.
"""

from __future__ import annotations

import json
import sys
from typing import Dict, List, Optional, Sequence

from deequ_tpu.observe.spans import Span


def process_index() -> int:
    """The jax process index when jax is initialized, else 0. Lazy so
    trace export never forces a jax import."""
    if "jax" in sys.modules:
        try:
            import jax

            return int(jax.process_index())
        except Exception:
            return 0
    return 0


def _events_for(
    span: Span,
    epoch: float,
    pid: int,
    tid_map: Dict[int, int],
    out: List[dict],
) -> None:
    tid = tid_map.setdefault(span.tid, len(tid_map))
    ts = max((span.t0 - epoch) * 1e6, 0.0)
    end = max((span.t1 - epoch) * 1e6, ts)
    args = {k: v for k, v in span.attrs.items()}
    args["cpu_ms"] = round(span.cpu_s * 1e3, 3)
    begin = {
        "ph": "B",
        "ts": ts,
        "pid": pid,
        "tid": tid,
        "name": span.name,
        "cat": span.cat or "other",
        "args": args,
    }
    out.append(begin)
    for child in span.children:
        _events_for(child, epoch, pid, tid_map, out)
    out.append(
        {
            "ph": "E",
            "ts": end,
            "pid": pid,
            "tid": tid,
            "name": span.name,
            "cat": span.cat or "other",
        }
    )


def chrome_trace(
    roots: Sequence[Span],
    epoch: float = 0.0,
    pid: Optional[int] = None,
    metadata: Optional[dict] = None,
) -> dict:
    """The trace document for a span forest: `{"traceEvents": [...]}`."""
    if pid is None:
        pid = process_index()
    events: List[dict] = []
    tid_map: Dict[int, int] = {}
    for root in roots:
        _events_for(root, epoch, pid, tid_map, events)
    meta = {"process_index": pid}
    if metadata:
        meta.update(metadata)
    events.append(
        {
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "name": "process_name",
            "args": {"name": f"deequ_tpu p{pid}"},
        }
    )
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": meta,
    }


def write_chrome_trace(
    path: str,
    roots: Sequence[Span],
    epoch: float = 0.0,
    pid: Optional[int] = None,
    metadata: Optional[dict] = None,
) -> str:
    """Serialize a span forest to `path` (atomic tmp+rename), return
    the path."""
    from deequ_tpu.core.fileio import write_text_output

    doc = chrome_trace(roots, epoch=epoch, pid=pid, metadata=metadata)
    write_text_output(path, json.dumps(doc), overwrite=True)
    return path


def merge_chrome_traces(paths: Sequence[str], out_path: Optional[str] = None) -> dict:
    """Merge per-process trace files (multihost runs write one per jax
    process) into a single document, keyed by each file's recorded
    process index — falling back to file order when indexes collide so
    no host's events shadow another's."""
    merged_events: List[dict] = []
    seen_pids: set = set()
    sources = []
    for order, path in enumerate(paths):
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        pid = doc.get("metadata", {}).get("process_index", order)
        while pid in seen_pids:
            pid += len(paths)
        seen_pids.add(pid)
        sources.append({"path": path, "process_index": pid})
        for event in doc.get("traceEvents", []):
            event = dict(event)
            event["pid"] = pid
            merged_events.append(event)
    merged = {
        "traceEvents": merged_events,
        "displayTimeUnit": "ms",
        "metadata": {"merged_from": sources},
    }
    if out_path is not None:
        from deequ_tpu.core.fileio import write_text_output

        write_text_output(out_path, json.dumps(merged), overwrite=True)
    return merged

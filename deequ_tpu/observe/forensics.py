"""Failure forensics: row-level violation capture + metric provenance.

The paper's core algebra (declarative checks over mergeable sufficient
statistics) deliberately discards row identity — a FAILURE status plus a
metric value is all an operator gets. This module restores just enough
identity to triage, without a second pass: when enabled
(`with_forensics()` / `DEEQU_TPU_FORENSICS=1`), the fused scan hands
every already-decoded batch to a `ForensicsCapture`, which

* statically classifies the plan's constraints into row-level-capable
  families (completeness, compliance/`satisfies`, pattern match, min/max
  bounds — the same prove-eligibility-from-the-plan discipline as
  `classify_wire_columns`), everything else falling off with a DQ316
  reason;
* recomputes each capable constraint's violation mask with the SAME
  `InputSpec`s the fold itself uses (`analyzer.input_specs()`), on the
  same decoded batch — no extra decode, no extra pass, and zero
  contamination of the fold inputs (the off path never allocates);
* keeps a bounded deterministic reservoir of violating rows with full
  coordinates `(partition, fingerprint, row group, row index, offending
  values)` — the reservoir RNG is seeded from the violating indices
  themselves (the `sketch._batch_seed` trick), so reruns sample the
  same rows;
* records the run's provenance — plan signature, partitions scanned vs
  merged from the state cache, row groups pruned statically, decode
  fast-path/wire/native-reader column splits — so the report can say
  "constraint X failed because rows like these, in these partitions,
  which were scanned (not cached) under this plan".

Capture never raises into the scan: every per-constraint failure is
swallowed and counted. Offending values are read through the
`data/expr.py` evaluator on the decoded batch.

This module is imported lazily by the verification layer; it must not
be imported from telemetry/heartbeat/engine code (tools/lint.py
FORENSICS rule) — sampled row values live in the audit trail only,
never in `engine.*` series, OpenMetrics text, or heartbeat snapshots.
"""

from __future__ import annotations

import bisect
import math
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "DEFAULT_MAX_SAMPLES",
    "FORENSICS_REPORT_VERSION",
    "ConstraintForensics",
    "ForensicsCapture",
    "ForensicsReport",
    "ViolationSample",
    "classify_constraints",
    "render_forensics",
]

DEFAULT_MAX_SAMPLES = 10

#: bump when ForensicsReport.to_dict's shape changes — the audit-trail
#: envelope (repository/audit.py) carries its own binary version on top
FORENSICS_REPORT_VERSION = 1


# ---------------------------------------------------------------------------
# static classification (mirrors lint/planlint._constraint_analyzers)
# ---------------------------------------------------------------------------


def _capable_kind(analyzer: Any) -> Optional[str]:
    """Row-level family of an analyzer, or None when its violating rows
    are not identifiable from one batch (aggregates, sketches, grouped
    metrics)."""
    from deequ_tpu.analyzers import (
        Completeness,
        Compliance,
        Maximum,
        Minimum,
        PatternMatch,
    )

    if isinstance(analyzer, Completeness):
        return "completeness"
    if isinstance(analyzer, Compliance):
        return "compliance"
    if isinstance(analyzer, PatternMatch):
        return "pattern"
    if isinstance(analyzer, Minimum):
        return "minimum"
    if isinstance(analyzer, Maximum):
        return "maximum"
    return None


def classify_constraints(
    checks: Sequence,
) -> List[Tuple[object, object, Optional[str], str]]:
    """(constraint, inner, kind-or-None, falloff-reason) per analysis
    constraint in plan order. `kind is None` means not forensics-capable
    (the EXPLAIN DQ316 population); the reason says why."""
    from deequ_tpu.lint.planlint import _constraint_analyzers

    out = []
    for constraint, inner in _constraint_analyzers(checks):
        kind = _capable_kind(inner.analyzer)
        if kind is None:
            out.append(
                (
                    constraint,
                    inner,
                    None,
                    "analyzer family has no per-row violation identity",
                )
            )
        elif inner.value_picker is not None:
            out.append(
                (
                    constraint,
                    inner,
                    None,
                    "custom value picker decouples the assertion from row values",
                )
            )
        else:
            out.append((constraint, inner, kind, ""))
    return out


# ---------------------------------------------------------------------------
# report surface
# ---------------------------------------------------------------------------


def _json_value(v: Any) -> Any:
    """One offending value made JSON-safe (numpy scalars unwrapped,
    non-finite floats stored as None like repository/serde.py does)."""
    if v is None:
        return None
    if isinstance(v, (np.bool_, bool)):
        return bool(v)
    if isinstance(v, (np.integer, int)):
        return int(v)
    if isinstance(v, (np.floating, float)):
        f = float(v)
        return f if math.isfinite(f) else None
    return str(v)


@dataclass
class ViolationSample:
    """One sampled violating row with full coordinates. `row_group` is
    -1 (and `row_index` the scan-global offset) for in-memory sources
    without parquet row groups."""

    partition: Optional[str]
    fingerprint: Optional[str]
    row_group: int
    row_index: int
    values: Dict[str, Any]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "partition": self.partition,
            "fingerprint": self.fingerprint,
            "rowGroup": self.row_group,
            "rowIndex": self.row_index,
            "values": dict(self.values),
        }

    @staticmethod
    def from_dict(data: Dict[str, Any]) -> "ViolationSample":
        return ViolationSample(
            data.get("partition"),
            data.get("fingerprint"),
            int(data.get("rowGroup", -1)),
            int(data.get("rowIndex", -1)),
            dict(data.get("values") or {}),
        )


@dataclass
class ConstraintForensics:
    """One capable constraint's captured evidence. For min/max bounds
    `violations_seen` counts tested extreme candidates that violated
    the assertion (a lower bound on true violations); for the ratio
    families it is the exact violating-row count over scanned data."""

    constraint: str
    analyzer: str
    kind: str
    columns: List[str]
    violations_seen: int
    samples: List[ViolationSample]
    status: Optional[str] = None
    capture_errors: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "constraint": self.constraint,
            "analyzer": self.analyzer,
            "kind": self.kind,
            "columns": list(self.columns),
            "violationsSeen": self.violations_seen,
            "samples": [s.to_dict() for s in self.samples],
            "status": self.status,
            "captureErrors": self.capture_errors,
        }

    @staticmethod
    def from_dict(data: Dict[str, Any]) -> "ConstraintForensics":
        return ConstraintForensics(
            str(data.get("constraint", "")),
            str(data.get("analyzer", "")),
            str(data.get("kind", "")),
            [str(c) for c in data.get("columns") or []],
            int(data.get("violationsSeen", 0)),
            [ViolationSample.from_dict(s) for s in data.get("samples") or []],
            data.get("status"),
            int(data.get("captureErrors", 0)),
        )


@dataclass
class ForensicsReport:
    """The persisted artifact: per-constraint evidence + run provenance
    + the DQ316 fall-off list. Round-trips through `to_dict`/`from_dict`
    (the audit-trail payload, repository/audit.py)."""

    constraints: List[ConstraintForensics] = field(default_factory=list)
    falloffs: List[Dict[str, str]] = field(default_factory=list)
    provenance: Dict[str, Any] = field(default_factory=dict)

    def failed(self) -> List[ConstraintForensics]:
        return [c for c in self.constraints if c.status == "FAILURE"]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "version": FORENSICS_REPORT_VERSION,
            "constraints": [c.to_dict() for c in self.constraints],
            "falloffs": [dict(f) for f in self.falloffs],
            "provenance": dict(self.provenance),
        }

    @staticmethod
    def from_dict(data: Dict[str, Any]) -> "ForensicsReport":
        return ForensicsReport(
            [
                ConstraintForensics.from_dict(c)
                for c in data.get("constraints") or []
            ],
            [dict(f) for f in data.get("falloffs") or []],
            dict(data.get("provenance") or {}),
        )

    def render(self) -> str:
        return render_forensics(self)

    def __str__(self) -> str:
        return self.render()


def _render_sample(sample: ViolationSample) -> str:
    where = sample.partition if sample.partition else "<data>"
    coord = (
        f"rg={sample.row_group} row={sample.row_index}"
        if sample.row_group >= 0
        else f"row={sample.row_index}"
    )
    vals = ", ".join(f"{k}={v!r}" for k, v in sorted(sample.values.items()))
    return f"{where} {coord}: {vals}"


def render_forensics(report: ForensicsReport) -> str:
    """Human-readable triage section: provenance first (what ran, what
    merged from cache), then per-constraint sampled rows."""
    lines = ["failure forensics:"]
    prov = report.provenance or {}
    sig = prov.get("planSignature")
    if sig:
        lines.append(f"  plan signature: {str(sig)[:16]}…")
    parts = prov.get("partitions") or []
    if parts:
        scanned = prov.get("partitionsScanned", 0)
        cached = prov.get("partitionsCached", 0)
        lines.append(
            f"  partitions: {scanned} scanned, {cached} merged from state"
            f" cache ({len(parts)} total)"
        )
        for p in parts:
            fp = str(p.get("fingerprint") or "")[:12]
            lines.append(
                f"    {p.get('name')} [{p.get('mode')}]"
                + (f" fingerprint={fp}…" if fp else "")
            )
    rg_scanned = prov.get("rowGroupsScanned")
    if rg_scanned is not None:
        lines.append(
            f"  row groups: {rg_scanned} scanned,"
            f" {prov.get('rowGroupsPruned', 0)} pruned statically"
        )
    decode = prov.get("decode") or {}
    if decode:
        lines.append(
            "  decode split: fast={fast} fallback={fallback} wire={wire}"
            " native-reader={reader}".format(
                fast=decode.get("colsFast", 0),
                fallback=decode.get("colsFallback", 0),
                wire=decode.get("colsWireFused", 0),
                reader=decode.get("colsReader", 0),
            )
        )
    for cf in report.constraints:
        status = f" [{cf.status}]" if cf.status else ""
        lines.append(
            f"  {cf.constraint}{status} — {cf.violations_seen} violating"
            f" row(s) seen, {len(cf.samples)} sampled"
        )
        for sample in cf.samples:
            lines.append(f"    {_render_sample(sample)}")
    for fo in report.falloffs:
        lines.append(
            f"  not forensics-capable (DQ316): {fo.get('constraint')}"
            f" — {fo.get('reason')}"
        )
    if len(lines) == 1:
        lines.append("  (no forensics-capable constraints in this plan)")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# value extraction (the data/expr.py evaluator on the decoded batch)
# ---------------------------------------------------------------------------


def _column_values(batch: Any, column: str, indices: Sequence[int]) -> List[Any]:
    """Offending values for `column` at batch-local `indices`, read
    through the expression evaluator (nulls -> None). Degrades to None
    values on any evaluation problem — forensics never invents data."""
    from deequ_tpu.data.expr import Predicate

    try:
        values, null, _kind = Predicate(column).eval(batch)
    except Exception:  # noqa: BLE001 - capture is best-effort by contract
        return [None for _ in indices]
    out = []
    for i in indices:
        out.append(None if bool(null[i]) else _json_value(values[i]))
    return out


def _batch_seed(indices: np.ndarray, seen: int) -> int:
    """Content-derived reservoir seed (the sketch._batch_seed trick):
    same violating rows in the same order -> same sampled subset."""
    h = zlib.crc32(np.ascontiguousarray(indices, dtype=np.int64).tobytes())
    return (h ^ (int(seen) * 0x9E3779B1) ^ (int(indices.size) << 17)) & 0x7FFFFFFF


# ---------------------------------------------------------------------------
# per-constraint capture entries
# ---------------------------------------------------------------------------


class _EntryBase:
    """Shared spec plumbing: masks are rebuilt from the analyzer's OWN
    `input_specs()` on the decoded batch — never read out of the fold's
    `HostInputs` (which may hold packed/device representations), so the
    fold arithmetic is untouchable from here."""

    def __init__(self, constraint: Any, inner: Any, kind: str, cap: int):
        self.constraint = constraint
        self.inner = inner
        self.kind = kind
        self.cap = max(1, int(cap))
        self.errors = 0
        self._specs: Dict[str, Any] = {}
        for spec in inner.analyzer.input_specs():
            prefix = spec.key.split(":", 1)[0]
            # first spec wins: input_specs orders the analyzer's own
            # where filter before the shared all-true mask
            self._specs.setdefault(prefix, spec)

    def _build(
        self, batch: Any, prefix: str, cache: Optional[Dict[str, Any]] = None
    ) -> np.ndarray:
        # spec keys are globally deduplicated across the pass (see
        # InputSpec), so one build per (batch, key) serves every entry
        spec = self._specs[prefix]
        if cache is None:
            return np.asarray(spec.build(batch))
        arr = cache.get(spec.key)
        if arr is None:
            arr = cache[spec.key] = np.asarray(spec.build(batch))
        return arr

    def _bool(
        self, batch: Any, prefix: str, cache: Optional[Dict[str, Any]] = None
    ) -> np.ndarray:
        return self._build(batch, prefix, cache).astype(bool, copy=False)

    def result(self) -> ConstraintForensics:
        raise NotImplementedError


class _RatioEntry(_EntryBase):
    """Completeness / compliance / pattern match: the violation mask is
    exact per batch, sampled by a deterministic Algorithm-R reservoir."""

    def __init__(self, constraint: Any, inner: Any, kind: str, cap: int):
        super().__init__(constraint, inner, kind, cap)
        analyzer = inner.analyzer
        if kind == "compliance":
            self.columns = _predicate_columns(analyzer)
        else:
            self.columns = [str(getattr(analyzer, "column", ""))]
        self.seen = 0
        self.samples: List[Optional[ViolationSample]] = []

    def _violation_mask(
        self, batch: Any, cache: Optional[Dict[str, Any]] = None
    ) -> np.ndarray:
        w = self._bool(batch, "where", cache)
        if self.kind == "completeness":
            return w & ~self._bool(batch, "valid", cache)
        if self.kind == "compliance":
            pred = self._bool(batch, "pred", cache)
            nonnull = self._bool(batch, "prednn", cache)
            return w & nonnull & ~pred
        # pattern: nulls are guarded by the valid mask, match has null->False
        return w & self._bool(batch, "valid", cache) & ~self._bool(
            batch, "match", cache
        )

    def capture(
        self,
        batch: Any,
        row_offset: int,
        owner: "ForensicsCapture",
        cache: Optional[Dict[str, Any]] = None,
    ) -> None:
        idx = np.flatnonzero(self._violation_mask(batch, cache))
        if idx.size == 0:
            return
        rng = np.random.default_rng(_batch_seed(idx, self.seen))
        winners: Dict[int, int] = {}
        t0, m = self.seen, int(idx.size)
        fill = max(0, min(self.cap - t0, m))
        for j in range(fill):
            self.samples.append(None)
            winners[t0 + j] = int(idx[j])
        if m > fill:
            # Algorithm R, vectorized: item t replaces slot r_t when
            # r_t = U[0, t] < cap. Expected hits per batch are
            # cap·ln((t0+m)/t0) — a handful — so the Python work below
            # is O(hits), not O(violations).
            ts = np.arange(t0 + fill, t0 + m, dtype=np.int64)
            rs = rng.integers(0, ts + 1)
            for h in np.flatnonzero(rs < self.cap).tolist():
                winners[int(rs[h])] = int(idx[fill + h])
        self.seen += m
        if not winners:
            return
        locals_needed = sorted(set(winners.values()))
        if self.kind == "completeness":
            # the offending value IS the null — record it as such
            values = {i: {c: None for c in self.columns} for i in locals_needed}
        else:
            per_col = {
                c: _column_values(batch, c, locals_needed) for c in self.columns
            }
            values = {
                i: {c: per_col[c][k] for c in self.columns}
                for k, i in enumerate(locals_needed)
            }
        for slot, i in winners.items():
            group, row = owner.coords(row_offset + i)
            self.samples[slot] = ViolationSample(
                owner.partition_name,
                owner.partition_fingerprint,
                group,
                row,
                values[i],
            )

    def result(self) -> ConstraintForensics:
        return ConstraintForensics(
            str(self.constraint),
            repr(self.inner.analyzer),
            self.kind,
            list(self.columns),
            self.seen,
            [s for s in self.samples if s is not None],
            capture_errors=self.errors,
        )


class _ExtremeEntry(_EntryBase):
    """Minimum / maximum bounds: per batch, test the k most extreme
    masked values through the real assertion and keep the k most
    extreme failures overall. The global extremum is some batch's
    extreme, so a failing constraint always yields >=1 sample — no
    reservoir needed, and at most `cap` Python assertion calls per
    batch."""

    def __init__(self, constraint: Any, inner: Any, kind: str, cap: int):
        super().__init__(constraint, inner, kind, cap)
        self.column = str(getattr(inner.analyzer, "column", ""))
        self.columns = [self.column]
        self.seen = 0
        self.candidates: List[Tuple[float, ViolationSample]] = []

    def _violates(self, value: float) -> bool:
        try:
            return not bool(self.inner.assertion(value))
        except Exception:  # noqa: BLE001 - a crashing assertion fails too
            return True

    def capture(
        self,
        batch: Any,
        row_offset: int,
        owner: "ForensicsCapture",
        cache: Optional[Dict[str, Any]] = None,
    ) -> None:
        num = self._build(batch, "num", cache)
        mask = self._bool(batch, "valid", cache) & self._bool(
            batch, "where", cache
        )
        idx = np.flatnonzero(mask)
        if idx.size == 0:
            return
        vals = np.asarray(num, dtype=np.float64)[idx]
        key = vals if self.kind == "minimum" else -vals
        if idx.size > self.cap:
            # O(n) partition for the k extremes, then sort only those k
            part = np.argpartition(key, self.cap - 1)[: self.cap]
            take = part[np.argsort(key[part], kind="stable")]
        else:
            take = np.argsort(key, kind="stable")
        for j in take.tolist():
            value = float(vals[j])
            if not self._violates(value):
                # candidates are sorted by extremity: once one passes,
                # every remaining (less extreme) one passes too
                break
            self.seen += 1
            group, row = owner.coords(row_offset + int(idx[j]))
            self.candidates.append(
                (
                    value,
                    ViolationSample(
                        owner.partition_name,
                        owner.partition_fingerprint,
                        group,
                        row,
                        {self.column: _json_value(value)},
                    ),
                )
            )
        self.candidates.sort(
            key=lambda t: t[0], reverse=(self.kind == "maximum")
        )
        del self.candidates[self.cap :]

    def result(self) -> ConstraintForensics:
        return ConstraintForensics(
            str(self.constraint),
            repr(self.inner.analyzer),
            self.kind,
            list(self.columns),
            self.seen,
            [s for _, s in self.candidates],
            capture_errors=self.errors,
        )


def _predicate_columns(analyzer: Any) -> List[str]:
    from deequ_tpu.data.expr import Predicate

    predicate = getattr(analyzer, "predicate", None)
    if not isinstance(predicate, str):
        return []
    try:
        cols = Predicate(predicate).referenced_columns()
    except Exception:  # noqa: BLE001 - unparseable predicate: no values
        return []
    out: List[str] = []
    for c in cols:
        if c not in out:
            out.append(c)
    return out


# ---------------------------------------------------------------------------
# the capture object threaded through the fused scan
# ---------------------------------------------------------------------------


class ForensicsCapture:
    """One per verification run (when forensics is enabled). The fused
    pass calls the `note_*` hooks as it plans and `capture_batch` once
    per decoded batch; the suite calls `finalize` after constraint
    evaluation to stamp statuses and freeze the report.

    Partitioned scans run serially in deterministic order, so the
    current-partition coordinate state lives on this one object
    (`enter_partition` re-aims it before each sub-scan)."""

    def __init__(self, checks: Sequence, max_samples: int = DEFAULT_MAX_SAMPLES):
        cap = max(1, int(max_samples))
        self.max_samples = cap
        self._entries: List[_EntryBase] = []
        self.falloffs: List[Dict[str, str]] = []
        for constraint, inner, kind, reason in classify_constraints(checks):
            if kind is None:
                self.falloffs.append(
                    {"constraint": str(constraint), "reason": reason}
                )
            elif kind in ("minimum", "maximum"):
                self._entries.append(_ExtremeEntry(constraint, inner, kind, cap))
            else:
                self._entries.append(_RatioEntry(constraint, inner, kind, cap))
        # provenance accumulators
        self.plan_signature: Optional[str] = None
        self.partitions: List[Dict[str, Any]] = []
        self.row_groups_scanned = 0
        self.row_groups_pruned = 0
        self.decode: Dict[str, int] = {
            "colsFast": 0,
            "colsFallback": 0,
            "colsWireFused": 0,
            "colsReader": 0,
            "readerGroups": 0,
        }
        # current-scan coordinate state
        self.partition_name: Optional[str] = None
        self.partition_fingerprint: Optional[str] = None
        self._rg_groups: Optional[List[int]] = None
        self._rg_starts: Optional[List[int]] = None

    # -- plan/provenance hooks (called by ops/fused.FusedScanPass) ----------

    def note_plan_signature(self, signature: str) -> None:
        self.plan_signature = str(signature)

    def note_partition(self, name: str, fingerprint: str, mode: str) -> None:
        self.partitions.append(
            {"name": str(name), "fingerprint": str(fingerprint), "mode": str(mode)}
        )

    def enter_partition(self, name: str, fingerprint: str) -> "ForensicsCapture":
        """Aim subsequent coordinates at one partition's sub-scan;
        partitions scan serially, so reusing this object is safe."""
        self.partition_name = str(name)
        self.partition_fingerprint = str(fingerprint)
        self._rg_groups = None
        self._rg_starts = None
        return self

    def note_table(self, source: Any) -> None:
        """Build the scan-offset -> (row group, row-in-group) map for
        the (already pruned) source about to be scanned, and fold its
        row-group counts into provenance. In-memory sources map to
        row_group -1 with scan-global row indices."""
        self._rg_groups = None
        self._rg_starts = None
        stats_fn = getattr(source, "row_group_stats", None)
        if not callable(stats_fn):
            return
        prune = getattr(source, "prune_groups", None) or frozenset()
        try:
            groups: List[int] = []
            starts: List[int] = []
            offset = 0
            for g in stats_fn():
                if g.index in prune:
                    continue
                groups.append(int(g.index))
                starts.append(offset)
                offset += int(g.num_rows)
            self._rg_groups = groups
            self._rg_starts = starts
            self.row_groups_scanned += len(groups)
            self.row_groups_pruned += len(prune)
        except Exception:  # noqa: BLE001 - degrade to scan-global coords
            self._rg_groups = None
            self._rg_starts = None

    def note_decode_plan(self, plan: Any) -> None:
        def _n(name: str) -> int:
            try:
                return len(getattr(plan, name, ()) or ())
            except TypeError:
                return 0

        self.decode["colsFast"] += _n("fast")
        self.decode["colsFallback"] += _n("fallbacks")
        self.decode["colsWireFused"] += _n("wire_fused")
        self.decode["colsReader"] += _n("reader_cols")
        self.decode["readerGroups"] += _n("reader_groups")

    # -- per-batch hook ------------------------------------------------------

    def coords(self, scan_row: int) -> Tuple[int, int]:
        if self._rg_starts:
            i = bisect.bisect_right(self._rg_starts, scan_row) - 1
            return self._rg_groups[i], scan_row - self._rg_starts[i]
        return -1, int(scan_row)

    def capture_batch(self, batch: Any, row_offset: int) -> None:
        """Sample violating rows from one decoded batch whose first row
        sits at scan offset `row_offset`. Never raises: a broken entry
        counts its error and the scan continues."""
        cache: Dict[str, Any] = {}
        for entry in self._entries:
            try:
                entry.capture(batch, int(row_offset), self, cache)
            except Exception:  # noqa: BLE001 - capture must not break scans
                entry.errors += 1

    # -- result side ---------------------------------------------------------

    def _provenance(self) -> Dict[str, Any]:
        scanned = sum(1 for p in self.partitions if p.get("mode") == "scan")
        cached = sum(1 for p in self.partitions if p.get("mode") == "cache")
        return {
            "planSignature": self.plan_signature,
            "partitions": [dict(p) for p in self.partitions],
            "partitionsScanned": scanned,
            "partitionsCached": cached,
            "rowGroupsScanned": self.row_groups_scanned,
            "rowGroupsPruned": self.row_groups_pruned,
            "decode": dict(self.decode),
        }

    def finalize(self, check_results: Optional[Dict] = None) -> ForensicsReport:
        status_by_id: Dict[int, str] = {}
        status_by_repr: Dict[str, str] = {}
        for cres in (check_results or {}).values():
            for cr in getattr(cres, "constraint_results", []):
                status_by_id[id(cr.constraint)] = cr.status.name
                status_by_repr.setdefault(str(cr.constraint), cr.status.name)
        constraints = []
        for entry in self._entries:
            cf = entry.result()
            cf.status = status_by_id.get(
                id(entry.constraint), status_by_repr.get(cf.constraint)
            )
            constraints.append(cf)
        return ForensicsReport(
            constraints, [dict(f) for f in self.falloffs], self._provenance()
        )

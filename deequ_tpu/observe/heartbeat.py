"""Live scan heartbeat: periodic progress snapshots for streaming scans.

The 1B-row cold pass runs for ~13 minutes and, before this module,
emitted nothing until it finished.  A heartbeat attaches to a scan and
periodically reports completed/predicted batches, instantaneous and
average rows/s, the current pipeline-stage bottleneck, and an ETA — to
registered callbacks and/or as JSONL lines — without perturbing the
scan itself.

Off by default.  Enable with `DEEQU_TPU_HEARTBEAT_S=<seconds>` (or an
explicit `interval=`); `DEEQU_TPU_HEARTBEAT_OUT=<path>` appends each
snapshot as a JSON line (the fallback sink is stderr — never stdout,
which belongs to results; the repo linter bans `print(` in observe/).

Design constraints mirror tracing:
  * near-zero-cost disabled path — `start()` returns a falsy singleton
    whose `advance()`/`timed()` are no-op attribute probes, and no
    timer thread is ever spawned;
  * all clock reads live here in `observe/` (the TIMING lint keeps
    `ops/` free of ad-hoc timing), so scan loops just wrap stages in
    `progress.timed(stage)`;
  * single-writer counters: only the scan (fold) thread calls
    `advance()`, so plain int updates suffice; the stage-busy map is
    written from multiple stage threads and guarded by one lock.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional

__all__ = [
    "ENV_KNOB",
    "ENV_OUT",
    "NOOP_PROGRESS",
    "ScanProgress",
    "current",
    "env_interval_s",
    "publish_event",
    "register_callback",
    "scan_heartbeat",
    "start",
    "unregister_callback",
]

ENV_KNOB = "DEEQU_TPU_HEARTBEAT_S"
ENV_OUT = "DEEQU_TPU_HEARTBEAT_OUT"

THREAD_NAME = "deequ-heartbeat"

_perf_counter = time.perf_counter

_callback_lock = threading.Lock()
_callbacks: List[Callable[[Dict[str, Any]], None]] = []


def register_callback(fn: Callable[[Dict[str, Any]], None]) -> None:
    """Register a process-wide heartbeat consumer (fn(snapshot_dict))."""
    with _callback_lock:
        if fn not in _callbacks:
            _callbacks.append(fn)


def unregister_callback(fn: Callable[[Dict[str, Any]], None]) -> None:
    with _callback_lock:
        if fn in _callbacks:
            _callbacks.remove(fn)


def publish_event(event: str, **fields: Any) -> None:
    """One-shot discrete pulse (vs the periodic scan snapshots): the DQ
    service publishes its lifecycle moments — preemptions, sheds,
    breaker trips, drain — through the same sinks a heartbeat uses, so
    one JSONL tail (DEEQU_TPU_HEARTBEAT_OUT) or one registered callback
    sees the whole fleet timeline interleaved with scan progress.

    Best-effort by design: a broken sink must never fail the service
    hot path, so every sink error is swallowed."""
    snap: Dict[str, Any] = {"ts": round(time.time(), 3), "event": event}
    snap.update(fields)
    with _callback_lock:
        registered = list(_callbacks)
    for fn in registered:
        try:
            fn(snap)
        except Exception:  # fault-ok: a sink must not fail the service
            pass
    out_path = os.environ.get(ENV_OUT, "").strip()
    if out_path:
        try:
            with open(out_path, "a", encoding="utf-8") as fh:
                fh.write(json.dumps(snap, sort_keys=True) + "\n")
        except OSError:  # fault-ok: sink errors never propagate
            pass


def env_interval_s() -> float:
    """Heartbeat interval from DEEQU_TPU_HEARTBEAT_S; 0.0 means off."""
    raw = os.environ.get(ENV_KNOB, "").strip()
    if not raw or raw.lower() in ("0", "off", "no", "false"):
        return 0.0
    try:
        return max(0.0, float(raw))
    except ValueError:
        return 0.0


# ---------------------------------------------------------------------------
# disabled fast path
# ---------------------------------------------------------------------------


class _NoopTimer:
    __slots__ = ()

    def __enter__(self) -> "_NoopTimer":
        return self

    def __exit__(self, *exc: object) -> bool:
        return False


_NOOP_TIMER = _NoopTimer()


class _NoopProgress:
    """Falsy inert progress handle returned when the heartbeat is off."""

    __slots__ = ()

    def __bool__(self) -> bool:
        return False

    def advance(self, rows: int, batches: int = 1) -> None:
        pass

    def timed(self, stage: str) -> _NoopTimer:
        return _NOOP_TIMER

    def note_readahead(self, hit: bool) -> None:
        pass

    def snapshot(self, final: bool = False) -> Optional[Dict[str, Any]]:
        return None

    def finish(self) -> None:
        pass


NOOP_PROGRESS = _NoopProgress()


# ---------------------------------------------------------------------------
# active-progress registry
# ---------------------------------------------------------------------------
#
# Worker threads the scan spawns (decode pool, the native reader's
# read-ahead fetch thread) have no handle on the scan's progress object;
# the registry lets them self-time under their stage without any
# plumbing: `heartbeat.current().timed("read")`. Process-wide, not
# thread-local, because those threads are precisely NOT the scan thread.

_active_lock = threading.Lock()
_active: List["ScanProgress"] = []


def current() -> Any:
    """The innermost live ScanProgress, or NOOP_PROGRESS when no
    heartbeat is running (the usual case — everything stays no-op)."""
    with _active_lock:
        return _active[-1] if _active else NOOP_PROGRESS


def _register(progress: "ScanProgress") -> None:
    with _active_lock:
        _active.append(progress)


def _unregister(progress: "ScanProgress") -> None:
    with _active_lock:
        if progress in _active:
            _active.remove(progress)


# ---------------------------------------------------------------------------
# live progress
# ---------------------------------------------------------------------------


class _StageTimer:
    __slots__ = ("_progress", "_stage", "_t0")

    def __init__(self, progress: "ScanProgress", stage: str) -> None:
        self._progress = progress
        self._stage = stage

    def __enter__(self) -> "_StageTimer":
        self._t0 = _perf_counter()
        return self

    def __exit__(self, *exc: object) -> bool:
        dt = _perf_counter() - self._t0
        progress = self._progress
        with progress._stage_lock:
            busy = progress._stage_busy
            busy[self._stage] = busy.get(self._stage, 0.0) + dt
        return False


class ScanProgress:
    """Mutable progress state for one scan plus its emission timer."""

    def __init__(
        self,
        interval: float,
        *,
        total_rows: Optional[int] = None,
        predicted_batches: Optional[int] = None,
        callback: Optional[Callable[[Dict[str, Any]], None]] = None,
        out_path: Optional[str] = None,
        name: str = "scan",
    ) -> None:
        self.interval = float(interval)
        self.total_rows = total_rows
        self.predicted_batches = predicted_batches
        self.name = name
        self.rows = 0
        self.batches = 0
        self.snapshots_emitted = 0
        self._callback = callback
        self._out_path = out_path
        self._t0 = _perf_counter()
        self._epoch_unix = time.time()
        self._last_rows = 0
        self._last_t = self._t0
        self._stage_lock = threading.Lock()
        self._stage_busy: Dict[str, float] = {}
        self._readahead_hits = 0
        self._readahead_misses = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def __bool__(self) -> bool:
        return True

    # -- scan-side hooks (hot path) -----------------------------------------

    def advance(self, rows: int, batches: int = 1) -> None:
        self.rows += int(rows)
        self.batches += batches

    def timed(self, stage: str) -> _StageTimer:
        return _StageTimer(self, stage)

    def note_readahead(self, hit: bool) -> None:
        """Read-ahead window accounting from the native parquet reader's
        decode side: `hit` means the prefetch future was already done
        when the decoder asked for it. A miss is a decode stall waiting
        on the window — time the stage timers misattribute to the
        *consumer's* stage, so it must be counted, not timed."""
        with self._stage_lock:
            if hit:
                self._readahead_hits += 1
            else:
                self._readahead_misses += 1

    # -- snapshots -----------------------------------------------------------

    def snapshot(self, final: bool = False) -> Dict[str, Any]:
        now = _perf_counter()
        wall = max(now - self._t0, 1e-9)
        rows, batches = self.rows, self.batches
        dt = max(now - self._last_t, 1e-9)
        inst = (rows - self._last_rows) / dt
        self._last_rows, self._last_t = rows, now
        avg = rows / wall
        with self._stage_lock:
            stages = dict(self._stage_busy)
            ra_hits, ra_misses = self._readahead_hits, self._readahead_misses

        eta: Optional[float] = None
        progress_frac: Optional[float] = None
        if self.total_rows and avg > 0.0:
            eta = max(self.total_rows - rows, 0) / avg
            progress_frac = min(rows / self.total_rows, 1.0)
        elif self.predicted_batches and batches > 0:
            eta = max(self.predicted_batches - batches, 0) * (wall / batches)
            progress_frac = min(batches / self.predicted_batches, 1.0)

        snap: Dict[str, Any] = {
            "ts": round(self._epoch_unix + (now - self._t0), 3),
            "name": self.name,
            "wall_s": round(wall, 3),
            "rows": rows,
            "batches": batches,
            "rows_per_s": round(inst, 1),
            "avg_rows_per_s": round(avg, 1),
            "done": bool(final),
        }
        if self.predicted_batches is not None:
            snap["predicted_batches"] = self.predicted_batches
        if self.total_rows is not None:
            snap["total_rows"] = self.total_rows
        if progress_frac is not None:
            snap["progress"] = round(progress_frac, 4)
        if eta is not None:
            snap["eta_s"] = round(eta, 3)
        if stages:
            snap["bottleneck"] = max(stages, key=lambda s: stages[s])
            snap["occupancy"] = {s: round(b / wall, 4) for s, b in sorted(stages.items())}
        if ra_hits or ra_misses:
            snap["readahead"] = {"hits": ra_hits, "misses": ra_misses}
            if ra_misses > ra_hits:
                # a starved read-ahead window stalls the decoder inside
                # its own stage timer; name the true bottleneck
                snap["bottleneck"] = "read"
        return snap

    def _emit(self, snap: Dict[str, Any]) -> None:
        self.snapshots_emitted += 1
        sinks = 0
        if self._callback is not None:
            sinks += 1
            try:
                self._callback(snap)
            except Exception:
                pass
        with _callback_lock:
            registered = list(_callbacks)
        for fn in registered:
            sinks += 1
            try:
                fn(snap)
            except Exception:
                pass
        line = json.dumps(snap, sort_keys=True) + "\n"
        if self._out_path:
            try:
                with open(self._out_path, "a", encoding="utf-8") as fh:
                    fh.write(line)
            except OSError:
                pass
        elif sinks == 0:
            # last-resort sink so an env-enabled heartbeat is never silent;
            # stderr, because stdout carries results (bench JSON contract)
            sys.stderr.write(line)

    # -- timer lifecycle ------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            self._emit(self.snapshot())

    def start_timer(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True, name=THREAD_NAME)
        self._thread.start()

    def finish(self) -> None:
        """Stop the timer and emit one final (done=True) snapshot."""
        _unregister(self)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self._emit(self.snapshot(final=True))


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def start(
    interval: Optional[float] = None,
    *,
    total_rows: Optional[int] = None,
    predicted_batches: Optional[int] = None,
    callback: Optional[Callable[[Dict[str, Any]], None]] = None,
    out_path: Optional[str] = None,
    name: str = "scan",
) -> Any:
    """Begin a heartbeat; returns NOOP_PROGRESS (falsy) when disabled.

    Imperative twin of `scan_heartbeat` for call sites that pair it with
    an existing try/finally; callers must invoke `.finish()`.
    """
    iv = env_interval_s() if interval is None else float(interval)
    if iv <= 0.0:
        return NOOP_PROGRESS
    if out_path is None:
        out_path = os.environ.get(ENV_OUT, "").strip() or None
    progress = ScanProgress(
        iv,
        total_rows=total_rows,
        predicted_batches=predicted_batches,
        callback=callback,
        out_path=out_path,
        name=name,
    )
    progress.start_timer()
    _register(progress)
    return progress


@contextlib.contextmanager
def scan_heartbeat(
    interval: Optional[float] = None,
    *,
    total_rows: Optional[int] = None,
    predicted_batches: Optional[int] = None,
    callback: Optional[Callable[[Dict[str, Any]], None]] = None,
    out_path: Optional[str] = None,
    name: str = "scan",
) -> Iterator[Any]:
    """Context-managed heartbeat around a scan (yields the progress handle)."""
    progress = start(
        interval,
        total_rows=total_rows,
        predicted_batches=predicted_batches,
        callback=callback,
        out_path=out_path,
        name=name,
    )
    try:
        yield progress
    finally:
        progress.finish()

"""Human-readable run reports and per-phase accounting.

`phase_seconds` buckets SELF time (a span's duration minus its
children's) by span category, so the buckets are disjoint and sum to
~the run's wall time — the per-operator accounting LaraDB
(arXiv:1703.07342) argues fused kernels need. `render_report` draws
the span tree with durations, categories and attributes; repeated
siblings (per-batch dispatches, per-family kernels) aggregate into one
`×N` line so streaming runs stay readable.

Both are pure functions of the span forest — the golden test feeds
hand-built spans with fixed times and string-compares the output.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from deequ_tpu.observe.spans import Span, Tracer

# The headline buckets (always present in phase_seconds, even at 0.0):
# fuse-group planning, kernel dispatch, device<->host transfer, state
# merge. Other categories (native, group, scan, constraint, ...) appear
# when spans carry them.
PHASES = ("plan", "dispatch", "transfer", "merge")

Roots = Union[Span, Tracer, Sequence[Span]]


def _roots_of(roots: Roots) -> Sequence[Span]:
    if isinstance(roots, Span):
        return [roots]
    if isinstance(roots, Tracer):
        return roots.roots
    return list(roots)


def phase_seconds(roots: Roots) -> Dict[str, float]:
    """Disjoint self-time per span category, in seconds."""
    buckets: Dict[str, float] = {phase: 0.0 for phase in PHASES}

    def visit(span: Span) -> None:
        child_total = sum(c.duration_s for c in span.children)
        self_time = max(span.duration_s - child_total, 0.0)
        cat = span.cat or "other"
        buckets[cat] = buckets.get(cat, 0.0) + self_time
        for child in span.children:
            visit(child)

    for root in _roots_of(roots):
        visit(root)
    return buckets


def _fmt_attr(value: Any) -> str:
    if isinstance(value, float):
        return f"{value:.4g}"
    return str(value)


def _attr_text(attrs: Dict[str, Any]) -> str:
    parts = [
        f"{key}={_fmt_attr(value)}"
        for key, value in sorted(attrs.items())
        if isinstance(value, (int, float, str, bool)) and key != "cpu_ms"
    ]
    return " ".join(parts)


def _aggregate(children: Sequence[Span]) -> List[Tuple[Span, int, float]]:
    """Collapse same-(name, cat) siblings: (exemplar, count, total_s)."""
    order: List[Tuple[str, Optional[str]]] = []
    groups: Dict[Tuple[str, Optional[str]], List[Span]] = {}
    for child in children:
        key = (child.name, child.cat)
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(child)
    out = []
    for key in order:
        members = groups[key]
        out.append((members[0], len(members), sum(m.duration_s for m in members)))
    return out


def _render_span(
    span: Span,
    count: int,
    total_s: float,
    prefix: str,
    is_last: bool,
    lines: List[str],
    depth: int,
    max_depth: int,
) -> None:
    connector = "└─ " if is_last else "├─ "
    label = span.name if count == 1 else f"{span.name} ×{count}"
    head = f"{prefix}{connector}{label}"
    tail = f"{total_s * 1e3:9.1f} ms"
    if span.cat:
        tail += f"  [{span.cat}]"
    attrs = _attr_text(span.attrs) if count == 1 else ""
    if attrs:
        tail += f"  {attrs}"
    lines.append(f"{head:<44}{tail}")
    if depth + 1 >= max_depth:
        return
    child_prefix = prefix + ("   " if is_last else "│  ")
    grouped = _aggregate(span.children)
    for i, (child, n, secs) in enumerate(grouped):
        _render_span(
            child,
            n,
            secs,
            child_prefix,
            i == len(grouped) - 1,
            lines,
            depth + 1,
            max_depth,
        )


def render_report(
    roots: Roots,
    counters: Optional[Dict[str, int]] = None,
    max_depth: int = 8,
) -> str:
    """The run report: headline counters, the (aggregated) span tree,
    and the per-phase self-time line."""
    root_list = _roots_of(roots)
    if not root_list:
        return "deequ_tpu run report — (no spans recorded)"
    head = root_list[0]
    wall_s = sum(r.duration_s for r in root_list)
    cpu_s = sum(r.cpu_s for r in root_list)
    title = head.name if len(root_list) == 1 else f"{len(root_list)} runs"
    lines = [f"deequ_tpu run report — {title}"]
    headline = [f"wall {wall_s * 1e3:.1f} ms", f"cpu {cpu_s * 1e3:.1f} ms"]
    for key in ("device_passes", "device_launches", "group_passes"):
        value = (counters or {}).get(key, head.attrs.get(key))
        if value is not None:
            headline.append(f"{key} {value}")
    lines.append(" | ".join(headline))
    for root in root_list:
        grouped = _aggregate(root.children)
        root_tail = f"{root.duration_s * 1e3:9.1f} ms"
        attrs = _attr_text(root.attrs)
        if attrs:
            root_tail += f"  {attrs}"
        lines.append(f"{root.name:<44}{root_tail}")
        for i, (child, n, secs) in enumerate(grouped):
            _render_span(
                child, n, secs, "", i == len(grouped) - 1, lines, 1, max_depth
            )
    phases = phase_seconds(root_list)
    phase_text = " | ".join(
        f"{name} {phases[name]:.3f}s"
        for name in sorted(phases, key=lambda k: (-phases[k], k))
        if phases[name] > 0 or name in PHASES
    )
    lines.append(f"phases (self-time): {phase_text}")
    return "\n".join(lines)

"""Human-readable run reports and per-phase accounting.

`phase_seconds` buckets SELF time (a span's duration minus its
children's) by span category, so the buckets are disjoint and sum to
~the run's wall time — the per-operator accounting LaraDB
(arXiv:1703.07342) argues fused kernels need. `render_report` draws
the span tree with durations, categories and attributes; repeated
siblings (per-batch dispatches, per-family kernels) aggregate into one
`×N` line so streaming runs stay readable.

Both are pure functions of the span forest — the golden test feeds
hand-built spans with fixed times and string-compares the output.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from deequ_tpu.observe.spans import Span, Tracer

# The headline buckets (always present in phase_seconds, even at 0.0):
# fuse-group planning, kernel dispatch, device<->host transfer, state
# merge. Other categories (native, group, scan, constraint, ...) appear
# when spans carry them.
PHASES = ("plan", "dispatch", "transfer", "merge")

# Stream-pipeline span vocabulary (ops/pipeline.py, data/source.py):
# one PIPE_STAGE_SPAN per stage-thread lifetime, one PIPE_ITEM_SPAN
# child per batch of actual stage work. Wall minus the items' busy time
# is stall — waiting on a queue, i.e. on another stage.
PIPE_STAGE_SPAN = "pipe_stage"
PIPE_ITEM_SPAN = "pipe_item"

Roots = Union[Span, Tracer, Sequence[Span]]


def _roots_of(roots: Roots) -> Sequence[Span]:
    if isinstance(roots, Span):
        return [roots]
    if isinstance(roots, Tracer):
        return roots.roots
    return list(roots)


def phase_seconds(roots: Roots) -> Dict[str, float]:
    """Disjoint self-time per span category, in seconds."""
    buckets: Dict[str, float] = {phase: 0.0 for phase in PHASES}

    def visit(span: Span) -> None:
        child_total = sum(c.duration_s for c in span.children)
        self_time = max(span.duration_s - child_total, 0.0)
        cat = span.cat or "other"
        buckets[cat] = buckets.get(cat, 0.0) + self_time
        for child in span.children:
            visit(child)

    for root in _roots_of(roots):
        visit(root)
    return buckets


def pipeline_occupancy(roots: Roots) -> List[Dict[str, Any]]:
    """Aggregate stream-pipeline stage utilisation from the span forest.

    For every `pipe_stage` span (one per stage-thread lifetime), its
    `pipe_item` children are the stage's actual per-batch work; the
    rest of the stage's wall is stall — blocked on an inter-stage queue,
    i.e. waiting for another stage. Returns one row per stage name:

        {stage, wall_s, busy_s, stall_s, occupancy, items}

    sorted by busy_s descending, so row 0 is the pipeline's bottleneck
    stage (the one the other stages stall on). The native parquet
    reader's read-ahead window (data/source.py `page_read` spans +
    `readahead_hit` on `page_decode`) folds in as a synthetic "read"
    row: when prefetch misses dominate, the decoder's blocked waits
    hide inside another stage's time, so the read row is promoted to
    the bottleneck slot instead of the stall showing up as idle decode.
    Pure function of the spans; the same rows back `render_report`'s
    pipeline section and the bench artifacts' occupancy breakdown.
    Empty when the run never engaged the pipeline (serial fallback,
    in-memory tables)."""
    rows: Dict[str, Dict[str, Any]] = {}
    order: List[str] = []
    readahead = {"spans": 0, "busy_s": 0.0, "hits": 0, "misses": 0}

    def visit(span: Span) -> None:
        if span.name == PIPE_STAGE_SPAN:
            stage = str(span.attrs.get("stage", "?"))
            row = rows.get(stage)
            if row is None:
                row = rows[stage] = {
                    "stage": stage, "wall_s": 0.0, "busy_s": 0.0, "items": 0,
                }
                order.append(stage)
            row["wall_s"] += span.duration_s
            for child in span.children:
                if child.name != PIPE_ITEM_SPAN:
                    continue
                # the eos item is the decode tail (flush + close): real
                # stage time, but not a delivered batch
                row["busy_s"] += child.duration_s
                if not child.attrs.get("eos"):
                    row["items"] += 1
        elif span.name == "page_read":
            readahead["spans"] += 1
            readahead["busy_s"] += span.duration_s
        elif span.name == "page_decode" and "readahead_hit" in span.attrs:
            key = "hits" if span.attrs.get("readahead_hit") else "misses"
            readahead[key] += 1
        for child in span.children:
            visit(child)

    for root in _roots_of(roots):
        visit(root)
    out = []
    for stage in order:
        row = rows[stage]
        row["stall_s"] = max(row["wall_s"] - row["busy_s"], 0.0)
        row["occupancy"] = (
            row["busy_s"] / row["wall_s"] if row["wall_s"] > 0 else 0.0
        )
        out.append(row)
    out.sort(key=lambda r: -r["busy_s"])
    if out and readahead["spans"]:
        # the fetch thread has no pipe_stage span of its own; its wall
        # is the pipeline's wall (the widest stage)
        wall = max(r["wall_s"] for r in out)
        busy = min(readahead["busy_s"], wall)
        row = {
            "stage": "read",
            "wall_s": wall,
            "busy_s": busy,
            "items": readahead["spans"],
            "stall_s": max(wall - busy, 0.0),
            "occupancy": busy / wall if wall > 0 else 0.0,
            "readahead_hits": readahead["hits"],
            "readahead_misses": readahead["misses"],
        }
        if readahead["misses"] > readahead["hits"]:
            # starved window: consumers block on fetch futures, so the
            # read stage is the true bottleneck
            out.insert(0, row)
        else:
            out.append(row)
    return out


def _fmt_attr(value: Any) -> str:
    if isinstance(value, float):
        return f"{value:.4g}"
    return str(value)


def _attr_text(attrs: Dict[str, Any]) -> str:
    parts = [
        f"{key}={_fmt_attr(value)}"
        for key, value in sorted(attrs.items())
        if isinstance(value, (int, float, str, bool)) and key != "cpu_ms"
    ]
    return " ".join(parts)


def _aggregate(children: Sequence[Span]) -> List[Tuple[Span, int, float]]:
    """Collapse same-(name, cat) siblings: (exemplar, count, total_s)."""
    order: List[Tuple[str, Optional[str]]] = []
    groups: Dict[Tuple[str, Optional[str]], List[Span]] = {}
    for child in children:
        key = (child.name, child.cat)
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(child)
    out = []
    for key in order:
        members = groups[key]
        out.append((members[0], len(members), sum(m.duration_s for m in members)))
    return out


def _render_span(
    span: Span,
    count: int,
    total_s: float,
    prefix: str,
    is_last: bool,
    lines: List[str],
    depth: int,
    max_depth: int,
) -> None:
    connector = "└─ " if is_last else "├─ "
    label = span.name if count == 1 else f"{span.name} ×{count}"
    head = f"{prefix}{connector}{label}"
    tail = f"{total_s * 1e3:9.1f} ms"
    if span.cat:
        tail += f"  [{span.cat}]"
    attrs = _attr_text(span.attrs) if count == 1 else ""
    if attrs:
        tail += f"  {attrs}"
    lines.append(f"{head:<44}{tail}")
    if depth + 1 >= max_depth:
        return
    child_prefix = prefix + ("   " if is_last else "│  ")
    grouped = _aggregate(span.children)
    for i, (child, n, secs) in enumerate(grouped):
        _render_span(
            child,
            n,
            secs,
            child_prefix,
            i == len(grouped) - 1,
            lines,
            depth + 1,
            max_depth,
        )


def render_report(
    roots: Roots,
    counters: Optional[Dict[str, int]] = None,
    max_depth: int = 8,
    forensics: Optional[Any] = None,
) -> str:
    """The run report: headline counters, the (aggregated) span tree,
    and the per-phase self-time line. Pass a ForensicsReport (e.g.
    `result.forensics()`) as `forensics` to append the failure-forensics
    section — sampled violating rows and scan provenance per failed
    constraint."""
    root_list = _roots_of(roots)
    if not root_list:
        return "deequ_tpu run report — (no spans recorded)"
    head = root_list[0]
    wall_s = sum(r.duration_s for r in root_list)
    cpu_s = sum(r.cpu_s for r in root_list)
    title = head.name if len(root_list) == 1 else f"{len(root_list)} runs"
    lines = [f"deequ_tpu run report — {title}"]
    headline = [f"wall {wall_s * 1e3:.1f} ms", f"cpu {cpu_s * 1e3:.1f} ms"]
    for key in ("device_passes", "device_launches", "group_passes"):
        value = (counters or {}).get(key, head.attrs.get(key))
        if value is not None:
            headline.append(f"{key} {value}")
    lines.append(" | ".join(headline))
    for root in root_list:
        grouped = _aggregate(root.children)
        root_tail = f"{root.duration_s * 1e3:9.1f} ms"
        attrs = _attr_text(root.attrs)
        if attrs:
            root_tail += f"  {attrs}"
        lines.append(f"{root.name:<44}{root_tail}")
        for i, (child, n, secs) in enumerate(grouped):
            _render_span(
                child, n, secs, "", i == len(grouped) - 1, lines, 1, max_depth
            )
    occupancy = pipeline_occupancy(root_list)
    if occupancy:
        lines.append("pipeline occupancy (busy/wall per stage):")
        for i, row in enumerate(occupancy):
            marker = "  <- bottleneck" if i == 0 else ""
            ra = ""
            if "readahead_hits" in row:
                ra = (
                    f"  readahead {row['readahead_hits']}h"
                    f"/{row['readahead_misses']}m"
                )
            lines.append(
                f"  {row['stage']:<8} {row['occupancy'] * 100:5.1f}%"
                f"  busy {row['busy_s']:.3f}s"
                f"  stall {row['stall_s']:.3f}s"
                f"  items {row['items']}{ra}{marker}"
            )
    phases = phase_seconds(root_list)
    phase_text = " | ".join(
        f"{name} {phases[name]:.3f}s"
        for name in sorted(phases, key=lambda k: (-phases[k], k))
        if phases[name] > 0 or name in PHASES
    )
    lines.append(f"phases (self-time): {phase_text}")
    if forensics is not None:
        # duck-typed (ForensicsReport.render via __str__) so this module
        # never imports observe/forensics — row VALUES belong to reports
        # the operator asks for, never to telemetry records
        lines.append(str(forensics))
    return "\n".join(lines)

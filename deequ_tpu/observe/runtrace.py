"""Top-level run tracing: the `DEEQU_TPU_TRACE` knob, `traced_run()`
entry points, and the `RunTrace` object attached to results.

`traced_run(name, enable=...)` is what the runners call around a whole
verification/analysis run:

  * already inside an active tracer (e.g. the suite traced and now the
    analysis run starts) → plain child span; the nested run still gets
    its own `RunTrace` covering its subtree;
  * `enable` True / a path / env knob set → a fresh root tracer for
    the run (the env knob reuses one process-wide tracer so sequential
    runs accumulate into one trace file);
  * otherwise → disabled: the handle is falsy and the body runs on the
    `span()` no-op fast path.

Env knob: `DEEQU_TPU_TRACE` unset/`0`/`false`/`off` disables; any
other value enables. A value that looks like a path (contains a
separator or ends in `.json`) doubles as the output path;
`DEEQU_TPU_TRACE_OUT` always wins. Default output lands in the system
temp dir, one file per OS process with the jax process index appended
under multihost (merge with `observe.merge_chrome_traces`).
"""

from __future__ import annotations

import contextlib
import os
import sys
import tempfile
import threading
from typing import Any, Dict, Iterator, Optional

from deequ_tpu.observe import export, report, spans
from deequ_tpu.observe.spans import Span, Tracer

ENV_KNOB = "DEEQU_TPU_TRACE"
ENV_OUT = "DEEQU_TPU_TRACE_OUT"

_FALSEY = ("", "0", "false", "no", "off")
_TRUTHY_PLAIN = ("1", "true", "yes", "on")

# Keep at most this many env-traced runs in the process-wide tracer so
# a long-lived process (bench loops, services) stays bounded.
_ENV_TRACER_MAX_ROOTS = 256

_env_lock = threading.Lock()
_env_tracer: Optional[Tracer] = None
_announced_paths: set = set()


def env_enabled() -> bool:
    return os.environ.get(ENV_KNOB, "").strip().lower() not in _FALSEY


def default_trace_path() -> str:
    return os.path.join(
        tempfile.gettempdir(), f"deequ_tpu_trace_{os.getpid()}.json"
    )


def _env_out_path() -> str:
    out = os.environ.get(ENV_OUT, "").strip()
    if out:
        return out
    value = os.environ.get(ENV_KNOB, "").strip()
    if value.lower() not in _TRUTHY_PLAIN and (
        os.sep in value or value.endswith(".json")
    ):
        return value
    return default_trace_path()


def _per_process_path(path: str) -> str:
    """Suffix the jax process index under multihost so every process
    writes its own file (merged later by `merge_chrome_traces`)."""
    if "jax" in sys.modules:
        try:
            import jax

            if jax.process_count() > 1:
                stem, ext = os.path.splitext(path)
                return f"{stem}_p{jax.process_index()}{ext or '.json'}"
        except Exception:
            pass
    return path


def _get_env_tracer() -> Tracer:
    global _env_tracer
    with _env_lock:
        if _env_tracer is None:
            _env_tracer = Tracer()
        elif len(_env_tracer.roots) >= _ENV_TRACER_MAX_ROOTS:
            with _env_tracer.lock:
                del _env_tracer.roots[: -_ENV_TRACER_MAX_ROOTS // 2]
        return _env_tracer


class RunTrace:
    """One traced run: its root span, counter snapshot, and exporters.
    Attached to `VerificationResult.run_trace` / `AnalyzerContext
    .run_trace` (the `validation_warnings` pattern from PR 2)."""

    __slots__ = ("root", "epoch", "counters", "path")

    def __init__(
        self,
        root: Span,
        epoch: float,
        counters: Dict[str, int],
        path: Optional[str] = None,
    ):
        self.root = root
        self.epoch = epoch
        self.counters = dict(counters)
        self.path = path  # where the trace file landed, when one was written

    @property
    def duration_s(self) -> float:
        return self.root.duration_s

    def phase_seconds(self) -> Dict[str, float]:
        return report.phase_seconds([self.root])

    def to_chrome_trace(self) -> dict:
        return export.chrome_trace([self.root], epoch=self.epoch)

    def write(self, path: Optional[str] = None) -> str:
        target = path or self.path or default_trace_path()
        self.path = export.write_chrome_trace(
            target, [self.root], epoch=self.epoch
        )
        return self.path

    def report(self) -> str:
        return report.render_report([self.root], counters=self.counters)

    def spans(self) -> Iterator[Span]:
        return self.root.walk()

    def __repr__(self) -> str:
        return (
            f"RunTrace({self.root.name!r}, {self.duration_s * 1e3:.1f}ms, "
            f"counters={self.counters})"
        )


class RunHandle:
    """Yielded by `traced_run`. Falsy when tracing is off; `.trace`
    holds the finished `RunTrace` after the block exits."""

    __slots__ = ("span", "trace")

    def __init__(self) -> None:
        self.span: Optional[Span] = None
        self.trace: Optional[RunTrace] = None

    def __bool__(self) -> bool:
        return self.span is not None


def _counter_delta(
    tracer: Tracer, before: Dict[str, int]
) -> Dict[str, int]:
    return {
        key: value - before.get(key, 0)
        for key, value in tracer.counters.items()
        if value - before.get(key, 0)
    }


def _set_resource_attrs(run_span: Span) -> None:
    """Stamp peak RSS / major faults on the run span (from /proc, no
    psutil), so run reports and bench records carry their own resource
    accounting. Both values are process-cumulative: for nested runs
    they describe the process at run end, not the run's own delta."""
    from deequ_tpu.observe import telemetry

    try:
        res = telemetry.proc_resources()
    except Exception:
        return
    if "peak_rss_mb" in res:
        run_span.set(peak_rss_mb=round(res["peak_rss_mb"], 2))
    if "major_faults" in res:
        run_span.set(major_faults=int(res["major_faults"]))


@contextlib.contextmanager
def traced_run(
    name: str, enable: Any = None, **attrs: Any
) -> Iterator[RunHandle]:
    handle = RunHandle()
    active = spans.current_tracer()
    if active is not None:
        # Nested under an outer traced run: contribute a child subtree.
        before = dict(active.counters)
        with spans.span(name, cat="run", **attrs) as run_span:
            handle.span = run_span
            try:
                yield handle
            finally:
                delta = _counter_delta(active, before)
                run_span.set(**delta)
                _set_resource_attrs(run_span)
                handle.trace = RunTrace(run_span, active.epoch, delta)
        return

    out_path: Optional[str] = None
    if enable is None:
        if env_enabled():
            tracer = _get_env_tracer()
            out_path = _per_process_path(_env_out_path())
        else:
            yield handle
            return
    elif isinstance(enable, str):
        tracer = Tracer()
        out_path = _per_process_path(enable)
    elif enable:
        tracer = Tracer()
        out_path = os.environ.get(ENV_OUT, "").strip() or None
        if out_path:
            out_path = _per_process_path(out_path)
    else:
        yield handle
        return

    before = dict(tracer.counters)
    with spans.tracing(tracer):
        with spans.span(name, cat="run", **attrs) as run_span:
            handle.span = run_span
            try:
                yield handle
            finally:
                delta = _counter_delta(tracer, before)
                run_span.set(**delta)
                _set_resource_attrs(run_span)
                handle.trace = RunTrace(run_span, tracer.epoch, delta)
    if out_path is not None and handle.trace is not None:
        try:
            # The env tracer accumulates runs: rewrite the whole forest
            # so the file always holds everything traced so far.
            roots = tracer.roots if tracer is _env_tracer else [handle.trace.root]
            export.write_chrome_trace(out_path, roots, epoch=tracer.epoch)
            handle.trace.path = out_path
            if out_path not in _announced_paths:
                _announced_paths.add(out_path)
                sys.stderr.write(
                    f"# deequ_tpu: trace -> {out_path} "
                    f"(load in https://ui.perfetto.dev)\n"
                )
        except OSError:
            pass

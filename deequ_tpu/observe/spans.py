"""Hierarchical spans and the thread-local trace context stack.

A `Span` is one timed region (wall via perf_counter, CPU via
process_time) with attributes and children. A `Tracer` owns a forest of
root spans plus run-level counters; `tracing()` installs one on the
current thread, `span()` opens a child of whatever is innermost.

The disabled fast path is the design center: with no tracer installed,
`span()` is a single thread-local attribute probe returning the
singleton `_NOOP` (falsy, inert context manager), so instrumented hot
paths pay ~a function call when observability is off. The per-phase
accounting (DrJAX-style structured telemetry, arXiv:2403.07128; LaraDB
per-operator accounting, arXiv:1703.07342) only materializes when a
tracer is active.

Worker-pool threads see an empty stack by construction (thread-local);
a dispatcher that fans work out to a pool captures `current_tracer()` /
`current_span()` and has workers adopt them with `attached()`.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

_perf_counter = time.perf_counter
_process_time = time.process_time

_local = threading.local()


def _stack() -> List[Tuple["Tracer", Optional["Span"]]]:
    try:
        return _local.stack
    except AttributeError:
        st: List[Tuple["Tracer", Optional["Span"]]] = []
        _local.stack = st
        return st


def current_tracer() -> Optional["Tracer"]:
    st = getattr(_local, "stack", None)
    return st[-1][0] if st else None


def current_span() -> Optional["Span"]:
    st = getattr(_local, "stack", None)
    return st[-1][1] if st else None


class Span:
    """One timed region of a traced run. Context manager: times the
    block, attaches itself under the innermost open span (or as a
    tracer root), and is the innermost span for the duration."""

    __slots__ = (
        "name",
        "cat",
        "t0",
        "t1",
        "cpu0",
        "cpu1",
        "tid",
        "attrs",
        "children",
    )

    def __init__(self, name: str, cat: Optional[str] = None, attrs=None):
        self.name = name
        self.cat = cat
        self.t0 = self.t1 = 0.0
        self.cpu0 = self.cpu1 = 0.0
        self.tid = 0
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}
        self.children: List[Span] = []

    @property
    def duration_s(self) -> float:
        return max(self.t1 - self.t0, 0.0)

    @property
    def cpu_s(self) -> float:
        return max(self.cpu1 - self.cpu0, 0.0)

    def set(self, **attrs: Any) -> "Span":
        self.attrs.update(attrs)
        return self

    def add(self, key: str, n: Any = 1) -> "Span":
        self.attrs[key] = self.attrs.get(key, 0) + n
        return self

    def walk(self) -> Iterator["Span"]:
        yield self
        for child in self.children:
            yield from child.walk()

    def __bool__(self) -> bool:
        return True

    def __enter__(self) -> "Span":
        st = _stack()
        tracer, parent = st[-1] if st else (None, None)
        self.tid = threading.get_ident()
        if tracer is not None:
            with tracer.lock:
                sink = parent.children if parent is not None else tracer.roots
                sink.append(self)
            st.append((tracer, self))
        self.cpu0 = _process_time()
        self.t0 = _perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.t1 = _perf_counter()
        self.cpu1 = _process_time()
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        st = getattr(_local, "stack", None)
        if st:
            if st[-1][1] is self:
                st.pop()
            else:  # unbalanced exit (span closed on another thread/path)
                for i in range(len(st) - 1, -1, -1):
                    if st[i][1] is self:
                        del st[i]
                        break
        return False

    def __repr__(self) -> str:
        return (
            f"Span({self.name!r}, cat={self.cat!r}, "
            f"dur={self.duration_s * 1e3:.3f}ms, "
            f"children={len(self.children)})"
        )


class _NoopSpan:
    """Singleton stand-in when no tracer is installed: falsy, inert."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False

    def set(self, **attrs: Any) -> "_NoopSpan":
        return self

    def add(self, key: str, n: Any = 1) -> "_NoopSpan":
        return self

    def __bool__(self) -> bool:
        return False


_NOOP = _NoopSpan()


def span(name: str, cat: Optional[str] = None, **attrs: Any):
    """Open a span under the current thread's trace context. Returns
    the inert singleton when tracing is off — the disabled fast path."""
    st = getattr(_local, "stack", None)
    if not st:
        return _NOOP
    return Span(name, cat, attrs)


def annotate(**attrs: Any) -> None:
    """Set attributes on the innermost open span; no-op when untraced."""
    s = current_span()
    if s is not None:
        s.attrs.update(attrs)


class Tracer:
    """Owns one trace: a forest of root spans, a monotonic epoch the
    exporter subtracts timestamps from, and run-level counters kept
    bit-identical to `ExecutionStats` (observe.counters feeds both)."""

    __slots__ = ("lock", "roots", "epoch", "epoch_unix", "counters", "labels")

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.roots: List[Span] = []
        self.epoch = _perf_counter()
        self.epoch_unix = time.time()
        self.counters: Dict[str, int] = {}
        self.labels: List[str] = []

    def count(self, name: str, n: int = 1, label: Optional[str] = None) -> None:
        with self.lock:
            self.counters[name] = self.counters.get(name, 0) + n
            if label is not None:
                self.labels.append(label)
        s = current_span()
        if s is not None:
            s.add(name, n)


@contextlib.contextmanager
def tracing(tracer: Optional[Tracer] = None) -> Iterator[Tracer]:
    """Install a tracer on this thread for the block. Spans opened
    inside (on this thread, or on workers that `attached()` to it)
    land in `tracer.roots`."""
    if tracer is None:
        tracer = Tracer()
    st = _stack()
    base = len(st)
    st.append((tracer, None))
    try:
        yield tracer
    finally:
        del st[base:]


@contextlib.contextmanager
def attached(tracer: Optional[Tracer], parent: Optional[Span]) -> Iterator[None]:
    """Adopt another thread's (tracer, parent span) as this thread's
    trace context — how worker-pool threads keep their spans under the
    dispatching scan's subtree. No-op when `tracer` is None, so callers
    can capture `current_tracer()/current_span()` unconditionally."""
    if tracer is None:
        yield
        return
    st = _stack()
    base = len(st)
    st.append((tracer, parent))
    try:
        yield
    finally:
        del st[base:]


def timed_call(fn) -> float:
    """Wall-clock seconds of `fn()`. The one sanctioned timing helper
    for engine code — `tools/lint.py` bans raw perf_counter/monotonic
    calls in `runners/` and `ops/` so timing stays observable here."""
    t0 = _perf_counter()
    fn()
    return _perf_counter() - t0

"""Engine telemetry: flat metric records, /proc resources, OpenMetrics.

The paper's product loop stores *data-quality* metrics in a repository
and runs anomaly detection over the resulting time series.  This module
turns the *engine's own health* into the same shape: a traced run (plus
its optional PlanCost prediction) flattens into one `Dict[str, float]`
record — throughput, per-phase seconds, exact wire bytes, pipeline
stage occupancy, peak RSS, predicted-vs-observed drift — that
`deequ_tpu.repository.engine` persists through the ordinary
`MetricsRepository`, so one store holds both kinds of series and one
anomaly stack (tools/sentinel.py) watches both.

Also here: an OpenMetrics / Prometheus text exporter over repository
results, ready for a future service layer to scrape.

Design constraints (same as the rest of `observe/`): no deequ_tpu
dependencies outside this package at import time — the repository and
lint layers are imported lazily inside functions, so `observe` stays
importable from every engine layer without cycles.
"""

from __future__ import annotations

import math
import re
import resource
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from deequ_tpu.observe import report

__all__ = [
    "ENGINE_PREFIX",
    "SERVICE_PREFIX",
    "engine_metric_record",
    "latest_results",
    "openmetrics_text",
    "proc_resources",
    "service_metric_record",
]

#: every key in an engine metric record starts with this prefix, which is
#: what lets the exporter and the sentinel tell engine series apart from
#: data-quality metrics sharing the same repository.
ENGINE_PREFIX = "engine."

#: the fleet-service slice of the engine namespace: queue depths,
#: admit/reject/shed/preempt counters, per-tenant scan bytes, breaker
#: state — produced by `deequ_tpu.service.telemetry` and consumed by the
#: same exporter/sentinel stack as any other `engine.` series.
SERVICE_PREFIX = ENGINE_PREFIX + "service."

#: span names whose `rows`/`batches` attributes count scanned work.
_SCAN_SPANS = ("fused_scan", "dist_scan")


def service_metric_record(values: Dict[str, Any]) -> Dict[str, float]:
    """Normalize a raw service-counter dict into an engine record.

    Keys gain the `engine.service.` prefix when they carry neither it
    nor the bare `engine.` prefix, and every value is coerced to float
    (non-finite values are dropped — repositories store finite floats),
    so ad-hoc dicts from operators' scripts and the `ServiceTelemetry`
    snapshot land in the repository in the same shape.
    """
    rec: Dict[str, float] = {}
    for key, value in values.items():
        name = key if key.startswith(ENGINE_PREFIX) else SERVICE_PREFIX + key
        try:
            v = float(value)
        except (TypeError, ValueError):
            continue
        if math.isfinite(v):
            rec[name] = v
    return rec


# ---------------------------------------------------------------------------
# /proc resource accounting (satellite: no psutil dependency)
# ---------------------------------------------------------------------------


def proc_resources() -> Dict[str, float]:
    """Peak RSS (MB) and cumulative major page faults for this process.

    Reads `/proc/self/status` (VmHWM) and `/proc/self/stat` (majflt,
    field 12); falls back to `resource.getrusage` where /proc is absent
    so callers never need an external measurement tool.
    """
    out: Dict[str, float] = {}
    try:
        with open("/proc/self/status", encoding="ascii") as fh:
            for line in fh:
                if line.startswith("VmHWM:"):
                    out["peak_rss_mb"] = float(line.split()[1]) / 1024.0
                    break
    except (OSError, ValueError, IndexError):
        pass
    try:
        with open("/proc/self/stat", encoding="ascii") as fh:
            # comm may contain spaces/parens: split after the closing paren,
            # which leaves state at index 0 and majflt (field 12) at index 9.
            tail = fh.read().rsplit(")", 1)[1].split()
        out["major_faults"] = float(int(tail[9]))
    except (OSError, ValueError, IndexError):
        pass
    if "peak_rss_mb" not in out or "major_faults" not in out:
        usage = resource.getrusage(resource.RUSAGE_SELF)
        # linux reports ru_maxrss in KB
        out.setdefault("peak_rss_mb", usage.ru_maxrss / 1024.0)
        out.setdefault("major_faults", float(usage.ru_majflt))
    return out


# ---------------------------------------------------------------------------
# flat engine metric record
# ---------------------------------------------------------------------------


def engine_metric_record(
    trace: Any,
    plan_cost: Any = None,
    *,
    extra: Optional[Dict[str, float]] = None,
) -> Dict[str, float]:
    """Flatten a RunTrace (and optional PlanCost) into one metric record.

    Keys are `engine.`-prefixed floats: wall/CPU seconds, scanned
    rows/batches and rows/s, summed dispatch wire bytes, disjoint
    per-phase self seconds, per-stage pipeline occupancy, trace
    counters, peak RSS / major faults, and — when `plan_cost` is given —
    `engine.drift.*` predicted-vs-observed deltas per PlanCost field
    (see `deequ_tpu.lint.cost.cost_drift`).
    """
    root = trace.root
    wall = float(trace.duration_s)
    rec: Dict[str, float] = {
        "engine.wall_s": wall,
        "engine.cpu_s": float(root.cpu_s),
    }

    rows = 0
    batches = 0
    saw_scan = False
    wire = 0
    saw_wire = False
    for sp in trace.spans():
        if sp.name in _SCAN_SPANS:
            attrs = sp.attrs
            if "rows" in attrs or "batches" in attrs:
                rows += int(attrs.get("rows", 0))
                batches += int(attrs.get("batches", 0))
                saw_scan = True
        elif sp.name == "dispatch" and "wire_bytes" in sp.attrs:
            wire += int(sp.attrs["wire_bytes"])
            saw_wire = True
    if saw_scan:
        rec["engine.rows"] = float(rows)
        rec["engine.batches"] = float(batches)
        if wall > 0.0:
            rec["engine.rows_per_s"] = rows / wall
    if saw_wire:
        rec["engine.wire_bytes"] = float(wire)

    for phase, secs in trace.phase_seconds().items():
        if secs > 0.0 or phase in report.PHASES:
            rec[f"engine.phase.{phase}_s"] = float(secs)

    for row in report.pipeline_occupancy([root]):
        stage = str(row["stage"])
        rec[f"engine.pipeline.{stage}.occupancy"] = float(row["occupancy"])
        rec[f"engine.pipeline.{stage}.busy_s"] = float(row["busy_s"])
        rec[f"engine.pipeline.{stage}.stall_s"] = float(row["stall_s"])

    for key, value in trace.counters.items():
        if isinstance(value, (int, float)):
            rec[f"engine.counter.{key}"] = float(value)

    # derived: fraction of parquet row groups the pushdown analyzer
    # skipped this run (the sentinel watches it for prune-effectiveness
    # regressions); only present when a prune decision actually ran
    rg_total = rec.get("engine.counter.rg_total", 0.0)
    if rg_total > 0.0:
        rec["engine.rg_skipped_ratio"] = (
            rec.get("engine.counter.rg_skipped", 0.0) / rg_total
        )

    # derived: fraction of scanned columns the buffer-level native
    # decode took, and the per-scan average worker count (exact when
    # every scan ran the same pool size) — the sentinel watches both for
    # decode-fast-path regressions; only present when a decode plan ran
    decode_total = rec.get("engine.counter.decode_cols_total", 0.0)
    if decode_total > 0.0:
        rec["engine.decode_fastpath_ratio"] = (
            rec.get("engine.counter.decode_cols_fast", 0.0) / decode_total
        )
    decode_passes = rec.get("engine.counter.decode_passes", 0.0)
    if decode_passes > 0.0:
        rec["engine.decode_workers"] = (
            rec.get("engine.counter.decode_workers", 0.0) / decode_passes
        )

    # derived: fraction of scanned columns decoded STRAIGHT to the wire
    # (decode-to-wire fusion) — the sentinel watches it for fall-off
    # regressions; only present when a wire verdict actually ran
    wire_total = rec.get("engine.counter.wire_cols_total", 0.0)
    if wire_total > 0.0:
        rec["engine.wire_fused_ratio"] = (
            rec.get("engine.counter.wire_fused_cols", 0.0) / wire_total
        )

    # derived: fraction of fast-path column-chunks the native parquet
    # page reader decoded (page bytes straight to arrow layout, no
    # pyarrow materialization) — the sentinel watches it for reader
    # fall-off regressions; only present when a reader verdict ran
    reader_total = rec.get("engine.counter.reader_chunks_total", 0.0)
    if reader_total > 0.0:
        rec["engine.reader_native_ratio"] = (
            rec.get("engine.counter.reader_chunks_native", 0.0) / reader_total
        )

    # derived: encoded-fold health. run_ratio = logical values folded
    # per (run, code) entry — the compression the fold exploited (the
    # sentinel watches it dropping toward 1.0: the data stopped
    # run-compressing and the fold stopped paying). fallback_ratio =
    # chunks that failed closed to the row-width path out of planned
    # run-fold chunks plus fallbacks (watched rising: pages stopped
    # being all-dictionary at decode). codes_folded / bytes_saved =
    # dictionary codes rolled up to engine values and row-width bytes
    # never materialized (watched dropping). Only present when an
    # encoded-fold chunk actually decoded.
    enc_chunks = rec.get("engine.counter.encfold_chunks", 0.0)
    enc_fallback = rec.get("engine.counter.encfold_chunks_fallback", 0.0)
    if enc_chunks > 0.0 or enc_fallback > 0.0:
        enc_runs = rec.get("engine.counter.encfold_runs", 0.0)
        if enc_runs > 0.0:
            rec["engine.encfold.run_ratio"] = (
                rec.get("engine.counter.encfold_values", 0.0) / enc_runs
            )
        rec["engine.encfold.fallback_ratio"] = enc_fallback / (
            enc_chunks + enc_fallback
        )
        rec["engine.encfold.codes_folded"] = rec.get(
            "engine.counter.encfold_codes_folded", 0.0
        )
        rec["engine.encfold.bytes_saved"] = rec.get(
            "engine.counter.encfold_bytes_saved", 0.0
        )

    # derived: fraction of dataset partitions whose analyzer states
    # loaded from the persistent state cache instead of scanning — the
    # sentinel watches it for incremental-scan regressions; only present
    # when a partitioned run actually split cached vs scanned
    partitions_total = rec.get("engine.counter.partitions_total", 0.0)
    if partitions_total > 0.0:
        rec["engine.state_cache_hit_ratio"] = (
            rec.get("engine.counter.partitions_cached", 0.0) / partitions_total
        )

    # derived: fraction of a window query's cover spans answered by a
    # precomputed segment envelope (the rest rebuilt from per-partition
    # states) — the sentinel watches it collapsing, which means segment
    # publication broke or churn outruns the covers; only present when
    # a window query actually resolved spans
    window_spans = rec.get("engine.counter.window.spans", 0.0)
    if window_spans > 0.0:
        rec["engine.window.segment_hit_ratio"] = (
            rec.get("engine.counter.window.segment_hits", 0.0) / window_spans
        )

    # derived: fraction of fused-fn lookups that found their plan
    # *shape* already compiled (the jit/fuse cost paid once per shape
    # fleet-wide) — the sentinel watches it dropping; only present when
    # a fused-fn lookup actually ran
    plan_lookups = rec.get("engine.counter.plan_cache.lookups", 0.0)
    if plan_lookups > 0.0:
        rec["engine.plan_cache_hit_ratio"] = (
            rec.get("engine.counter.plan_cache.hits", 0.0) / plan_lookups
        )

    # derived: fraction of retried transient-IO operations that
    # recovered within the retry budget (the rest degraded to the
    # pyarrow fallback) — the sentinel watches it dropping; only present
    # when a retry outcome was actually recorded
    retried = rec.get("engine.counter.retry.recovered", 0.0) + rec.get(
        "engine.counter.retry.exhausted", 0.0
    )
    if retried > 0.0:
        rec["engine.retry.recovery_ratio"] = (
            rec.get("engine.counter.retry.recovered", 0.0) / retried
        )

    # derived: fraction of observed faults that cost a unit its native
    # decode (degraded to the pyarrow fallback) — the sentinel watches
    # it rising; only present when a fault was actually observed
    faults = rec.get("engine.counter.fault.observed", 0.0)
    if faults > 0.0:
        rec["engine.fault.fallback_ratio"] = (
            rec.get("engine.counter.fault.fallback_units", 0.0) / faults
        )

    # derived: sharded-scan health (one record per participating
    # process). skew_ratio = this mesh's largest shard vs the even
    # split (1.0 = perfectly balanced; the sentinel watches it rising),
    # rows_per_s = THIS shard's fold throughput (watched dropping),
    # merge_bytes = gathered state-envelope bytes that crossed the
    # process boundary (watched rising — states, never rows, so this
    # should stay KB-scale). Only present when a sharded scan ran.
    shard_count = rec.get("engine.counter.shard.count", 0.0)
    if shard_count > 0.0:
        shard_total = rec.get("engine.counter.shard.partitions_total", 0.0)
        if shard_total > 0.0:
            rec["engine.shard.skew_ratio"] = rec.get(
                "engine.counter.shard.partitions_max", 0.0
            ) / (shard_total / shard_count)
        rec["engine.shard.merge_bytes"] = rec.get(
            "engine.counter.shard.merge_bytes", 0.0
        )
        if wall > 0.0:
            rec["engine.shard.rows_per_s"] = (
                rec.get("engine.counter.shard.rows_local", 0.0) / wall
            )

    # satellite: traced_run stamps these on the root span; live /proc read
    # covers traces produced before the attributes existed.
    res = proc_resources()
    rec["engine.peak_rss_mb"] = float(root.attrs.get("peak_rss_mb", res.get("peak_rss_mb", 0.0)))
    rec["engine.major_faults"] = float(root.attrs.get("major_faults", res.get("major_faults", 0.0)))

    if plan_cost is not None:
        from deequ_tpu.lint.cost import cost_drift  # lazy: observe must not need lint at import

        for key, value in cost_drift(plan_cost, trace).items():
            rec[f"engine.{key}"] = float(value)

    if extra:
        for key, value in extra.items():
            name = key if key.startswith(ENGINE_PREFIX) else ENGINE_PREFIX + key
            rec[name] = float(value)
    return rec


# ---------------------------------------------------------------------------
# OpenMetrics / Prometheus exposition
# ---------------------------------------------------------------------------

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_OK = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(prefix: str, raw: str) -> str:
    name = _NAME_OK.sub("_", f"{prefix}_{raw}")
    if name[:1].isdigit():
        name = "_" + name
    return name


def _label_name(raw: str) -> str:
    name = _LABEL_OK.sub("_", raw)
    if not name or name[:1].isdigit():
        name = "_" + name
    return name


def _escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(labels: Sequence[Tuple[str, str]]) -> str:
    if not labels:
        return ""
    body = ",".join(f'{_label_name(k)}="{_escape(str(v))}"' for k, v in labels)
    return "{" + body + "}"


def latest_results(results: Iterable[Any]) -> List[Any]:
    """Keep the newest result per distinct tag set (by data_set_date).

    OpenMetrics forbids duplicate label sets within a family, so a
    scrape exposes the *latest* point of each series; history stays in
    the repository for the sentinel.
    """
    by_tags: Dict[Tuple[Tuple[str, str], ...], Any] = {}
    for res in results:
        key = tuple(sorted(res.result_key.tags.items()))
        cur = by_tags.get(key)
        if cur is None or res.result_key.data_set_date >= cur.result_key.data_set_date:
            by_tags[key] = res
    return [by_tags[key] for key in sorted(by_tags)]


def openmetrics_text(results: Iterable[Any], *, prefix: str = "deequ_tpu") -> str:
    """Render repository results as OpenMetrics exposition text.

    Engine telemetry metrics (names under `engine.`) become one gauge
    family each (`<prefix>_engine_rows_per_s{...}`); data-quality
    metrics share a single `<prefix>_metric` family labelled by
    metric/instance/entity.  Result-key tags become labels on every
    sample.  Failed and non-finite metric values are skipped.  Output
    ends with the mandatory `# EOF` terminator.
    """
    families: Dict[str, List[str]] = {}
    seen: set = set()

    def _emit(family: str, labels: List[Tuple[str, str]], value: float) -> None:
        if not math.isfinite(value):
            return
        label_str = _label_str(labels)
        dedupe = (family, label_str)
        if dedupe in seen:
            return
        seen.add(dedupe)
        families.setdefault(family, []).append(f"{family}{label_str} {value!r}")

    dq_family = _metric_name(prefix, "metric")
    for res in latest_results(results):
        tags = sorted(res.result_key.tags.items())
        for metric in res.analyzer_context.metric_map.values():
            for flat in metric.flatten():
                if not flat.value.is_success:
                    continue
                try:
                    value = float(flat.value.get())
                except (TypeError, ValueError):
                    continue
                if flat.name.startswith(ENGINE_PREFIX):
                    family = _metric_name(prefix, flat.name.replace(".", "_"))
                    labels = [("instance", flat.instance)] + list(tags)
                else:
                    family = dq_family
                    labels = [
                        ("metric", flat.name),
                        ("instance", flat.instance),
                        ("entity", getattr(flat.entity, "value", str(flat.entity))),
                    ] + list(tags)
                _emit(family, labels, value)

    lines: List[str] = []
    for family in sorted(families):
        lines.append(f"# TYPE {family} gauge")
        lines.extend(sorted(families[family]))
    lines.append("# EOF")
    return "\n".join(lines) + "\n"

"""Counts-based family fast path for low-range integer columns.

The family kernel (`ops/native masked_moments_select`) pays ~10ns/row to
produce a (column, where) family's fused moments, decimated quantile
sample and HLL++ registers. For an integer column whose values fit a
65536-wide window (quantities, codes, flags, ordinals — the common
shapes of the reference's TPC-H-style profiling targets), ONE dense
windowed count pass (~2-3ns/row, `bincount_window_i64`) captures the
full value distribution, and every family output derives from the
counts table in O(#bins):

- moments: weighted sums over the distinct values (the sum is EXACT
  integer arithmetic, tighter than the kernel's long-double stream);
- the decimated sample: the select kernel's contract is
  ``sorted(x[mask])[stride/2::stride][:cap]`` — rank lookups into the
  cumulative counts reproduce those order statistics EXACTLY (float64
  conversion is monotonic, so int-order rank values equal f64-order
  rank values);
- HLL registers: registers are a max over per-value ranks, so hashing
  each DISTINCT value once yields bit-identical registers to hashing
  every row (duplicates never change a max) — the same argument
  _LowCardCounts uses for string dictionaries;
- the level law mirrors the C kernel exactly
  (``while (cap << level) < m: level++``).

The window is guessed from three 4096-row probes (head / middle /
tail); a wrong guess aborts the C pass at the first out-of-window value
and the caller falls back to the select kernel, so the speculation
costs only the scanned prefix. Role in the reference: this replaces the
per-partition update of catalyst/StatefulApproxQuantile.scala:28 and
StatefulHyperloglogPlus.scala:31 for integer columns with an exact
count-table equivalent.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

WINDOW = 1 << 16
_PROBE = 4096
_MARGIN = 4096


def enabled() -> bool:
    return not os.environ.get("DEEQU_TPU_NO_COUNTS_FASTPATH")


def _probe_range(
    values: np.ndarray, valid: Optional[np.ndarray]
) -> Optional[Tuple[int, int]]:
    """(min, max) over up to three 4096-row slices of the valid values;
    None when every probed row is null (no information — fall back)."""
    n = len(values)
    segments = ((0, _PROBE), (n // 2, n // 2 + _PROBE), (max(0, n - _PROBE), n))
    vmin: Optional[int] = None
    vmax: Optional[int] = None
    for a, b in segments:
        v = values[a:b]
        if valid is not None:
            v = v[valid[a:b]]
        if len(v) == 0:
            continue
        lo, hi = int(v.min()), int(v.max())
        vmin = lo if vmin is None else min(vmin, lo)
        vmax = hi if vmax is None else max(vmax, hi)
    if vmin is None or vmax is None:
        return None
    return vmin, vmax


def counts_for_column(
    values: np.ndarray,
    valid: Optional[np.ndarray],
    where: Optional[np.ndarray],
) -> Optional[Tuple[np.ndarray, int, int, int]]:
    """(counts[WINDOW], lo, n_valid, n_where) for an int64 column whose
    valid values fit a speculative WINDOW-wide range; None when the
    column is not int64, the probe spans too wide, or the window guess
    missed (the C pass aborts on the first out-of-window value)."""
    from deequ_tpu.ops import native

    if values.dtype != np.int64 or len(values) == 0:
        return None
    probed = _probe_range(values, valid)
    if probed is None:
        return None
    vmin, vmax = probed
    span = vmax - vmin
    if span >= WINDOW - 2 * _MARGIN:
        return None
    # center the window around the probed range so unprobed outliers get
    # equal slack on both sides; clamp so the whole window stays inside
    # int64 (values near Long.MIN/MAX sentinels must not wrap)
    lo = vmin - (WINDOW - span) // 2
    lo = max(-(1 << 63), min(lo, (1 << 63) - WINDOW))
    res = native.bincount_window(values, valid, where, lo, WINDOW)
    if res is None:
        return None
    counts, n_valid, n_where = res
    return counts, lo, n_valid, n_where


def weighted_moments_and_sample(
    values_sorted: np.ndarray,
    counts_sorted: np.ndarray,
    cap: int,
    exact_sum: "int | None" = None,
):
    """The kernel-parity core shared by every counts-based path: given
    value-SORTED (distinct value, count) pairs, derive
    (count, sum, min, max, m2), the decimated sample and the level —
    mirroring the C kernel's decimation law
    (``while (cap << level) < m: level++``; sample =
    ``sorted(x)[stride/2::stride][:kept]`` via rank lookups into the
    cumulative counts). `exact_sum` supplies an exactly-computed total
    (integer paths); float paths take the weighted long-double dot."""
    cs = counts_sorted
    vs = values_sorted
    m = int(cs.sum())
    if m == 0:
        return (
            (0.0, 0.0, float("inf"), float("-inf"), 0.0),
            np.zeros(0, dtype=np.float64),
            0,
            0,
        )
    if exact_sum is not None:
        sum_d = float(exact_sum)
    else:
        sum_d = float(np.dot(cs.astype(np.longdouble), vs))
    avg = sum_d / m
    with np.errstate(over="ignore"):
        # d*d squares in float64 on purpose: the C kernel's `double d`
        # overflows to inf at the same magnitudes, and parity means
        # matching that (inf == inf), not avoiding it
        d = vs - avg
        m2 = float(
            np.dot(cs.astype(np.longdouble), (d * d).astype(np.longdouble))
        )
    level = 0
    while (cap << level) < m:
        level += 1
    stride = 1 << level
    offset = stride >> 1
    kept = max(0, (m - offset + stride - 1) // stride)
    if kept:
        ranks = offset + stride * np.arange(kept, dtype=np.int64)
        positions = np.searchsorted(np.cumsum(cs), ranks, side="right")
        sample = vs[positions]
    else:
        sample = np.zeros(0, dtype=np.float64)
    return (float(m), sum_d, float(vs[0]), float(vs[-1]), m2), sample, m, level


_SIGN = np.uint64(1) << np.uint64(63)


def hash_counts_for_column(
    values: np.ndarray,
    valid: Optional[np.ndarray],
    where: Optional[np.ndarray],
):
    """(distinct_keys_u64, counts, n_valid, n_where) via the
    open-addressing C counter, for float64 (keys = bit patterns) or
    int64 (keys = values) columns; None when native is unavailable or
    the column exceeds 65536 distinct values (the kernel aborts after a
    prefix). Extends the counts fast path to low-cardinality FLOAT
    columns (discount/tax/rate-style) and sparse wide-range integers
    the dense window cannot hold."""
    from deequ_tpu.ops import native

    if values.dtype not in (np.float64, np.int64) or len(values) == 0:
        return None
    return native.hashcount(values.view(np.uint64), valid, where)


def family_from_hash_counts(
    keys_u64: np.ndarray,
    counts: np.ndarray,
    kind: str,
    cap: int,
    n_where: int,
    want_regs: bool,
):
    """Derive the select kernel's output tuple from hash-table distinct
    counts. `kind` is 'f64' (keys are bit patterns; sort order is the C
    kernel's f64_key total order, so -0.0 sorts before +0.0 exactly like
    the radix select) or 'i64' (keys are values)."""
    keys_u64 = np.asarray(keys_u64, dtype=np.uint64)
    counts = np.asarray(counts)
    exact_sum = None
    if kind == "f64":
        order = np.argsort(np.where(keys_u64 >> np.uint64(63), ~keys_u64,
                                    keys_u64 | _SIGN))
        vs = keys_u64[order].view(np.float64)
        cs = counts[order]
    else:
        ints = keys_u64.view(np.int64)
        order = np.argsort(ints)
        ints = ints[order]
        vs = ints.astype(np.float64)
        cs = counts[order]
        if len(ints):
            amax = max(abs(int(ints[0])), abs(int(ints[-1])))
            if amax < (1 << 31):
                exact_sum = int(np.dot(cs, ints))
            else:
                exact_sum = sum(
                    int(c) * int(v) for c, v in zip(cs, ints)
                )
    core, sample, m, level = weighted_moments_and_sample(
        vs, cs, cap, exact_sum=exact_sum
    )
    mom = np.array(list(core) + [float(n_where)], dtype=np.float64)
    regs = None
    if want_regs:
        from deequ_tpu.ops.sketches import hll

        regs = np.zeros(hll.M, dtype=np.int32)
        if len(keys_u64):
            packed = hll.pack_codes(
                keys_u64.view(np.int64),
                np.ones(len(keys_u64), dtype=bool),
            )
            np.maximum.at(
                regs, packed >> 6, (packed & 0x3F).astype(np.int32)
            )
    return mom, sample, m, level, regs


def family_from_value_counts(
    values: np.ndarray,
    counts: np.ndarray,
    kind: str,
    cap: int,
    n_where: int,
    want_regs: bool,
):
    """Derive the select kernel's output tuple from distinct
    (value, count) pairs in engine representation — int64 values for
    'i64', float64 for 'f64'. The encoded-fold path lands here after
    rolling dictionary codes up to values: reinterpreting the values as
    hash keys makes this literally family_from_hash_counts, so every
    derivation rule (f64 total order, exact integer sums, level law,
    distinct-only HLL) is shared with the row path's counts fast path —
    which is what makes the two paths bit-identical for the same
    multiset."""
    values = np.ascontiguousarray(values)
    return family_from_hash_counts(
        values.view(np.uint64), counts, kind, cap, n_where, want_regs
    )


def family_from_counts(
    counts: np.ndarray,
    lo: int,
    cap: int,
    n_where: int,
    want_regs: bool,
):
    """Derive the select kernel's outputs from a dense counts window:
    (mom6, sample, n_valid, level, registers_or_None) — the exact tuple
    masked_moments_select returns, same layouts, same level law."""
    nz = np.flatnonzero(counts)
    cs = counts[nz]
    ints = (nz + lo).astype(np.int64)
    vs = ints.astype(np.float64)
    m = int(cs.sum())
    if m > 0:
        # exact integer sum: products stay inside int64 when
        # |value| < 2^31 (counts are < 2^63 / 2^31); big ints otherwise
        amax = max(abs(int(ints[0])), abs(int(ints[-1])))
        if amax < (1 << 31):
            total = int(np.dot(cs, ints))
        else:
            total = sum(int(c) * int(v) for c, v in zip(cs, ints))
    else:
        total = 0
    core, sample, m, level = weighted_moments_and_sample(
        vs, cs, cap, exact_sum=total
    )
    mom = np.array(list(core) + [float(n_where)], dtype=np.float64)
    regs = None
    if want_regs:
        from deequ_tpu.ops.sketches import hll

        regs = np.zeros(hll.M, dtype=np.int32)
        if len(ints):
            packed = hll.pack_codes(ints, np.ones(len(ints), dtype=bool))
            np.maximum.at(
                regs, packed >> 6, (packed & 0x3F).astype(np.int32)
            )
    return mom, sample, m, level, regs

"""Shared device aggregation over a frequencies table.

One compiled program per grouping set computes every requested frequency
aggregation (uniqueness, distinctness, entropy, ...) over the padded counts
array — the analogue of the reference sharing `frequencies.agg(all fns)`
(reference: runners/AnalysisRunner.scala:466-534, esp. :497-500).
"""

from __future__ import annotations

import threading
from typing import TYPE_CHECKING, Any, Dict, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deequ_tpu import observe
from deequ_tpu.ops import runtime
from deequ_tpu.ops.fused import _pad_size, _to_f64

if TYPE_CHECKING:
    from deequ_tpu.analyzers.frequency import (
        FrequenciesAndNumRows,
        ScanShareableFrequencyBasedAnalyzer,
    )

_FREQ_CACHE: Dict[Any, Any] = {}
_FREQ_CACHE_LOCK = threading.Lock()

# below this many groups the jit round-trip costs more than numpy
_DEVICE_THRESHOLD = 1 << 16


def _get_freq_fn(analyzers: Sequence["ScanShareableFrequencyBasedAnalyzer"]):
    key = (tuple(repr(a) for a in analyzers), bool(jax.config.jax_enable_x64))
    with _FREQ_CACHE_LOCK:
        fn = _FREQ_CACHE.get(key)
    if fn is None:

        def fused(counts, num_rows):
            return tuple(a.freq_reduce(counts, num_rows, jnp) for a in analyzers)

        fn = jax.jit(fused)
        with _FREQ_CACHE_LOCK:
            fn = _FREQ_CACHE.setdefault(key, fn)
    return fn


def run_shared_freq_agg(
    state: "FrequenciesAndNumRows",
    analyzers: Sequence["ScanShareableFrequencyBasedAnalyzer"],
) -> List[Any]:
    """One fused aggregation pass -> one metric per analyzer (in order)."""
    spilled = bool(getattr(state, "is_spilled", False))
    with observe.span(
        "freq_agg",
        cat="group",
        analyzers=len(analyzers),
        groups=-1 if spilled else len(getattr(state, "counts", ())),
        spilled=spilled,
    ):
        return _run_shared_freq_agg(state, analyzers)


def _run_shared_freq_agg(
    state: "FrequenciesAndNumRows",
    analyzers: Sequence["ScanShareableFrequencyBasedAnalyzer"],
) -> List[Any]:
    runtime.record_pass("freq-agg:" + ",".join(a.name for a in analyzers))
    if getattr(state, "is_spilled", False):
        # disk-spilled frequencies: every freq_reduce is a sum over
        # groups, so the aggregation streams partition by partition and
        # sums the (scalar) aggregate leaves — exact, never materializing
        # the full counts array
        totals: List[Any] = [None] * len(analyzers)
        for part in state.partitions():
            part_counts = part.counts.astype(np.float64)
            for i, analyzer in enumerate(analyzers):
                agg = analyzer.freq_reduce(part_counts, float(state.num_rows), np)
                totals[i] = (
                    agg
                    if totals[i] is None
                    else {k: totals[i][k] + agg[k] for k in agg}
                )
        empty = np.zeros(0, dtype=np.float64)
        aggs = [
            t
            if t is not None
            else a.freq_reduce(empty, float(state.num_rows), np)
            for a, t in zip(analyzers, totals)
        ]
        return [
            a.metric_from_freq_agg(agg, state) for a, agg in zip(analyzers, aggs)
        ]
    counts = state.counts.astype(np.float64)

    if len(counts) >= _DEVICE_THRESHOLD:
        dtype = runtime.compute_dtype()
        padded = runtime.pad_to(counts.astype(dtype), _pad_size(len(counts), 1 << 62))
        runtime.record_launch()
        fn = _get_freq_fn(analyzers)
        aggs = [
            _to_f64(t)
            for t in jax.device_get(fn(jnp.asarray(padded), dtype(state.num_rows)))
        ]
    else:
        aggs = [a.freq_reduce(counts, float(state.num_rows), np) for a in analyzers]

    return [
        a.metric_from_freq_agg(agg, state) for a, agg in zip(analyzers, aggs)
    ]
